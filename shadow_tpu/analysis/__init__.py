"""shadowlint: the device-purity & determinism static-analysis plane.

Two layers guard the invariants every PR silently depends on:

  * an AST rule engine (`rules.py` + `linter.py`, rule codes ``STL0xx``)
    that classifies modules as **kernel** (compiled into device window
    programs) vs **host** and bans the constructs that break Shadow's
    determinism promise — wall clocks and ambient RNG in kernel code,
    unseeded RNG construction outside ``core/rng.py``'s fold-in lineage,
    traced-value coercion/branching inside jitted bodies, unaudited
    callbacks, unsorted dict iteration feeding pytrees, and metric keys
    outside the ``tools/validate_metrics.py`` namespace schema;
  * a compiled-kernel auditor (`hlo_audit.py`) that lowers every
    registered window-kernel variant ({conservative, optimistic} ×
    {global, islands, fleet} × gear tiers) to optimized HLO and asserts
    the op bans (no scatter, no serializing gather, bounded sort rows),
    plus a retrace detector that makes "one sweep = one compile" a
    statically gated property.

Entry points: ``tools/shadowlint.py`` (CLI), ``bench.py --lint-smoke``
(gate), ``tests/test_analysis.py`` (tier-1).  See
docs/static_analysis.md for the rule catalog and workflows.
"""

from shadow_tpu.analysis.linter import (  # noqa: F401
    Finding,
    classify_module,
    lint_file,
    lint_paths,
    lint_source,
)
