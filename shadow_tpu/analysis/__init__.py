"""shadowlint: the device-purity, determinism & contract analysis plane.

Five passes guard the invariants every PR silently depends on:

  * an AST rule engine (`rules.py` + `linter.py`, rule codes ``STL0xx``)
    that classifies modules as **kernel** (compiled into device window
    programs) vs **host** and bans the constructs that break Shadow's
    determinism promise — wall clocks and ambient RNG in kernel code,
    unseeded RNG construction outside ``core/rng.py``'s fold-in lineage,
    traced-value coercion/branching inside jitted bodies, unaudited
    callbacks, unsorted dict iteration feeding pytrees, and metric keys
    outside the ``tools/validate_metrics.py`` namespace schema;
  * a compiled-kernel auditor (`hlo_audit.py`) that lowers every
    registered window-kernel variant ({conservative, optimistic} ×
    {global, islands, fleet} × gear tiers) to optimized HLO and asserts
    the op bans (no scatter, no serializing gather, bounded sort rows),
    plus a retrace detector that makes "one sweep = one compile" a
    statically gated property;
  * a cross-plane contract auditor (`contracts.py`, ``SLC0xx``) that
    cross-checks the hand-maintained registries — metric namespaces,
    fault-op tables and their injector arms, schema-version literals in
    docs and tests, config_spec rows, supervisor policies — against
    every emit/consume site;
  * a host-thread race lint (`threads.py`, ``STH0xx``) applying
    Eraser-style declared-guard lock discipline to the thread-bearing
    host modules (the serve daemon and friends);
  * an HLO budget ledger (`hlo_audit.py` + ``hlo_baseline.json``,
    ``SLH001``) diffing each variant's exact collective / sort / gather
    / buffer budget against a checked-in baseline.

Entry points: ``tools/shadowlint.py`` (CLI; ``--contracts``
``--threads`` ``--hlo``), ``bench.py --lint-smoke`` (gate, all passes),
``tests/test_analysis.py`` (tier-1).  See docs/static_analysis.md for
the rule catalogs and the waiver / ledger-regeneration workflows.
"""

from shadow_tpu.analysis.linter import (  # noqa: F401
    Finding,
    classify_module,
    lint_file,
    lint_paths,
    lint_source,
)
