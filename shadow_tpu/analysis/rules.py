"""AST rules for shadowlint (codes STL0xx).

Each rule is a function ``rule(ctx) -> Iterable[RawFinding]`` registered
in ``RULES``; ``linter.py`` owns file walking, module classification,
``# noqa`` suppression, and the baseline workflow.  Rules see a
``RuleContext`` carrying the parsed tree, an import-resolution map, and
the module classification — so a call like ``np.random.uniform(...)``
resolves to ``numpy.random.uniform`` no matter the alias.

Rule catalog (docs/static_analysis.md is the user-facing copy):

  STL001  wall-clock read in a kernel module
  STL002  ambient (non fold-in) randomness in a kernel module
  STL003  unseeded RNG construction / PRNGKey outside core/rng.py
  STL004  float()/int()/bool() coercion of a traced value in a jitted body
  STL005  Python branching on a traced value in a jitted body
  STL006  host callback / jax.debug in a kernel module without allowlist
  STL007  unsorted dict iteration feeding pytree construction (kernel)
  STL008  metric key outside the validate_metrics namespace schema

Adding a rule: write ``def rule_stl0xx(ctx)``, append a ``Rule`` row to
``RULES``, add a firing fixture to tests/test_analysis.py, and document
the code in docs/static_analysis.md.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Callable, Iterable, Iterator, NamedTuple

# ---------------------------------------------------------------------------
# rule plumbing
# ---------------------------------------------------------------------------


class RawFinding(NamedTuple):
    """A rule hit before suppression/baseline filtering (linter.py turns
    these into `Finding`s with path/text attached)."""

    line: int
    col: int
    code: str
    message: str


class Rule(NamedTuple):
    code: str
    summary: str
    kernel_only: bool
    fn: Callable[["RuleContext"], Iterable[RawFinding]]


@dataclass
class RuleContext:
    tree: ast.AST
    relpath: str  # repo-relative, forward slashes
    kind: str  # "kernel" | "host"
    imports: dict[str, str]  # local name -> dotted module/object it names
    parents: dict[ast.AST, ast.AST]
    traced: set[ast.AST]  # FunctionDef/Lambda nodes that run under trace


# Callbacks a kernel module may legitimately carry: (relpath, callable)
# pairs.  Empty on purpose — the tree is callback-free today; additions
# must name the exact site so a review sees them.
CALLBACK_ALLOWLIST: set[tuple[str, str]] = set()

_WALL_CLOCK = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "time.clock_gettime", "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today",
}

# ambient RNG roots banned from kernel modules (STL002): any call whose
# resolved dotted name starts with one of these
_AMBIENT_RNG_PREFIXES = (
    "random.", "numpy.random.", "os.urandom", "secrets.", "uuid.uuid4",
)

_TRACE_ENTRIES = {
    "jax.jit", "jax.pmap", "jax.vmap", "jax.checkpoint", "jax.remat",
    "jax.lax.while_loop", "jax.lax.cond", "jax.lax.scan",
    "jax.lax.fori_loop", "jax.lax.switch", "jax.lax.map",
    "jax.lax.associative_scan", "jax.custom_jvp", "jax.custom_vjp",
}

_CALLBACKS = {
    "jax.pure_callback", "jax.experimental.io_callback",
    "jax.debug.print", "jax.debug.callback", "jax.debug.breakpoint",
    "jax.experimental.host_callback.call",
    "jax.experimental.host_callback.id_tap",
}


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------


def build_imports(tree: ast.AST) -> dict[str, str]:
    """Map local names to the dotted thing they import.

    ``import numpy as np``          -> {"np": "numpy"}
    ``from jax import lax``         -> {"lax": "jax.lax"}
    ``from time import time as t``  -> {"t": "time.time"}
    """
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def build_parents(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def resolve_name(
    node: ast.AST, imports: dict[str, str], require_import: bool = False
) -> str | None:
    """Dotted name of an expression, with its head resolved through the
    import map: ``np.random.uniform`` -> ``numpy.random.uniform``.
    With ``require_import`` the head must actually be imported — so a
    local variable that happens to be named ``time`` never matches the
    stdlib module."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    if require_import and node.id not in imports:
        return None
    head = imports.get(node.id, node.id)
    return ".".join([head] + list(reversed(parts)))


def _func_scope(node: ast.AST, parents) -> ast.AST | None:
    """Nearest enclosing function/lambda (or None at module level)."""
    node = parents.get(node)
    while node is not None and not isinstance(
        node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
    ):
        node = parents.get(node)
    return node


def find_traced_functions(
    tree: ast.AST, imports: dict[str, str], parents
) -> set[ast.AST]:
    """Function/Lambda nodes whose bodies execute under a jax trace:

      * passed (by local name, or as an inline lambda) to a trace entry
        point — jit/vmap/lax.while_loop/cond/scan/... ;
      * decorated with one (``@jax.jit`` / ``@partial(jax.jit, ...)``);
      * defined inside any of the above (a helper def'd in a traced body
        runs under the same trace).
    """
    # name -> defs, per enclosing scope, for by-name argument resolution
    defs: dict[tuple[ast.AST | None, str], list[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault((_func_scope(node, parents), node.name), []).append(node)

    traced: set[ast.AST] = set()

    def mark_arg(arg: ast.AST, scope: ast.AST | None) -> None:
        if isinstance(arg, ast.Lambda):
            traced.add(arg)
        elif isinstance(arg, ast.Name):
            s = scope
            while True:
                for d in defs.get((s, arg.id), ()):
                    traced.add(d)
                if s is None:
                    break
                s = _func_scope(s, parents)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = resolve_name(node.func, imports)
            if name in _TRACE_ENTRIES:
                scope = _func_scope(node, parents)
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    mark_arg(arg, scope)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = resolve_name(target, imports)
                if name in _TRACE_ENTRIES or (
                    isinstance(dec, ast.Call)
                    and name in {"functools.partial", "partial"}
                    and dec.args
                    and resolve_name(dec.args[0], imports) in _TRACE_ENTRIES
                ):
                    traced.add(node)

    # propagate into nested defs/lambdas
    changed = True
    while changed:
        changed = False
        for node in ast.walk(tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ) and node not in traced:
                s = _func_scope(node, parents)
                if s is not None and s in traced:
                    traced.add(node)
                    changed = True
    return traced


def _traced_scope_chain(node: ast.AST, ctx: RuleContext) -> list[ast.AST]:
    """Enclosing traced functions of `node`, innermost first (empty when
    the node is not inside a traced body)."""
    chain = []
    fn = _func_scope(node, ctx.parents)
    while fn is not None:
        if fn in ctx.traced:
            chain.append(fn)
        fn = _func_scope(fn, ctx.parents)
    return chain


def _traced_local_names(fns: Iterable[ast.AST], parents) -> set[str]:
    """Names that carry traced values inside the given traced functions:
    their parameters plus every name assigned within their bodies.
    (Closure names from non-traced factory scopes stay out — branching
    on those is trace-time configuration, which is legitimate.)"""
    names: set[str] = set()
    fns = set(fns)
    for fn in fns:
        a = fn.args
        for arg in (
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
            + ([a.vararg] if a.vararg else []) + ([a.kwarg] if a.kwarg else [])
        ):
            names.add(arg.arg)
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            names.add(n.id)
            elif isinstance(node, (ast.For, ast.comprehension)):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
    return names


def _is_static_expr(node: ast.AST) -> bool:
    """Constant-foldable at trace time: literals and arithmetic on them."""
    return all(
        isinstance(
            n,
            (
                ast.Constant, ast.BinOp, ast.UnaryOp, ast.BoolOp, ast.Compare,
                ast.Tuple, ast.List, ast.operator, ast.unaryop, ast.boolop,
                ast.cmpop, ast.Load,
            ),
        )
        for n in ast.walk(node)
    )


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------


def rule_stl001(ctx: RuleContext) -> Iterator[RawFinding]:
    """Wall-clock reads in kernel modules: device kernels must be pure
    functions of (state, params, window) — host time leaking in breaks
    replay and the audit digest chain."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = resolve_name(node.func, ctx.imports, require_import=True)
            if name in _WALL_CLOCK:
                yield RawFinding(
                    node.lineno, node.col_offset, "STL001",
                    f"wall-clock read `{name}()` in kernel module "
                    f"(kernel code must be pure in (state, params, window))",
                )


def rule_stl002(ctx: RuleContext) -> Iterator[RawFinding]:
    """Ambient randomness in kernel modules: every random decision must
    come from core/rng.py's (seed, host, counter) fold-in lineage."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = resolve_name(node.func, ctx.imports, require_import=True)
            if name is None:
                continue
            if name.startswith("jax.random."):
                continue  # the sanctioned device lineage (STL003 gates keys)
            if any(
                name == p.rstrip(".") or name.startswith(p)
                for p in _AMBIENT_RNG_PREFIXES
            ):
                yield RawFinding(
                    node.lineno, node.col_offset, "STL002",
                    f"ambient randomness `{name}` in kernel module — use "
                    f"core/rng.py's fold-in lineage",
                )


def rule_stl003(ctx: RuleContext) -> Iterator[RawFinding]:
    """Unseeded RNG construction (any module) and PRNGKey construction
    outside core/rng.py.  Seed lineage must be rooted in the experiment
    seed: `random.Random()` with no argument seeds from OS entropy, and
    a stray PRNGKey(...) forks a second, unaudited device lineage."""
    in_rng = ctx.relpath.endswith("core/rng.py")
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = resolve_name(node.func, ctx.imports, require_import=True)
        if name in {"random.Random", "random.SystemRandom",
                    "numpy.random.default_rng", "numpy.random.RandomState"}:
            if not node.args and not node.keywords:
                yield RawFinding(
                    node.lineno, node.col_offset, "STL003",
                    f"unseeded `{name}()` — derive the seed from the "
                    f"experiment master seed",
                )
        elif name in {"jax.random.PRNGKey", "jax.random.key"} and not in_rng:
            yield RawFinding(
                node.lineno, node.col_offset, "STL003",
                f"`{name}` outside core/rng.py — root all device "
                f"randomness in rng.host_keys' fold-in lineage",
            )
        elif name in {"dataclasses.field", "field"}:
            for kw in node.keywords:
                if kw.arg == "default_factory" and resolve_name(
                    kw.value, ctx.imports, require_import=True
                ) in {"random.Random", "random.SystemRandom",
                      "numpy.random.default_rng"}:
                    yield RawFinding(
                        node.lineno, node.col_offset, "STL003",
                        "unseeded RNG default_factory — the field seeds "
                        "from OS entropy on construction",
                    )


def rule_stl004(ctx: RuleContext) -> Iterator[RawFinding]:
    """float()/int()/bool() inside a traced body concretizes a traced
    value: at best a TracerBoolConversionError at trace time, at worst a
    silent constant baked in from the tracer's aval."""
    rebound = {
        n.name for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n.name in {"float", "int", "bool"}
    }
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in {"float", "int", "bool"} - rebound
            and node.args
            and not _is_static_expr(node.args[0])
            and _traced_scope_chain(node, ctx)
        ):
            yield RawFinding(
                node.lineno, node.col_offset, "STL004",
                f"`{node.func.id}()` coercion inside a jitted body — "
                f"concretizes a traced value (use .astype / lax ops)",
            )


def _static_container_names(fns: Iterable[ast.AST]) -> set[str]:
    """Names assigned a list/tuple/dict display or comprehension inside
    the given functions: their *truthiness* (length) is static at trace
    time even when the elements are traced arrays."""
    names: set[str] = set()
    for fn in fns:
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value,
                (ast.List, ast.Tuple, ast.Dict, ast.Set, ast.ListComp,
                 ast.DictComp, ast.SetComp),
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _suspect_test_names(test: ast.AST) -> set[str]:
    """Names in a branch test that could carry traced *values*.  Skips
    the trace-time-static idioms: identity comparisons (`x is None`
    pytree-structure checks) and isinstance/hasattr/getattr/callable/len
    calls (lengths and attrs of traced arrays are static)."""
    names: set[str] = set()

    def visit(n: ast.AST) -> None:
        if isinstance(n, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops
        ):
            return
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) and (
            n.func.id in {"isinstance", "hasattr", "getattr", "callable",
                          "len"}
        ):
            return
        if isinstance(n, ast.Name):
            names.add(n.id)
        for c in ast.iter_child_nodes(n):
            visit(c)

    visit(test)
    return names


def rule_stl005(ctx: RuleContext) -> Iterator[RawFinding]:
    """Python `if`/`while` on a traced value inside a jitted body — the
    branch is resolved once at trace time (or fails to trace); use
    jnp.where / lax.cond.  Branching on factory-closure configuration,
    pytree structure (`x is None`), or static container lengths is fine:
    only names carrying traced values inside the traced scope count."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
            continue
        chain = _traced_scope_chain(node, ctx)
        if not chain:
            continue
        local = _traced_local_names(chain, ctx.parents)
        local -= _static_container_names(chain)
        test_names = _suspect_test_names(node.test)
        if test_names & local and not _is_static_expr(node.test):
            kind = {ast.If: "if", ast.While: "while", ast.IfExp: "ternary"}[
                type(node)
            ]
            yield RawFinding(
                node.lineno, node.col_offset, "STL005",
                f"Python `{kind}` on a traced value inside a jitted body "
                f"— use jnp.where / lax.cond / lax.while_loop",
            )


def rule_stl006(ctx: RuleContext) -> Iterator[RawFinding]:
    """Host callbacks / jax.debug in kernel modules: a callback re-enters
    Python mid-kernel — nondeterministic ordering under async dispatch
    and a serialization point on TPU.  Additions must be allowlisted in
    rules.CALLBACK_ALLOWLIST with the exact (module, callable) site."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = resolve_name(node.func, ctx.imports, require_import=True)
            if name in _CALLBACKS or (
                name is not None and name.startswith("jax.debug.")
            ):
                if (ctx.relpath, name) in CALLBACK_ALLOWLIST:
                    continue
                yield RawFinding(
                    node.lineno, node.col_offset, "STL006",
                    f"host callback `{name}` in kernel module without a "
                    f"CALLBACK_ALLOWLIST entry",
                )


def rule_stl007(ctx: RuleContext) -> Iterator[RawFinding]:
    """Unsorted dict iteration in kernel modules: dict order is insertion
    order, which upstream config/build wiring does not pin — iteration
    feeding pytree construction or kernel wiring must sort first."""
    for node in ast.walk(ctx.tree):
        iters: list[ast.AST] = []
        if isinstance(node, (ast.For, ast.AsyncFor)):
            iters = [node.iter]
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            iters = [g.iter for g in node.generators]
        for it in iters:
            if (
                isinstance(it, ast.Call)
                and isinstance(it.func, ast.Attribute)
                and it.func.attr in {"items", "keys", "values"}
                and not it.args
            ):
                yield RawFinding(
                    it.lineno, it.col_offset, "STL007",
                    f"unsorted `.{it.func.attr}()` iteration in kernel "
                    f"module — wrap in sorted(...) so pytree/kernel wiring "
                    f"order is pinned",
                )


_METRIC_EMITTERS = {"counter_set", "counter_add", "gauge_set", "histogram"}


def _literal_key_prefix(node: ast.AST) -> str | None:
    """Static prefix of a metric-key argument: full value for a str
    constant, the leading literal run for an f-string.  None when the key
    has no static prefix (dynamic keys are out of scope here — the
    runtime validator owns those)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        out = ""
        for part in node.values:
            if isinstance(part, ast.Constant) and isinstance(part.value, str):
                out += part.value
            else:
                break
        return out or None
    return None


def rule_stl008(ctx: RuleContext) -> Iterator[RawFinding]:
    """Metric-key namespace discipline: every statically-visible key fed
    to counter_set/counter_add/gauge_set/histogram must live in a
    namespace the tools/validate_metrics.py schema knows — the class of
    schema-drift bug that forced the v2→v6 validator chasing."""
    from shadow_tpu.obs.metrics import KNOWN_METRIC_NAMESPACES

    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _METRIC_EMITTERS
            and node.args
        ):
            continue
        prefix = _literal_key_prefix(node.args[0])
        if prefix is None or "." not in prefix:
            # dynamic key or no namespace segment visible — not decidable
            # statically; wall.* style helpers pass f"{prefix}.{f}"
            continue
        ns = prefix.split(".", 1)[0]
        if ns not in KNOWN_METRIC_NAMESPACES:
            yield RawFinding(
                node.args[0].lineno, node.args[0].col_offset, "STL008",
                f"metric namespace `{ns}.*` is not in the "
                f"validate_metrics schema (KNOWN_METRIC_NAMESPACES, "
                f"obs/metrics.py) — register it with a schema bump",
            )


RULES: list[Rule] = [
    Rule("STL001", "wall-clock read in kernel module", True, rule_stl001),
    Rule("STL002", "ambient randomness in kernel module", True, rule_stl002),
    Rule("STL003", "unseeded RNG / stray PRNGKey lineage", False, rule_stl003),
    Rule("STL004", "traced-value coercion in jitted body", True, rule_stl004),
    Rule("STL005", "Python branching on traced value", True, rule_stl005),
    Rule("STL006", "unallowlisted host callback in kernel", True, rule_stl006),
    Rule("STL007", "unsorted dict iteration in kernel", True, rule_stl007),
    Rule("STL008", "metric key outside namespace schema", False, rule_stl008),
]

RULE_INDEX: dict[str, Rule] = {r.code: r for r in RULES}
