"""Pass 3 of shadowlint: the cross-plane contract auditor (codes SLC0xx).

Every plane added since PR 7 carries hand-maintained contracts that span
files: the closed metric-namespace table and its schema version
(``obs/metrics.py``), the fault-op registries and their per-op field
contracts (``faults/plan.py``) plus the injector arms that execute them
(``core/engine.py``, ``procs/driver.py``), the supervisor policy set
(``core/supervisor.py``) re-validated by the config loader, the
schema-version literals quoted in docs tables and sample documents, and
the ``docs/config_spec.md`` tables that must mirror what the loader
actually parses.  Drift between any pair is a silent correctness bug —
caught today by whichever smoke gate happens to trip, or not at all.

This pass extracts each registry from its single source of truth (the
constants are plain data, imported directly) and statically cross-checks
every emit/consume site against it:

  SLC001  metric emitter writes a namespace outside KNOWN_METRIC_NAMESPACES
  SLC002  registered metric namespace with no statically-visible emitter
  SLC003  fault op with no injector-handler arm in its executing plane
  SLC004  fault-op docs table drift (missing or stale row)
  SLC005  stale schema-version literal (docs sample/heading, test assert)
  SLC006  config_spec table drift (stale row / undocumented loader key)
  SLC007  supervisor policy set drift (config validator / docs)
  SLC008  fault-op registry drift (ALL_OPS vs the _FIELDS validation table)
  SLC009  journal record-type docs table drift (serve/journal.py
          RECORD_TYPES vs the docs/serving.md §2 table)

Every check is a pure function over explicit inputs so the test suite
can forge drift fixtures; ``audit_tree`` wires the real files in.
``# noqa: SLC0xx`` suppresses line-anchored findings in .py sources; the
shared ``.shadowlint_baseline.json`` waiver workflow covers the rest
(docs findings have no line to annotate).
"""

from __future__ import annotations

import ast
import os
import re

from shadow_tpu.analysis import linter
from shadow_tpu.analysis import rules as rules_mod
from shadow_tpu.analysis.linter import Finding

# Documents whose fenced samples / headings quote a schema version, and
# the source constant each kind must match (SLC005).
def doc_schema_versions() -> dict[str, int]:
    from shadow_tpu.faults import plan as plan_mod
    from shadow_tpu.obs import audit as audit_mod
    from shadow_tpu.obs import metrics as metrics_mod
    from shadow_tpu.obs import prof as prof_mod

    return {
        "shadow_tpu.metrics": metrics_mod.SCHEMA_VERSION,
        "shadow_tpu.fault_plan": plan_mod.PLAN_SCHEMA_VERSION,
        "shadow_tpu.digest": audit_mod.DIGEST_SCHEMA_VERSION,
        "shadow_tpu.profile": prof_mod.PROFILE_SCHEMA_VERSION,
    }


# Config-loader fields documented collectively in prose rather than as
# table rows (docs/config_spec.md): the reference-compatible flag block
# and the device-network seam subsection.  Everything else must have a
# row (SLC006).
CONFIG_PROSE_DOCUMENTED: dict[str, frozenset[str]] = {
    "experimental": frozenset({
        "runahead", "interface_buffer", "interface_qdisc",
        "socket_recv_buffer", "socket_send_buffer",
        "socket_recv_autotune", "socket_send_autotune",
        "use_memory_manager", "use_seccomp", "use_syscall_counters",
        "use_object_counters", "worker_threads", "interpose_method",
        # "The device-network seam" subsection documents the pair
        "use_device_network", "use_device_tcp",
    }),
}

_METRIC_EMITTERS = ("counter_set", "counter_add", "gauge_set", "histogram")

# Namespace evidence must look like a dotted metric key head.
_NS_RE = re.compile(r"^([a-z][a-z0-9_]*)\.")


def _finding(path: str, line: int, col: int, code: str, message: str,
             text: str = "") -> Finding:
    return Finding(path=path, line=line, col=col, code=code,
                   message=message, text=text)


def _line_text(lines: list[str], lineno: int) -> str:
    return lines[lineno - 1].strip() if 0 < lineno <= len(lines) else ""


def _suppress(findings: list[Finding], src_lines: dict[str, list[str]]
              ) -> list[Finding]:
    """Apply ``# noqa`` suppression to line-anchored .py findings."""
    out = []
    for f in findings:
        lines = src_lines.get(f.path)
        if lines is not None:
            text = _line_text(lines, f.line)
            if linter._suppressed(text, f.code):
                continue
        out.append(f)
    return out


# ---------------------------------------------------------------------------
# SLC001/SLC002: metric namespace emit sites vs the closed table
# ---------------------------------------------------------------------------


def audit_metric_sources(
    sources: dict[str, str], known: frozenset[str] | None = None
) -> list[Finding]:
    """Cross-check every statically-visible metric emit site against the
    closed namespace table.  `sources` maps repo-relative path -> source
    text.  SLC001: an emitter call (`counter_set` / `counter_add` /
    `gauge_set` / `histogram`) whose key has a static dotted prefix
    outside the table.  SLC002: a table namespace no scanned module
    shows evidence of emitting — evidence is an emitter-call prefix OR
    any string literal argument shaped `ns.rest` (helpers like
    `_sub_counter(reg, nic, "net.nic", ...)` pass the namespace through
    an argument, not the emitter call itself)."""
    if known is None:
        from shadow_tpu.obs.metrics import KNOWN_METRIC_NAMESPACES

        known = KNOWN_METRIC_NAMESPACES
    findings: list[Finding] = []
    evidence: set[str] = set()
    src_lines: dict[str, list[str]] = {}
    for relpath in sorted(sources):
        src = sources[relpath]
        src_lines[relpath] = src.splitlines()
        try:
            tree = ast.parse(src, filename=relpath)
        except SyntaxError:
            # the driver surfaces parse errors once (exit 2); skip here
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            # broad evidence: any literal/f-string argument `ns.rest`
            for arg in list(node.args) + [k.value for k in node.keywords]:
                prefix = rules_mod._literal_key_prefix(arg)
                if prefix:
                    m = _NS_RE.match(prefix)
                    if m:
                        evidence.add(m.group(1))
            # strict check: the emitter methods themselves
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_EMITTERS
                and node.args
            ):
                prefix = rules_mod._literal_key_prefix(node.args[0])
                if prefix is None:
                    continue
                m = _NS_RE.match(prefix)
                if m is None:
                    continue
                ns = m.group(1)
                if ns not in known:
                    findings.append(_finding(
                        relpath, node.lineno, node.col_offset, "SLC001",
                        f"metric emitter writes namespace `{ns}.*` which "
                        f"is not in KNOWN_METRIC_NAMESPACES "
                        f"(obs/metrics.py) — register it with a schema "
                        f"bump and a docs row",
                        _line_text(src_lines[relpath], node.lineno),
                    ))
    for ns in sorted(known - evidence):
        findings.append(_finding(
            "shadow_tpu/obs/metrics.py", 1, 0, "SLC002",
            f"metric namespace `{ns}.*` is registered in "
            f"KNOWN_METRIC_NAMESPACES but no scanned module emits it — "
            f"dead table row (drop it with a schema bump) or an emitter "
            f"the scan cannot see (add a literal-key emit site)",
            f"namespace:{ns}",
        ))
    return _suppress(findings, src_lines)


# ---------------------------------------------------------------------------
# SLC003: fault ops vs injector-handler arms
# ---------------------------------------------------------------------------


def handled_op_strings(src: str) -> set[str]:
    """String constants a consumer module compares/collects fault ops
    with: every `f.op == "kill_host"`-style arm, membership tuple, or
    set literal contributes its strings.  The engine's handler chains
    name every op explicitly (the final `else` raises on an unhandled
    op), so presence of the op string is the handler contract."""
    out: set[str] = set()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
    return out


def audit_fault_handlers(
    consumers: list[tuple[str, str, frozenset[str]]],
) -> list[Finding]:
    """`consumers` rows are (relpath, source, ops-this-plane-executes).
    Every op in the plane set must appear as a string constant in the
    consumer (the explicit handler arm / scheduling filter)."""
    findings: list[Finding] = []
    for relpath, src, ops in consumers:
        present = handled_op_strings(src)
        for op in sorted(ops - present):
            findings.append(_finding(
                relpath, 1, 0, "SLC003",
                f"fault op `{op}` has no handler arm in {relpath} — the "
                f"plan schema (faults/plan.py) registers it for this "
                f"plane but nothing executes it",
                f"op:{op}",
            ))
    return findings


# ---------------------------------------------------------------------------
# SLC004: fault-op docs table
# ---------------------------------------------------------------------------

_DOC_OP_ROW_RE = re.compile(r"^\|\s*`(\w+)`\s*\|")


def doc_op_table(md_text: str) -> set[str]:
    return {
        m.group(1)
        for line in md_text.splitlines()
        if (m := _DOC_OP_ROW_RE.match(line.strip())) is not None
    }


def audit_doc_op_table(
    md_text: str, relpath: str, all_ops: frozenset[str]
) -> list[Finding]:
    rows = doc_op_table(md_text)
    findings: list[Finding] = []
    for op in sorted(all_ops - rows):
        findings.append(_finding(
            relpath, 1, 0, "SLC004",
            f"fault op `{op}` has no row in the {relpath} op table — "
            f"every op in faults/plan.py needs a documented effect",
            f"op:{op}",
        ))
    for op in sorted(rows - all_ops):
        # rows for non-op keys (config tables share the cell style) are
        # only stale when they LOOK like ops: restrict to the op table
        # region by requiring the row to carry a plane column
        findings.append(_finding(
            relpath, 1, 0, "SLC004",
            f"docs table row `{op}` names an op faults/plan.py does not "
            f"register — stale row (the op was removed or renamed)",
            f"stale:{op}",
        ))
    return findings


def extract_op_table_region(md_text: str) -> str:
    """The §1 ops-by-plane table: rows between the `| op | plane |`
    header and the next blank-line/heading break."""
    lines = md_text.splitlines()
    out: list[str] = []
    in_table = False
    for line in lines:
        s = line.strip()
        if re.match(r"^\|\s*op\s*\|\s*plane\s*\|", s):
            in_table = True
            continue
        if in_table:
            if not s.startswith("|"):
                break
            out.append(line)
    return "\n".join(out)


# ---------------------------------------------------------------------------
# SLC009: journal record-type docs table
# ---------------------------------------------------------------------------


def extract_journal_table_region(md_text: str) -> str:
    """The docs/serving.md §2 record-type table: rows between the
    `| type | when |` header and the next non-table line."""
    lines = md_text.splitlines()
    out: list[str] = []
    in_table = False
    for line in lines:
        s = line.strip()
        if re.match(r"^\|\s*type\s*\|\s*when\s*\|", s):
            in_table = True
            continue
        if in_table:
            if not s.startswith("|"):
                break
            out.append(line)
    return "\n".join(out)


def audit_journal_record_table(
    md_text: str, relpath: str, record_types: tuple[str, ...] | frozenset
) -> list[Finding]:
    """Journal record-type drift: every type in serve/journal.py
    RECORD_TYPES needs a documented row (same cell style as the fault-op
    table, so `doc_op_table` reads it), and every row must name a
    registered type — a HANDOFF/REGISTER-class record that replay folds
    but operators can't look up is exactly the docs/journal drift SLC004
    catches for fault ops."""
    rows = doc_op_table(md_text)
    registered = set(record_types)
    findings: list[Finding] = []
    for rtype in sorted(registered - rows):
        findings.append(_finding(
            relpath, 1, 0, "SLC009",
            f"journal record type `{rtype}` has no row in the {relpath} "
            f"record table — every type in serve/journal.py RECORD_TYPES "
            f"needs a documented trigger and payload",
            f"record:{rtype}",
        ))
    for rtype in sorted(rows - registered):
        findings.append(_finding(
            relpath, 1, 0, "SLC009",
            f"record table row `{rtype}` names a type serve/journal.py "
            f"does not register — stale row (the record was removed or "
            f"renamed)",
            f"stale:{rtype}",
        ))
    return findings


# ---------------------------------------------------------------------------
# SLC005: schema-version literals in docs and tests
# ---------------------------------------------------------------------------

_DOC_KIND_RE = re.compile(r'"kind":\s*"(shadow_tpu\.\w+)"')
_DOC_VER_RE = re.compile(r'"schema_version":\s*(\d+)')
_DOC_INLINE_VER_RE = re.compile(r"`schema_version`\s+(\d+)")


def audit_doc_schema_versions(
    md_text: str, relpath: str, versions: dict[str, int],
    inline_kind: str | None = None,
) -> list[Finding]:
    """Fenced samples: a `"kind": "shadow_tpu.X"` line binds the nearest
    `"schema_version": N` (within 8 lines either side) to X's source
    constant.  `inline_kind` additionally checks bare
    `` `schema_version` N `` mentions (observability.md's headings)
    against that kind's constant."""
    lines = md_text.splitlines()
    findings: list[Finding] = []
    kind_at = [
        (i, m.group(1))
        for i, ln in enumerate(lines)
        if (m := _DOC_KIND_RE.search(ln)) is not None
    ]
    for i, kind in kind_at:
        if kind not in versions:
            continue
        want = versions[kind]
        window = sorted(
            range(max(0, i - 8), min(len(lines), i + 9)),
            key=lambda j: (abs(j - i), j),
        )
        for j in window:
            m = _DOC_VER_RE.search(lines[j])
            if m is None:
                continue
            got = int(m.group(1))
            if got != want:
                findings.append(_finding(
                    relpath, j + 1, 0, "SLC005",
                    f"sample document quotes {kind} schema_version "
                    f"{got}, but the source constant is {want} — stale "
                    f"docs literal",
                    lines[j].strip(),
                ))
            break  # nearest version line only
    if inline_kind is not None and inline_kind in versions:
        want = versions[inline_kind]
        for i, ln in enumerate(lines):
            for m in _DOC_INLINE_VER_RE.finditer(ln):
                got = int(m.group(1))
                if got != want:
                    findings.append(_finding(
                        relpath, i + 1, 0, "SLC005",
                        f"doc text quotes `schema_version` {got}, but "
                        f"the {inline_kind} source constant is {want}",
                        ln.strip(),
                    ))
    return findings


def audit_test_version_literals(src: str, relpath: str) -> list[Finding]:
    """A test that asserts `doc["schema_version"] == <int literal>` has
    to be hand-edited on every schema bump — six files' worth per bump
    before this pass existed.  The shared helper
    (tests/_contracts.assert_current_metrics_schema) imports the source
    constant instead; any remaining literal comparison is drift bait."""
    findings: list[Finding] = []
    try:
        tree = ast.parse(src, filename=relpath)
    except SyntaxError:
        return findings
    lines = src.splitlines()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left] + list(node.comparators)
        has_key = any(
            isinstance(s, ast.Subscript)
            and isinstance(s.slice, ast.Constant)
            and s.slice.value == "schema_version"
            for s in sides
        )
        has_literal = any(
            isinstance(s, ast.Constant) and isinstance(s.value, int)
            and not isinstance(s.value, bool)
            for s in sides
        )
        if has_key and has_literal:
            findings.append(_finding(
                relpath, node.lineno, node.col_offset, "SLC005",
                "hard-coded schema-version literal in a test — import "
                "the source constant (or use "
                "tests/_contracts.assert_current_metrics_schema) so a "
                "schema bump cannot strand it",
                _line_text(lines, node.lineno),
            ))
    return _suppress(findings, {relpath: lines})


# ---------------------------------------------------------------------------
# SLC006: config_spec.md tables vs the loader's dataclass fields
# ---------------------------------------------------------------------------

_SPEC_SECTION_RE = re.compile(r"^###\s+`(\w+)`")
_SPEC_ROW_RE = re.compile(r"^\|\s*`(\w+)`\s*\|")


def config_spec_rows(md_text: str) -> dict[str, set[str]]:
    """Per-section documented field rows of docs/config_spec.md."""
    out: dict[str, set[str]] = {}
    section = None
    for line in md_text.splitlines():
        s = line.strip()
        m = _SPEC_SECTION_RE.match(s)
        if m:
            section = m.group(1)
            continue
        if s.startswith("#"):
            section = None
            continue
        if section is not None:
            m = _SPEC_ROW_RE.match(s)
            if m and m.group(1) not in ("field",):
                out.setdefault(section, set()).add(m.group(1))
    return out


def audit_config_spec(
    md_text: str, relpath: str,
    fields_by_section: dict[str, set[str]] | None = None,
    prose_documented: dict[str, frozenset[str]] | None = None,
) -> list[Finding]:
    if fields_by_section is None:
        import dataclasses

        from shadow_tpu.core import config as config_mod

        fields_by_section = {
            "general": {
                f.name for f in dataclasses.fields(config_mod.GeneralOptions)
            },
            "experimental": {
                f.name
                for f in dataclasses.fields(config_mod.ExperimentalOptions)
            },
            "fleet": {
                f.name for f in dataclasses.fields(config_mod.FleetOptions)
            },
        }
    if prose_documented is None:
        prose_documented = CONFIG_PROSE_DOCUMENTED
    rows = config_spec_rows(md_text)
    findings: list[Finding] = []
    for section, fields in sorted(fields_by_section.items()):
        documented = rows.get(section, set())
        prose = prose_documented.get(section, frozenset())
        for key in sorted(documented - fields):
            findings.append(_finding(
                relpath, 1, 0, "SLC006",
                f"{relpath} documents `{section}.{key}` but the config "
                f"loader (core/config.py) parses no such field — stale "
                f"row",
                f"stale:{section}.{key}",
            ))
        for key in sorted(fields - documented - prose):
            findings.append(_finding(
                relpath, 1, 0, "SLC006",
                f"config loader parses `{section}.{key}` but {relpath} "
                f"has no row for it — undocumented knob",
                f"missing:{section}.{key}",
            ))
    return findings


# ---------------------------------------------------------------------------
# SLC007: supervisor policy set vs config validator and docs
# ---------------------------------------------------------------------------


def config_policy_literals(config_src: str) -> set[str] | None:
    """The on_backend_loss validation tuple in core/config.py: the
    string-tuple comparator that contains "wait" (the policy set's
    signature member).  None when no such tuple is found."""
    try:
        tree = ast.parse(config_src)
    except SyntaxError:
        return None
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        for comp in node.comparators:
            if isinstance(comp, (ast.Tuple, ast.List, ast.Set)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in comp.elts
            ):
                vals = {e.value for e in comp.elts}
                if "wait" in vals:
                    return vals
    return None


def audit_policy_sets(
    config_src: str, config_relpath: str, policies: tuple[str, ...],
    docs_text: str = "", docs_relpath: str = "",
) -> list[Finding]:
    findings: list[Finding] = []
    lits = config_policy_literals(config_src)
    if lits is None:
        findings.append(_finding(
            config_relpath, 1, 0, "SLC007",
            "could not locate the on_backend_loss policy validation "
            "tuple in the config loader — the policy contract check "
            "needs its literal set",
            "policies:unlocatable",
        ))
    elif lits != set(policies):
        findings.append(_finding(
            config_relpath, 1, 0, "SLC007",
            f"config loader validates on_backend_loss against "
            f"{sorted(lits)} but supervisor.POLICIES is "
            f"{sorted(policies)} — the sets drifted",
            f"policies:{','.join(sorted(lits ^ set(policies)))}",
        ))
    if docs_text:
        for pol in sorted(set(policies)):
            if pol not in docs_text:
                findings.append(_finding(
                    docs_relpath or config_relpath, 1, 0, "SLC007",
                    f"supervisor policy `{pol}` is never mentioned in "
                    f"{docs_relpath} — undocumented --on-backend-loss "
                    f"arm",
                    f"policy:{pol}",
                ))
    return findings


# ---------------------------------------------------------------------------
# SLC008: the fault-plan registry's own consistency
# ---------------------------------------------------------------------------


def audit_plan_registry(
    all_ops: frozenset[str], field_table_ops: set[str]
) -> list[Finding]:
    findings: list[Finding] = []
    path = "shadow_tpu/faults/plan.py"
    for op in sorted(all_ops - field_table_ops):
        findings.append(_finding(
            path, 1, 0, "SLC008",
            f"fault op `{op}` is registered in ALL_OPS but has no "
            f"_FIELDS validation row — parse would KeyError on first "
            f"use",
            f"op:{op}",
        ))
    for op in sorted(field_table_ops - all_ops):
        findings.append(_finding(
            path, 1, 0, "SLC008",
            f"_FIELDS validates op `{op}` that no plane set registers "
            f"— dead validation row",
            f"stale:{op}",
        ))
    return findings


# ---------------------------------------------------------------------------
# the whole-tree audit
# ---------------------------------------------------------------------------


def _read(root: str, relpath: str) -> str:
    with open(os.path.join(root, relpath), encoding="utf-8") as f:
        return f.read()


def audit_tree(root: str) -> list[Finding]:
    """Run every contract check over the real tree.  Raises SyntaxError
    (for the CLI's exit-2 path) only from the linter's own file walk;
    unparseable files inside a sub-check are skipped there because the
    STL pass already surfaces them."""
    from shadow_tpu.faults import plan as plan_mod

    findings: list[Finding] = []

    # SLC001/SLC002 over the metric-emitting scope
    py_sources: dict[str, str] = {}
    for path in linter.iter_python_files(
        [os.path.join(root, p) for p in ("shadow_tpu", "tools", "bench.py")]
    ):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            py_sources[rel] = f.read()
    findings += audit_metric_sources(py_sources)

    # SLC003: handler arms per executing plane
    engine_rel = "shadow_tpu/core/engine.py"
    driver_rel = "shadow_tpu/procs/driver.py"
    findings += audit_fault_handlers([
        (engine_rel, py_sources.get(engine_rel, ""),
         plan_mod.DEVICE_OPS | plan_mod.BACKEND_OPS | plan_mod.FILE_OPS),
        (driver_rel, py_sources.get(driver_rel, ""),
         plan_mod.PROC_OPS | plan_mod.FILE_OPS | frozenset({"kill_host"})),
    ])

    # SLC004: the fault-op docs table
    ft_md = _read(root, "docs/fault_tolerance.md")
    findings += audit_doc_op_table(
        extract_op_table_region(ft_md), "docs/fault_tolerance.md",
        plan_mod.ALL_OPS,
    )

    # SLC005: docs samples + headings, then test literals
    versions = doc_schema_versions()
    findings += audit_doc_schema_versions(
        _read(root, "docs/observability.md"), "docs/observability.md",
        versions, inline_kind="shadow_tpu.metrics",
    )
    findings += audit_doc_schema_versions(
        ft_md, "docs/fault_tolerance.md", versions,
    )
    tests_dir = os.path.join(root, "tests")
    if os.path.isdir(tests_dir):
        for path in linter.iter_python_files([tests_dir]):
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                findings += audit_test_version_literals(f.read(), rel)

    # SLC006: config_spec tables vs loader fields
    findings += audit_config_spec(
        _read(root, "docs/config_spec.md"), "docs/config_spec.md",
    )

    # SLC007: policy sets
    from shadow_tpu.core.supervisor import BackendSupervisor

    findings += audit_policy_sets(
        py_sources.get("shadow_tpu/core/config.py", ""),
        "shadow_tpu/core/config.py", BackendSupervisor.POLICIES,
        docs_text=ft_md, docs_relpath="docs/fault_tolerance.md",
    )

    # SLC008: the plan registry itself
    findings += audit_plan_registry(
        plan_mod.ALL_OPS, set(plan_mod._FIELDS),
    )

    # SLC009: the serve journal record-type table
    from shadow_tpu.serve import journal as journal_mod

    findings += audit_journal_record_table(
        extract_journal_table_region(_read(root, "docs/serving.md")),
        "docs/serving.md", journal_mod.RECORD_TYPES,
    )

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


CONTRACT_RULES = {
    "SLC001": "metric emitter outside the namespace table",
    "SLC002": "registered metric namespace with no emitter",
    "SLC003": "fault op with no injector-handler arm",
    "SLC004": "fault-op docs table drift",
    "SLC005": "stale schema-version literal",
    "SLC006": "config_spec table drift",
    "SLC007": "supervisor policy set drift",
    "SLC008": "fault-op registry drift",
    "SLC009": "journal record-type docs table drift",
}
