"""Pass 4 of shadowlint: the host-thread race lint (codes STH0xx).

The serve daemon made the host side a real multi-threaded program: an
HTTP handler pool, a worker loop, and POSIX signal handlers all touch
the same scheduler state, with mutual exclusion maintained by hand.  The
device plane's determinism story ends at the handoff boundary — a torn
queue or a lost journal record on the host corrupts a run just as surely
as a kernel race would.

This pass applies Eraser-style *declared-guard* discipline statically
(Savage et al.'s lockset idea, restricted to what an AST can see) over
the declared thread-bearing host modules:

  STH001  write to a lock-guarded attribute outside the lock
  STH002  condition wait/notify without holding the condition's lock
  STH003  signal-handler method touches non-Event shared state
  STH004  `lock.acquire(blocking=False)` — silently skips mutual
          exclusion when contended (the drain-path smell class)

Model, per class in a scanned module:

* **Locks** are attributes assigned ``threading.Lock()`` / ``RLock()``
  in ``__init__``; **conditions** are ``threading.Condition(...)``
  (holding a condition counts as holding its lock); **events** are
  ``threading.Event()`` (atomic, safe anywhere — the one thing a signal
  handler may touch).
* A class participates when it spawns a thread (``threading.Thread``),
  installs a signal handler, or declares a lock.
* The **guarded set** is inferred from the class's own discipline: any
  attribute accessed at least once under a ``with <lock>:`` block is
  declared guarded; writes to it anywhere else must hold the lock too.
  (Reads outside the lock are out of scope — too many benign
  racy-read-then-lock-and-check idioms; the write side is where state
  tears.)
* A method whose every intra-class call site sits inside a locked
  region is a **locked-context** method (``retry_after_s`` called only
  from ``with self._lock`` bodies); its accesses count as held.
  ``__init__`` is construction-time single-threaded and exempt.
* Locked regions: ``with self._lock`` / ``with self._wake`` bodies, the
  body of ``if self._lock.acquire(timeout=...):``, and statements
  between a blocking ``.acquire()`` call and the matching
  ``.release()`` in the same block.

Suppression: ``# noqa: STH0xx`` on the flagged line, or a
``DECLARED_SAFE`` entry naming (module, class) -> attributes that are
intentionally lock-free (reviewed owner-thread-only state).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from shadow_tpu.analysis import linter
from shadow_tpu.analysis.linter import Finding
from shadow_tpu.analysis.rules import build_imports, resolve_name

# The thread-bearing host modules (repo-relative).  Modules without a
# lock-declaring class scan clean by construction — they stay listed so
# the day one of them grows a thread, the discipline applies.
THREAD_MODULES = (
    "shadow_tpu/serve/daemon.py",
    "shadow_tpu/serve/journal.py",
    "shadow_tpu/serve/federation.py",
    "shadow_tpu/serve/router.py",
    "shadow_tpu/fleet/scheduler.py",
    "shadow_tpu/core/supervisor.py",
    "shadow_tpu/parallel/elastic.py",
    "shadow_tpu/core/hostplane.py",
)

# (relpath, classname) -> attrs intentionally shared without the lock.
# Empty on purpose: additions must name the exact site so review sees
# them (the CALLBACK_ALLOWLIST posture).
DECLARED_SAFE: dict[tuple[str, str], frozenset[str]] = {}

_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popleft", "clear",
    "update", "setdefault", "add", "discard", "sort", "reverse",
}
_COND_OPS = {"wait", "wait_for", "notify", "notify_all"}

_LOCK_CTORS = {"threading.Lock", "threading.RLock"}
_COND_CTORS = {"threading.Condition"}
_EVENT_CTORS = {"threading.Event"}
_THREAD_CTORS = {"threading.Thread"}


def _self_attr(node: ast.AST) -> str | None:
    """`self.<attr>` -> attr name (else None)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


@dataclass
class ClassModel:
    name: str
    node: ast.ClassDef
    locks: set[str] = field(default_factory=set)
    conds: set[str] = field(default_factory=set)
    events: set[str] = field(default_factory=set)
    spawns_threads: bool = False
    handler_methods: set[str] = field(default_factory=set)
    methods: dict[str, ast.AST] = field(default_factory=dict)

    def lock_like(self) -> set[str]:
        return self.locks | self.conds


@dataclass
class _Access:
    node: ast.AST
    attr: str
    kind: str  # "write" | "mutate" | "read" | "cond" | "acquire_nb"
    held: bool
    method: str


def _is_lock_expr(model: ClassModel, node: ast.AST) -> bool:
    a = _self_attr(node)
    return a is not None and a in model.lock_like()


def _acquire_is_blocking(call: ast.Call) -> bool:
    """False only for `.acquire(blocking=False)` / `.acquire(False)`."""
    for kw in call.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    if call.args and isinstance(call.args[0], ast.Constant):
        return bool(call.args[0].value)
    return True


def _collect_model(tree: ast.AST, imports: dict[str, str]) -> list[ClassModel]:
    models = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        model = ClassModel(name=node.name, node=node)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                model.methods[item.name] = item
        init = model.methods.get("__init__")
        if init is not None:
            for n in ast.walk(init):
                if not (isinstance(n, ast.Assign) and isinstance(
                        n.value, ast.Call)):
                    continue
                ctor = resolve_name(n.value.func, imports)
                for t in n.targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if ctor in _LOCK_CTORS:
                        model.locks.add(attr)
                    elif ctor in _COND_CTORS:
                        model.conds.add(attr)
                    elif ctor in _EVENT_CTORS:
                        model.events.add(attr)
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                name = resolve_name(n.func, imports)
                if name in _THREAD_CTORS:
                    model.spawns_threads = True
                elif name == "signal.signal" and len(n.args) >= 2:
                    h = n.args[1]
                    if isinstance(h, ast.Lambda) and isinstance(
                            h.body, ast.Call):
                        attr = _self_attr(h.body.func)
                        if attr:
                            model.handler_methods.add(attr)
                    else:
                        attr = _self_attr(h)
                        if attr:
                            model.handler_methods.add(attr)
        models.append(model)
    return models


def _walk_method(model: ClassModel, mname: str, fn: ast.AST,
                 out: list[_Access]) -> None:
    """Record attribute accesses with lock-held status.  Linear walk of
    each statement list tracking manual acquire()/release() pairs; with-
    blocks and `if lock.acquire(...):` bodies set held for their suite."""

    def expr_accesses(node: ast.AST, held: bool) -> None:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                attr = _self_attr(n.func.value) if isinstance(
                    n.func, ast.Attribute) else None
                if attr is not None:
                    meth = n.func.attr
                    if attr in model.lock_like() and meth == "acquire" \
                            and not _acquire_is_blocking(n):
                        out.append(_Access(n, attr, "acquire_nb", held,
                                           mname))
                    elif attr in model.conds and meth in _COND_OPS:
                        out.append(_Access(n, attr, "cond", held, mname))
                    elif meth in _MUTATORS and attr not in model.lock_like():
                        out.append(_Access(n, attr, "mutate", held, mname))
            elif isinstance(n, ast.Attribute) and isinstance(
                    n.ctx, ast.Load):
                attr = _self_attr(n)
                if attr is not None:
                    out.append(_Access(n, attr, "read", held, mname))

    def target_accesses(t: ast.AST, node: ast.AST, held: bool) -> None:
        attr = _self_attr(t)
        if attr is not None:
            out.append(_Access(node, attr, "write", held, mname))
            return
        if isinstance(t, ast.Subscript):
            # self.d[k] = v / self.d[k] += 1: a mutation of self.d
            attr = _self_attr(t.value)
            if attr is not None:
                out.append(_Access(node, attr, "mutate", held, mname))
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                target_accesses(e, node, held)

    def walk_body(body: list[ast.stmt], held: bool) -> None:
        held_here = held
        for stmt in body:
            walk_stmt(stmt, held_here)
            # manual acquire/release tracking within this suite
            if isinstance(stmt, ast.Expr) and isinstance(
                    stmt.value, ast.Call):
                call = stmt.value
                if isinstance(call.func, ast.Attribute) and _is_lock_expr(
                        model, call.func.value):
                    if call.func.attr == "acquire" and \
                            _acquire_is_blocking(call):
                        held_here = True
                    elif call.func.attr == "release":
                        held_here = held

    def walk_stmt(stmt: ast.stmt, held: bool) -> None:
        if isinstance(stmt, ast.With):
            locked = held or any(
                _is_lock_expr(model, item.context_expr)
                for item in stmt.items
            )
            for item in stmt.items:
                expr_accesses(item.context_expr, held)
            walk_body(stmt.body, locked)
        elif isinstance(stmt, ast.If):
            test_locks = False
            if isinstance(stmt.test, ast.Call) and isinstance(
                    stmt.test.func, ast.Attribute):
                if (_is_lock_expr(model, stmt.test.func.value)
                        and stmt.test.func.attr == "acquire"
                        and _acquire_is_blocking(stmt.test)):
                    test_locks = True
            expr_accesses(stmt.test, held)
            walk_body(stmt.body, held or test_locks)
            walk_body(stmt.orelse, held)
        elif isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            for t in targets:
                target_accesses(t, stmt, held)
            if getattr(stmt, "value", None) is not None:
                expr_accesses(stmt.value, held)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            expr_accesses(stmt.iter, held)
            walk_body(stmt.body, held)
            walk_body(stmt.orelse, held)
        elif isinstance(stmt, ast.While):
            expr_accesses(stmt.test, held)
            walk_body(stmt.body, held)
            walk_body(stmt.orelse, held)
        elif isinstance(stmt, ast.Try):
            walk_body(stmt.body, held)
            for h in stmt.handlers:
                walk_body(h.body, held)
            walk_body(stmt.orelse, held)
            walk_body(stmt.finalbody, held)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            pass  # nested defs analyzed only via their own call sites
        else:
            expr_accesses(stmt, held)

    walk_body(fn.body, False)


def _analyze_class(model: ClassModel, relpath: str,
                   declared_safe: frozenset[str]) -> list[Finding]:
    accesses: list[_Access] = []
    for mname, fn in model.methods.items():
        if mname == "__init__":
            continue
        _walk_method(model, mname, fn, accesses)

    # locked-context methods: every intra-class call site (a
    # `self.<method>` load) sits inside a locked region, directly or via
    # a caller that is itself locked-context — fixpoint over the class
    method_sites: dict[str, list[_Access]] = {}
    for a in accesses:
        if a.kind == "read" and a.attr in model.methods:
            method_sites.setdefault(a.attr, []).append(a)
    locked_ctx: set[str] = set()
    changed = True
    while changed:
        changed = False
        for m, sites in method_sites.items():
            if m in locked_ctx:
                continue
            if sites and all(
                a.held or a.method in locked_ctx for a in sites
            ):
                locked_ctx.add(m)
                changed = True

    def effective_held(a: _Access) -> bool:
        return a.held or a.method in locked_ctx

    special = model.lock_like() | model.events
    guarded = {
        a.attr for a in accesses
        if effective_held(a) and a.attr not in special
        and a.attr not in model.methods
    } - declared_safe

    findings: list[Finding] = []
    for a in accesses:
        if a.kind == "acquire_nb":
            findings.append(Finding(
                path=relpath, line=a.node.lineno, col=a.node.col_offset,
                code="STH004",
                message=(
                    f"`{model.name}.{a.attr}.acquire(blocking=False)` "
                    f"silently skips mutual exclusion when contended — "
                    f"use `with {a.attr}` or a bounded "
                    f"`acquire(timeout=...)`"
                ),
                text="",
            ))
        elif a.kind == "cond" and not effective_held(a):
            findings.append(Finding(
                path=relpath, line=a.node.lineno, col=a.node.col_offset,
                code="STH002",
                message=(
                    f"condition wait/notify on `{a.attr}` outside its "
                    f"lock in {model.name}.{a.method} — both require "
                    f"the condition's lock held"
                ),
                text="",
            ))
        elif a.kind in ("write", "mutate") and a.attr in guarded \
                and not effective_held(a):
            findings.append(Finding(
                path=relpath, line=a.node.lineno, col=a.node.col_offset,
                code="STH001",
                message=(
                    f"write to `{model.name}.{a.attr}` outside the "
                    f"declared lock in {a.method}() — the attribute is "
                    f"lock-guarded elsewhere in the class"
                ),
                text="",
            ))

    # STH003: handler methods may only touch Events / declared-safe state
    for h in sorted(model.handler_methods):
        fn = model.methods.get(h)
        if fn is None:
            continue
        for a in accesses:
            if a.method != h or a.kind not in ("write", "mutate"):
                continue
            if a.attr in model.events or a.attr in declared_safe:
                continue
            if effective_held(a):
                continue  # lock held: the handler did it properly
            findings.append(Finding(
                path=relpath, line=a.node.lineno, col=a.node.col_offset,
                code="STH003",
                message=(
                    f"signal handler `{model.name}.{h}` writes "
                    f"`self.{a.attr}` — handlers may only touch Events "
                    f"and declared-safe state (they interrupt the worker "
                    f"mid-critical-section)"
                ),
                text="",
            ))
    return findings


def lint_threads_source(src: str, relpath: str) -> list[Finding]:
    """Race-lint one module's source (fixture entry point)."""
    relpath = relpath.replace(os.sep, "/")
    tree = ast.parse(src, filename=relpath)
    imports = build_imports(tree)
    lines = src.splitlines()
    findings: list[Finding] = []
    for model in _collect_model(tree, imports):
        if not (model.locks or model.conds or model.spawns_threads
                or model.handler_methods):
            continue
        if not model.lock_like():
            continue  # no declared guard to check against
        safe = DECLARED_SAFE.get((relpath, model.name), frozenset())
        findings.extend(_analyze_class(model, relpath, safe))
    out = []
    for f in findings:
        text = (
            lines[f.line - 1].strip() if 0 < f.line <= len(lines) else ""
        )
        if linter._suppressed(text, f.code):
            continue
        out.append(Finding(path=f.path, line=f.line, col=f.col,
                           code=f.code, message=f.message, text=text))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return out


def lint_threads_paths(root: str, modules=THREAD_MODULES) -> list[Finding]:
    findings: list[Finding] = []
    for rel in modules:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            continue
        with open(path, encoding="utf-8") as f:
            findings.extend(lint_threads_source(f.read(), rel))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


THREAD_RULES = {
    "STH001": "guarded-attribute write outside the lock",
    "STH002": "condition wait/notify without its lock",
    "STH003": "signal handler touches non-Event state",
    "STH004": "non-blocking lock acquire skips exclusion",
}
