"""Layer 2 of shadowlint: the compiled-kernel auditor.

The AST rules (rules.py) gate what the *source* says; this module gates
what XLA actually *compiles*.  It generalizes the gearbox HLO guard that
used to live in tests/test_gearbox.py to every registered window-kernel
variant — {conservative, optimistic} × {global, islands, fleet} × gear
tiers — lowering each to OPTIMIZED HLO (raw StableHLO still carries
jax's constant-column ``.at[].set`` scatters, which XLA canonicalizes to
dynamic-update-slices; only what survives optimization can serialize)
and asserting the engine's op contract:

  * **no scatter** — engine.py's stated ban (a scatter serializes on
    TPU and breaks the all-SoA update discipline);
  * **no serializing gather** — take_along_axis-shaped per-element
    fetches out of >=2-D operands; whole-row gathers and 1-D host-table
    lookups stay vectorized and are allowed;
  * **bounded sort rows** — every sort's row count stays within the
    structural bound of the variant's gear (pool capacity + the dense
    window/outbox blocks); a sort beyond it means a shape regression
    re-grew the very volume the gearbox exists to shrink.

Plus the **retrace detector**: after a driver run, every bound jitted
kernel must have been lowered at most once (per gear) — the fleet's
"one sweep = one compile" invariant (PR 4's ``kernel_traces``), now a
statically gated property for ALL engines.  A second trace of the same
kernel means dtype/weak-type/shape drift in driver arguments — the
silent compile-cache-miss perf-bug class from the r03–r05 bench rounds.

Used by tests/test_analysis.py (tier-1 + slow matrix cells) and
re-exported to tests/test_gearbox.py so there is exactly one copy of
the HLO-parsing logic.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable

# A window end comfortably past the first windows of any tiny model; the
# value only shapes traced scalars, never the compiled program.
DEFAULT_WIN_END = 50_000_000

SYNC_MODES = ("conservative", "optimistic")
LAYOUTS = ("global", "islands", "fleet")


class HloAuditError(AssertionError):
    """A compiled window kernel violates the op contract."""


class RetraceError(AssertionError):
    """A bound kernel was lowered more than once during a driver run."""


# ---------------------------------------------------------------------------
# HLO text checks (migrated from tests/test_gearbox.py — single copy)
# ---------------------------------------------------------------------------


def kernel_hlo(sim, win_end: int = DEFAULT_WIN_END) -> str:
    """The OPTIMIZED HLO of the bound jitted window step: what actually
    runs for this sim's active gear (global and islands layouts — the
    bound ``_step`` has the same (state, params, ws, we) signature in
    both)."""
    return (
        sim._step.lower(sim.state, sim.params, 0, win_end)
        .compile()
        .as_text()
    )


def gather_is_serializing(line: str) -> bool:
    """take_along_axis-shaped gather: every slice is a single element out
    of a >=2-D operand — a per-element fetch that serializes on TPU
    (engine.py's stated ban).  Whole-row gathers and 1-D host-table
    lookups stay vectorized and are the module's bread and butter."""
    ss = re.search(r"slice_sizes=\{([0-9,]*)\}", line)
    if ss is None or not ss.group(1):
        return False
    sizes = [int(x) for x in ss.group(1).split(",")]
    operand = re.search(r"gather\(\s*\w+\[([0-9,]*)\]", line)
    if operand is None:
        return False
    rank = len([d for d in operand.group(1).split(",") if d])
    return all(s == 1 for s in sizes) and rank >= 2


def scatter_lines(hlo: str) -> list[str]:
    # (?<!-): a `reduce-scatter` collective is not the banned op
    return [
        ln.strip()[:120]
        for ln in hlo.splitlines()
        if re.search(r"= .*(?<!-)\bscatter\(", ln)
    ]


def serializing_gather_lines(hlo: str) -> list[str]:
    # (?<!-): an `all-gather` collective is not a fetch gather
    return [
        ln.strip()[:120]
        for ln in hlo.splitlines()
        if re.search(r"= .*(?<!-)\bgather\(", ln)
        and gather_is_serializing(ln)
    ]


def all_gather_lines(hlo: str) -> list[str]:
    """Every all-gather in the program (incl. async -start forms): the
    mesh async kernel's frontier exchange must compile to neighbor-only
    collective-permutes, so its optimized HLO carries ZERO of these —
    the gated property that makes cross-chip collective volume scale
    with topology degree instead of mesh size. (The gather arm of the
    bench comparison, and any GSPMD resharding regression, shows up
    here.)"""
    return [
        ln.strip()[:120]
        for ln in hlo.splitlines()
        if re.search(r"= .*\ball-gather(-start)?\(", ln)
    ]


def sort_rows(hlo: str) -> list[int]:
    """Row count (last dim) of every sort in the program."""
    rows = []
    for line in hlo.splitlines():
        if re.search(r"\bsort\(", line) and "= " in line:
            m = re.search(r"\[([0-9,]+)\]", line)
            if m:
                rows.append(int(m.group(1).split(",")[-1]))
    return rows


def audit_hlo(
    hlo: str,
    max_sort_rows: int | None = None,
    max_serializing_gathers: int = 0,
    max_all_gathers: int | None = None,
) -> list[str]:
    """The op-contract violations in one optimized-HLO program (empty
    list = clean).

    `max_serializing_gathers` is the variant's documented allowance for
    the ONE unavoidable by-dst lookup (engine.py: the speculation-
    violation check reads `done_t[dst]`).  Solo-global kernels read a
    1-D host table — invisible to the rank>=2 heuristic — but the lane/
    shard vmap of the fleet and islands layouts batches the same lookup
    into a rank>=2 gather.  The allowance pins the count, so any NEW
    per-element fetch still fails the audit.

    `max_all_gathers` (None = unchecked) pins the all-gather count: 0
    for the mesh async kernel whose frontier exchange is neighbor-only
    ppermute (parallel/islands.make_shard_run_to_async shifts arm)."""
    violations: list[str] = []
    for ln in scatter_lines(hlo):
        violations.append(f"scatter survived to the compiled kernel: {ln}")
    sg = serializing_gather_lines(hlo)
    if len(sg) > max_serializing_gathers:
        for ln in sg:
            violations.append(
                f"serializing gather ({len(sg)} found, "
                f"{max_serializing_gathers} allowed): {ln}"
            )
    if max_all_gathers is not None:
        ag = all_gather_lines(hlo)
        if len(ag) > max_all_gathers:
            for ln in ag:
                violations.append(
                    f"all-gather ({len(ag)} found, {max_all_gathers} "
                    f"allowed — the mesh frontier exchange must ride "
                    f"neighbor-only ppermute): {ln}"
                )
    if max_sort_rows is not None:
        for rows in sort_rows(hlo):
            if rows > max_sort_rows:
                violations.append(
                    f"sort of {rows} rows exceeds the structural bound "
                    f"{max_sort_rows} (shape regression re-grew the sort "
                    f"volume)"
                )
    return violations


# ---------------------------------------------------------------------------
# the variant matrix
# ---------------------------------------------------------------------------


@dataclass
class KernelVariant:
    """One cell of the window-kernel matrix: a zero-argument `lower`
    thunk producing the cell's optimized HLO, plus the structural sort
    bound for its gear."""

    sync: str  # conservative | optimistic
    layout: str  # global | islands | fleet
    gear: int
    label: str
    max_sort_rows: int
    # allowance for the documented by-dst done_t lookups (audit_hlo)
    max_serializing_gathers: int
    lower: Callable[[], str] = field(repr=False)
    # all-gather pin (audit_hlo): 0 for the mesh/ppermute async kernel,
    # None = unchecked (vmap lowers collectives to reshapes anyway)
    max_all_gathers: int | None = None

    def hlo(self) -> str:
        return self.lower()

    def audit(self) -> list[str]:
        return [
            f"{self.label}: {v}"
            for v in audit_hlo(
                self.hlo(),
                max_sort_rows=self.max_sort_rows,
                max_serializing_gathers=self.max_serializing_gathers,
                max_all_gathers=self.max_all_gathers,
            )
        ]


def _sort_bound(spec, num_hosts: int, outbox: int) -> int:
    """Structural row bound for a gear's sorts.  The largest sort any
    window pipeline runs is the merge: pool leftovers (<= capacity) plus
    the emission block (<= H × max(K, O) rows per emission record, a
    handful of records).  4× slack keeps the bound far from incidental
    padding while still catching quadratic/regression blowups."""
    return 4 * (spec.capacity + num_hosts * max(spec.K, outbox, 1))


def _gear_levels(ladder, gears) -> list[int]:
    if gears is None:
        return [s.level for s in ladder]
    return [s.level for s in ladder if s.level in set(gears)]


def _bind_gear(sim, level: int) -> None:
    if sim._gear != level:
        sim._shift_gear(level)


def variants_for_sim(sim, layout: str, *, sync_modes=SYNC_MODES,
                     gears=None, win_end: int = DEFAULT_WIN_END
                     ) -> list[KernelVariant]:
    """Matrix cells for a built Simulation / IslandSimulation: the bound
    window-step kernel (conservative) and the optimistic attempt /
    sub-step kernel, per requested gear tier."""
    out: list[KernelVariant] = []
    for level in _gear_levels(sim._gear_ladder, gears):
        spec = sim._gear_ladder[level]
        bound = _sort_bound(spec, sim.num_hosts, sim.O)
        for sync in sync_modes:
            def lower(sim=sim, level=level, sync=sync):
                _bind_gear(sim, level)
                if sync == "conservative":
                    fn = sim._gear_fns[level]["step"]
                else:
                    fn = sim._gear_fns[level]["attempt"]
                    if fn is None:  # islands compile the sub-step lazily
                        sim._ensure_optimistic()
                        fn = sim._gear_fns[level]["attempt"]
                return (
                    fn.lower(sim.state, sim.params, 0, win_end)
                    .compile()
                    .as_text()
                )

            # islands-optimistic carries the two shard-batched done_t
            # lookups (emission check + assemble's arrival check); the
            # solo-global ones are 1-D and invisible to the heuristic
            allow = 2 if (layout == "islands" and sync == "optimistic") else 0
            out.append(KernelVariant(
                sync=sync, layout=layout, gear=level,
                label=f"{layout}/{sync}/gear{level}",
                max_sort_rows=bound, max_serializing_gathers=allow,
                lower=lower,
            ))
        # async conservative loop (parallel/islands.make_shard_run_to_async):
        # the fused per-shard-frontier kernel an async islands build
        # actually dispatches — the frontier all_gather and horizon math
        # must not smuggle in a scatter/serializing gather, and the loop
        # body's sorts are the same step sorts (same structural bound)
        if "conservative" in sync_modes and getattr(sim, "_async", False):
            def lower_async(sim=sim, level=level):
                _bind_gear(sim, level)
                fn = sim._gear_fns[level]["run_to_async"]
                return (
                    fn.lower(
                        sim.state, sim.params, sim._async_runahead,
                        sim._async_look_in, sim._async_spread,
                        win_end, 8,
                    )
                    .compile()
                    .as_text()
                )

            # ppermute exchange: the compiled frontier exchange must
            # carry ZERO all-gathers — the mesh gate (meaningful under
            # shard_map lowering, where collectives survive to HLO;
            # trivially clean under vmap, where they lower to reshapes)
            allow_ag = (
                0 if getattr(sim, "_exchange", None) == "ppermute"
                else None
            )
            out.append(KernelVariant(
                sync="async", layout=layout, gear=level,
                label=f"{layout}/async/gear{level}",
                max_sort_rows=bound, max_serializing_gathers=0,
                max_all_gathers=allow_ag,
                lower=lower_async,
            ))
    return out


def variants_for_fleet(fleet, *, sync_modes=SYNC_MODES, gears=None,
                       win_end: int = DEFAULT_WIN_END) -> list[KernelVariant]:
    """Matrix cells for a FleetSimulation: the vmapped run_to (what the
    conservative sweep dispatches) and the vmapped per-lane attempt."""
    import jax.numpy as jnp
    import numpy as np

    t = fleet.template
    out: list[KernelVariant] = []
    for level in _gear_levels(fleet._ladder, gears):
        spec = fleet._ladder[level]
        bound = _sort_bound(spec, t.num_hosts, t.O)
        for sync in sync_modes:
            def lower(fleet=fleet, level=level, sync=sync):
                if fleet._gear != level:
                    fleet._shift_gear(level)
                L = fleet.lanes
                we = jnp.full((L,), win_end, jnp.int64)
                if sync == "conservative":
                    fn = fleet._gear_fns[level]["run_to"]
                    if getattr(fleet, "_async", False):
                        # async fleets dispatch the per-shard-frontier
                        # loop: per-lane width/lookahead/spread stacks
                        lowered = fn.lower(
                            fleet.state, fleet.params,
                            jnp.asarray(fleet._async_runahead),
                            jnp.asarray(fleet._async_look),
                            jnp.asarray(fleet._async_spread), we, 8,
                        )
                    else:
                        lowered = fn.lower(
                            fleet.state, fleet.params,
                            jnp.asarray(np.asarray(fleet._runahead)), we, 8,
                        )
                else:
                    fleet._ensure_attempt()
                    fn = fleet._gear_fns[level]["attempt"]
                    lowered = fn.lower(
                        fleet.state, fleet.params,
                        jnp.zeros((L,), jnp.int64), we,
                    )
                return lowered.compile().as_text()

            # the lane vmap batches the template's by-dst done_t lookups
            # into rank>=2 gathers: islands templates carry two under
            # optimistic sync (compiled out under conservative); global
            # templates always compile the lax.cond'd check (one gather)
            if fleet._islands:
                allow = 2 if sync == "optimistic" else 0
            else:
                allow = 1
            out.append(KernelVariant(
                sync=sync, layout="fleet", gear=level,
                label=f"fleet/{sync}/gear{level}",
                max_sort_rows=bound, max_serializing_gathers=allow,
                lower=lower,
            ))
    return out


def audit_variants(variants: list[KernelVariant]) -> dict[str, list[str]]:
    """Run the op-contract audit over every cell; {label: violations}."""
    return {v.label: v.audit() for v in variants}


def assert_variants_clean(variants: list[KernelVariant]) -> None:
    bad = {k: v for k, v in audit_variants(variants).items() if v}
    if bad:
        lines = [x for vs in bad.values() for x in vs]
        raise HloAuditError(
            f"{len(lines)} op-contract violation(s) across "
            f"{len(bad)} kernel variant(s):\n" + "\n".join(lines)
        )


# ---------------------------------------------------------------------------
# the HLO budget ledger (ISSUE 14): per-variant op accounting vs a
# checked-in baseline
# ---------------------------------------------------------------------------
#
# The point asserts above gate *classes* of violation (any scatter, any
# new serializing gather, sorts past a structural bound).  The ledger
# gates *drift*: an exact per-variant account of collective counts by
# kind, sort count + row volume, gather/scatter counts, and estimated
# buffer bytes, checked against shadow_tpu/analysis/hlo_baseline.json.
# A lowering regression — a new all-gather on the mesh path, a
# sort-volume blowup that still fits under the 4x structural slack —
# fails with a field-level diff against the ledger instead of slipping
# under a hand-pinned allowance.  Regenerate legitimately (an intended
# kernel change) with:
#
#   python tools/shadowlint.py --hlo --write-hlo-baseline --virtual-devices 8
#
# (the virtual-device force lets the mesh/shard_map cells lower on a CPU
# box; without it those cells are skipped and their baseline entries
# kept).

HLO_BASELINE_NAME = "hlo_baseline.json"
HLO_BASELINE_VERSION = 1

COLLECTIVE_KINDS = (
    "all-gather", "all-reduce", "reduce-scatter", "collective-permute",
    "all-to-all",
)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(
    r"\b(pred|s8|u8|s16|u16|f16|bf16|s32|u32|f32|s64|u64|f64|c64|c128)"
    r"\[([0-9,]*)\]"
)


class HloBaselineError(ValueError):
    """The checked-in HLO baseline is missing, corrupt or version-skewed
    (the CLI maps this to exit 2 with a regeneration hint)."""


def _shape_token_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES[dtype]


def collective_counts(hlo: str) -> dict[str, int]:
    """Per-kind collective-op counts (the async `-start` form counts,
    the `-done` completion of the same op does not)."""
    counts = {k: 0 for k in COLLECTIVE_KINDS}
    for line in hlo.splitlines():
        if "= " not in line:
            continue
        for kind in COLLECTIVE_KINDS:
            if re.search(rf"= .*\b{kind}(-start)?\(", line):
                counts[kind] += 1
    return {k: v for k, v in counts.items() if v}


def estimate_buffer_bytes(hlo: str) -> dict[str, int]:
    """Peak-buffer proxies parsed from the optimized-HLO text: the
    entry parameters' total (resident state the kernel is bound over)
    and the largest single tensor any instruction materializes (the
    dominant working-set term — sort temporaries and exchange buffers
    show up here).  Proxies, not an allocator replay: they move when and
    only when the compiled program's shapes move, which is exactly the
    regression signal the ledger wants."""
    param_bytes = 0
    largest = 0
    for line in hlo.splitlines():
        if "= " not in line:
            continue
        line_best = 0
        for m in _SHAPE_RE.finditer(line):
            line_best = max(
                line_best, _shape_token_bytes(m.group(1), m.group(2))
            )
        largest = max(largest, line_best)
        if re.search(r"\bparameter\(\d+\)", line):
            m = _SHAPE_RE.search(line)
            if m:
                param_bytes += _shape_token_bytes(m.group(1), m.group(2))
    return {"param_bytes": param_bytes, "largest_tensor_bytes": largest}


def hlo_budget(hlo: str) -> dict:
    """The ledger row for one compiled program."""
    rows = sort_rows(hlo)
    return {
        "collectives": collective_counts(hlo),
        "sorts": len(rows),
        "sort_rows": sum(rows),
        "gathers": len([
            ln for ln in hlo.splitlines()
            if re.search(r"= .*(?<!-)\bgather\(", ln)
        ]),
        "serializing_gathers": len(serializing_gather_lines(hlo)),
        "scatters": len(scatter_lines(hlo)),
        **estimate_buffer_bytes(hlo),
    }


_EXACT_BUDGET_KEYS = (
    "sorts", "sort_rows", "gathers", "serializing_gathers", "scatters",
)
_BYTES_BUDGET_KEYS = ("param_bytes", "largest_tensor_bytes")


def diff_budget(label: str, cur: dict, base: dict,
                bytes_tol: float = 0.25) -> list[str]:
    """Field-level differences of one variant's budget against its
    ledger entry.  Count fields compare exactly; the byte proxies
    tolerate `bytes_tol` relative drift (layout/padding jitter across
    compiler point releases must not cry wolf)."""
    out = []
    kinds = sorted(set(cur.get("collectives", {}))
                   | set(base.get("collectives", {})))
    for kind in kinds:
        c = cur.get("collectives", {}).get(kind, 0)
        b = base.get("collectives", {}).get(kind, 0)
        if c != b:
            out.append(
                f"{label}: {kind} count {c} != ledger {b}"
                + (" (a NEW collective on this path)" if c > b else
                   " (ledger is stale — regenerate to ratchet down)")
            )
    for key in _EXACT_BUDGET_KEYS:
        c, b = cur.get(key, 0), base.get(key, 0)
        if c != b:
            out.append(f"{label}: {key} {c} != ledger {b}")
    for key in _BYTES_BUDGET_KEYS:
        c, b = cur.get(key, 0), base.get(key, 0)
        lo, hi = b * (1 - bytes_tol), b * (1 + bytes_tol)
        if not (lo <= c <= hi):
            out.append(
                f"{label}: {key} {c} outside ledger {b} "
                f"(±{int(bytes_tol * 100)}%)"
            )
    return out


def budget_ledger(variants: list[KernelVariant]) -> dict[str, dict]:
    """{label: budget} over the variant cells (one compile each)."""
    return {v.label: hlo_budget(v.hlo()) for v in variants}


def baseline_path(root: str | None = None) -> str:
    if root is not None:
        return os.path.join(
            root, "shadow_tpu", "analysis", HLO_BASELINE_NAME
        )
    return os.path.join(os.path.dirname(__file__), HLO_BASELINE_NAME)


def load_hlo_baseline(path: str | None = None) -> dict[str, dict]:
    path = path or baseline_path()
    if not os.path.exists(path):
        raise HloBaselineError(
            f"HLO baseline {path} is missing — regenerate with "
            f"`python tools/shadowlint.py --hlo --write-hlo-baseline "
            f"--virtual-devices 8`"
        )
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise HloBaselineError(
            f"HLO baseline {path} is unreadable ({e}) — regenerate with "
            f"`python tools/shadowlint.py --hlo --write-hlo-baseline`"
        ) from e
    if doc.get("version") != HLO_BASELINE_VERSION:
        raise HloBaselineError(
            f"HLO baseline {path}: version {doc.get('version')!r} != "
            f"{HLO_BASELINE_VERSION} — regenerate with "
            f"`python tools/shadowlint.py --hlo --write-hlo-baseline`"
        )
    return doc.get("entries", {})


def write_hlo_baseline(ledger: dict[str, dict],
                       path: str | None = None) -> dict:
    import jax

    path = path or baseline_path()
    doc = {
        "version": HLO_BASELINE_VERSION,
        # informational only (never compared): the toolchain the budgets
        # were captured under, so a diff after a jax upgrade reads right
        "jax": jax.__version__,
        "entries": {k: ledger[k] for k in sorted(ledger)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc


def check_ledger(
    ledger: dict[str, dict], baseline: dict[str, dict],
    bytes_tol: float = 0.25,
) -> list[str]:
    """Every lowered variant against its ledger entry.  Variants in the
    baseline but not lowered in THIS environment (mesh cells on a
    single-device box) are skipped — each environment audits what it can
    compile; tests and the smoke gates between them cover the union."""
    problems: list[str] = []
    for label in sorted(ledger):
        if label not in baseline:
            problems.append(
                f"{label}: variant has no ledger entry — a new kernel "
                f"cell landed without regenerating hlo_baseline.json "
                f"(`python tools/shadowlint.py --hlo "
                f"--write-hlo-baseline`)"
            )
            continue
        problems.extend(
            diff_budget(label, ledger[label], baseline[label], bytes_tol)
        )
    return problems


def default_ledger_variants(include_mesh: bool | None = None
                            ) -> list[KernelVariant]:
    """The canonical tiny builds whose kernels the ledger accounts:
    {conservative, optimistic} x {global, islands, fleet} x gear plus
    the async islands loop, and — when >= 2 devices are visible — the
    shard_map mesh cells whose frontier exchange must stay
    neighbor-only.  Builder parameters are pinned HERE so budgets are
    comparable across the test process, the bench gate, and the
    regeneration CLI."""
    import jax

    from shadow_tpu.flagship import SELF_LOOP_50MS_GML, build_phold_flagship
    from shadow_tpu.fleet import JobSpec, build_fleet
    from shadow_tpu.sim import build_simulation

    if include_mesh is None:
        include_mesh = len(jax.devices()) >= 2

    def tiny(**kw):
        return build_phold_flagship(
            32, msgload=2, stop_s=2, runtime_s=2, seed=3,
            event_capacity=2048, pool_gears=2, **kw)

    def fleet_cfg(seed):
        return {
            "general": {"stop_time": "1 s", "seed": seed},
            "network": {
                "graph": {"type": "gml", "inline": SELF_LOOP_50MS_GML}
            },
            "experimental": {
                "event_capacity": 1024, "events_per_host_per_window": 8,
                "outbox_slots": 8, "inbox_slots": 4, "pool_gears": 2,
            },
            "hosts": {"peer": {
                "quantity": 8, "app_model": "phold",
                "app_options": {"msgload": 2, "runtime": 2,
                                "start_time": "100 ms"},
            }},
        }

    def qdisc_cfg(discipline):
        # a NetStack workload (phold has none) with the device queue
        # discipline at full feature load: wfq ranks + codel drop hook —
        # the ledger cells that pin "no scatter, no sorts" for the
        # compare-and-place / bucket-scan kernels
        return {
            "general": {"stop_time": "1 s", "seed": 4},
            "network": {
                "graph": {"type": "gml", "inline": SELF_LOOP_50MS_GML}
            },
            "experimental": {
                "event_capacity": 1024, "events_per_host_per_window": 8,
            },
            "qdisc": {
                "discipline": discipline, "rank": "wfq", "drop": "codel",
                "queue_slots": 16, "buckets": 8,
            },
            "hosts": {
                "server": {"app_model": "udp_flood",
                           "app_options": {"role": "server"}},
                "client": {
                    "quantity": 7, "app_model": "udp_flood",
                    "app_options": {"interval": "50 ms", "size": 400,
                                    "runtime": 1},
                },
            },
        }

    out: list[KernelVariant] = []
    out += variants_for_sim(tiny(), "global")
    out += variants_for_sim(
        tiny(num_shards=2, exchange_slots=16), "islands")
    for disc in ("pifo", "eiffel"):
        out += variants_for_sim(
            build_simulation(qdisc_cfg(disc)), f"qdisc_{disc}",
            sync_modes=("conservative",),
        )
    out += variants_for_fleet(build_fleet(
        [JobSpec("a", fleet_cfg(1)), JobSpec("b", fleet_cfg(2))]))
    if include_mesh:
        # the mesh hot path: shard_map lowering, where collectives
        # survive to HLO — the cells whose all-gather count the ledger
        # (and audit_hlo's zero-pin) must hold at 0
        out += variants_for_sim(
            tiny(num_shards=2, exchange_slots=16,
                 island_mode="shard_map"),
            "mesh", sync_modes=("conservative",),
        )
    return out


# ---------------------------------------------------------------------------
# retrace detector
# ---------------------------------------------------------------------------


def kernel_cache_sizes(sim) -> dict[str, int]:
    """Per-kernel compiled-trace counts for a Simulation /
    IslandSimulation / FleetSimulation after a driver run: label ->
    number of lowerings the bound jit accumulated.  0 = never dispatched
    (lazy), 1 = the expected single compile, >=2 = a retrace (argument
    dtype/weak-type/shape drift across dispatches)."""
    out: dict[str, int] = {}
    for level in sorted(sim._gear_fns):
        for name, fn in sorted(sim._gear_fns[level].items()):
            if name == "step_fn" or fn is None:
                continue
            size = getattr(fn, "_cache_size", None)
            if not callable(size):
                import jax

                raise RetraceError(
                    f"kernel gear{level}.{name} exposes no _cache_size "
                    f"(jax {jax.__version__}): the retrace detector needs "
                    f"the jit trace-cache introspection API"
                )
            out[f"gear{level}.{name}"] = int(size())
    return out


def retrace_report(sim, max_per_kernel: int = 1) -> dict:
    """The retrace-detector report for a driver smoke run."""
    sizes = kernel_cache_sizes(sim)
    retraced = {k: n for k, n in sizes.items() if n > max_per_kernel}
    rep = {
        "kernels": sizes,
        "compiles_total": sum(sizes.values()),
        "retraced": retraced,
        "ok": not retraced,
    }
    traces = getattr(sim, "kernel_traces", None)
    if traces is not None:  # fleet: the PR-4 audit metric rides along
        rep["kernel_traces"] = int(traces)
    return rep


def assert_no_retrace(sim, max_per_kernel: int = 1) -> dict:
    """Fail on unexpected recompiles: every bound kernel must have been
    lowered at most `max_per_kernel` times across the run."""
    rep = retrace_report(sim, max_per_kernel)
    if not rep["ok"]:
        raise RetraceError(
            f"kernel retrace(s) detected (expected <= {max_per_kernel} "
            f"lowering(s) per kernel): {rep['retraced']} — driver "
            f"arguments drifted in dtype/weak-type/shape between "
            f"dispatches"
        )
    return rep
