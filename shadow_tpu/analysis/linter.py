"""shadowlint driver: file walking, kernel/host classification, ``# noqa``
suppression, and the baseline (grandfathering) workflow.

Classification (the kernel/host module map, docs/static_analysis.md):
a **kernel** module contributes code that is traced into device window
programs — its text is subject to the full purity rule set.  Everything
else is **host** (drivers, schedulers, config, tools): only the
module-agnostic rules (seed lineage STL003, metric keys STL008) apply.
``shadow_tpu/obs/metrics.py``'s ``time.time()`` is the canonical host
example: wall-clock metadata on a host-side registry is fine — the
classification allowlists it structurally instead of per-line.

Suppression: append ``# noqa: STL0xx`` (or a bare ``# noqa``) to the
flagged line.  Baseline: ``.shadowlint_baseline.json`` at the repo root
grandfathers pre-existing findings by (path, code, normalized source
line) fingerprint — stable across unrelated line-number churn; new code
can never hide behind it because any new finding is a new fingerprint.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import os
import re
from dataclasses import asdict, dataclass

from shadow_tpu.analysis import rules as rules_mod

BASELINE_NAME = ".shadowlint_baseline.json"
BASELINE_VERSION = 1
# the findings_doc JSON report: v2 added the per-pass `passes` counts
REPORT_SCHEMA_VERSION = 2

# The kernel/host module map (repo-relative, forward slashes).  These
# modules produce code that is traced into compiled device programs.
KERNEL_MODULE_PATTERNS = (
    "shadow_tpu/core/engine.py",
    "shadow_tpu/core/state.py",
    "shadow_tpu/core/soa.py",
    "shadow_tpu/core/spill.py",
    "shadow_tpu/core/gearbox.py",
    "shadow_tpu/net/*.py",
    "shadow_tpu/obs/counters.py",
    "shadow_tpu/obs/audit.py",
    "shadow_tpu/obs/flight.py",
    "shadow_tpu/parallel/*.py",
    "shadow_tpu/fleet/engine.py",
)

# Structurally HOST modules inside a kernel pattern: the elastic mesh
# runner (parallel/elastic.py) is pure orchestration — it builds sims,
# probes chips on the WALL clock and measures relayout downtime; no
# code in it is ever traced into a kernel (the same posture as
# core/supervisor.py, which lives outside the kernel set entirely).
# Simulation results never depend on its clocks: every relayout resumes
# from a committed-frontier drain checkpoint.
HOST_MODULE_EXCEPTIONS = (
    "shadow_tpu/parallel/elastic.py",
)

_NOQA_RE = re.compile(
    r"#\s*noqa(?::\s*(?P<codes>[A-Z]+[0-9]+(?:\s*,\s*[A-Z]+[0-9]+)*))?",
    re.IGNORECASE,
)


@dataclass(frozen=True)
class Finding:
    path: str  # repo-relative
    line: int
    col: int
    code: str
    message: str
    text: str  # stripped source line (fingerprint component)

    def fingerprint(self) -> tuple[str, str, str]:
        return (self.path, self.code, self.text)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: {self.code} {self.message}"


def classify_module(relpath: str) -> str:
    """'kernel' or 'host' for a repo-relative path."""
    p = relpath.replace(os.sep, "/")
    if p in HOST_MODULE_EXCEPTIONS:
        return "host"
    for pat in KERNEL_MODULE_PATTERNS:
        if fnmatch.fnmatch(p, pat):
            return "kernel"
    return "host"


def _suppressed(line_text: str, code: str) -> bool:
    m = _NOQA_RE.search(line_text)
    if not m:
        return False
    codes = m.group("codes")
    if codes is None:
        return True  # bare `# noqa` silences everything on the line
    return code.upper() in {c.strip().upper() for c in codes.split(",")}


def lint_source(
    src: str,
    relpath: str,
    kind: str | None = None,
    select: set[str] | None = None,
) -> list[Finding]:
    """Lint one module's source text.  `kind` overrides classification
    (fixture tests lint snippets "as if" kernel/host); `select` restricts
    to a subset of rule codes."""
    relpath = relpath.replace(os.sep, "/")
    if kind is None:
        kind = classify_module(relpath)
    tree = ast.parse(src, filename=relpath)
    imports = rules_mod.build_imports(tree)
    parents = rules_mod.build_parents(tree)
    ctx = rules_mod.RuleContext(
        tree=tree,
        relpath=relpath,
        kind=kind,
        imports=imports,
        parents=parents,
        traced=rules_mod.find_traced_functions(tree, imports, parents),
    )
    lines = src.splitlines()
    out: list[Finding] = []
    for rule in rules_mod.RULES:
        if select is not None and rule.code not in select:
            continue
        if rule.kernel_only and kind != "kernel":
            continue
        for raw in rule.fn(ctx):
            text = (
                lines[raw.line - 1] if 0 < raw.line <= len(lines) else ""
            )
            if _suppressed(text, raw.code):
                continue
            out.append(
                Finding(
                    path=relpath, line=raw.line, col=raw.col,
                    code=raw.code, message=raw.message, text=text.strip(),
                )
            )
    out.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return out


def lint_file(path: str, root: str, select: set[str] | None = None) -> list[Finding]:
    relpath = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return lint_source(src, relpath, select=select)


def iter_python_files(paths: list[str]):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in {"__pycache__", ".git", ".jax_cache"}
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def lint_paths(
    paths: list[str], root: str, select: set[str] | None = None
) -> list[Finding]:
    out: list[Finding] = []
    for path in iter_python_files(paths):
        out.extend(lint_file(path, root, select=select))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return out


# ---------------------------------------------------------------------------
# baseline (grandfathered findings)
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> dict[tuple[str, str, str], int]:
    """Fingerprint -> grandfathered count.  Missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: version {doc.get('version')!r} != "
            f"{BASELINE_VERSION}"
        )
    out: dict[tuple[str, str, str], int] = {}
    for e in doc.get("entries", []):
        key = (e["path"], e["code"], e["text"])
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def split_baselined(
    findings: list[Finding], baseline: dict[tuple[str, str, str], int]
) -> tuple[list[Finding], list[Finding]]:
    """(new, grandfathered): each fingerprint absorbs up to its
    baselined count of findings; the rest are new."""
    remaining = dict(baseline)
    new: list[Finding] = []
    old: list[Finding] = []
    for f in findings:
        k = f.fingerprint()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


def write_baseline(findings: list[Finding], path: str) -> dict:
    counts: dict[tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.fingerprint()] = counts.get(f.fingerprint(), 0) + 1
    doc = {
        "version": BASELINE_VERSION,
        "entries": [
            {"path": p, "code": c, "text": t, "count": n}
            for (p, c, t), n in sorted(counts.items())
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    return doc


def findings_doc(
    new: list[Finding], grandfathered: list[Finding], scanned: list[str],
    passes: dict[str, int] | None = None,
) -> dict:
    """The machine-readable report (`tools/shadowlint.py --format json`).

    Schema v2 (ISSUE 14): `passes` carries per-pass NEW-finding counts —
    {"lint": n, "contracts": n, "threads": n, "hlo": n} for whichever
    passes ran — alongside the flat findings list; v1 documents carried
    the lint pass only and no `passes` object."""
    by_code: dict[str, int] = {}
    for f in new:
        by_code[f.code] = by_code.get(f.code, 0) + 1
    return {
        "kind": "shadow_tpu.shadowlint",
        "schema_version": REPORT_SCHEMA_VERSION,
        "ok": not new,
        "files_scanned": len(scanned),
        "findings": [asdict(f) for f in new],
        "grandfathered": [asdict(f) for f in grandfathered],
        "counts": {
            "new": len(new),
            "grandfathered": len(grandfathered),
            "by_code": dict(sorted(by_code.items())),
        },
        "passes": dict(sorted((passes or {"lint": len(new)}).items())),
        "rules": {
            r.code: r.summary for r in rules_mod.RULES
        },
    }
