"""The `serve` CLI subcommand: ``python -m shadow_tpu serve``.

Starts the resident sim-as-a-service daemon (serve/daemon.py): journaled
sweep queue, AOT-cached fleet kernels, graceful SIGTERM drain, admission
quotas. Operators talk to it with tools/shadowctl.py over the unix
socket. Exit status 0 on a graceful drain; a SIGKILL needs no goodbye —
the next start replays the journal (docs/serving.md).
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="shadow_tpu serve",
        description="crash-safe sim-as-a-service daemon (docs/serving.md)",
    )
    p.add_argument(
        "--state-dir", required=True, metavar="DIR",
        help="daemon state root: journal.wal, per-sweep checkpoint "
             "directories, serve.metrics.json; restart with the same DIR "
             "to replay the journal and finish accepted sweeps",
    )
    p.add_argument(
        "--socket", metavar="PATH",
        help="unix socket for the HTTP API (default <state-dir>/serve.sock)",
    )
    p.add_argument(
        "--lanes", type=int, metavar="N",
        help="device lanes per fleet (default: the sweep's own "
             "fleet.lanes / sweep.lanes)",
    )
    p.add_argument(
        "--max-queue", type=int, default=16, metavar="N",
        help="queue-depth backpressure: submissions beyond N queued+"
             "running sweeps are shed with HTTP 429 (default 16)",
    )
    p.add_argument(
        "--default-quota", type=int, default=8, metavar="N",
        help="per-tenant admission quota: max unfinished sweeps a tenant "
             "may hold (default 8)",
    )
    p.add_argument(
        "--quota", action="append", default=[], metavar="TENANT=N",
        help="per-tenant quota override (repeatable)",
    )
    p.add_argument(
        "--checkpoint-every-dispatches", type=int, default=4, metavar="K",
        help="flush the running fleet's slices + manifest every K "
             "dispatch slices (default 4); smaller = tighter recovery "
             "point, more I/O",
    )
    p.add_argument(
        "--cache-dir", metavar="DIR",
        help="compile-cache root shared with bench.py (default "
             "$SHADOW_TPU_CACHE_DIR or <repo>/.jax_cache); AOT window-"
             "kernel exports live under <DIR>/aot",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    quotas = {}
    for q in args.quota:
        if "=" not in q:
            print(f"error: --quota wants TENANT=N, got {q!r}",
                  file=sys.stderr)
            return 2
        tenant, _, n = q.partition("=")
        try:
            quotas[tenant] = int(n)
        except ValueError:
            print(f"error: --quota {q!r}: {n!r} is not an integer",
                  file=sys.stderr)
            return 2
    from shadow_tpu.serve.daemon import ServeOptions, ShadowDaemon

    opts = ServeOptions(
        state_dir=args.state_dir,
        socket_path=args.socket,
        lanes=args.lanes,
        max_queue_depth=args.max_queue,
        default_quota=args.default_quota,
        tenant_quotas=quotas,
        checkpoint_every_dispatches=args.checkpoint_every_dispatches,
        cache_dir=args.cache_dir,
    )
    return ShadowDaemon(opts).serve_forever()
