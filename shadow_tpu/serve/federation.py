"""Federated serve plane: N daemons behind one placement brain.

A single serve daemon already treats its own death as a non-event
(journal replay, serve/journal.py). This module scales that contract
horizontally: several daemons — each with its own `--state-dir`,
sharing the content-addressed machine-fingerprinted kcache root — are
registered in a peer table and fronted by a thin router
(serve/router.py, `python -m shadow_tpu route --peers ...`). Losing a
box is then a journal replay, not an outage:

* **Placement.** Incoming sweeps go to the peer with the best
  `placement_score` — queue depth x mesh posture (chips_total /
  chips_up) x memory headroom, all read off the fields every daemon
  already publishes on `/healthz`. A tenant sticks to its last peer
  (warm AOT kernels, colocated checkpoints) while that peer stays
  healthy and within ~2x of the best score.

* **Probing.** Each peer carries a `ProbeLadder`
  (core/supervisor.py): HEALTHY -> SUSPECT on a missed probe ->
  LOST after `lost_after` consecutive misses, with jittered
  exponential backoff between retries — the BackendSupervisor
  bounded-retry classification idiom applied to peer liveness. Every
  successful probe also mirrors the peer's journal (`GET
  /v1/journal`), so a peer whose state-dir dies WITH its box can
  still be replayed from the router's last mirror.

* **Failover.** A LOST peer's journal (live `journal.wal` preferred,
  mirror as fallback) is folded with `JournalState` and every
  unfinished sweep is re-placed onto surviving peers, who finish them
  from scratch or from their drain checkpoints with audit chains
  bit-identical to an uninterrupted run (the shared kcache means warm
  peers re-dispatch without a single kernel recompile).

* **Stealing.** An idle peer pulls queued work from a loaded one
  through the router. The handoff is journaled at every step — the
  source daemon appends HANDOFF before the sweep leaves its queue,
  the router appends its own HANDOFF intent before asking, and the
  receiver journals the sweep's `origin` handle with its SUBMIT — so
  a crash at ANY point mid-steal never duplicates or drops a sweep
  (`recover_handoffs` proves each intent landed exactly once). Same
  torn-tail discipline as the single-daemon WAL.

Lock discipline (analysis/threads.py, STH001-004): `_lock` guards the
peer table, placements, affinity and counters; network I/O (probes,
submits, releases) ALWAYS happens outside the lock — decide under the
lock, act outside it, fold results back under it.
"""

from __future__ import annotations

import os
import threading

from shadow_tpu.core.supervisor import (
    PEER_HEALTHY,
    PEER_LOST,
    PEER_SUSPECT,
    ProbeLadder,
)
from shadow_tpu.serve import journal as journal_mod
from shadow_tpu.serve.client import ServeClient, ServeClientError

# a tenant's affine peer keeps winning until it is this much worse than
# the best-scoring peer (warm kernels + colocated checkpoints are worth
# a bounded amount of queueing, not an unbounded pile-up)
AFFINITY_SLACK = 2.0
# steal trigger: an idle peer (depth 0, nothing running) pulls from a
# peer with at least this many queued sweeps
STEAL_MIN_DEPTH = 2


class FederationError(RuntimeError):
    pass


def parse_peer_spec(spec: str) -> tuple[str, str]:
    """`NAME=STATE_DIR` or bare `STATE_DIR` (name = directory basename).
    Returns (name, state_dir). Names join sweep handles as
    `name:sid`, so ':' and '=' are refused."""
    if "=" in spec:
        name, state_dir = spec.split("=", 1)
    else:
        state_dir = spec
        name = os.path.basename(os.path.abspath(spec))
    name = name.strip()
    if not name or ":" in name or "=" in name:
        raise FederationError(f"bad peer name in spec {spec!r}")
    if not state_dir:
        raise FederationError(f"bad state dir in spec {spec!r}")
    return name, os.path.abspath(state_dir)


def split_handle(handle: str) -> tuple[str, str]:
    """A federation sweep handle is `peer:sid` — each daemon numbers
    sweeps independently, so the bare sid is ambiguous across peers."""
    if ":" not in handle:
        raise FederationError(f"bad sweep handle {handle!r} (want peer:sid)")
    peer, sid = handle.split(":", 1)
    return peer, sid


def placement_score(health: dict) -> float:
    """Lower is better. Queue wait (the daemon's own `retry_after_s`
    estimate + raw depth) scaled by mesh degradation (a 7-of-8-chip
    peer runs ~8/7 slower, and admission already shrank its memory
    budget to match), plus a hard penalty when memory headroom is
    exhausted (its next admission would shed anyway)."""
    queue = health.get("queue") or {}
    depth = int(queue.get("depth", 0)) + (1 if queue.get("running") else 0)
    wait_s = float(health.get("retry_after_s", 0) or 0)
    mesh = health.get("mesh") or {}
    chips_up = int(mesh.get("chips_up", 0) or 0)
    chips_total = int(mesh.get("chips_total", 0) or 0)
    if chips_total > 0 and chips_up <= 0:
        return float("inf")  # a meshless peer cannot run anything
    factor = (chips_total / chips_up) if chips_total > 0 else 1.0
    score = (depth + wait_s) * factor
    memory = health.get("memory") or {}
    headroom = memory.get("headroom_bytes")
    if headroom is not None and int(headroom) <= 0:
        score += 1000.0
    if health.get("draining"):
        score = float("inf")
    return score


class Peer:
    """One federation member. Mutable fields are guarded by the owning
    Federation's `_lock`; the ServeClient is only used OUTSIDE it."""

    def __init__(self, name: str, state_dir: str, *,
                 lost_after: int = 3, seed: int = 0,
                 client_factory=None):
        self.name = name
        self.state_dir = state_dir
        self.socket_path = os.path.join(state_dir, "serve.sock")
        factory = client_factory or (
            lambda path: ServeClient(path, timeout=30.0)
        )
        self.client = factory(self.socket_path)
        self.ladder = ProbeLadder(lost_after=lost_after, seed=seed)
        self.health: dict = {}
        self.journal_mirror: list[dict] = []
        self.next_probe_at = 0.0  # monotonic; 0 = probe immediately
        self.lost_handled = False

    def journal_records(self) -> list[dict]:
        """The LOST peer's journal: prefer the live `journal.wal` in its
        state-dir (survives daemon death on a shared filesystem), fall
        back to the router's last probe-time mirror (survives the box)."""
        path = os.path.join(self.state_dir, "journal.wal")
        if os.path.exists(path):
            try:
                return journal_mod.scan(path)["records"]
            except journal_mod.JournalError:
                pass  # unreadable with the box: use the mirror
        return list(self.journal_mirror)


class Federation:
    """Peer table + placement + probe ladder + failover/steal logic.

    The router process (serve/router.py) owns the HTTP surface and the
    probe cadence; everything stateful lives here so tests can drive
    loss, failover and crash-mid-steal recovery in-process.

    Single-writer journal discipline: the router journal is appended
    only from the supervising thread (`probe_once` -> `fail_over`,
    `steal_once`, and `__init__`) — HTTP threads call `place`/`locate`/
    introspection, which never append — so the router journal needs no
    lock of its own."""

    def __init__(self, peer_specs: list[str], journal: journal_mod.Journal,
                 *, lost_after: int = 3, probe_interval_s: float = 1.0,
                 seed: int = 0, client_factory=None, now=None):
        import time as _time

        self._now = now or _time.monotonic
        self._lock = threading.Lock()
        self.journal = journal
        self.probe_interval_s = float(probe_interval_s)
        self.peers: dict[str, Peer] = {}
        self.counters: dict[str, int] = {
            "placements": 0,
            "steals": 0,
            "failovers": 0,
            "replayed_sweeps": 0,
            "probes": 0,
            "peers_lost": 0,
            "handoff_recoveries": 0,
        }
        # handle -> {"peer": name, "sid": sid, "tenant": tenant}; after
        # a failover the ORIGINAL handle stays stable and remaps here
        self.placements: dict[str, dict] = {}
        self.affinity: dict[str, str] = {}  # tenant -> peer name
        already = {
            rec.get("name") for rec in journal.records
            if rec["type"] == journal_mod.REGISTER
        }
        for i, spec in enumerate(peer_specs):
            name, state_dir = parse_peer_spec(spec)
            if name in self.peers:
                raise FederationError(f"duplicate peer name {name!r}")
            self.peers[name] = Peer(
                name, state_dir, lost_after=lost_after, seed=seed + i,
                client_factory=client_factory,
            )
            if name not in already:
                journal.append(
                    journal_mod.REGISTER, name=name, state_dir=state_dir,
                    socket=self.peers[name].socket_path,
                )
        if not self.peers:
            raise FederationError("a federation needs at least one peer")

    # ------------------------------------------------------------------
    # probing (router probe thread)
    # ------------------------------------------------------------------

    def probe_once(self) -> list[str]:
        """One probe round: hit every due peer's /healthz (+ journal
        mirror), fold the results through each ProbeLadder, then run
        failover for any peer that just crossed into LOST. Returns the
        names of peers declared lost this round."""
        now = self._now()
        with self._lock:
            due = [p for p in self.peers.values() if now >= p.next_probe_at]
        results: list[tuple[Peer, dict | None, dict | None]] = []
        for p in due:  # network I/O: outside the lock
            try:
                health = p.client.health()
                mirror = p.client.journal()
            except ServeClientError:
                results.append((p, None, None))
            else:
                results.append((p, health, mirror))
        newly_lost: list[Peer] = []
        resurrected: list[Peer] = []
        with self._lock:
            for p, health, mirror in results:
                self.counters["probes"] += 1
                before = p.ladder.state
                state = p.ladder.record(health is not None)
                if health is not None:
                    p.health = health
                    p.journal_mirror = mirror.get("records", [])
                    p.next_probe_at = self._now() + self.probe_interval_s
                    if before == PEER_LOST:
                        resurrected.append(p)
                    p.lost_handled = False
                else:
                    p.next_probe_at = self._now() + p.ladder.backoff_s()
                if state == PEER_LOST and before != PEER_LOST:
                    self.counters["peers_lost"] += 1
                if (state == PEER_LOST and not p.lost_handled):
                    p.lost_handled = True
                    newly_lost.append(p)
        for p in newly_lost:  # replay + re-place: outside the lock
            self.fail_over(p.name)
        for p in resurrected:
            self._reconcile_resurrected(p)
        return [p.name for p in newly_lost]

    def _reconcile_resurrected(self, peer: Peer) -> None:
        """A peer declared LOST — and failed over — has come back. Its
        own journal replay is about to re-run sweeps the federation
        already moved, so release every such still-queued sweep on the
        returned peer (journaling handed_off there). A sweep its replay
        already re-admitted races through (release answers 409 busy);
        the placement map keeps routing reads to the failover copy, so
        the duplicate compute is wasted but never observed — and with
        deterministic fleets both copies produce bit-identical chains."""
        with self._lock:
            stale = [
                (split_handle(h)[1], placed["peer"])
                for h, placed in self.placements.items()
                if split_handle(h)[0] == peer.name
                and placed["peer"] != peer.name
            ]
        for sid, holder in stale:  # network I/O: outside the lock
            try:
                peer.client.release(sid, to_peer=holder)
            except ServeClientError:
                pass  # 409 busy / 404 / unreachable: routing unaffected

    # ------------------------------------------------------------------
    # placement (router HTTP threads)
    # ------------------------------------------------------------------

    def _pick_peer(self, tenant: str,
                   exclude: set[str] = frozenset()) -> Peer | None:
        """Call under `_lock`. Best non-excluded live peer by
        placement_score, with sticky tenant affinity within
        AFFINITY_SLACK. None when no candidate can take work."""
        scored = [
            (placement_score(p.health), p.name, p)
            for p in self.peers.values()
            if p.ladder.state != PEER_LOST and p.name not in exclude
        ]
        scored = [(s, n, p) for s, n, p in scored if s != float("inf")]
        if not scored:
            return None
        scored.sort(key=lambda t: (t[0], t[1]))
        best_score, _, best = scored[0]
        affine = self.affinity.get(tenant)
        if affine is not None:
            for s, n, p in scored:
                if n == affine and s <= best_score * AFFINITY_SLACK + 1.0:
                    return p
        return best

    def place(self, doc: dict, tenant: str = "default",
              backend_faults: list | None = None) -> dict:
        """Place one sweep: pick under the lock, submit outside it,
        record the placement under it. A peer that refuses (shed) or
        drops mid-submit is skipped and the next-best peer tried; the
        last shed body is surfaced when every peer sheds."""
        tried: set[str] = set()
        last_shed: dict | None = None
        while True:
            with self._lock:
                peer = self._pick_peer(tenant, exclude=tried)
            if peer is None:
                break
            tried.add(peer.name)
            try:
                out = peer.client.submit(
                    doc, tenant=tenant, backend_faults=backend_faults
                )
            except ServeClientError:
                continue  # probe ladder will catch up; try the next peer
            if "shed" in out:
                last_shed = out
                continue
            handle = f"{peer.name}:{out['id']}"
            with self._lock:
                self.placements[handle] = {
                    "peer": peer.name, "sid": out["id"], "tenant": tenant,
                }
                self.affinity[tenant] = peer.name
                self.counters["placements"] += 1
            return {**out, "id": handle, "peer": peer.name}
        if last_shed is not None:
            return last_shed
        raise FederationError("no live peer can accept work")

    def locate(self, handle: str) -> tuple[Peer, str]:
        """Resolve a (possibly failed-over) handle to (peer, local sid)."""
        with self._lock:
            placed = self.placements.get(handle)
            if placed is not None:
                peer = self.peers.get(placed["peer"])
                if peer is None:
                    raise FederationError(
                        f"handle {handle!r} placed on unknown peer"
                    )
                return peer, placed["sid"]
            name, sid = split_handle(handle)
            peer = self.peers.get(name)
            if peer is None:
                raise FederationError(f"unknown peer in handle {handle!r}")
            return peer, sid

    # ------------------------------------------------------------------
    # failover (probe thread) + stealing (router rebalance tick)
    # ------------------------------------------------------------------

    def fail_over(self, name: str) -> list[str]:
        """Replay a LOST peer's journal and re-place every unfinished
        sweep onto surviving peers. Handoff intents are journaled before
        each re-place, and re-places carry the original handle as their
        `origin`, so a router crash mid-failover resumes exactly where
        it stopped (recover_handoffs) without duplicating a sweep.
        Returns the re-placed handles."""
        with self._lock:
            peer = self.peers.get(name)
            if peer is None:
                raise FederationError(f"unknown peer {name!r}")
        records = peer.journal_records()  # filesystem I/O: outside lock
        st = journal_mod.JournalState(records)
        unfinished = st.unfinished()
        if unfinished:
            with self._lock:
                self.counters["failovers"] += 1
        moved: list[str] = []
        for s in unfinished:
            handle = f"{name}:{s['id']}"
            if self._handoff_landed(handle):
                continue  # an earlier incarnation already moved it
            self.journal.append(
                journal_mod.HANDOFF, id=handle, from_peer=name,
                to_peer="*failover*",
            )
            placed = self._replace_sweep(handle, s)
            if placed:
                moved.append(handle)
        return moved

    def _replace_sweep(self, handle: str, s: dict) -> bool:
        """Submit a replayed sweep to the best surviving peer, origin
        marker attached. Updates the placement map so the ORIGINAL
        handle keeps resolving. Returns False when no live peer took it
        (the next probe round retries via recover_handoffs)."""
        tenant = s.get("tenant", "default")
        # the handle's source is NOT pre-excluded: a LOST source is
        # already masked by its ladder state, and a live source (steal
        # whose receiver shed) may legitimately re-take the sweep under
        # a fresh sid — its old sid is journaled handed_off
        tried: set[str] = set()
        while True:
            with self._lock:
                peer = self._pick_peer(tenant, exclude=tried)
            if peer is None:
                return False
            tried.add(peer.name)
            try:
                out = peer.client.submit(
                    s["doc"], tenant=tenant,
                    backend_faults=s.get("backend_faults") or None,
                    origin=handle,
                )
            except ServeClientError:
                continue
            if "shed" in out:
                continue
            with self._lock:
                self.placements[handle] = {
                    "peer": peer.name, "sid": out["id"], "tenant": tenant,
                }
                self.affinity[tenant] = peer.name
                self.counters["replayed_sweeps"] += 1
            return True

    def steal_once(self) -> dict | None:
        """One rebalance tick: if some peer sits idle while another has
        ≥ STEAL_MIN_DEPTH queued sweeps, pull the newest queued sweep
        across. Fully journaled: router HANDOFF intent first, then the
        source's own HANDOFF (release), then the receiver's SUBMIT with
        the origin marker — crash anywhere and recover_handoffs settles
        it. Returns {"id", "from", "to"} or None when balanced."""
        with self._lock:
            healthy = [
                p for p in self.peers.values()
                if p.ladder.state == PEER_HEALTHY and p.health
            ]
            idle = [
                p for p in healthy
                if int((p.health.get("queue") or {}).get("depth", 0)) == 0
                and not (p.health.get("queue") or {}).get("running")
                and not p.health.get("draining")
            ]
            loaded = [
                p for p in healthy
                if int((p.health.get("queue") or {}).get("depth", 0))
                >= STEAL_MIN_DEPTH
            ]
            if not idle or not loaded:
                return None
            # steal from the peer with the most predicted queued work
            # (fleet/scheduler.steal_export lifted onto /healthz) — the
            # LPT logic FleetScheduler.pick applies to lanes, applied
            # across daemons
            loaded.sort(
                key=lambda p: (
                    -float(
                        (p.health.get("steal") or {})
                        .get("queued_predicted_load", 0.0)
                    ),
                    -int((p.health.get("queue") or {}).get("depth", 0)),
                    p.name,
                ),
            )
            src, dst = loaded[0], idle[0]
        # which sweep? the NEWEST queued one: the head of the queue is
        # about to start on the loaded peer anyway (sticky cache worth
        # keeping); the tail has the longest wait and loses nothing
        try:
            queued = [
                s for s in src.client.sweeps() if s["status"] == "queued"
            ]
        except ServeClientError:
            return None
        if len(queued) < STEAL_MIN_DEPTH:
            return None  # raced a drain/admit; next tick re-evaluates
        sid = queued[-1]["id"]
        handle = f"{src.name}:{sid}"
        self.journal.append(
            journal_mod.HANDOFF, id=handle, from_peer=src.name,
            to_peer=dst.name,
        )
        try:
            released = src.client.release(sid, to_peer=dst.name)
        except ServeClientError:
            # 409/404/unreachable: nothing left the source queue, so the
            # journaled intent is a no-op (recover_handoffs verifies the
            # source journal and finds no handed_off record)
            return None
        out = dst.client.submit(
            released["doc"], tenant=released.get("tenant", "default"),
            backend_faults=released.get("backend_faults") or None,
            origin=handle,
        )
        if "shed" in out:
            # receiver refused AFTER the source released: recover NOW by
            # re-placing anywhere (the journaled intent + origin marker
            # keep this idempotent)
            self._replace_sweep(handle, released)
            with self._lock:
                self.counters["steals"] += 1
            return {"id": handle, "from": src.name, "to": "*recovered*"}
        with self._lock:
            self.placements[handle] = {
                "peer": dst.name, "sid": out["id"],
                "tenant": released.get("tenant", "default"),
            }
            self.counters["steals"] += 1
        return {"id": handle, "from": src.name, "to": dst.name}

    # ------------------------------------------------------------------
    # crash recovery (router startup)
    # ------------------------------------------------------------------

    def _handoff_landed(self, handle: str) -> bool:
        """Did any live peer journal a SUBMIT with this origin? Probes
        each peer's journal over the wire (outside `_lock`)."""
        with self._lock:
            peers = list(self.peers.values())
        for p in peers:
            try:
                mirror = p.client.journal()
            except ServeClientError:
                records = p.journal_records()
            else:
                records = mirror.get("records", [])
            for rec in records:
                if (rec["type"] == journal_mod.SUBMIT
                        and rec.get("origin") == handle):
                    with self._lock:
                        self.placements[handle] = {
                            "peer": p.name, "sid": rec["id"],
                            "tenant": rec.get("tenant", "default"),
                        }
                    return True
        return False

    def recover_handoffs(self) -> list[str]:
        """Settle every journaled HANDOFF intent after a router restart:
        for each intent, either the receiver journaled the origin-marked
        SUBMIT (done — rebuild the placement map entry), or the source
        shows `handed_off` with no receiver claim (crash mid-steal: the
        doc still rides the source journal, re-place it), or the source
        never released (the intent was a no-op). Never duplicates —
        `_handoff_landed` checks before every re-place and receivers
        refuse duplicate origins — and never drops, because the doc is
        always recoverable from the source's SUBMIT record. Returns the
        handles that needed re-placement."""
        intents = [
            rec for rec in self.journal.records
            if rec["type"] == journal_mod.HANDOFF
        ]
        recovered: list[str] = []
        for rec in intents:
            handle = rec["id"]
            if self._handoff_landed(handle):
                continue
            src_name, sid = split_handle(handle)
            with self._lock:
                src = self.peers.get(src_name)
            if src is None:
                continue
            st = journal_mod.JournalState(src.journal_records())
            s = st.sweeps.get(sid)
            if s is None or s["status"] != "handed_off":
                continue  # release never happened: intent was a no-op
            if self._replace_sweep(handle, s):
                with self._lock:
                    self.counters["handoff_recoveries"] += 1
                recovered.append(handle)
        return recovered

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    def mirror_sweep_info(self, peer: Peer, sid: str) -> dict | None:
        """Fold a dead (or unreachable) peer's journal and serve the
        sweep's last durable state from it: a sweep that COMPLETED on a
        lost box still answers with its results and audit chains,
        because they ride the COMPLETE record the router mirrored."""
        st = journal_mod.JournalState(peer.journal_records())
        s = st.sweeps.get(sid)
        if s is None:
            return None
        info = {k: v for k, v in s.items() if k != "doc"}
        info["from_mirror"] = True
        return info

    def peers_up(self) -> int:
        with self._lock:
            return sum(
                1 for p in self.peers.values()
                if p.ladder.state == PEER_HEALTHY
            )

    def placements_list(self) -> list[dict]:
        """The placement table (GET /v1/sweeps on the router): every
        handle with the peer + local sid it currently resolves to."""
        with self._lock:
            return [
                {"id": h, **placed}
                for h, placed in sorted(self.placements.items())
            ]

    def status_rows(self) -> list[dict]:
        """One row per peer (shadowctl status --peers)."""
        with self._lock:
            rows = []
            for name in sorted(self.peers):
                p = self.peers[name]
                q = p.health.get("queue") or {}
                rows.append({
                    "peer": name,
                    "state": p.ladder.state,
                    "ok": bool(p.health.get("ok")),
                    "depth": int(q.get("depth", 0)),
                    "running": q.get("running"),
                    "retry_after_s": p.health.get("retry_after_s"),
                    "socket": p.socket_path,
                })
            return rows

    def health_doc(self) -> dict:
        with self._lock:
            states = {
                n: p.ladder.state for n, p in self.peers.items()
            }
            up = sum(1 for s in states.values() if s == PEER_HEALTHY)
            suspect = sum(1 for s in states.values() if s == PEER_SUSPECT)
            depths = [
                int((p.health.get("queue") or {}).get("depth", 0))
                for p in self.peers.values()
                if p.ladder.state != PEER_LOST
            ]
            return {
                "ok": up > 0,
                "peers_total": len(self.peers),
                "peers_up": up,
                "peers_suspect": suspect,
                "peers_lost": sum(
                    1 for s in states.values() if s == PEER_LOST
                ),
                "peers": states,
                "placements": len(self.placements),
                "queue_depth_max": max(depths) if depths else 0,
                "queue_depth_min": min(depths) if depths else 0,
                "counters": dict(self.counters),
            }

    def metrics_doc(self) -> dict:
        """Schema-v16 `federation.*` metrics (obs/metrics.py): counters
        for placements / steals / failovers / replayed sweeps, gauges
        for fleet membership and the queue-depth spread the stealer is
        trying to flatten."""
        from shadow_tpu.obs import metrics as obs_metrics

        reg = obs_metrics.MetricsRegistry()
        h = self.health_doc()
        reg.counter_set("federation.placements", h["counters"]["placements"])
        reg.counter_set("federation.steals", h["counters"]["steals"])
        reg.counter_set("federation.failovers", h["counters"]["failovers"])
        reg.counter_set(
            "federation.replayed_sweeps", h["counters"]["replayed_sweeps"]
        )
        reg.counter_set("federation.probes", h["counters"]["probes"])
        reg.counter_set("federation.peers_lost", h["counters"]["peers_lost"])
        reg.counter_set(
            "federation.handoff_recoveries",
            h["counters"]["handoff_recoveries"],
        )
        reg.gauge_set("federation.peers_total", h["peers_total"])
        reg.gauge_set("federation.peers_up", h["peers_up"])
        reg.gauge_set("federation.peers_suspect", h["peers_suspect"])
        reg.gauge_set("federation.placements_tracked", h["placements"])
        reg.gauge_set("federation.queue_depth_max", h["queue_depth_max"])
        reg.gauge_set("federation.queue_depth_min", h["queue_depth_min"])
        return reg.to_doc()
