"""HTTP-over-unix-socket client for the serve daemon.

`tools/shadowctl.py` wraps this for operators; tests and bench.py's
--serve-smoke gate use it directly. Every method returns the decoded
JSON body; `submit` surfaces admission backpressure (HTTP 429) as a
`Shed` exception carrying the daemon's Retry-After hint rather than a
silent retry loop — the CALLER owns the retry policy.
"""

from __future__ import annotations

import http.client
import json
import socket
import time


class ServeClientError(RuntimeError):
    pass


class Shed(ServeClientError):
    """Admission refused the sweep (quota / queue depth / draining)."""

    def __init__(self, body: dict):
        super().__init__(
            f"submission shed ({body.get('shed')}); retry after "
            f"{body.get('retry_after_s')}s"
        )
        self.body = body
        self.retry_after_s = float(body.get("retry_after_s") or 1)


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, path: str, timeout: float):
        super().__init__("localhost", timeout=timeout)
        self._unix_path = path

    def connect(self):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        s.connect(self._unix_path)
        self.sock = s


class ServeClient:
    def __init__(self, socket_path: str, timeout: float = 60.0):
        self.socket_path = socket_path
        self.timeout = float(timeout)

    def request(self, method: str, path: str,
                body: dict | None = None) -> tuple[int, dict]:
        conn = _UnixHTTPConnection(self.socket_path, self.timeout)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            try:
                doc = json.loads(raw.decode() or "{}")
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                raise ServeClientError(
                    f"{method} {path}: non-JSON response ({raw[:120]!r})"
                ) from e
            return resp.status, doc
        except (ConnectionError, socket.timeout, FileNotFoundError,
                OSError) as e:
            raise ServeClientError(
                f"{method} {path}: daemon unreachable at "
                f"{self.socket_path}: {e}"
            ) from e
        finally:
            conn.close()

    # -- typed surface --

    def wait_ready(self, timeout_s: float = 30.0,
                   poll_s: float = 0.1) -> dict:
        """Poll /healthz until the daemon answers (a freshly restarted
        daemon may still be binding; a SIGKILLed one leaves a stale
        socket file, so existence of the path proves nothing). Returns
        the first health document."""
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                return self.health()
            except ServeClientError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(poll_s)

    def health(self) -> dict:
        status, doc = self.request("GET", "/healthz")
        if status != 200:
            raise ServeClientError(f"/healthz returned {status}: {doc}")
        return doc

    def metrics(self) -> dict:
        status, doc = self.request("GET", "/metricz")
        if status != 200:
            raise ServeClientError(f"/metricz returned {status}: {doc}")
        return doc

    def submit(self, sweep_doc: dict, tenant: str = "default",
               backend_faults: list | None = None) -> dict:
        payload: dict = {"sweep": sweep_doc, "tenant": tenant}
        if backend_faults:
            payload["backend_faults"] = backend_faults
        status, doc = self.request("POST", "/v1/sweeps", payload)
        if status == 429:
            raise Shed(doc)
        if status != 200:
            raise ServeClientError(
                f"submit refused ({status}): {doc.get('error', doc)}"
            )
        return doc

    def sweeps(self) -> list[dict]:
        status, doc = self.request("GET", "/v1/sweeps")
        if status != 200:
            raise ServeClientError(f"/v1/sweeps returned {status}")
        return doc["sweeps"]

    def sweep(self, sid: str) -> dict:
        status, doc = self.request("GET", f"/v1/sweeps/{sid}")
        if status == 404:
            raise ServeClientError(doc.get("error", f"no sweep {sid}"))
        return doc

    def drain(self) -> dict:
        status, doc = self.request("POST", "/v1/drain", {})
        if status != 200:
            raise ServeClientError(f"/v1/drain returned {status}")
        return doc

    def wait(self, sid: str, timeout_s: float = 600.0,
             poll_s: float = 0.25) -> dict:
        """Block until the sweep settles (done/failed); returns its final
        info. Raises ServeClientError on timeout — never spins forever
        against a wedged daemon."""
        deadline = time.monotonic() + timeout_s
        while True:
            info = self.sweep(sid)
            if info["status"] in ("done", "failed"):
                return info
            if time.monotonic() >= deadline:
                raise ServeClientError(
                    f"sweep {sid} still {info['status']!r} after "
                    f"{timeout_s:.0f}s"
                )
            time.sleep(poll_s)
