"""HTTP-over-unix-socket client for the serve daemon.

`tools/shadowctl.py` wraps this for operators; tests and bench.py's
--serve-smoke gate use it directly. Every method returns the decoded
JSON body; `submit` surfaces admission backpressure (HTTP 429) as a
`Shed` exception carrying the daemon's Retry-After hint rather than a
silent retry loop — the CALLER owns the retry policy.
"""

from __future__ import annotations

import http.client
import json
import random
import socket
import time


class ServeClientError(RuntimeError):
    pass


class Shed(ServeClientError):
    """Admission refused the sweep (quota / queue depth / draining)."""

    def __init__(self, body: dict):
        super().__init__(
            f"submission shed ({body.get('shed')}); retry after "
            f"{body.get('retry_after_s')}s"
        )
        self.body = body
        self.retry_after_s = float(body.get("retry_after_s") or 1)


class _Refused(Exception):
    """Internal marker: connect() itself failed (daemon restarting or a
    stale socket file) — the one failure mode `ServeClient.request` may
    safely retry, because the daemon never saw the request."""

    def __init__(self, cause: OSError):
        super().__init__(str(cause))
        self.cause = cause


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, path: str, timeout: float):
        super().__init__("localhost", timeout=timeout)
        self._unix_path = path

    def connect(self):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        s.connect(self._unix_path)
        self.sock = s


class ServeClient:
    def __init__(self, socket_path: str, timeout: float = 60.0,
                 retries: int = 0, backoff_base_s: float = 0.1,
                 backoff_cap_s: float = 2.0, sleep=time.sleep):
        """`retries` bounds the in-client retry of CONNECTION-phase
        failures only — `ConnectionRefusedError` and the stale-socket
        `FileNotFoundError` a restarting daemon leaves behind — with
        jittered exponential backoff between attempts. A request that
        reached the daemon is NEVER retried here (a replayed submit
        would double-journal a sweep); the caller owns that policy, as
        it owns the 429 policy. `sleep` is injectable for tests."""
        self.socket_path = socket_path
        self.timeout = float(timeout)
        self.retries = max(0, int(retries))
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self._sleep = sleep
        self._rng = random.Random(0)

    def _retry_wait_s(self, attempt: int) -> float:
        base = min(
            self.backoff_base_s * (2 ** attempt), self.backoff_cap_s
        )
        return base * (0.5 + self._rng.random())  # ±50% decorrelation

    def request(self, method: str, path: str,
                body: dict | None = None) -> tuple[int, dict]:
        for attempt in range(self.retries + 1):
            try:
                return self._request_once(method, path, body)
            except _Refused as e:
                # the daemon never saw this request (connect() failed):
                # a bounded, jittered retry rides out a restart window
                # instead of surfacing a bare traceback (shadowctl)
                if attempt >= self.retries:
                    raise ServeClientError(
                        f"{method} {path}: daemon unreachable at "
                        f"{self.socket_path} after "
                        f"{self.retries + 1} attempt(s): {e.cause}"
                    ) from e.cause
                self._sleep(self._retry_wait_s(attempt))
        raise AssertionError("unreachable")  # pragma: no cover

    def _request_once(self, method: str, path: str,
                      body: dict | None) -> tuple[int, dict]:
        conn = _UnixHTTPConnection(self.socket_path, self.timeout)
        try:
            try:
                conn.connect()
            except (ConnectionRefusedError, FileNotFoundError) as e:
                # refused / stale socket: the retryable restart window
                raise _Refused(e) from e
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            try:
                doc = json.loads(raw.decode() or "{}")
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                raise ServeClientError(
                    f"{method} {path}: non-JSON response ({raw[:120]!r})"
                ) from e
            return resp.status, doc
        except (ConnectionError, socket.timeout, FileNotFoundError,
                OSError) as e:
            raise ServeClientError(
                f"{method} {path}: daemon unreachable at "
                f"{self.socket_path}: {e}"
            ) from e
        finally:
            conn.close()

    # -- typed surface --

    def wait_ready(self, timeout_s: float = 30.0,
                   poll_s: float = 0.1) -> dict:
        """Poll /healthz until the daemon answers (a freshly restarted
        daemon may still be binding; a SIGKILLed one leaves a stale
        socket file, so existence of the path proves nothing). Returns
        the first health document."""
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                return self.health()
            except ServeClientError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(poll_s)

    def health(self) -> dict:
        status, doc = self.request("GET", "/healthz")
        if status != 200:
            raise ServeClientError(f"/healthz returned {status}: {doc}")
        return doc

    def metrics(self) -> dict:
        status, doc = self.request("GET", "/metricz")
        if status != 200:
            raise ServeClientError(f"/metricz returned {status}: {doc}")
        return doc

    def timez(self) -> dict:
        """The daemon's live profile document (obs/prof.py): interval
        ring + mergeable latency histograms. The federation router
        merges these across peers on its own /timez."""
        status, doc = self.request("GET", "/timez")
        if status != 200:
            raise ServeClientError(f"/timez returned {status}: {doc}")
        return doc

    def submit(self, sweep_doc: dict, tenant: str = "default",
               backend_faults: list | None = None,
               origin: str | None = None) -> dict:
        payload: dict = {"sweep": sweep_doc, "tenant": tenant}
        if backend_faults:
            payload["backend_faults"] = backend_faults
        if origin is not None:
            payload["origin"] = origin
        status, doc = self.request("POST", "/v1/sweeps", payload)
        if status == 429:
            raise Shed(doc)
        if status != 200:
            raise ServeClientError(
                f"submit refused ({status}): {doc.get('error', doc)}"
            )
        return doc

    def sweeps(self) -> list[dict]:
        status, doc = self.request("GET", "/v1/sweeps")
        if status != 200:
            raise ServeClientError(f"/v1/sweeps returned {status}")
        return doc["sweeps"]

    def sweep(self, sid: str) -> dict:
        status, doc = self.request("GET", f"/v1/sweeps/{sid}")
        if status != 200:
            raise ServeClientError(
                doc.get("error", f"sweep {sid}: HTTP {status}")
            )
        return doc

    def journal(self) -> dict:
        """The daemon's journal mirror: {"records": [...],
        "torn_tail_dropped": bool}. The federation router pulls this on
        every probe so it can replay a lost peer even when that peer's
        state-dir died with its box."""
        status, doc = self.request("GET", "/v1/journal")
        if status != 200:
            raise ServeClientError(f"/v1/journal returned {status}")
        return doc

    def release(self, sid: str, to_peer: str) -> dict:
        """Ask the daemon to hand queued sweep `sid` to `to_peer` (work
        stealing). Returns the released sweep document on success;
        raises Shed on 409 (the sweep already started — running work is
        never stolen) and ServeClientError on 404."""
        status, doc = self.request(
            "POST", f"/v1/sweeps/{sid}/release", {"to_peer": to_peer}
        )
        if status == 404:
            raise ServeClientError(doc.get("error", f"no sweep {sid}"))
        if status == 409:
            raise Shed({"shed": "busy", "retry_after_s": 1, **doc})
        if status != 200:
            raise ServeClientError(f"release {sid} returned {status}")
        return doc

    def drain(self) -> dict:
        status, doc = self.request("POST", "/v1/drain", {})
        if status != 200:
            raise ServeClientError(f"/v1/drain returned {status}")
        return doc

    def wait(self, sid: str, timeout_s: float = 600.0,
             poll_s: float = 0.25) -> dict:
        """Block until the sweep settles (done/failed); returns its final
        info. Raises ServeClientError on timeout — never spins forever
        against a wedged daemon."""
        deadline = time.monotonic() + timeout_s
        while True:
            info = self.sweep(sid)
            if info["status"] in ("done", "failed"):
                return info
            if time.monotonic() >= deadline:
                raise ServeClientError(
                    f"sweep {sid} still {info['status']!r} after "
                    f"{timeout_s:.0f}s"
                )
            time.sleep(poll_s)
