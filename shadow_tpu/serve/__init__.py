"""Sim-as-a-service: the crash-safe fleet daemon (docs/serving.md).

- `serve.daemon`   the resident multi-tenant daemon (journaled queue,
                   graceful drain, admission quotas, /healthz)
- `serve.journal`  write-ahead job journal (CRC-framed, fsync'd, replay)
- `serve.kcache`   AOT window-kernel cache (jax.export artifacts keyed
                   by config digest / gear / avals / jaxlib version)
- `serve.client`   HTTP-over-unix-socket client (tools/shadowctl.py)
"""

from shadow_tpu.serve.journal import Journal, JournalError, JournalState
from shadow_tpu.serve.kcache import (
    KernelCache,
    cache_root,
    kernel_config_digest,
    sweep_corrupt_entries,
)

__all__ = [
    "Journal",
    "JournalError",
    "JournalState",
    "KernelCache",
    "cache_root",
    "kernel_config_digest",
    "sweep_corrupt_entries",
]
