"""Sim-as-a-service: the crash-safe fleet daemon (docs/serving.md).

- `serve.daemon`     the resident multi-tenant daemon (journaled queue,
                     graceful drain, admission quotas, /healthz)
- `serve.journal`    write-ahead job journal (CRC-framed, fsync'd, replay)
- `serve.kcache`     AOT window-kernel cache (jax.export artifacts keyed
                     by config digest / gear / avals / jaxlib version)
- `serve.client`     HTTP-over-unix-socket client (tools/shadowctl.py)
- `serve.federation` N-daemon peer table: placement, probe ladders,
                     journal-replay failover, journaled work stealing
- `serve.router`     the federation front process
                     (`python -m shadow_tpu route --peers ...`)
"""

from shadow_tpu.serve.journal import Journal, JournalError, JournalState
from shadow_tpu.serve.kcache import (
    KernelCache,
    cache_root,
    kernel_config_digest,
    sweep_corrupt_entries,
)

__all__ = [
    "Journal",
    "JournalError",
    "JournalState",
    "KernelCache",
    "cache_root",
    "kernel_config_digest",
    "sweep_corrupt_entries",
    "Federation",
    "FederationError",
    "ShadowRouter",
]


def __getattr__(name):
    # federation/router import the client + supervisor stacks; keep the
    # base package import light (journal replay tools shouldn't pull in
    # HTTP machinery) by resolving these lazily
    if name in ("Federation", "FederationError"):
        from shadow_tpu.serve import federation as _federation

        return getattr(_federation, name)
    if name == "ShadowRouter":
        from shadow_tpu.serve import router as _router

        return _router.ShadowRouter
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
