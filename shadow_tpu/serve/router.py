"""The federation router: `python -m shadow_tpu route --peers ...`.

A thin placement front for N serve daemons (serve/federation.py owns
the brain; this module owns the process). Same serving surface as the
daemon — HTTP over a unix socket — so `tools/shadowctl.py` talks to a
router exactly as it talks to a single daemon, sweep handles are just
`peer:sid` instead of `sid`:

    GET  /healthz            federation posture: peers_up/peers_total,
                             per-peer ladder states, queue spread
    GET  /metricz            schema-v16 `federation.*` metrics document
    GET  /v1/sweeps          placement table (handles -> peer + sid)
    GET  /v1/sweeps/<h>      proxied sweep info from the owning peer
                             (follows failover remaps transparently)
    GET  /v1/journal         the ROUTER's journal (REGISTER + HANDOFF)
    POST /v1/sweeps          place a sweep on the best peer (429 body
                             proxied through when every peer sheds)
    POST /v1/drain           stop the probe loop and exit

Threads: HTTP handlers (placement + reads) run on the server's thread
pool; the main loop is the supervising thread — probe ladder ticks,
failover, steal ticks and every router-journal append happen THERE,
so the journal has a single writer. `drain()` runs from signal
handlers on the main thread and uses the same bounded-acquire idiom
as the daemon (STH004).

The router restarts under the same contract it enforces: its journal
replays the peer table and every in-flight handoff intent
(`Federation.recover_handoffs`), so a router crash mid-steal or
mid-failover never duplicates or drops a sweep.
"""

from __future__ import annotations

import json
import os
import signal
import socketserver
import threading
import time
from http.server import BaseHTTPRequestHandler

from shadow_tpu.serve import journal as journal_mod
from shadow_tpu.serve.federation import Federation, FederationError

ROUTER_JOURNAL_NAME = "router.wal"
ROUTER_METRICS_NAME = "router.metrics.json"


class RouterOptions:
    def __init__(
        self,
        state_dir: str,
        peers: list[str],
        socket_path: str | None = None,
        probe_interval_s: float = 1.0,
        lost_after: int = 3,
        steal: bool = True,
        seed: int = 0,
    ):
        self.state_dir = os.path.abspath(state_dir)
        self.peers = list(peers)
        self.socket_path = socket_path or os.path.join(
            self.state_dir, "route.sock"
        )
        self.probe_interval_s = float(probe_interval_s)
        self.lost_after = int(lost_after)
        self.steal = bool(steal)
        self.seed = int(seed)


class ShadowRouter:
    def __init__(self, opts: RouterOptions, *, client_factory=None):
        os.makedirs(opts.state_dir, exist_ok=True)
        self.opts = opts
        self.journal = journal_mod.Journal(
            os.path.join(opts.state_dir, ROUTER_JOURNAL_NAME)
        )
        self.federation = Federation(
            opts.peers,
            self.journal,
            lost_after=opts.lost_after,
            probe_interval_s=opts.probe_interval_s,
            seed=opts.seed,
            client_factory=client_factory,
        )
        self._draining = threading.Event()
        self._server: socketserver.ThreadingMixIn | None = None
        self._started = threading.Event()

    # ------------------------------------------------------------------
    # introspection (HTTP threads — no journal appends here)
    # ------------------------------------------------------------------

    def health(self) -> dict:
        doc = self.federation.health_doc()
        doc["draining"] = self._draining.is_set()
        doc["ok"] = doc["ok"] and not self._draining.is_set()
        return doc

    def journal_doc(self) -> dict:
        return {
            "records": self.journal.records,
            "torn_tail_dropped": self.journal.torn_tail_dropped,
        }

    def placements_list(self) -> list[dict]:
        return self.federation.placements_list()

    def sweep_info(self, handle: str) -> tuple[int, dict]:
        """Proxy a sweep read to the peer that currently owns it."""
        from shadow_tpu.serve.client import ServeClientError

        try:
            peer, sid = self.federation.locate(handle)
        except FederationError as e:
            return 404, {"error": str(e)}
        try:
            info = peer.client.sweep(sid)
        except ServeClientError as e:
            # dead / unreachable peer: serve the sweep's last durable
            # state from the mirrored journal — a sweep that completed
            # on a lost box still answers with its results
            info = self.federation.mirror_sweep_info(peer, sid)
            if info is None:
                return 503, {"error": str(e), "peer": peer.name}
        info["id"] = handle  # the federation handle, not the local sid
        info["peer"] = peer.name
        return 200, info

    def timez_doc(self) -> dict:
        """Federation /timez: every reachable peer's profile document
        merged into one — histograms folded exactly (int64 adds), rings
        interleaved onto one wall clock (obs/prof.merge_profile_docs).
        Unreachable or stale-schema peers are skipped and listed, never
        allowed to poison the fold."""
        from shadow_tpu.obs import prof as prof_mod
        from shadow_tpu.serve.client import ServeClientError

        docs: dict[str, dict] = {}
        skipped: dict[str, str] = {}
        for name, peer in sorted(self.federation.peers.items()):
            try:
                doc = peer.client.timez()
                prof_mod.validate_profile_doc(doc)
                docs[name] = doc
            except (ServeClientError, ValueError) as e:
                skipped[name] = str(e)
        merged = prof_mod.merge_profile_docs(docs)
        merged["peers_merged"] = len(docs)
        if skipped:
            merged["peers_skipped"] = skipped
        return merged

    def _dump_metrics(self) -> None:
        from shadow_tpu.obs.metrics import dump_json_atomic

        doc = self.federation.metrics_doc()
        path = os.path.join(self.opts.state_dir, ROUTER_METRICS_NAME)
        dump_json_atomic(path, doc)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def drain(self) -> None:
        """Stop routing: runs from signal handlers ON the main (probe)
        thread, so only the Event is touched — the probe loop checks it
        every tick and exits; no lock is taken here at all (STH003)."""
        self._draining.set()

    def _probe_loop(self) -> None:
        """The supervising thread: probe ladders, failover, steal ticks
        and metrics dumps — the single writer of the router journal."""
        while not self._draining.is_set():
            t0 = time.monotonic()
            self.federation.probe_once()
            if self.opts.steal:
                self.federation.steal_once()
            self._dump_metrics()
            # sleep the remainder of the probe interval in short slices
            # so a drain never waits a full interval to take effect
            while (not self._draining.is_set()
                   and time.monotonic() - t0 < self.opts.probe_interval_s):
                time.sleep(0.05)

    def _make_server(self):
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def address_string(self):  # pragma: no cover - logging only
                return "unix"

            def log_message(self, *a):  # quiet by default
                pass

            def _reply(self, code: int, body: dict,
                       headers: dict | None = None) -> None:
                blob = (json.dumps(body) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(blob)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(blob)

            def do_GET(self):
                if self.path == "/healthz":
                    return self._reply(200, router.health())
                if self.path == "/metricz":
                    return self._reply(200, router.federation.metrics_doc())
                if self.path == "/timez":
                    return self._reply(200, router.timez_doc())
                if self.path == "/v1/journal":
                    return self._reply(200, router.journal_doc())
                if self.path == "/v1/sweeps":
                    return self._reply(
                        200, {"sweeps": router.placements_list()}
                    )
                if self.path.startswith("/v1/sweeps/"):
                    handle = self.path.rsplit("/", 1)[-1]
                    code, doc = router.sweep_info(handle)
                    return self._reply(code, doc)
                return self._reply(404, {"error": "unknown path"})

            def do_POST(self):
                from shadow_tpu.serve.client import ServeClientError
                from shadow_tpu.serve.daemon import ServeError

                n = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(n) if n else b"{}"
                try:
                    payload = json.loads(raw.decode() or "{}")
                except (UnicodeDecodeError, json.JSONDecodeError):
                    return self._reply(400, {"error": "body is not JSON"})
                if self.path == "/v1/drain":
                    router.drain()
                    return self._reply(200, {"draining": True})
                if self.path == "/v1/sweeps":
                    if router._draining.is_set():
                        return self._reply(
                            429,
                            {"shed": "draining", "retry_after_s": 30},
                            headers={"Retry-After": "30"},
                        )
                    doc = payload.get("sweep")
                    if not isinstance(doc, dict):
                        return self._reply(
                            400,
                            {"error": "payload needs a `sweep` document"},
                        )
                    try:
                        out = router.federation.place(
                            doc,
                            tenant=str(payload.get("tenant", "default")),
                            backend_faults=payload.get("backend_faults"),
                        )
                    except FederationError as e:
                        return self._reply(503, {"error": str(e)})
                    except ServeClientError as e:
                        # a ServeError on the peer surfaces as a client
                        # error string; proxy the 400 through
                        return self._reply(400, {"error": str(e)})
                    except ServeError as e:  # pragma: no cover - local
                        return self._reply(400, {"error": str(e)})
                    if "shed" in out:
                        return self._reply(
                            429, out,
                            headers={
                                "Retry-After": str(out["retry_after_s"]),
                            },
                        )
                    return self._reply(200, out)
                return self._reply(404, {"error": "unknown path"})

        class Server(socketserver.ThreadingMixIn,
                     socketserver.UnixStreamServer):
            daemon_threads = True
            allow_reuse_address = True

        sock = self.opts.socket_path
        os.makedirs(os.path.dirname(os.path.abspath(sock)), exist_ok=True)
        if os.path.exists(sock):
            os.unlink(sock)  # stale socket from a killed incarnation
        return Server(sock, Handler)

    def serve_forever(self, install_signals: bool = True) -> int:
        """Run until drained (SIGTERM / POST /v1/drain). Returns 0 on a
        graceful exit."""
        recovered = self.federation.recover_handoffs()
        self._server = self._make_server()
        if install_signals:
            signal.signal(signal.SIGTERM, lambda *_: self.drain())
            signal.signal(signal.SIGINT, lambda *_: self.drain())
        th = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        th.start()
        self._started.set()
        print(
            f"route: listening on {self.opts.socket_path} "
            f"({len(self.federation.peers)} peer(s), "
            f"{len(recovered)} handoff(s) recovered)",
            flush=True,
        )
        try:
            self._probe_loop()
        finally:
            self._server.shutdown()
            self._server.server_close()
            try:
                os.unlink(self.opts.socket_path)
            except OSError:
                pass
            self._dump_metrics()
            self.journal.close()
        print("route: drained, exiting", flush=True)
        return 0


# ----------------------------------------------------------------------
# CLI (python -m shadow_tpu route ...)
# ----------------------------------------------------------------------


def build_parser():
    import argparse

    p = argparse.ArgumentParser(
        prog="shadow_tpu route",
        description=(
            "federation router: place sweeps across serve daemons, "
            "replay a lost peer's journal onto the survivors"
        ),
    )
    p.add_argument(
        "--state-dir", required=True,
        help="router state root: router.wal journal + metrics dump",
    )
    p.add_argument(
        "--peers", required=True, nargs="+", metavar="SPEC",
        help=(
            "federation members, NAME=STATE_DIR or bare STATE_DIR "
            "(socket assumed at <state_dir>/serve.sock)"
        ),
    )
    p.add_argument(
        "--socket", default=None,
        help="unix socket for the HTTP API "
             "(default <state-dir>/route.sock)",
    )
    p.add_argument(
        "--probe-interval", type=float, default=1.0, metavar="S",
        help="seconds between peer health probes (default 1.0)",
    )
    p.add_argument(
        "--lost-after", type=int, default=3, metavar="N",
        help="consecutive missed probes before a peer is declared "
             "lost and failed over (default 3)",
    )
    p.add_argument(
        "--no-steal", action="store_true",
        help="disable idle-peer work stealing (placement + failover only)",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        router = ShadowRouter(RouterOptions(
            state_dir=args.state_dir,
            peers=args.peers,
            socket_path=args.socket,
            probe_interval_s=args.probe_interval,
            lost_after=args.lost_after,
            steal=not args.no_steal,
        ))
    except FederationError as e:
        print(f"route: {e}", flush=True)
        return 2
    return router.serve_forever()
