"""The sim-as-a-service daemon: a crash-safe, multi-tenant fleet host.

``python -m shadow_tpu serve --state-dir DIR`` starts a resident process
that accepts sweep jobs over a local HTTP-over-unix-socket API
(tools/shadowctl.py is the operator client) and owns a fleet scheduler
across restarts. Three mechanisms make its own death a non-event:

1. **Write-ahead journal** (serve/journal.py): every scheduler
   transition — submit, admit, drain, requeue, complete — is fsync'd to
   an append-only CRC-framed log before it takes effect. `kill -9` the
   daemon, restart it, and replay re-queues unfinished sweeps and
   re-attaches in-flight fleets via their checkpoint directories; the
   finished sweep's per-job audit digest chains are bit-identical to an
   uninterrupted run (tests/test_serve.py, bench.py --serve-smoke).

2. **AOT kernel cache** (serve/kcache.py): fleet window kernels bind
   from serialized exports keyed by (config digest, gear, avals, jaxlib
   version). A warm restart re-binds every known fleet shape with ZERO
   Python traces — `kernel_traces` stays 0 — and a corrupt or
   version-skewed entry is evicted and recompiled, never trusted.

3. **Graceful degradation**: SIGTERM drains the running fleet to its
   checkpoint (one dispatch of latency, then a clean exit whose journal
   DRAIN record lets the next boot resume); admission applies per-tenant
   quotas, queue-depth backpressure AND memory-aware preflight (a sweep
   whose estimated HBM footprint exceeds the live headroom —
   core/pressure.estimate_config_bytes vs device_memory_budget — sheds
   HTTP 429 `memory_pressure` instead of OOMing mid-run); `/healthz`
   reports backend liveness (the supervisor probe of core/supervisor.py
   — the cs/0409032 bounded-lag signal), queue depth, journal lag, the
   memory-headroom gauges, and the running fleet's pressure-ladder
   posture (journaled as PRESSURE records as rungs fire). Backend loss mid-sweep rides the PR-6 supervision
   plane: the fleet drains, the sweep is journaled REQUEUE, and the
   worker retries it — `kill_backend` fault plans submitted with a sweep
   drive this end to end in chaos tests.

Metrics ride the schema-v8 `serve.*` + `pressure.*` namespaces
(obs/metrics.py), dumped
to `<state-dir>/serve.metrics.json` at every sweep settlement.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import socketserver
import threading
import time
from http.server import BaseHTTPRequestHandler

from shadow_tpu.serve import journal as journal_mod
from shadow_tpu.serve.kcache import KernelCache, cache_root

JOURNAL_NAME = "journal.wal"
METRICS_NAME = "serve.metrics.json"

# EWMA seed for the Retry-After estimate before any sweep has finished
_DEFAULT_SWEEP_WALL_S = 30.0
_EWMA_ALPHA = 0.3


class ServeError(ValueError):
    pass


class ServeOptions:
    """Daemon configuration (CLI flags / ServeOptions kwargs)."""

    def __init__(
        self,
        state_dir: str,
        socket_path: str | None = None,
        lanes: int | None = None,
        max_queue_depth: int = 16,
        default_quota: int = 8,
        tenant_quotas: dict[str, int] | None = None,
        checkpoint_every_dispatches: int = 4,
        cache_dir: str | None = None,
    ):
        self.state_dir = os.path.abspath(state_dir)
        self.socket_path = socket_path or os.path.join(
            self.state_dir, "serve.sock"
        )
        self.lanes = lanes
        self.max_queue_depth = int(max_queue_depth)
        self.default_quota = int(default_quota)
        self.tenant_quotas = dict(tenant_quotas or {})
        self.checkpoint_every_dispatches = max(
            1, int(checkpoint_every_dispatches)
        )
        self.cache_dir = cache_dir or cache_root()


class ShadowDaemon:
    """One resident daemon: journal + queue + worker + API server."""

    def __init__(self, opts: ServeOptions):
        self.opts = opts
        os.makedirs(opts.state_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._draining = threading.Event()
        self.journal = journal_mod.Journal(
            os.path.join(opts.state_dir, JOURNAL_NAME)
        )
        self.kcache = KernelCache(opts.cache_dir)
        self.counters = {
            "sweeps_submitted": 0,
            "sweeps_completed": 0,
            "sweeps_failed": 0,
            "sweeps_requeued": 0,
            "sweeps_drained": 0,
            "jobs_completed": 0,
            "sheds": 0,
            "memory_sheds": 0,
            "sweeps_handed_off": 0,
            "pressure_records": 0,
            "balance_records": 0,
            "journal_replays": 0,
            "kernel_traces": 0,
        }
        # memory-aware admission (core/pressure.py, docs/serving.md): the
        # running sweep's preflight HBM estimate, compared against the
        # device budget when one is known; updated on admit/settle. The
        # last published pressure-ladder posture rides /healthz.
        self._running_est_bytes = 0
        self._last_pressure: dict = {}
        self._journaled_pressure: dict[str, int] = {}
        # self-balancing plane (ISSUE 11): the running fleet's balance
        # posture (lane steals, packing decisions) + async posture
        # (frontier spread, laggard shard) for /healthz and shadowctl
        # status; BALANCE journal records mirror the PRESSURE pattern
        self._last_balance: dict = {}
        # elastic mesh posture (schema v12): chips up/total + last
        # relayout from the running fleet, for /healthz + /metricz and
        # the surviving-chip admission budget scale
        self._last_mesh: dict = {}
        self._last_async: dict = {}
        # the running fleet's lane-steal posture lifted for the router
        # (fleet/scheduler.steal_export): queued predicted load is the
        # cross-daemon steal ordering signal (serve/federation.py)
        self._last_steal: dict = {}
        self._journaled_balance: dict[str, int] = {}
        # replay: fold the journal into scheduler-plane truth
        st = self.journal.state()
        self.sweeps: dict[str, dict] = {
            sid: dict(st.sweeps[sid]) for sid in st.order
        }
        self._order: list[str] = list(st.order)
        self._queue: list[str] = [s["id"] for s in st.unfinished()]
        if self._queue or self.journal.torn_tail_dropped:
            self.counters["journal_replays"] = 1
        self._seq = len(self._order)
        self._running: str | None = None
        self._avg_sweep_wall_s = _DEFAULT_SWEEP_WALL_S
        self._server: socketserver.ThreadingMixIn | None = None
        self._started = threading.Event()
        # shadowscope profiling plane (obs/prof.py): request-latency
        # histograms + a per-dispatch-slice interval ring ticked from
        # the running fleet, served live at GET /timez and rolled up
        # across peers by the federation router. Guarded by self._lock
        # (the recorder itself is not thread-safe).
        from shadow_tpu.obs import prof as prof_mod

        self.prof = prof_mod.ProfRecorder()

    # ------------------------------------------------------------------
    # admission (HTTP thread)
    # ------------------------------------------------------------------

    def _tenant_load(self, tenant: str) -> int:
        return sum(
            1 for s in self.sweeps.values()
            if s["tenant"] == tenant
            and s["status"] in ("queued", "running", "drained")
        )

    def retry_after_s(self) -> int:
        """Backpressure hint: how long until a queue slot likely frees —
        queue depth (sweeps ahead) x the EWMA completed-sweep wall.
        Zero when the daemon is idle: an empty queue has no wait, and
        the federation router's placement score must see an idle peer
        as immediately available, not penalized by its sweep-wall EWMA."""
        depth = len(self._queue) + (1 if self._running else 0)
        if depth == 0:
            return 0
        return max(1, int(round(depth * self._avg_sweep_wall_s)))

    def _shed_retry_after_s(self) -> int:
        """Retry hint for SHED responses: never 0 — a rejected client
        told to retry in 0 s would hot-spin against the same refusal.
        Only /healthz reports the raw 0-at-idle value, for placement."""
        return max(1, self.retry_after_s())

    def _effective_budget(self):
        """The admission memory budget, scaled to the SURVIVING mesh
        (schema v12): a fleet degraded to 7 of 8 chips holds 7 chips'
        HBM, so admission must not fill the dead chip's share — budget ×
        chips_up / chips_total whenever the mesh posture reports a
        loss. None when the backend reports no limit."""
        from shadow_tpu.core import pressure as pressure_mod

        budget = pressure_mod.device_memory_budget()
        m = self._last_mesh
        if (budget is not None and m
                and int(m.get("chips_total", 0)) > 0):
            budget = (budget * int(m.get("chips_up", 0))
                      ) // int(m["chips_total"])
        return budget

    def _memory_view(self) -> dict:
        """The /healthz memory-headroom gauges (docs/serving.md): device
        budget (scaled to the surviving mesh), the running sweep's
        preflight estimate, and live headroom (nulls when the backend
        reports no limit)."""
        budget = self._effective_budget()
        return {
            "budget_bytes": budget,
            "estimated_running_bytes": int(self._running_est_bytes),
            "headroom_bytes": (
                budget - int(self._running_est_bytes)
                if budget is not None else None
            ),
        }

    @staticmethod
    def _estimate_sweep_bytes(jobs, lanes) -> int:
        """Preflight footprint of a sweep: per-job config estimate x the
        lane count it will occupy (core/pressure.estimate_config_bytes)."""
        from shadow_tpu.core.config import load_config
        from shadow_tpu.core import pressure as pressure_mod

        cfg = load_config(jobs[0].config)
        L = min(len(jobs), lanes) if lanes else len(jobs)
        return pressure_mod.estimate_config_bytes(cfg, lanes=L)

    def submit(self, doc: dict, tenant: str = "default",
               backend_faults: list | None = None,
               origin: str | None = None) -> dict:
        """Validate + journal + enqueue one sweep. Raises ServeError
        (HTTP 400) on a bad document; returns {"shed": ...} (HTTP 429)
        when admission refuses it.

        `origin` is the federation handoff marker (serve/federation.py):
        a sweep re-placed here after a steal or peer-loss failover
        carries its origin handle, journaled with the SUBMIT record, so
        the router's crash recovery can prove the handoff landed instead
        of re-submitting it (the no-duplicate half of the steal
        contract). A sweep with an origin already present in this
        journal is refused as a duplicate."""
        from shadow_tpu.fleet import SweepError, load_sweep

        with self._lock:
            if self._draining.is_set():
                self.counters["sheds"] += 1
                return {"shed": "draining", "retry_after_s": 30}
            if origin is not None:
                for s in self.sweeps.values():
                    if s.get("origin") == origin:
                        # handoff replayed by the router's crash
                        # recovery: the first landing is the claim
                        return {"id": s["id"], "duplicate": True}
            depth = len(self._queue) + (1 if self._running else 0)
            if depth >= self.opts.max_queue_depth:
                self.counters["sheds"] += 1
                return {
                    "shed": "queue_full",
                    "queue_depth": depth,
                    "retry_after_s": self._shed_retry_after_s(),
                }
            quota = self.opts.tenant_quotas.get(
                tenant, self.opts.default_quota
            )
            if self._tenant_load(tenant) >= quota:
                self.counters["sheds"] += 1
                return {
                    "shed": "tenant_quota",
                    "quota": quota,
                    "retry_after_s": self._shed_retry_after_s(),
                }
        # expansion/validation is pure host work: do it OUTSIDE the lock
        # (a slow config build must not block /healthz), and fail the
        # submission here with the offending job named — never mid-fleet
        try:
            jobs, sweep_opts = load_sweep(doc)
        except (SweepError, ValueError) as e:
            raise ServeError(str(e)) from e
        if backend_faults:
            from shadow_tpu.faults import plan as plan_mod

            # kill_chip targets bounds-check against the sweep's own
            # mesh size (experimental.num_shards; None = no mesh, any
            # kill_chip is then refused by the range check at size 0).
            # A bad plan is a CLIENT error: fold it into ServeError so
            # the HTTP layer answers 400 instead of the handler thread
            # dying connection-open (pre-elastic the same escape killed
            # the thread on any malformed backend_faults list).
            exp = (jobs[0].config.get("experimental") or {})
            mesh_size = int(exp.get("num_shards", 1) or 1)
            try:
                plan_mod.check_backend_ops(
                    plan_mod.parse_fault_plan(backend_faults),
                    mesh_size=mesh_size if mesh_size > 1 else None,
                )
            except plan_mod.FaultPlanError as e:
                raise ServeError(f"backend_faults: {e}") from e
        # memory-aware admission (docs/serving.md): preflight the sweep's
        # HBM footprint against the live headroom — a sweep the device
        # cannot place sheds NOW with a 429, instead of OOMing mid-run
        lanes = self.opts.lanes or (
            int(sweep_opts["lanes"]) if sweep_opts.get("lanes") else None
        )
        try:
            est_bytes = self._estimate_sweep_bytes(jobs, lanes)
        except (ValueError, OSError):
            est_bytes = 0  # advisory: a truly bad config failed above
        budget = self._effective_budget()
        with self._lock:
            if budget is not None \
                    and est_bytes > budget - self._running_est_bytes:
                self.counters["sheds"] += 1
                self.counters["memory_sheds"] += 1
                return {
                    "shed": "memory_pressure",
                    "estimated_bytes": int(est_bytes),
                    "headroom_bytes": int(
                        budget - self._running_est_bytes
                    ),
                    "retry_after_s": self._shed_retry_after_s(),
                }
            sid = f"s{self._seq:06d}"
            self._seq += 1
            extra = {"origin": origin} if origin is not None else {}
            self.journal.append(
                journal_mod.SUBMIT, id=sid, tenant=tenant, doc=doc,
                backend_faults=backend_faults or [], **extra,
            )
            self.sweeps[sid] = {
                "id": sid, "tenant": tenant, "doc": doc,
                "status": "queued", "ckpt_dir": None, "results": None,
                "admits": 0, "backend_faults": backend_faults or [],
                **extra,
            }
            self._order.append(sid)
            self._queue.append(sid)
            self.counters["sweeps_submitted"] += 1
            self._wake.notify_all()
            return {"id": sid, "jobs": len(jobs),
                    "queue_position": len(self._queue) - 1}

    def release_sweep(self, sid: str, to_peer: str) -> dict | None:
        """Hand a QUEUED sweep to another federation member (the router's
        work-steal / rebalance pull, serve/federation.py). The HANDOFF
        record is journaled BEFORE the sweep leaves the queue — the
        torn-tail discipline of PR 8 applied to stealing: a crash after
        this append can never run the sweep here again (replay folds
        `handed_off`, which `unfinished()` skips), and a crash BEFORE it
        leaves nothing for the receiver to duplicate. Returns the full
        journaled document (the receiver re-submits it under its own
        journal); None when the sweep is unknown, and a `busy` marker
        when it is not queued (running/settled sweeps are never stolen —
        their checkpoints live in THIS daemon's state-dir)."""
        with self._lock:
            s = self.sweeps.get(sid)
            if s is None:
                return None
            if s["status"] != "queued" or sid not in self._queue:
                return {"busy": s["status"]}
            self.journal.append(
                journal_mod.HANDOFF, id=sid, to_peer=str(to_peer),
            )
            self._queue.remove(sid)
            s["status"] = "handed_off"
            s["handoff_to"] = str(to_peer)
            self.counters["sweeps_handed_off"] += 1
            return {
                "id": sid, "tenant": s["tenant"], "doc": s["doc"],
                "backend_faults": s.get("backend_faults") or [],
            }

    # ------------------------------------------------------------------
    # introspection (HTTP thread)
    # ------------------------------------------------------------------

    def journal_doc(self) -> dict:
        """The journal as JSON (GET /v1/journal): the peer-to-peer
        journal copy the federation router mirrors on every probe, so a
        peer whose state-dir becomes unreadable with the box can still
        be replayed from the router's last mirror."""
        with self._lock:
            return {
                "records": self.journal.records,
                "torn_tail_dropped": self.journal.torn_tail_dropped,
            }

    def health(self) -> dict:
        from shadow_tpu.core.supervisor import probe_backend

        import jax

        probe_ok = probe_backend()
        with self._lock:
            by_status: dict[str, int] = {}
            for s in self.sweeps.values():
                by_status[s["status"]] = by_status.get(s["status"], 0) + 1
            return {
                "ok": probe_ok and not self._draining.is_set(),
                "draining": self._draining.is_set(),
                "backend": {
                    "platform": jax.default_backend(),
                    "probe_ok": probe_ok,
                },
                "queue": {
                    "depth": len(self._queue),
                    "running": self._running,
                    "sweeps": by_status,
                },
                "journal": {
                    "records": len(self.journal.records),
                    "lag": self.journal.lag(),
                    "torn_tail_dropped": self.journal.torn_tail_dropped,
                },
                "kcache": self.kcache.stats(),
                "memory": self._memory_view(),
                "pressure": dict(self._last_pressure),
                "balance": dict(self._last_balance),
                "async": dict(self._last_async),
                "mesh": dict(self._last_mesh),
                "steal": dict(self._last_steal),
                "prof": self._prof_posture(),
                "retry_after_s": self.retry_after_s(),
            }

    def _prof_posture(self) -> dict:
        """Critical-path posture for /healthz (caller holds the lock):
        which shard the running fleet's wall is attributable to and the
        blocked fraction of all shard-supersteps; -1/0.0 before any
        per-shard interval lands (barrier fleets, idle daemon)."""
        from shadow_tpu.obs import prof as prof_mod

        cp = prof_mod.critical_path(self.prof.to_doc())
        if cp is None:
            return {"critical_shard": -1, "blocked_frac": 0.0}
        return {
            "critical_shard": int(cp["critical_shard"]),
            "blocked_frac": round(float(cp["blocked_frac"]), 4),
            "wall_frac": round(float(cp["wall_frac"]), 4),
        }

    def timez_doc(self) -> dict:
        """The live profile document (GET /timez): the interval ring +
        histograms as a schema-versioned shadow_tpu.profile doc — the
        unit the federation router merges across peers."""
        with self._lock:
            return self.prof.to_doc(meta={"daemon": "shadow_tpu serve"})

    def _observe_request(self, dt_s: float) -> None:
        with self._lock:
            self.prof.observe_wall("serve_request_ns", dt_s)

    def sweep_info(self, sid: str) -> dict | None:
        with self._lock:
            s = self.sweeps.get(sid)
            return dict(s) if s is not None else None

    def sweep_list(self) -> list[dict]:
        with self._lock:
            return [
                {k: self.sweeps[sid][k]
                 for k in ("id", "tenant", "status")}
                | {"progress": self.sweeps[sid].get("progress")}
                for sid in self._order
            ]

    def metrics_doc(self) -> dict:
        from shadow_tpu.obs import metrics as obs_metrics

        reg = obs_metrics.MetricsRegistry()
        with self._lock:
            for k, v in self.counters.items():
                reg.counter_set(f"serve.{k}", int(v))
            for k, v in self.kcache.stats_counters.items():
                reg.counter_set(f"serve.kcache_{k}", int(v))
            reg.counter_set(
                "serve.journal_records", len(self.journal.records)
            )
            reg.gauge_set("serve.queue_depth", len(self._queue))
            reg.gauge_set("serve.journal_lag", self.journal.lag())
            reg.gauge_set(
                "serve.draining", int(self._draining.is_set())
            )
            reg.gauge_set("serve.kcache_entries", self.kcache.entries())
            # pressure plane (schema v8): the memory-headroom gauges the
            # memory-aware admission compares, + ladder posture counters
            mem = self._memory_view()
            reg.gauge_set(
                "pressure.estimated_bytes", mem["estimated_running_bytes"]
            )
            if mem["headroom_bytes"] is not None:
                reg.gauge_set(
                    "pressure.headroom_bytes", mem["headroom_bytes"]
                )
            for k, v in self._last_pressure.items():
                reg.counter_set(f"pressure.{k}", int(v))
            # mesh plane (schema v12): chips up/total + elastic
            # relayout posture of the running fleet
            for k, v in self._last_mesh.items():
                if k in ("chips_up", "chips_total", "shard_map"):
                    reg.gauge_set(f"mesh.{k}", int(v))
                elif k in ("exchange_rebuilds", "relayouts",
                           "re_expansions"):
                    reg.counter_set(f"mesh.{k}", int(v))
            # balance plane (schema v10): the running fleet's packing +
            # steal tallies ("packing" is a string — gauge-encoded)
            for k, v in self._last_balance.items():
                if k == "packing":
                    reg.gauge_set("balance.packing_load",
                                  int(v == "load"))
                else:
                    reg.counter_set(f"balance.{k}", int(v))
            # profiling plane (schema v18): latency percentiles +
            # critical-path posture folded from the live recorder
            obs_metrics.snapshot_prof(self.prof, reg)
        return reg.to_doc(meta={"daemon": "shadow_tpu serve"})

    def _dump_metrics(self) -> None:
        from shadow_tpu.obs.metrics import dump_json_atomic

        doc = self.metrics_doc()
        path = os.path.join(self.opts.state_dir, METRICS_NAME)
        dump_json_atomic(path, doc)

    # ------------------------------------------------------------------
    # the worker (main thread): one sweep at a time, drained on SIGTERM
    # ------------------------------------------------------------------

    def drain(self) -> None:
        """Initiate graceful shutdown: the worker flushes the running
        fleet to its checkpoint, journals DRAIN, and exits. Runs from
        signal handlers, which execute ON the worker thread — possibly
        while it holds the lock, so an unbounded blocking acquire could
        deadlock against ourselves and a non-blocking one silently skips
        the wake-up whenever an HTTP thread holds the lock (the race the
        STH004 lint flags). A bounded acquire gets both: mutual
        exclusion whenever the lock frees within the timeout, and a
        guaranteed return either way — the worker polls the event every
        0.25 s slice, so a skipped notify only delays, never loses, the
        drain."""
        self._draining.set()
        if self._lock.acquire(timeout=1.0):
            try:
                self._wake.notify_all()
            finally:
                self._lock.release()

    def _sweep_ckpt_dir(self, sid: str) -> str:
        return os.path.join(self.opts.state_dir, "sweeps", sid)

    def _build_or_resume(self, s: dict):
        """A FleetSimulation for the sweep: re-attached from its
        checkpoint directory when a previous incarnation left slices
        there, else built fresh from the journaled document."""
        from shadow_tpu.core.checkpoint import CheckpointError
        from shadow_tpu.core.config import load_config
        from shadow_tpu.fleet import build_fleet, load_sweep, resume_fleet
        from shadow_tpu.fleet import checkpoint as fleet_ckpt

        ckpt_dir = self._sweep_ckpt_dir(s["id"])
        jobs, sweep_opts = load_sweep(s["doc"])
        fopts = load_config(jobs[0].config).fleet
        lanes = self.opts.lanes or (
            int(sweep_opts["lanes"]) if sweep_opts.get("lanes")
            else (fopts.lanes or None)
        )
        if os.path.exists(os.path.join(ckpt_dir, fleet_ckpt.MANIFEST)):
            try:
                fleet = resume_fleet(
                    ckpt_dir, lanes=lanes,
                    windows_per_dispatch=fopts.windows_per_dispatch,
                )
            except CheckpointError as e:
                if "already terminal" in str(e):
                    # the crash landed between the final manifest write
                    # and the COMPLETE record: the results are all in the
                    # manifest — settle from it without re-running
                    doc = fleet_ckpt.load_manifest(ckpt_dir)
                    return None, doc, fopts
                raise
        else:
            fleet = build_fleet(jobs, lanes=lanes,
                                windows_per_dispatch=fopts.windows_per_dispatch,
                                checkpoint_dir=ckpt_dir)
        fleet.attach_kernel_cache(self.kcache)
        # the daemon is the loop's outer ring (parallel/balancer.py's
        # inner loop heals shards; this packs whole jobs): freed lanes
        # take the heaviest pending job by predicted load, and an early-
        # finishing lane steals ahead of FIFO order (fleet/scheduler.py)
        fleet.sched.packing = "load"
        if s.get("backend_faults"):
            from shadow_tpu.faults import plan as plan_mod

            fleet.attach_faults(
                plan_mod.parse_fault_plan(s["backend_faults"])
            )
        return fleet, None, fopts

    def _publish_progress(self, sid: str, fleet) -> None:
        st = fleet.sched.stats()
        pst = fleet.pressure_stats()
        bst = fleet.balance_stats() or {}
        with self._lock:
            self.sweeps[sid]["progress"] = {
                "jobs_done": st["jobs_done"],
                "jobs_running": st["jobs_running"],
                "jobs_queued": st["jobs_queued"],
                "kernel_traces": fleet.kernel_traces,
                "pressure_steps": int(pst.get("ladder_steps", 0)),
                "lane_steals": int(st.get("lane_steals", 0)),
            }
            self._last_pressure = pst
            self._last_balance = {
                "packing": fleet.sched.packing, **bst,
            }
            self._last_async = fleet.async_posture()
            self._last_mesh = fleet.mesh_posture()
            self._last_steal = fleet.sched.steal_export()
            # one profiling-plane interval per dispatch slice: deltas of
            # the fleet's committed events + async counters, with the
            # per-(shard) frontier surface when the fleet runs async
            self.prof.tick_from(fleet)
            # journal each new batch of ladder rungs: a post-mortem can
            # see WHEN the sweep started degrading even if we die next
            steps = int(pst.get("ladder_steps", 0))
            if steps > self._journaled_pressure.get(sid, 0):
                self._journaled_pressure[sid] = steps
                self.journal.append(
                    journal_mod.PRESSURE, id=sid, steps=steps, counters=pst
                )
                self.counters["pressure_records"] += 1
            # likewise each new balance action (migration, rollback or
            # lane steal): the journal shows WHEN healing started
            acts = sum(int(v) for v in bst.values())
            if acts > self._journaled_balance.get(sid, 0):
                self._journaled_balance[sid] = acts
                self.journal.append(
                    journal_mod.BALANCE, id=sid, actions=acts, counters=bst
                )
                self.counters["balance_records"] += 1

    def _run_sweep(self, sid: str) -> None:
        from shadow_tpu.core.checkpoint import CheckpointError
        from shadow_tpu.core.supervisor import BackendLost
        from shadow_tpu.fleet import FleetError, SweepError, save_fleet

        s = self.sweeps[sid]
        ckpt_dir = self._sweep_ckpt_dir(sid)
        t0 = time.monotonic()
        with self._lock:
            self._running = sid
            s["status"] = "running"
            s["ckpt_dir"] = ckpt_dir
            self.journal.append(
                journal_mod.ADMIT, id=sid, ckpt_dir=ckpt_dir
            )
        fleet = None
        try:
            fleet, settled_manifest, fopts = self._build_or_resume(s)
            if fleet is None:
                self._settle_from_manifest(sid, settled_manifest)
                return
            # the live footprint the admission check subtracts from the
            # device budget while this sweep runs (docs/serving.md)
            from shadow_tpu.core import pressure as pressure_mod

            try:
                est = pressure_mod.estimate_hbm_bytes(fleet)["total_bytes"]
            except Exception:
                est = 0
            with self._lock:
                self._running_est_bytes = est
            # first manifest BEFORE the first dispatch: a kill landing
            # anywhere after this point re-attaches instead of rebuilding
            save_fleet(fleet, ckpt_dir)
            optimistic = fopts.sync == "optimistic"
            slices = 0
            while not fleet.sched.all_terminal():
                if self._draining.is_set():
                    save_fleet(fleet, ckpt_dir)
                    with self._lock:
                        s["status"] = "drained"
                        self.journal.append(journal_mod.DRAIN, id=sid)
                        self.counters["sweeps_drained"] += 1
                        self._running = None
                    return
                if optimistic:
                    fleet.run_optimistic(max_rounds=1)
                else:
                    fleet.run(max_dispatches=1)
                slices += 1
                self._publish_progress(sid, fleet)
                if slices % self.opts.checkpoint_every_dispatches == 0:
                    save_fleet(fleet, ckpt_dir)
            save_fleet(fleet, ckpt_dir)
            self._settle(sid, fleet, time.monotonic() - t0)
        except BackendLost:
            # the supervision plane already drained the fleet to its
            # checkpoint (save_fleet BEFORE requeueing the lanes, so the
            # slices survive — re-saving here would overwrite them with
            # sliceless QUEUED rows); hand the sweep back FIFO
            with self._lock:
                s["status"] = "queued"
                self.journal.append(
                    journal_mod.REQUEUE, id=sid, reason="backend_lost"
                )
                self.counters["sweeps_requeued"] += 1
                self._queue.insert(0, sid)
                self._running = None
        except (FleetError, SweepError, CheckpointError, ValueError) as e:
            with self._lock:
                s["status"] = "failed"
                s["results"] = {"error": str(e)}
                self.journal.append(
                    journal_mod.COMPLETE, id=sid, ok=False,
                    results={"error": str(e)},
                )
                self.counters["sweeps_failed"] += 1
                self._running = None
            self._dump_metrics()
        finally:
            with self._lock:
                self._running_est_bytes = 0

    def _settle(self, sid: str, fleet, wall_s: float) -> None:
        rows = fleet.results()
        stats = fleet.fleet_stats()
        stats["wall_s"] = round(wall_s, 3)
        stats["resilience"] = fleet.resilience_stats()
        ok = fleet.ok()
        with self._lock:
            s = self.sweeps[sid]
            s["status"] = "done" if ok else "failed"
            s["results"] = rows
            s["stats"] = stats
            self.journal.append(
                journal_mod.COMPLETE, id=sid, ok=ok, results=rows,
                stats=stats,
            )
            self.counters["sweeps_completed" if ok else "sweeps_failed"] += 1
            self.counters["jobs_completed"] += stats["jobs_done"]
            self.counters["kernel_traces"] += fleet.kernel_traces
            self._avg_sweep_wall_s = (
                (1 - _EWMA_ALPHA) * self._avg_sweep_wall_s
                + _EWMA_ALPHA * max(wall_s, 0.001)
            )
            self._running = None
        self._dump_metrics()

    def _settle_from_manifest(self, sid: str, manifest: dict) -> None:
        """Every job in the re-attached manifest is already terminal
        (the previous incarnation died after its final save, before the
        COMPLETE record): settle from the recorded summaries."""
        rows = [e["summary"] for e in manifest["jobs"]]
        ok = all(r["status"] == "done" for r in rows)
        with self._lock:
            s = self.sweeps[sid]
            s["status"] = "done" if ok else "failed"
            s["results"] = rows
            s["stats"] = manifest.get("stats")
            self.journal.append(
                journal_mod.COMPLETE, id=sid, ok=ok, results=rows,
                stats=manifest.get("stats"),
            )
            self.counters["sweeps_completed" if ok else "sweeps_failed"] += 1
            self._running = None
        self._dump_metrics()

    def _worker(self) -> None:
        while not self._draining.is_set():
            with self._lock:
                sid = self._queue.pop(0) if self._queue else None
                if sid is None:
                    self._wake.wait(timeout=0.25)
                    continue
            self._run_sweep(sid)

    # ------------------------------------------------------------------
    # the API server (background thread, unix socket)
    # ------------------------------------------------------------------

    def _make_server(self):
        daemon = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            # unix sockets have no peer (host, port) pair
            def address_string(self):  # pragma: no cover - logging only
                return "unix"

            def log_message(self, *a):  # quiet by default
                pass

            def _reply(self, code: int, body: dict,
                       headers: dict | None = None) -> None:
                blob = (json.dumps(body) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(blob)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(blob)

            def do_GET(self):
                t0 = time.perf_counter()
                try:
                    self._route_get()
                finally:
                    daemon._observe_request(time.perf_counter() - t0)

            def _route_get(self):
                if self.path == "/healthz":
                    return self._reply(200, daemon.health())
                if self.path == "/metricz":
                    return self._reply(200, daemon.metrics_doc())
                if self.path == "/timez":
                    return self._reply(200, daemon.timez_doc())
                if self.path == "/v1/sweeps":
                    return self._reply(200, {"sweeps": daemon.sweep_list()})
                if self.path == "/v1/journal":
                    return self._reply(200, daemon.journal_doc())
                if self.path.startswith("/v1/sweeps/"):
                    sid = self.path.rsplit("/", 1)[-1]
                    info = daemon.sweep_info(sid)
                    if info is None:
                        return self._reply(
                            404, {"error": f"no sweep {sid!r}"}
                        )
                    info.pop("doc", None)  # results, not the input blob
                    return self._reply(200, info)
                return self._reply(404, {"error": "unknown path"})

            def do_POST(self):
                t0 = time.perf_counter()
                try:
                    self._route_post()
                finally:
                    daemon._observe_request(time.perf_counter() - t0)

            def _route_post(self):
                n = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(n) if n else b"{}"
                try:
                    payload = json.loads(raw.decode() or "{}")
                except (UnicodeDecodeError, json.JSONDecodeError):
                    return self._reply(400, {"error": "body is not JSON"})
                if self.path == "/v1/drain":
                    daemon.drain()
                    return self._reply(200, {"draining": True})
                if (self.path.startswith("/v1/sweeps/")
                        and self.path.endswith("/release")):
                    sid = self.path.rsplit("/", 2)[-2]
                    out = daemon.release_sweep(
                        sid, to_peer=str(payload.get("to_peer", "?"))
                    )
                    if out is None:
                        return self._reply(
                            404, {"error": f"no sweep {sid!r}"}
                        )
                    if "busy" in out:
                        # running/settled sweeps never leave their box
                        return self._reply(409, out)
                    return self._reply(200, out)
                if self.path == "/v1/sweeps":
                    doc = payload.get("sweep")
                    if not isinstance(doc, dict):
                        return self._reply(
                            400,
                            {"error": "payload needs a `sweep` document"},
                        )
                    try:
                        origin = payload.get("origin")
                        out = daemon.submit(
                            doc,
                            tenant=str(payload.get("tenant", "default")),
                            backend_faults=payload.get("backend_faults"),
                            origin=(
                                str(origin) if origin is not None else None
                            ),
                        )
                    except ServeError as e:
                        return self._reply(400, {"error": str(e)})
                    if "shed" in out:
                        return self._reply(
                            429, out,
                            headers={
                                "Retry-After": str(out["retry_after_s"]),
                            },
                        )
                    return self._reply(200, out)
                return self._reply(404, {"error": "unknown path"})

        class Server(socketserver.ThreadingMixIn,
                     socketserver.UnixStreamServer):
            daemon_threads = True
            allow_reuse_address = True

        sock = self.opts.socket_path
        os.makedirs(os.path.dirname(os.path.abspath(sock)), exist_ok=True)
        if os.path.exists(sock):
            os.unlink(sock)  # stale socket from a killed incarnation
        return Server(sock, Handler)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def serve_forever(self, install_signals: bool = True) -> int:
        """Run until drained (SIGTERM / POST /v1/drain). Returns 0 on a
        graceful exit; the journal records how far every sweep got."""
        from shadow_tpu.serve.kcache import enable_xla_cache

        # AOT entries skip Python re-traces; the XLA persistent cache
        # (same root, shared with bench.py) skips the StableHLO→binary
        # compile of a deserialized artifact — together a warm restart
        # redispatches in milliseconds
        enable_xla_cache(self.opts.cache_dir)
        self._server = self._make_server()
        if install_signals:
            signal.signal(signal.SIGTERM, lambda *_: self.drain())
            signal.signal(signal.SIGINT, lambda *_: self.drain())
        th = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        th.start()
        self._started.set()
        print(
            f"serve: listening on {self.opts.socket_path} "
            f"(state {self.opts.state_dir}, "
            f"{len(self._queue)} sweep(s) replayed into the queue)",
            flush=True,
        )
        try:
            self._worker()
        finally:
            self._server.shutdown()
            self._server.server_close()
            try:
                os.unlink(self.opts.socket_path)
            except OSError:
                pass
            self._dump_metrics()
            self.journal.close()
        print("serve: drained, exiting", flush=True)
        return 0
