"""AOT window-kernel cache: warm restart means zero recompiles.

Every gear tier, fleet shape, and sync mode compiles its own window
kernel, and every process pays those traces again from scratch — the
r03–r05 bench rounds showed cold-start compiles dominating small sweeps.
This module persists compiled fleet kernels with JAX's AOT export
machinery (`jax.export.export` → StableHLO bytes → `deserialize`), keyed
by everything that shapes the program:

    (kernel-config digest, kernel tag, argument avals, jax/jaxlib
     version, backend platform)

so a restarted daemon (or a rerun bench) re-binds its fleet kernels from
disk without re-tracing a single Python window step — the
`kernel_traces` metric stays 0, which is exactly the gated property the
serve smoke asserts. Determinism is free: the deserialized artifact is
the same StableHLO the live trace produced, and the engine's integer
kernels are exact, so cached and fresh kernels commit bit-identical
event streams (tests/test_serve.py pins this).

Trust nothing on disk: each entry carries a sidecar header with a
sha256 content digest and the producing jax/jaxlib versions. A corrupt,
torn, or version-skewed entry is EVICTED and recompiled — never
deserialized on faith (`evictions` counts them).

The cache root is shared with bench.py's persistent XLA compile cache
(`cache_root()`, overridable via SHADOW_TPU_CACHE_DIR): AOT entries live
under `<root>/aot/`, XLA's own artifacts directly under `<root>`, so the
daemon and the bench warm each other.
"""

from __future__ import annotations

import hashlib
import json
import os

_AOT_SUBDIR = "aot"
HEADER_VERSION = 1


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def cache_root() -> str:
    """The shared compile-cache root: SHADOW_TPU_CACHE_DIR when set,
    else `.jax_cache` next to the repo (bench.py's historical default)."""
    return os.environ.get("SHADOW_TPU_CACHE_DIR") or os.path.join(
        repo_root(), ".jax_cache"
    )


_MACHINE_FP: str | None = None
_MACHINE_MARKER = "machine.json"


def machine_fingerprint() -> str:
    """Digest of the TARGET MACHINE the compiler lowers for: backend
    device kind plus (on CPU backends) the host's CPU feature flags.

    XLA:CPU bakes the compile host's feature set (AVX-512 tiers, AMX…)
    into every artifact; executing an entry compiled on a different
    machine emits the "Machine type used for XLA:CPU compilation doesn't
    match the machine type for execution … could lead to execution
    errors such as SIGILL" warning visible in every MULTICHIP_r0* tail
    when a cache directory travels between hosts. Folding this digest
    into the kcache key (and the XLA cache's machine marker,
    enable_xla_cache) makes a foreign-machine entry a clean miss/evict
    instead of a warning-spewing hazard."""
    global _MACHINE_FP
    if _MACHINE_FP is not None:
        return _MACHINE_FP
    import platform

    # Host-derived only — this must NOT touch jax.devices(): it runs at
    # cache-enable time, which is often BEFORE device virtualization
    # (bench --mesh-smoke forces an 8-device CPU mesh after import), and
    # initializing the backend here would pin the real device set. The
    # accelerator platform already rides the cache key separately
    # (jax.default_backend() at key time); this digest captures the HOST
    # the XLA:CPU code generator targets.
    parts = [platform.machine(), platform.system()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    parts.append(" ".join(sorted(line.split(":", 1)[1]
                                                 .split())))
                    break
    except OSError:
        pass
    _MACHINE_FP = hashlib.sha256(
        "\n".join(parts).encode()
    ).hexdigest()[:16]
    return _MACHINE_FP


def _sweep_foreign_machine(root: str, fp: str) -> int:
    """Evict XLA persistent-cache entries compiled on a DIFFERENT
    machine: the root carries a machine marker; on mismatch every
    top-level entry (XLA's flat layout) is removed and the marker
    rewritten — a machine change costs one cold compile, never warning
    spam or a SIGILL hazard. AOT entries under aot/ are key-guarded by
    the same fingerprint and evict themselves on read."""
    marker = os.path.join(root, _MACHINE_MARKER)
    try:
        with open(marker) as f:
            recorded = json.load(f).get("machine")
    except (OSError, ValueError):
        recorded = None
    removed = 0
    if recorded is not None and recorded != fp:
        for name in os.listdir(root):
            p = os.path.join(root, name)
            if name == _MACHINE_MARKER or not os.path.isfile(p):
                continue
            try:
                os.unlink(p)
                removed += 1
            except OSError:
                pass
    if recorded != fp:
        tmp = f"{marker}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump({"machine": fp}, f)
            os.replace(tmp, marker)
        except OSError:
            pass
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
    return removed


def sweep_corrupt_entries(root: str) -> int:
    """Evict unreadable/zero-length XLA persistent-cache entries so a
    torn write from a killed process never makes jax raise mid-run.
    Walks only the top level (XLA's layout) plus our aot/ sidecars;
    returns the number of entries removed."""
    removed = 0
    for d in (root, os.path.join(root, _AOT_SUBDIR)):
        try:
            names = os.listdir(d)
        except OSError:
            continue
        for name in names:
            p = os.path.join(d, name)
            if not os.path.isfile(p):
                continue
            try:
                with open(p, "rb") as f:
                    ok = bool(f.read(4)) or os.path.getsize(p) == 0
                if os.path.getsize(p) == 0:
                    ok = False
            except OSError:
                ok = False
            if not ok:
                try:
                    os.unlink(p)
                    removed += 1
                except OSError:
                    pass
    return removed


def enable_xla_cache(root: str | None = None) -> tuple[str, int]:
    """Point JAX's persistent compilation cache at the shared root
    (evicting corrupt entries first) — one call shared by bench.py and
    the serve daemon, so both warm the same cache. Returns
    (root, evicted_count)."""
    import jax

    root = root or cache_root()
    os.makedirs(root, exist_ok=True)
    evicted = _sweep_foreign_machine(root, machine_fingerprint())
    evicted += sweep_corrupt_entries(root)
    jax.config.update("jax_compilation_cache_dir", root)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    return root, evicted


def kernel_config_digest(config: dict) -> str:
    """Digest of a job config's KERNEL-SHAPING fields only: the data-
    plane fields a sweep may vary (seeds, stop times, graph values —
    fleet/sweep.py DATA_PATHS) are excluded, so every job of a kernel-
    compatible sweep maps to the same cache key."""
    from shadow_tpu.fleet.sweep import _flatten, _is_data_path

    flat = _flatten(config)
    shaping = {k: flat[k] for k in sorted(flat) if not _is_data_path(k)}
    return hashlib.sha256(
        json.dumps(shaping, sort_keys=True, default=str).encode()
    ).hexdigest()


_SRC_FINGERPRINT: str | None = None


def kernel_source_fingerprint() -> str:
    """Digest of every KERNEL module's source text (the shadowlint
    module map is the authority on what compiles into window programs).
    Folded into every cache key so a daemon restarted across a code
    upgrade can never hit a stale export and silently replay the OLD
    kernel's semantics — a code change is a cache miss, not a hazard."""
    global _SRC_FINGERPRINT
    if _SRC_FINGERPRINT is not None:
        return _SRC_FINGERPRINT
    from shadow_tpu.analysis.linter import classify_module

    root = repo_root()
    h = hashlib.sha256()
    pkg = os.path.join(root, "shadow_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            if classify_module(rel) != "kernel":
                continue
            h.update(rel.encode())
            with open(path, "rb") as f:
                h.update(f.read())
    _SRC_FINGERPRINT = h.hexdigest()
    return _SRC_FINGERPRINT


def _avals_signature(args) -> str:
    """shape/dtype signature of the flattened call arguments — part of
    the key, so a hit is guaranteed arg-compatible with the artifact."""
    import jax
    import numpy as np

    parts = []
    for leaf in jax.tree_util.tree_leaves(args):
        a = np.asarray(leaf)
        parts.append(f"{a.dtype}{list(a.shape)}")
    return ";".join(parts)


class KernelCache:
    """Content-addressed store of serialized `jax.export.Exported`
    window kernels under `<root>/aot/`."""

    def __init__(self, root: str | None = None):
        self.root = root or cache_root()
        self.dir = os.path.join(self.root, _AOT_SUBDIR)
        os.makedirs(self.dir, exist_ok=True)
        self.stats_counters = {
            "hits": 0, "misses": 0, "puts": 0, "evictions": 0,
        }

    # -- keys --

    def key(self, config_digest: str, tag: str, args) -> str:
        import jax
        import jaxlib

        ident = json.dumps({
            "config": config_digest,
            "tag": tag,
            "avals": _avals_signature(args),
            "src": kernel_source_fingerprint(),
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "platform": jax.default_backend(),
            # target-machine fingerprint: an XLA:CPU artifact bakes the
            # compile host's feature set; a cache dir that traveled to a
            # different machine must miss cleanly, not SIGILL-hazard
            "machine": machine_fingerprint(),
        }, sort_keys=True)
        return hashlib.sha256(ident.encode()).hexdigest()[:40]

    def _paths(self, key: str) -> tuple[str, str]:
        base = os.path.join(self.dir, f"k-{key}")
        return f"{base}.bin", f"{base}.json"

    # -- store / load --

    def get(self, key: str):
        """The deserialized Exported for `key`, or None (miss). A
        corrupt/torn/version-skewed entry is evicted and reported as a
        miss — the caller recompiles, it never trusts bad bytes."""
        import jax
        import jaxlib
        from jax import export as jax_export

        bin_path, hdr_path = self._paths(key)
        if not (os.path.exists(bin_path) and os.path.exists(hdr_path)):
            self.stats_counters["misses"] += 1
            return None
        try:
            with open(hdr_path) as f:
                hdr = json.load(f)
            blob = open(bin_path, "rb").read()
            if (
                hdr.get("header_version") != HEADER_VERSION
                or hdr.get("sha256") != hashlib.sha256(blob).hexdigest()
                or hdr.get("jax") != jax.__version__
                or hdr.get("jaxlib") != jaxlib.__version__
                or hdr.get("machine", machine_fingerprint())
                != machine_fingerprint()
            ):
                raise ValueError("header mismatch")
            ex = jax_export.deserialize(bytearray(blob))
        except Exception:  # noqa: BLE001 — any bad entry means EVICT
            self._evict(key)
            self.stats_counters["misses"] += 1
            return None
        self.stats_counters["hits"] += 1
        return ex

    def _evict(self, key: str) -> None:
        for p in self._paths(key):
            try:
                os.unlink(p)
            except OSError:
                pass
        self.stats_counters["evictions"] += 1

    def put(self, key: str, exported) -> None:
        """Persist one Exported atomically (tmp + fsync + rename for the
        payload, header last — a crash mid-put leaves at worst a headerless
        payload that `get` treats as a miss)."""
        import jax
        import jaxlib

        blob = bytes(exported.serialize())
        bin_path, hdr_path = self._paths(key)
        for path, data in (
            (bin_path, blob),
            (hdr_path, json.dumps({
                "header_version": HEADER_VERSION,
                "sha256": hashlib.sha256(blob).hexdigest(),
                "jax": jax.__version__,
                "jaxlib": jaxlib.__version__,
                "machine": machine_fingerprint(),
                "platforms": list(exported.platforms),
                "bytes": len(blob),
            }, indent=1).encode()),
        ):
            tmp = f"{path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        self.stats_counters["puts"] += 1

    def export_and_put(self, key: str, fn, args):
        """Trace `fn` at `args` (the one compile a cold cache pays),
        persist the artifact, and return the Exported."""
        import jax
        from jax import export as jax_export

        exported = jax_export.export(jax.jit(fn))(*args)
        self.put(key, exported)
        return exported

    # -- introspection --

    def entries(self) -> int:
        try:
            return sum(
                1 for n in os.listdir(self.dir)
                if n.startswith("k-") and n.endswith(".bin")
            )
        except OSError:
            return 0

    def stats(self) -> dict:
        d = dict(self.stats_counters)
        d["entries"] = self.entries()
        return d
