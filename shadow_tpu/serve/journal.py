"""Write-ahead job journal: the daemon's crash-durable source of truth.

The serve daemon (shadow_tpu/serve/daemon.py) must treat its own death —
`kill -9`, OOM, node reboot — as a non-event: restart replays the journal
and the fleet finishes every accepted sweep with audit digest chains
bit-identical to an uninterrupted run. That works because every
scheduler-plane transition is journaled BEFORE it takes effect:

    SUBMIT   a sweep was accepted (the full sweep document rides the
             record — replay needs no other file to re-expand the jobs)
    ADMIT    the worker started running it (its checkpoint directory is
             recorded, so replay knows where the fleet slices live)
    DRAIN    a graceful shutdown flushed the running fleet to its
             checkpoint (SIGTERM path); replay resumes from the slices
    REQUEUE  an admitted sweep was returned to the queue (backend loss
             under policy abort, or an operator requeue)
    PRESSURE the running fleet's degradation ladder took rungs
             (core/pressure.py): the cumulative pressure counters ride
             the record, so a post-mortem can see WHEN a sweep started
             degrading even if the daemon later died
    BALANCE  the running fleet's self-balancing plane acted
             (parallel/balancer.py + fleet/scheduler.py load packing):
             cumulative migration / rollback / lane-steal counters ride
             the record, so a post-mortem can see WHEN the daemon began
             healing a hot shard — and whether a migration rolled back
    COMPLETE the sweep finished; per-job results (including each job's
             `audit.chain` digest) ride the record
    HANDOFF  a queued sweep left this journal's owner for another
             federation member (serve/federation.py work stealing or
             peer-loss failover). Appended BEFORE the sweep is handed
             over, so a crash mid-steal can never run the sweep here
             AND on the receiving peer: replay sees the HANDOFF and
             does not requeue it — the receiver's own SUBMIT record is
             the single surviving claim
    REGISTER a federation member joined the router's peer table
             (informational: carries the peer name, socket and
             state-dir; never a sweep transition)

Framing: append-only binary records, each `!II` (payload length, CRC32)
followed by the JSON payload, fsync'd per append. A SIGKILL mid-append
leaves a torn tail frame whose length field overruns the file or whose
CRC fails — replay stops cleanly at the first bad frame and reports it as
`torn_tail`, exactly the crash-consistency contract of a WAL. A bad frame
can never be followed by a good one (appends are sequential and fsync'd),
so stopping is lossless.

Replay folds the records into per-sweep state (`JournalState`): queued /
running / done / failed sweeps in submission order. "Journal lag" — the
health signal `/healthz` reports — is the number of records appended
since the last COMPLETE: how far the durable tip has run ahead of
fully-settled state.
"""

from __future__ import annotations

import json
import os
import struct
import zlib

_FRAME = struct.Struct("!II")  # (payload_len, crc32(payload))
_MAX_RECORD = 64 << 20  # one sweep doc will never be 64 MiB; torn-length guard

SUBMIT = "submit"
ADMIT = "admit"
DRAIN = "drain"
REQUEUE = "requeue"
PRESSURE = "pressure"
BALANCE = "balance"
COMPLETE = "complete"
HANDOFF = "handoff"
REGISTER = "register"

RECORD_TYPES = (
    SUBMIT, ADMIT, DRAIN, REQUEUE, PRESSURE, BALANCE, COMPLETE,
    HANDOFF, REGISTER,
)


class JournalError(ValueError):
    pass


class Journal:
    """Append-only CRC-framed record log with fsync-per-append."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        existing = scan(path) if os.path.exists(path) else None
        if existing is not None and existing["truncated_at"] is not None:
            # drop the torn tail frame so the next append starts at a
            # clean frame boundary (otherwise the old partial frame would
            # corrupt every record appended after it)
            with open(path, "r+b") as f:
                f.truncate(existing["truncated_at"])
        self._records = existing["records"] if existing else []
        self._seq = (
            self._records[-1]["seq"] + 1 if self._records else 0
        )
        self._f = open(path, "ab")
        self.torn_tail_dropped = bool(
            existing and existing["truncated_at"] is not None
        )

    # -- writes --

    def append(self, rtype: str, **fields) -> dict:
        if rtype not in RECORD_TYPES:
            raise JournalError(f"unknown journal record type {rtype!r}")
        rec = {"type": rtype, "seq": self._seq, **fields}
        payload = json.dumps(rec, sort_keys=True).encode()
        self._f.write(_FRAME.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._seq += 1
        self._records.append(rec)
        return rec

    def close(self) -> None:
        self._f.close()

    # -- reads --

    @property
    def records(self) -> list[dict]:
        return list(self._records)

    def state(self) -> "JournalState":
        return JournalState(self._records)

    def lag(self) -> int:
        """Records appended since the last COMPLETE (0 for a settled
        journal): the `/healthz` journal-lag gauge."""
        lag = 0
        for rec in reversed(self._records):
            if rec["type"] == COMPLETE:
                break
            lag += 1
        return lag


def scan(path: str) -> dict:
    """Read every intact frame of a journal file.

    Returns {"records": [...], "truncated_at": byte_offset | None}:
    `truncated_at` is the offset of the first torn/corrupt frame (the
    SIGKILL-mid-append tail), None when the file ends on a clean frame
    boundary. Raises JournalError only on I/O failure opening the file.
    """
    try:
        blob = open(path, "rb").read()
    except OSError as e:
        raise JournalError(f"{path}: unreadable journal: {e}") from e
    records: list[dict] = []
    off = 0
    n = len(blob)
    while off < n:
        if off + _FRAME.size > n:
            return {"records": records, "truncated_at": off}
        length, crc = _FRAME.unpack_from(blob, off)
        start = off + _FRAME.size
        if length > _MAX_RECORD or start + length > n:
            return {"records": records, "truncated_at": off}
        payload = blob[start:start + length]
        if zlib.crc32(payload) != crc:
            return {"records": records, "truncated_at": off}
        try:
            rec = json.loads(payload.decode())
        except (UnicodeDecodeError, json.JSONDecodeError):
            return {"records": records, "truncated_at": off}
        if not isinstance(rec, dict) or rec.get("type") not in RECORD_TYPES:
            return {"records": records, "truncated_at": off}
        records.append(rec)
        off = start + length
    return {"records": records, "truncated_at": None}


class JournalState:
    """The folded scheduler-plane truth a replayed journal describes."""

    def __init__(self, records: list[dict]):
        self.sweeps: dict[str, dict] = {}
        self.order: list[str] = []  # submission order
        for rec in records:
            self._apply(rec)

    def _apply(self, rec: dict) -> None:
        t = rec["type"]
        sid = rec.get("id")
        if t == REGISTER:
            # peer-table membership (router journal): never a sweep
            # transition, so replay folding ignores it
            return
        if t == SUBMIT:
            if sid in self.sweeps:
                return  # replayed duplicate; first submit wins
            self.sweeps[sid] = {
                "id": sid,
                "tenant": rec.get("tenant", "default"),
                "doc": rec.get("doc"),
                "status": "queued",
                "ckpt_dir": None,
                "results": None,
                "admits": 0,
                "backend_faults": rec.get("backend_faults") or [],
            }
            if rec.get("origin") is not None:
                # federation handoff marker: must survive replay so a
                # restarted receiver still refuses the duplicate
                self.sweeps[sid]["origin"] = rec["origin"]
            self.order.append(sid)
        elif sid in self.sweeps:
            s = self.sweeps[sid]
            if t == ADMIT:
                s["status"] = "running"
                s["ckpt_dir"] = rec.get("ckpt_dir")
                s["admits"] += 1
            elif t == DRAIN:
                s["status"] = "drained"
            elif t == REQUEUE:
                s["status"] = "queued"
            elif t == PRESSURE:
                # informational: latest ladder posture; never a status
                # transition (the sweep keeps running degraded)
                s["pressure"] = rec.get("counters")
            elif t == BALANCE:
                # informational: latest self-balancing posture
                s["balance"] = rec.get("counters")
            elif t == COMPLETE:
                s["status"] = "done" if rec.get("ok") else "failed"
                s["results"] = rec.get("results")
                s["stats"] = rec.get("stats")
            elif t == HANDOFF:
                # the sweep now belongs to another federation member:
                # replay must NOT requeue it here (the torn-tail
                # discipline's no-duplicate half) — the receiving
                # peer's SUBMIT record is the single surviving claim
                s["status"] = "handed_off"
                s["handoff_to"] = rec.get("to_peer")

    def unfinished(self) -> list[dict]:
        """Sweeps the restarted daemon must pick back up, in submission
        order: queued ones re-run from their journaled document; running
        or drained ones re-attach via their fleet checkpoint directory
        (falling back to a from-scratch re-run when the crash landed
        before the first checkpoint reached disk)."""
        return [
            self.sweeps[sid] for sid in self.order
            if self.sweeps[sid]["status"] in ("queued", "running", "drained")
        ]

    def completed(self) -> list[dict]:
        return [
            self.sweeps[sid] for sid in self.order
            if self.sweeps[sid]["status"] in ("done", "failed")
        ]

    def handed_off(self) -> list[dict]:
        """Sweeps this journal's owner gave to another federation member
        (work stealing / failover): replay skips them — the receiver's
        journal carries the live claim."""
        return [
            self.sweeps[sid] for sid in self.order
            if self.sweeps[sid]["status"] == "handed_off"
        ]
