"""PARSIR-style multi-worker host plane: sharded handoff drain with a
deterministic merge.

PR 15's pipeline hides the host drain behind in-flight device work, but
one host thread still serializes every per-handoff action — managed-plane
ticks, spill/fault/audit bookkeeping, flight-spool extraction, modeled
drain work — so on handoff-heavy runs the drain itself is the critical
path inside the overlap window. PARSIR (PAPERS.md) shows the right shape
for multi-processor DES host work: bind each simulated host to ONE worker
(per-worker host binding), run the partitions concurrently, and merge at
the barrier in a canonical order so the parallel schedule is
observationally identical to the serial one.

The unit of work is a :class:`HostAction`: ``(vt, gid, work, merge)``.

- ``work()`` runs on the worker the owning host ``gid`` is pinned to.
  It may touch ONLY partition-local state (that host's rows, its own
  accumulators) — never ``sim.state`` or another host's partition.
- ``merge(result)`` runs on the coordinator thread, strictly in
  canonical ``(vt, gid, seq)`` order (``seq`` = submission order, the
  tiebreak), AFTER every worker finished its batch. All cross-partition
  effects — appending to a shared spool, folding a digest, mutating
  driver state — belong here, so committed order, audit chains and
  checkpoint bytes are identical to the serial drain by construction.

Pinning is stable and placement-derived: ``worker = slot_of[gid] %
workers`` when the caller installs the rebalance seam's slot table
(:meth:`HostPlane.set_slot_map`), else ``gid % workers``. A live
migration that moves a host's slot re-pins it deterministically (same
slot table -> same pin on every run) and is counted in ``pin_moves``.

A worker exception never kills the drain: the failed actions re-run
serially on the coordinator in canonical order (``work`` must therefore
tolerate a re-run after a mid-action exception — keep it idempotent),
counted in ``serial_fallbacks``.

``workers == 1`` is not this module's concern: callers keep today's
inline serial drain (no threads, no stats keys — the bit-exact default
path). A plane is only constructed for ``workers > 1``.

Thread discipline (policed by shadowlint STH001-004, analysis/
threads.py — this module is in THREAD_MODULES): every shared attribute
(`_queues`, `_results`, `_batch_times`, `_pending`, `_stop`, `_pins`,
`_slot_map`, `stats`) is touched only under ``self._lock``; both
condition variables share that lock; waits are bounded.
"""

from __future__ import annotations

import threading
import time as wall_time
from typing import Any, Callable

# Chrome-trace tid block for drain workers: far above the fleet lane
# tids (lane j rides tid j+1) so the rows never collide.
WORKER_TID_BASE = 100


def new_stats(workers: int) -> dict:
    """The `hostplane.*` stats dict (metrics schema v15). Created lazily
    by the owning engine the first time a multi-worker plane is built, so
    workers=1 runs emit no hostplane keys at all."""
    st = {
        "workers": int(workers),     # configured pool width (posture)
        "sharded_drains": 0,         # drains that fanned out to workers
        "merge_ns": 0,               # coordinator time in canonical merge
        "serial_fallbacks": 0,       # actions re-run serially after a
                                     # worker exception
        "pin_moves": 0,              # host->worker re-pins (migrations)
    }
    for w in range(int(workers)):
        st[f"drain_ns_w{w}"] = 0     # per-worker wall in work() batches
    return st


class HostAction:
    """One drainable handoff action owned by host ``gid`` at virtual
    time ``vt``. ``work`` runs on the pinned worker (partition-local
    effects only); ``merge`` (optional) runs on the coordinator in
    canonical (vt, gid, seq) order with ``work``'s return value."""

    __slots__ = ("vt", "gid", "seq", "work", "merge")

    def __init__(self, vt: int, gid: int, work: Callable[[], Any],
                 merge: Callable[[Any], None] | None = None):
        self.vt = int(vt)
        self.gid = int(gid)
        self.seq = 0  # assigned at submission (the canonical tiebreak)
        self.work = work
        self.merge = merge


class HostPlane:
    """A pool of pinned drain workers with a deterministic merge barrier.

    One instance per engine, persistent across handoffs (threads start
    lazily on the first sharded drain and idle between boundaries).
    ``drain`` is coordinator-only: one thread submits, waits the barrier,
    and merges; the plane never overlaps two drains."""

    def __init__(self, workers: int, stats: dict):
        self.workers = max(1, int(workers))
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)  # workers: batch ready
        self._done = threading.Condition(self._lock)  # coordinator: barrier
        # guarded state (see module docstring for the discipline)
        self._queues: list[list[HostAction]] = [
            [] for _ in range(self.workers)
        ]
        self._results: list[tuple[HostAction, Any, BaseException | None]] = []
        self._batch_times: list[tuple[int, float, float]] = []
        self._pending = 0
        self._stop = False
        self._pins: dict[int, int] = {}
        self._slot_map = None
        with self._lock:
            self.stats = stats
        # coordinator-only (never touched under the lock by design)
        self._threads: list[threading.Thread] = []

    # -- pinning (PARSIR per-worker host binding) --

    def set_slot_map(self, slot_map) -> None:
        """Install the placement seam's host->slot table (None = identity).
        Pins derive from it, so a migration that changes a host's slot
        re-pins that host deterministically on the next drain."""
        with self._lock:
            self._slot_map = slot_map

    def _pin(self, gid: int) -> int:
        # caller holds self._lock
        sm = self._slot_map
        slot = gid
        if sm is not None and 0 <= gid < len(sm):
            slot = int(sm[gid])
        w = slot % self.workers
        old = self._pins.get(gid)
        if old is not None and old != w:
            self.stats["pin_moves"] += 1
        self._pins[gid] = w
        return w

    # -- worker pool --

    def _ensure_threads(self) -> None:
        if self._threads:
            return
        for wid in range(self.workers):
            th = threading.Thread(
                target=self._worker, args=(wid,),
                name=f"hostplane-w{wid}", daemon=True,
            )
            self._threads.append(th)
            th.start()

    def _worker(self, wid: int) -> None:
        while True:
            with self._lock:
                while not self._queues[wid] and not self._stop:
                    self._wake.wait(timeout=0.25)
                if self._stop and not self._queues[wid]:
                    return
                batch = self._queues[wid]
                self._queues[wid] = []
            # execute outside the lock: work() is partition-local by
            # contract, so batches from different workers never touch
            # the same state
            t0 = wall_time.perf_counter()
            out = []
            for a in batch:
                try:
                    out.append((a, a.work(), None))
                except BaseException as e:  # re-run serially at the merge
                    out.append((a, None, e))
            t1 = wall_time.perf_counter()
            with self._lock:
                self._results.extend(out)
                self._batch_times.append((wid, t0, t1))
                self.stats[f"drain_ns_w{wid}"] += int((t1 - t0) * 1e9)
                self._pending -= len(batch)
                if self._pending <= 0:
                    self._done.notify_all()

    def close(self) -> None:
        """Stop the pool (threads are daemons; close is for tests and
        symmetric shutdown, not correctness)."""
        with self._lock:
            self._stop = True
            self._wake.notify_all()
        for th in self._threads:
            th.join(timeout=2.0)
        self._threads = []

    # -- the drain barrier --

    def drain(self, actions, tracer=None) -> int:
        """Shard `actions` to pinned workers, wait the barrier, merge in
        canonical (vt, gid, seq) order. Returns the action count.

        When a tracer (obs/trace.ChromeTracer) is attached, each worker
        batch is emitted as a `host_drain` span on its own tid
        (WORKER_TID_BASE + wid) so tools/trace_summary.py can report
        drain parallelism."""
        acts = list(actions)
        if not acts:
            return 0
        for i, a in enumerate(acts):
            a.seq = i
        order = sorted(acts, key=lambda a: (a.vt, a.gid, a.seq))
        self._ensure_threads()
        with self._lock:
            # enqueue in canonical order so each partition also executes
            # its own actions in canonical order
            for a in order:
                self._queues[self._pin(a.gid)].append(a)
            self._pending += len(order)
            self._batch_times = []
            self._wake.notify_all()
            while self._pending > 0:
                self._done.wait(timeout=0.25)
            results = self._results
            self._results = []
            batch_times = self._batch_times
            self._batch_times = []
            self.stats["sharded_drains"] += 1
        t0 = wall_time.perf_counter()
        got: dict[int, tuple[Any, BaseException | None]] = {
            id(a): (r, e) for a, r, e in results
        }
        fallbacks = 0
        for a in order:
            r, e = got[id(a)]
            if e is not None:
                # a worker raised: re-run serially on the coordinator, IN
                # PLACE in the canonical walk so the merge order is still
                # exactly the serial drain's — the plane degrades, it
                # never drops work or reorders it
                fallbacks += 1
                r = a.work()
            if a.merge is not None:
                a.merge(r)
        merge_ns = int((wall_time.perf_counter() - t0) * 1e9)
        with self._lock:
            self.stats["merge_ns"] += merge_ns
            self.stats["serial_fallbacks"] += fallbacks
        if tracer is not None:
            # map the workers' perf_counter stamps onto the tracer's
            # relative-µs clock through one coordinator-side anchor
            base_us = tracer.now_us()
            base_pc = wall_time.perf_counter()
            for wid, b0, b1 in batch_times:
                tracer.thread_name(
                    WORKER_TID_BASE + wid, f"hostplane w{wid}"
                )
                tracer.complete(
                    "host_drain",
                    base_us - (base_pc - b0) * 1e6,
                    (b1 - b0) * 1e6,
                    tid=WORKER_TID_BASE + wid, worker=wid,
                )
        return len(order)
