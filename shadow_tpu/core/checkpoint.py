"""Checkpoint/resume of device simulation state.

The reference has none (SURVEY.md §5.4): simulation state lives partly in
native process memory of managed plugins, which makes snapshots hard. Here
the device-plane state is a pure pytree of arrays, so a checkpoint is just
those arrays on disk — resume is bit-exact because a window step is a pure
function of (state, params, window).

Format: one .npz whose keys are the pytree key-paths of SimState leaves,
plus a `__meta__` JSON blob (host count, sim time, version) for validation.
Restoring requires a Simulation built from the SAME config (the kernel and
state structure are compile-time artifacts; only the array contents travel).
"""

from __future__ import annotations

import io
import json

import jax
import numpy as np

FORMAT_VERSION = 1


class CheckpointError(ValueError):
    pass


def _leaf_paths(state):
    leaves, treedef = jax.tree_util.tree_flatten_with_path(state)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in leaves], treedef


def save(sim, path: str) -> None:
    """Write sim.state (and metadata) to `path` as an .npz archive."""
    pairs, _ = _leaf_paths(sim.state)
    arrays = {}
    for key, leaf in pairs:
        arrays[key] = np.asarray(jax.device_get(leaf))
    meta = {
        "version": FORMAT_VERSION,
        "num_hosts": sim.num_hosts,
        "stop_time": sim.stop_time,
        "runahead": sim.runahead,
        "now": int(jax.device_get(sim.state.now)),
        "leaves": sorted(arrays),
    }
    # Pool gearing (core/gearbox.py): the active gear decides the pool
    # leaves' shapes, so restore must re-bind the same gear before the
    # shape check. Recorded for every build (pool_gears=1 is a one-tier
    # ladder whose level is always 0).
    ladder = getattr(sim, "_gear_ladder", None)
    if ladder:
        meta["gear"] = {
            "level": int(sim._gear),
            "capacity": int(ladder[sim._gear].capacity),
            "tiers": len(ladder),
        }
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8
    )
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    with open(path, "wb") as f:
        f.write(buf.getvalue())


def load_meta(path: str) -> dict:
    with np.load(path) as z:
        return json.loads(bytes(z["__meta__"]).decode())


def restore(sim, path: str) -> None:
    """Replace sim.state with the checkpointed arrays (in place).

    The Simulation must be built from the same config: every state leaf must
    exist in the checkpoint with identical shape and dtype.
    """
    meta = load_meta(path)
    if meta["version"] != FORMAT_VERSION:
        raise CheckpointError(
            f"checkpoint version {meta['version']} != {FORMAT_VERSION}"
        )
    if meta["num_hosts"] != sim.num_hosts:
        raise CheckpointError(
            f"checkpoint has {meta['num_hosts']} hosts, sim has "
            f"{sim.num_hosts} (must be built from the same config)"
        )
    gear = meta.get("gear")
    ladder = getattr(sim, "_gear_ladder", None)
    if gear is not None and ladder:
        lvl = int(gear["level"])
        if (
            len(ladder) != int(gear.get("tiers", len(ladder)))
            or lvl >= len(ladder)
            or ladder[lvl].capacity != int(gear["capacity"])
        ):
            raise CheckpointError(
                f"checkpoint gear {gear} does not exist on this build's "
                f"ladder ({[(s.level, s.capacity) for s in ladder]}); the "
                f"sim must be built from the same config (including "
                f"experimental.pool_gears)"
            )
        if lvl != sim._gear:
            # re-bind the checkpointed gear so every pool leaf matches the
            # recorded shapes; the transitional resize + telemetry bumps
            # land on state that the leaf restore below replaces wholesale
            sim._shift_gear(lvl)
    pairs, treedef = _leaf_paths(sim.state)
    with np.load(path) as z:
        want = {k for k, _ in pairs}
        have = set(meta["leaves"])
        if want != have:
            missing = sorted(want - have)
            extra = sorted(have - want)
            raise CheckpointError(
                f"state structure mismatch: missing {missing[:5]}, "
                f"unexpected {extra[:5]} (sim config differs from the one "
                f"checkpointed)"
            )
        new_leaves = []
        for key, leaf in pairs:
            arr = z[key]
            if arr.shape != leaf.shape or arr.dtype != np.asarray(leaf).dtype:
                raise CheckpointError(
                    f"leaf {key}: checkpoint {arr.shape}/{arr.dtype} vs sim "
                    f"{leaf.shape}/{np.asarray(leaf).dtype}"
                )
            new_leaves.append(jax.numpy.asarray(arr))
    sim.state = jax.tree_util.tree_unflatten(treedef, new_leaves)
