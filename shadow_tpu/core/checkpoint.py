"""Crash-consistent checkpoint/resume of device simulation state.

The reference has none (SURVEY.md §5.4): simulation state lives partly in
native process memory of managed plugins, which makes snapshots hard. Here
the device-plane state is a pure pytree of arrays, so a checkpoint is just
those arrays on disk — resume is bit-exact because a window step is a pure
function of (state, params, window).

Format (FORMAT_VERSION 2): one .npz whose keys are the pytree key-paths of
SimState leaves, plus
  ``__meta__``    JSON blob (host count, sim time, version, gear) for
                  validation, and
  ``__digest__``  sha256 over every other entry's name, dtype, shape and
                  raw bytes (sorted by name) — content integrity that a
                  zip CRC pass alone cannot provide for a flipped byte
                  that survives decompression.

Crash consistency: `save` writes to a same-directory temp file, fsyncs,
then renames into place — a simulator SIGKILLed mid-write leaves either
the previous checkpoint or a temp file that resume ignores, never a
half-written archive under the real name. `resume_latest` walks the
retention ring newest-first and falls back past any checkpoint that fails
integrity validation (truncated, flipped, wrong structure), so one corrupt
file costs one interval of progress, not the run.

Restoring requires a Simulation built from the SAME config (the kernel and
state structure are compile-time artifacts; only the array contents travel).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import re
import zipfile
import zlib

import jax
import numpy as np

FORMAT_VERSION = 2

# auto-checkpoint ring entries: <prefix>-<seq>-<sim_ns>.npz — seq gives
# the newest-first order even if two boundaries share a frontier time.
# Two namespaces share one monotonic seq counter: "ckpt" (the periodic
# retention ring) and "drain" (emergency drain checkpoints — supervisor
# backend-loss / pool-exhaustion / elastic relayout flushes). Drains
# rotate only against other drains, so a burst of chip losses can never
# rotate out the last periodic checkpoint (and vice versa).
RING_PREFIXES = ("ckpt", "drain")
_RING_RE = re.compile(r"^(ckpt|drain)-(\d{6})-(\d+)\.npz$")


class CheckpointError(ValueError):
    pass


def _leaf_paths(state):
    leaves, treedef = jax.tree_util.tree_flatten_with_path(state)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in leaves], treedef


def _digest(arrays: dict) -> str:
    h = hashlib.sha256()
    for key in sorted(arrays):
        a = np.ascontiguousarray(arrays[key])
        h.update(key.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def save(sim, path: str, extra_meta: dict | None = None) -> None:
    """Write sim.state (and metadata) to `path` as an .npz archive,
    atomically: tmp file + fsync + rename (crash mid-save never leaves a
    torn archive under `path`). `extra_meta` keys merge into the header —
    the backend supervisor records its drain reason/policy there
    (`__meta__.drain`, core/supervisor.py) so an operator can tell a
    scheduled ring entry from an emergency drain."""
    pairs, _ = _leaf_paths(sim.state)
    arrays = {}
    for key, leaf in pairs:
        arrays[key] = np.asarray(jax.device_get(leaf))
    meta = {
        "version": FORMAT_VERSION,
        "num_hosts": sim.num_hosts,
        "stop_time": sim.stop_time,
        "runahead": sim.runahead,
        "now": int(np.max(np.asarray(jax.device_get(sim.state.now)))),
        "leaves": sorted(arrays),
    }
    # Pool gearing (core/gearbox.py): the active gear decides the pool
    # leaves' shapes, so restore must re-bind the same gear before the
    # shape check. Recorded for every build (pool_gears=1 is a one-tier
    # ladder whose level is always 0).
    ladder = getattr(sim, "_gear_ladder", None)
    if ladder:
        meta["gear"] = {
            "level": int(sim._gear),
            "capacity": int(ladder[sim._gear].capacity),
            "tiers": len(ladder),
        }
    # Determinism-audit chain (obs/audit.py): a header copy of the digest
    # chain at this boundary, so tools/diff_digest.py can audit a
    # checkpoint against a digest document without decompressing leaves.
    ob = getattr(sim.state, "obs", None)
    if ob is not None and getattr(ob, "host_digest", None) is not None:
        from shadow_tpu.obs import audit as audit_mod

        meta["audit"] = {
            "chain": audit_mod.combine(
                np.asarray(jax.device_get(ob.host_digest))
            ),
        }
    # Async conservative sync (parallel/islands.py): the derived
    # per-shard window widths / lookahead critical link / last frontier
    # surface ride the header so an operator can audit a resumed run's
    # async posture — informational only (resume re-derives frontiers
    # from pool state, so the restart is always safe).
    am = getattr(sim, "_async_meta", None)
    if am is not None:
        a = am()
        if a:
            meta["async"] = a
    # Self-balancing plane (parallel/balancer.py): the LIVE host->slot
    # assignment and controller posture ride the header, so a migrated
    # layout survives drain-to-checkpoint and an operator can audit it
    # without replay. Restore rebuilds the routing table from the state's
    # own gid rows (the _post_restore hook below), so the block is also
    # what re-arms an in-progress cooldown on resume.
    bm = getattr(sim, "_balance_meta", None)
    if bm is not None:
        b = bm()
        if b:
            meta["balance"] = b
    if extra_meta:
        meta.update(extra_meta)
    meta["digest"] = _digest(arrays)
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8
    )
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(buf.getvalue())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    # the rename itself must survive a crash: fsync the directory entry
    d = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(d)
    finally:
        os.close(d)


def _open_checkpoint(path: str):
    """np.load with every failure class collapsed to CheckpointError:
    callers (and the resume fallback) see one clean exception type instead
    of zipfile/KeyError/json internals. A zero-length or mid-write-
    truncated file lands here (np.load raises EOFError / BadZipFile on
    them), as does a file whose bytes parse as a bare .npy array rather
    than an .npz archive — resume_latest falls back past all of them."""
    try:
        z = np.load(path)
    except (zipfile.BadZipFile, zlib.error, OSError, ValueError,
            EOFError) as e:
        raise CheckpointError(f"{path}: unreadable archive: {e}") from e
    if not isinstance(z, np.lib.npyio.NpzFile):
        raise CheckpointError(
            f"{path}: not an .npz archive (loaded as {type(z).__name__}; "
            f"overwritten or corrupt checkpoint)"
        )
    return z


def load_meta(path: str) -> dict:
    with _open_checkpoint(path) as z:
        try:
            raw = z["__meta__"]
        except (KeyError, zipfile.BadZipFile, zlib.error, EOFError,
                OSError, ValueError) as e:
            # ValueError covers a torn .npy member header inside a zip
            # whose directory survived the truncation
            raise CheckpointError(
                f"{path}: missing or unreadable __meta__ entry"
            ) from e
        try:
            meta = json.loads(bytes(raw).decode())
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise CheckpointError(f"{path}: corrupt __meta__ JSON") from e
    if not isinstance(meta, dict) or "version" not in meta:
        raise CheckpointError(f"{path}: __meta__ is not a checkpoint header")
    return meta


def verify(path: str) -> dict:
    """Full integrity validation without touching any sim: header parses,
    format version matches, every recorded leaf decompresses, and the
    content digest matches. Returns the meta on success."""
    meta = load_meta(path)
    if meta["version"] != FORMAT_VERSION:
        raise CheckpointError(
            f"{path}: checkpoint version {meta['version']} != {FORMAT_VERSION}"
        )
    want = meta.get("digest")
    if not want:
        raise CheckpointError(f"{path}: header carries no content digest")
    arrays = {}
    with _open_checkpoint(path) as z:
        names = set(z.files) - {"__meta__"}
        if names != set(meta.get("leaves", [])):
            raise CheckpointError(
                f"{path}: archive entries do not match the recorded leaf "
                f"set (torn or tampered archive)"
            )
        for key in names:
            try:
                arrays[key] = z[key]
            except (zipfile.BadZipFile, zlib.error, EOFError, OSError,
                    ValueError) as e:
                raise CheckpointError(
                    f"{path}: leaf {key} unreadable: {e}"
                ) from e
    got = _digest(arrays)
    if got != want:
        raise CheckpointError(
            f"{path}: content digest mismatch (corrupt checkpoint): "
            f"{got[:12]} != {want[:12]}"
        )
    return meta


def restore(sim, path: str) -> None:
    """Replace sim.state with the checkpointed arrays (in place).

    The Simulation must be built from the same config: every state leaf must
    exist in the checkpoint with identical shape and dtype. Integrity is
    verified (digest) before any state is touched.
    """
    meta = verify(path)
    if meta["num_hosts"] != sim.num_hosts:
        raise CheckpointError(
            f"checkpoint has {meta['num_hosts']} hosts, sim has "
            f"{sim.num_hosts} (must be built from the same config)"
        )
    gear = meta.get("gear")
    ladder = getattr(sim, "_gear_ladder", None)
    if gear is not None and ladder:
        lvl = int(gear["level"])
        if (
            len(ladder) != int(gear.get("tiers", len(ladder)))
            or lvl >= len(ladder)
            or ladder[lvl].capacity != int(gear["capacity"])
        ):
            raise CheckpointError(
                f"checkpoint gear {gear} does not exist on this build's "
                f"ladder ({[(s.level, s.capacity) for s in ladder]}); the "
                f"sim must be built from the same config (including "
                f"experimental.pool_gears)"
            )
        if lvl != sim._gear:
            # re-bind the checkpointed gear so every pool leaf matches the
            # recorded shapes; the transitional resize + telemetry bumps
            # land on state that the leaf restore below replaces wholesale
            sim._shift_gear(lvl)
    pairs, treedef = _leaf_paths(sim.state)
    with _open_checkpoint(path) as z:
        want = {k for k, _ in pairs}
        have = set(meta["leaves"])
        if want != have:
            missing = sorted(want - have)
            extra = sorted(have - want)
            raise CheckpointError(
                f"state structure mismatch: missing {missing[:5]}, "
                f"unexpected {extra[:5]} (sim config differs from the one "
                f"checkpointed)"
            )
        new_leaves = []
        for key, leaf in pairs:
            try:
                arr = z[key]
            except (zipfile.BadZipFile, zlib.error, EOFError, OSError,
                    ValueError, KeyError) as e:
                raise CheckpointError(
                    f"{path}: leaf {key} unreadable: {e}"
                ) from e
            if arr.shape != leaf.shape or arr.dtype != np.asarray(leaf).dtype:
                raise CheckpointError(
                    f"leaf {key}: checkpoint {arr.shape}/{arr.dtype} vs sim "
                    f"{leaf.shape}/{np.asarray(leaf).dtype}"
                )
            new_leaves.append(jax.numpy.asarray(arr))
    sim.state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    # layout-derived runtime state (islands slot_of routing table, async
    # lookahead, balancer posture) lives outside the state pytree; give
    # the sim a chance to re-sync it against the restored leaves — a
    # checkpoint taken after a live migration restores PERMUTED host rows
    hook = getattr(sim, "_post_restore", None)
    if hook is not None:
        hook(meta)


def restore_relayout(sim, path: str) -> None:
    """Restore a checkpoint whose STATE LAYOUT differs from this build's:
    a mesh run checkpointed at one shard count resumed on a different
    mesh size, an islands checkpoint resumed on the global engine, or
    the reverse. Falls through to the strict `restore` when every leaf
    already matches (same layout, same gear).

    The config must otherwise agree (host count, app planes, counter
    block) — only the PARTITION travels. Integrity is verified before
    any state is touched; the re-layout itself is the sim's
    `_import_foreign_layout(foreign_state, meta)` hook (the islands
    engine globalizes by gid — migrated layouts land in canonical
    order — and re-routes into its own partition; the resumed run's
    audit chain extends the checkpointed one exactly, which
    tests/test_mesh.py pins across 8→4→global resume chains)."""
    meta = verify(path)
    if meta["num_hosts"] != sim.num_hosts:
        raise CheckpointError(
            f"checkpoint has {meta['num_hosts']} hosts, sim has "
            f"{sim.num_hosts} (only the partition may differ on a "
            f"relayout resume)"
        )
    pairs, treedef = _leaf_paths(sim.state)
    want = {k for k, _ in pairs}
    have = set(meta.get("leaves", []))
    if want != have:
        missing = sorted(want - have)
        extra = sorted(have - want)
        raise CheckpointError(
            f"state structure mismatch beyond layout: missing "
            f"{missing[:5]}, unexpected {extra[:5]} (relayout resume "
            f"needs the same config apart from the partition)"
        )
    with _open_checkpoint(path) as z:
        arrays = {}
        for key in meta["leaves"]:
            try:
                arrays[key] = z[key]
            except (zipfile.BadZipFile, zlib.error, EOFError, OSError,
                    ValueError, KeyError) as e:
                raise CheckpointError(
                    f"{path}: leaf {key} unreadable: {e}"
                ) from e
    if all(
        arrays[k].shape == leaf.shape
        and arrays[k].dtype == np.asarray(leaf).dtype
        for k, leaf in pairs
    ):
        restore(sim, path)  # same layout: the strict path (gear rebind)
        return
    hook = getattr(sim, "_import_foreign_layout", None)
    if hook is None:
        raise CheckpointError(
            f"{path}: layout differs and this engine has no "
            f"_import_foreign_layout hook; rebuild with the "
            f"checkpointed partition"
        )
    foreign = jax.tree_util.tree_unflatten(
        treedef, [arrays[k] for k, _ in pairs]
    )
    shapes = {k: np.asarray(leaf).shape for k, leaf in pairs}
    try:
        hook(foreign, meta)
    except ValueError as e:
        raise CheckpointError(f"{path}: relayout failed: {e}") from e
    got, _ = _leaf_paths(sim.state)
    bad = [
        k for k, leaf in got
        if np.asarray(leaf).shape != shapes.get(k)
    ]
    if bad:
        raise CheckpointError(
            f"{path}: relayout produced wrong shapes for {bad[:5]}"
        )
    post = getattr(sim, "_post_restore", None)
    if post is not None:
        post(meta)


# ---------------------------------------------------------------------------
# auto-checkpoint retention ring (--checkpoint-every / --resume)
# ---------------------------------------------------------------------------


def ring_entries(ckpt_dir: str,
                 prefix: str | None = None) -> list[tuple[int, int, str]]:
    """(seq, sim_ns, path) ring entries in `ckpt_dir`, oldest first —
    one namespace when `prefix` is given ("ckpt" or "drain"), both
    otherwise (seq is shared and monotonic across them, so the merged
    sort IS newest-last). Temp files and foreign names are ignored."""
    out = []
    try:
        names = os.listdir(ckpt_dir)
    except FileNotFoundError:
        return []
    for name in names:
        m = _RING_RE.match(name)
        if m and (prefix is None or m.group(1) == prefix):
            out.append((int(m.group(2)), int(m.group(3)),
                        os.path.join(ckpt_dir, name)))
    out.sort()
    return out


def save_ring(sim, ckpt_dir: str, seq: int, sim_ns: int,
              retain: int = 3, extra_meta: dict | None = None,
              prefix: str = "ckpt") -> tuple[str, int]:
    """Write one ring checkpoint <prefix>-<seq>-<sim_ns>.npz and prune
    the oldest SAME-NAMESPACE entries beyond `retain` — a drain burst
    rotates drains only, never the periodic ring (and vice versa).
    Returns (path, pruned_count)."""
    if prefix not in RING_PREFIXES:
        raise ValueError(
            f"checkpoint ring prefix must be one of {RING_PREFIXES}, "
            f"got {prefix!r}"
        )
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"{prefix}-{seq:06d}-{sim_ns}.npz")
    save(sim, path, extra_meta=extra_meta)
    pruned = 0
    entries = ring_entries(ckpt_dir, prefix=prefix)
    for _, _, old in entries[:max(0, len(entries) - max(1, retain))]:
        os.unlink(old)
        pruned += 1
    return path, pruned


def resume_latest(sim, ckpt_dir: str) -> dict:
    """Restore the newest ring checkpoint that passes integrity
    validation — periodic AND drain namespaces, newest-first by the
    shared seq counter — falling back past corrupt ones (each fallback
    is counted).
    Returns {"path", "meta", "fallbacks", "rejected": [(path, error)]}.
    Raises CheckpointError when no entry validates."""
    entries = ring_entries(ckpt_dir)
    if not entries:
        raise CheckpointError(
            f"{ckpt_dir}: no checkpoints to resume from (expected "
            f"ckpt-<seq>-<ns>.npz or drain-<seq>-<ns>.npz entries)"
        )
    rejected = []
    for seq, sim_ns, path in reversed(entries):
        try:
            restore(sim, path)
        except CheckpointError as e:
            rejected.append((path, str(e)))
            continue
        return {
            "path": path,
            "meta": load_meta(path),
            "seq": seq,
            "sim_ns": sim_ns,
            "fallbacks": len(rejected),
            "rejected": rejected,
        }
    detail = "; ".join(f"{os.path.basename(p)}: {e}" for p, e in rejected)
    raise CheckpointError(
        f"{ckpt_dir}: every checkpoint failed integrity validation "
        f"({detail})"
    )
