"""Masked per-row SoA updates without XLA scatters.

TPU lowers `arr.at[rows, col].set/add` to a serialized element-by-element
scatter (~0.5 µs each — docs/bench_notes.md measured the engine's removal
of these at 0.84× → 3.6× baseline). Every hot-path "write one slot per
host" update in the framework goes through these helpers instead: a
broadcast compare builds the [H, S] hit mask and a single elementwise
select rewrites the array — full-bandwidth traffic, no serialization.

`arr` is [H, S] or [H, S, P]; `col` is [H] (the slot per host); `mask` is
[H] (which hosts write). `val` may be scalar, [H], or [H, P].
"""

from __future__ import annotations

import jax.numpy as jnp


def _hit(arr, mask, col):
    S = arr.shape[1]
    cols = jnp.arange(S, dtype=jnp.int32)
    return mask[:, None] & (cols[None, :] == col[:, None])  # [H, S]


def set_at(arr, mask, col, val):
    """arr[h, col[h]] = val[h] where mask[h]."""
    hit = _hit(arr, mask, col)
    val = jnp.asarray(val, arr.dtype)
    if arr.ndim == 3:
        if val.ndim == 2:
            val = val[:, None, :]
        return jnp.where(hit[:, :, None], val, arr)
    if val.ndim == 1:
        val = val[:, None]
    return jnp.where(hit, val, arr)


def get_at(arr, col):
    """arr[h, col[h]] via a one-hot masked reduce — NOT a gather, which
    serializes per output element on TPU (~9 ns/element, docs/bench_notes.md
    round-2 profile). Rows whose col is outside [0, S) return 0."""
    hit = _hit(arr, jnp.ones(col.shape, bool), col)
    if arr.ndim == 3:
        return jnp.sum(
            jnp.where(hit[:, :, None], arr, 0), axis=1, dtype=arr.dtype
        )
    return jnp.sum(jnp.where(hit, arr, 0), axis=1, dtype=arr.dtype)


def add_at(arr, mask, col, val):
    """arr[h, col[h]] += val[h] where mask[h]."""
    hit = _hit(arr, mask, col)
    val = jnp.asarray(val, arr.dtype)
    if arr.ndim == 3:
        if val.ndim == 2:
            val = val[:, None, :]
        return arr + jnp.where(hit[:, :, None], val, jnp.zeros_like(arr))
    if val.ndim == 1:
        val = val[:, None]
    return arr + jnp.where(hit, val, jnp.zeros_like(arr))


def pack_words(payload):
    """[..., P] i32 payload words → [..., ceil(P/2)] i64, pairs packed as
    (hi word 2w+1) << 32 | (lo word 2w, zero-extended).

    The engine's sorts carry every payload word as an operand; packing
    halves the operand count (and the box-write traffic) at the cost of
    one elementwise pass at the pack/unpack boundaries — profiled on v5e,
    the sorts dominate the window step at netstack shapes, so this is a
    direct win. Odd P pads the last high word with zero."""
    P = payload.shape[-1]
    if P % 2:
        payload = jnp.concatenate(
            [payload, jnp.zeros(payload.shape[:-1] + (1,), payload.dtype)],
            axis=-1,
        )
    lo = payload[..., 0::2].astype(jnp.int64) & 0xFFFFFFFF
    hi = payload[..., 1::2].astype(jnp.int64)
    return (hi << 32) | lo


def unpack_words(packed, P: int):
    """Inverse of pack_words: [..., PP] i64 → [..., P] i32."""
    lo = (packed & 0xFFFFFFFF).astype(jnp.int32)
    hi = (packed >> 32).astype(jnp.int32)
    out = jnp.stack([lo, hi], axis=-1).reshape(packed.shape[:-1] + (-1,))
    return out[..., :P]


def packed_words(P: int) -> int:
    return (P + 1) // 2
