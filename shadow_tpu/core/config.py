"""Experiment configuration: YAML file + programmatic overrides.

Accepts the reference's YAML schema (docs/shadow_config_spec.md;
src/main/core/support/configuration.rs): ``general``, ``network``,
``experimental``, ``host_defaults``, and ``hosts.<name>`` with a ``processes``
list and ``quantity`` expansion. Host defaults merge field-wise into each host
(configuration.rs:102-108); unknown fields are rejected like serde's
``deny_unknown_fields``.

Device-facing additions (not in the reference schema) live under
``experimental``: event pool capacity, per-window event cap, sockets per host
— the static shapes the TPU engine compiles against.
"""

from __future__ import annotations

import dataclasses
import io
from typing import Any, Optional

import yaml

from shadow_tpu.core import units


class ConfigError(ValueError):
    pass


def _check_fields(section: str, d: dict, allowed: set[str]) -> None:
    unknown = set(d) - allowed
    if unknown:
        raise ConfigError(f"unknown field(s) in {section}: {sorted(unknown)}")


@dataclasses.dataclass
class GeneralOptions:
    """docs/shadow_config_spec.md `general` (configuration.rs:129-178)."""

    stop_time: int = 0  # ns
    seed: int = 1
    parallelism: int = 1
    bootstrap_end_time: int = 0  # ns; infinite-bandwidth lossless warmup
    log_level: str = "info"
    heartbeat_interval: int = units.parse_time_ns("1 s")
    data_directory: str = "shadow.data"
    template_directory: Optional[str] = None
    progress: bool = False

    @classmethod
    def from_dict(cls, d: dict) -> "GeneralOptions":
        _check_fields("general", d, {f.name for f in dataclasses.fields(cls)})
        out = cls()
        if "stop_time" not in d:
            raise ConfigError("general.stop_time is required")
        out.stop_time = units.parse_time_ns(d["stop_time"])
        out.seed = int(d.get("seed", out.seed))
        out.parallelism = int(d.get("parallelism", out.parallelism))
        out.bootstrap_end_time = units.parse_time_ns(d.get("bootstrap_end_time", 0))
        out.log_level = str(d.get("log_level", out.log_level))
        out.heartbeat_interval = units.parse_time_ns(
            d.get("heartbeat_interval", "1 s")
        )
        out.data_directory = str(d.get("data_directory", out.data_directory))
        td = d.get("template_directory")
        out.template_directory = None if td is None else str(td)
        out.progress = bool(d.get("progress", False))
        return out


@dataclasses.dataclass
class GraphSource:
    """network.graph: gml file/inline or built-in named graph."""

    type: str = "gml"  # "gml" | "1_gbit_switch"
    path: Optional[str] = None
    inline: Optional[str] = None

    @classmethod
    def from_dict(cls, d: dict) -> "GraphSource":
        _check_fields("network.graph", d, {"type", "path", "inline", "file"})
        g = cls(type=str(d.get("type", "gml")))
        if g.type not in ("gml", "1_gbit_switch"):
            raise ConfigError(f"unknown network.graph.type {g.type!r}")
        g.path = d.get("path") or d.get("file")
        g.inline = d.get("inline")
        if g.type == "gml" and not (g.path or g.inline):
            raise ConfigError("network.graph needs `path` or `inline` for type gml")
        return g


# Built-in graph matching the reference's `1_gbit_switch` compiled-in topology.
ONE_GBIT_SWITCH_GML = """\
graph [
  directed 0
  node [
    id 0
    bandwidth_down "1 Gbit"
    bandwidth_up "1 Gbit"
  ]
  edge [
    source 0
    target 0
    latency "1 ms"
    packet_loss 0.0
  ]
]
"""


@dataclasses.dataclass
class NetworkOptions:
    """docs/shadow_config_spec.md `network` (configuration.rs:198-209)."""

    graph: GraphSource = dataclasses.field(default_factory=GraphSource)
    use_shortest_path: bool = True

    @classmethod
    def from_dict(cls, d: dict) -> "NetworkOptions":
        _check_fields("network", d, {"graph", "use_shortest_path"})
        if "graph" not in d:
            raise ConfigError("network.graph is required")
        return cls(
            graph=GraphSource.from_dict(d["graph"]),
            use_shortest_path=bool(d.get("use_shortest_path", True)),
        )


@dataclasses.dataclass
class ExperimentalOptions:
    """Reference experimental flags we honor (configuration.rs:229-340) plus
    the TPU engine's static-shape knobs."""

    # Reference-compatible:
    runahead: Optional[int] = None  # ns; None = derive from min topology latency
    interface_buffer: int = units.parse_bytes("1024000")
    interface_qdisc: str = "fifo"  # "fifo" | "roundrobin"
    socket_recv_buffer: int = 174760
    socket_send_buffer: int = 131072
    socket_recv_autotune: bool = True
    socket_send_autotune: bool = True
    use_memory_manager: bool = True
    use_seccomp: bool = True
    use_syscall_counters: bool = False
    use_object_counters: bool = True
    worker_threads: Optional[int] = None
    interpose_method: str = "preload"
    # TPU engine static shapes:
    event_capacity: int = 1 << 14  # event-pool rows per shard
    # Occupancy-adaptive pool gearing (core/gearbox.py): compile the window
    # kernel at a ladder of pool capacities (pool_gears tiers: C/4, C/2, C
    # for 3) and let the drivers pick the smallest gear covering live
    # occupancy plus hysteresis headroom at each dispatch boundary. 1 = a
    # single fixed-capacity kernel (the pre-gearbox build). Results are
    # identical either way (capacity only bounds what fits, never the
    # order); gears only change wall time and compile count.
    pool_gears: int = 1
    events_per_host_per_window: int = 32  # K: scan depth of the window kernel
    sockets_per_host: int = 8
    router_queue_slots: int = 64  # per-host CoDel ring capacity
    # router vtable variant (router.c:49-57): codel | static | single
    router_queue_variant: str = "codel"
    # per-syscall-handler wall timing (-DUSE_PERF_TIMERS analog, setup:76-79)
    use_perf_timers: bool = False
    # shim-side sim-time stamping of managed stdout/stderr lines
    # (shim_logger.c analog; off by default so app output stays byte-exact
    # for the determinism comparisons)
    use_shim_log_stamps: bool = False
    # Managed-plane path model: None = auto (lazy per-source Dijkstra with
    # a row cache — topology.c:1144-1259 analog — once the graph exceeds
    # lazy_paths_threshold used vertices; dense baked matrices below).
    # True/False force. The device plane always bakes dense (per-packet
    # lookups on device cannot fault rows in).
    lazy_paths: Optional[bool] = None
    lazy_paths_threshold: int = 4096
    # Per-packet delivery-status breadcrumb trails (packet.c:37-77 PDS_*):
    # packets carry an extra trail word; per-host registers keep the last
    # dropped/delivered packet's ordered stage chain. Debug mode (one
    # extra payload word of sort traffic).
    packet_trails: bool = False
    devices: int = 1  # mesh size over the host axis
    # Islands engine (engine.IslandSpec / parallel/islands.py): split the
    # host axis into num_shards blocks, each owning a local event pool and
    # a local dense window; cross-shard emissions ride a bounded
    # all_to_all (exchange_slots rows per destination shard per window).
    # 1 = the global single-pool engine. island_mode "vmap" batches the
    # shards on one chip (virtual islands); "shard_map" places them on
    # real mesh devices.
    num_shards: int = 1
    exchange_slots: int = 0  # 0 = auto-size
    island_mode: str = "vmap"  # "vmap" | "shard_map"
    # Asynchronous conservative sync (cs/0409032): the fused islands
    # driver advances per-shard virtual-time frontiers bounded by
    # topology-derived lookahead instead of one fleet-wide window
    # barrier; false restores the lockstep barrier loop. async_spread
    # bounds how far (ns of virtual time) any shard may run ahead of the
    # slowest before yielding its slot (roughness suppression,
    # cond-mat/0302050); 0 auto-derives from the lookahead matrix.
    async_islands: bool = True
    async_spread: int = 0
    # Multi-chip frontier exchange (parallel/islands.py): "ppermute"
    # replaces the async driver's all_gather with neighbor-only
    # collective-permute rounds covering the in-edge lookahead matrix
    # (per-chip volume scales with topology degree, not mesh size);
    # "all_gather" keeps the gather — the bench comparison arm. Chains
    # are bit-identical either way.
    mesh_exchange: str = "ppermute"  # "ppermute" | "all_gather"
    # Initial host->chip placement: "block" = contiguous global-id
    # blocks; "min_cut" = greedy affinity clustering at partition time
    # (parallel/balancer.min_cut_placement) so lookahead-critical
    # low-latency links land intra-chip (implies `rebalance`).
    placement: str = "block"  # "block" | "min_cut"
    # Dead chips to build AROUND (elastic mesh resilience,
    # parallel/elastic.py): indices into the deterministic device order
    # that the surviving-mesh rebuild must skip. Normally set by the
    # elastic runner's relayout, not by hand.
    exclude_chips: tuple = ()
    # Between-window host->shard re-sharding on load skew (the P3
    # work-stealing replacement, scheduler_policy_host_steal.c analog).
    rebalance: bool = False
    # Self-balancing fleet (parallel/balancer.py): the closed-loop
    # hot-shard controller — detect a chronic frontier laggard with
    # skewed resident load, refine the host->shard assignment by greedy
    # min-cut, migrate live at a dispatch boundary with a verified digest
    # chain, roll back + cool down on any mid-migration failure. Implies
    # `rebalance` (the slot_of routing seam). The balance_* knobs are the
    # hysteresis guards (docs/fault_tolerance.md §6).
    balancer: bool = False
    balance_hot_ratio: float = 1.5
    balance_streak: int = 3
    balance_cooldown: int = 8
    balance_max_moves: int = 8
    inbox_slots: int = 8  # B: per-host intra-window self-event slots
    outbox_slots: int = 64  # O: per-host emission slots per window
    # CPU model (host/cpu.c analog): simulated processing cost per syscall
    # on the managed-process plane; accumulated delay is applied to the
    # virtual clock once it exceeds max_unapplied_cpu_latency.
    cpu_ns_per_syscall: int = 0  # 0 = CPU model off
    max_unapplied_cpu_latency: int = units.parse_time_ns("1 us")
    # Device telemetry counter block (shadow_tpu/obs/counters.py): window
    # -plane counters + per-host event/virtual-time rows carried in
    # SimState and updated inside the jitted kernel. On by default (the
    # updates are fused adds, measured <= 3% of step time by bench.py's
    # obs-overhead smoke row); False compiles them out — the control arm
    # of that measurement.
    obs_counters: bool = True
    # Determinism-audit digest chain (shadow_tpu/obs/audit.py): fold every
    # committed event's key into the per-host rolling-mix chain inside the
    # window kernel. On by default (fused i64 arithmetic, gated <= 3% by
    # bench.py --audit-smoke); False compiles the folds out — the control
    # arm of that measurement.
    audit_digest: bool = True
    # Flight recorder (shadow_tpu/obs/flight.py): device-resident ring of
    # the last R committed event records per host, flushed to a binary
    # spool at handoff boundaries (--flight-out) and convertible into a
    # virtual-time Perfetto clock domain (tools/flight_to_trace.py).
    # Accepts an integer capacity or {capacity: R}; 0 = compiled out.
    flight_recorder: int = 0
    # Pipelined CPU↔TPU handoff (core/pipeline.py): the driver loops
    # double-buffer window dispatches — issue window N+1 asynchronously
    # while the host drains window N's deliveries, synchronizing only at
    # the fetch point. Results are bit-identical either way (speculative
    # issues are recomputed, never reused, whenever a handoff mutates
    # state); false restores the strictly-serial loop — the bench
    # comparison arm (bench.py --pipeline-smoke).
    pipelined_dispatch: bool = True
    # Multi-worker host plane (core/hostplane.py): shard the host-side
    # handoff drain per owning host across N pinned workers with a
    # deterministic (virtual-time, host-gid) merge — bit-identical to the
    # serial drain by construction. 1 (the default) keeps today's serial
    # inline drain and emits no hostplane.* metrics keys.
    host_workers: int = 1
    # Profiling plane (obs/prof.py, schema v18 `prof.*`): record a
    # fixed-capacity ring of per-handoff interval deltas (wall +
    # committed virtual time, event/window/yield/blocked counters,
    # per-shard async frontiers) plus log-bucketed latency histograms,
    # dumped as a schema-versioned profile doc (--profile-out overrides
    # the path). Off by default — the recorder is read-only against the
    # sim, but the ticks themselves cost a little host wall per handoff.
    profiler: bool = False
    # Ring capacity in intervals; oldest intervals are dropped (and
    # counted) once the ring wraps. Must be >= 8.
    profiler_ring: int = 512
    # CPU↔TPU seam: route managed-process UDP through the device-stepped
    # network (procs/bridge.py). The BASELINE north-star path.
    use_device_network: bool = False
    # Also carry managed TCP connections on the device TCP state machine
    # (net/tcp.py): handshake, Reno, retransmission and delivery timing all
    # computed by the window kernel. Requires use_device_network.
    use_device_tcp: bool = False

    @classmethod
    def from_dict(cls, d: dict) -> "ExperimentalOptions":
        fields = {f.name for f in dataclasses.fields(cls)}
        # Accept (and ignore) reference-only flags that have no TPU analog so
        # reference configs load unmodified.
        ignored = {
            "use_cpu_pinning", "use_sched_fifo", "scheduler_policy",
            "preload_spin_max", "use_explicit_block_message",
            "use_shim_syscall_handler", "use_o_n_waitpid_workarounds",
            "use_legacy_working_dir", "host_heartbeat_interval",
        }
        _check_fields("experimental", d, fields | ignored)
        out = cls()
        if d.get("runahead") is not None:
            # Bare numbers are seconds (configuration.rs:289 value_name="seconds").
            out.runahead = units.parse_time_ns(d["runahead"])
        for name in ("interface_buffer", "socket_recv_buffer", "socket_send_buffer"):
            if name in d:
                setattr(out, name, units.parse_bytes(d[name]))
        for name in (
            "use_device_network", "use_device_tcp", "obs_counters",
            "audit_digest", "pipelined_dispatch",
            "socket_recv_autotune", "socket_send_autotune", "use_memory_manager",
            "use_seccomp", "use_syscall_counters", "use_object_counters",
        ):
            if name in d:
                setattr(out, name, bool(d[name]))
        if out.use_device_tcp and not out.use_device_network:
            raise ConfigError(
                "experimental.use_device_tcp requires use_device_network"
            )
        if d.get("cpu_ns_per_syscall") is not None:
            # bare numbers are NANOSECONDS here (the field name says so)
            out.cpu_ns_per_syscall = units.parse_time_ns(
                d["cpu_ns_per_syscall"], default_unit="ns"
            )
        if d.get("max_unapplied_cpu_latency") is not None:
            out.max_unapplied_cpu_latency = units.parse_time_ns(
                d["max_unapplied_cpu_latency"], default_unit="ns"
            )
        for name in (
            "event_capacity", "events_per_host_per_window", "sockets_per_host",
            "router_queue_slots", "devices", "inbox_slots", "outbox_slots",
            "num_shards", "exchange_slots", "pool_gears",
        ):
            if name in d:
                setattr(out, name, int(d[name]))
        if out.pool_gears < 1:
            raise ConfigError("experimental.pool_gears must be >= 1")
        if d.get("host_workers") is not None:
            out.host_workers = int(d["host_workers"])
            if out.host_workers < 1:
                raise ConfigError("experimental.host_workers must be >= 1")
        if "profiler" in d:
            out.profiler = bool(d["profiler"])
        if d.get("profiler_ring") is not None:
            out.profiler_ring = int(d["profiler_ring"])
            if out.profiler_ring < 8:
                raise ConfigError("experimental.profiler_ring must be >= 8")
        if d.get("flight_recorder") is not None:
            v = d["flight_recorder"]
            if isinstance(v, dict):
                _check_fields("experimental.flight_recorder", v, {"capacity"})
                v = v.get("capacity", 0)
            out.flight_recorder = int(v)
            if out.flight_recorder < 0:
                raise ConfigError(
                    "experimental.flight_recorder capacity must be >= 0"
                )
        if "rebalance" in d:
            out.rebalance = bool(d["rebalance"])
        if "balancer" in d:
            out.balancer = bool(d["balancer"])
        for name in ("balance_streak", "balance_cooldown",
                     "balance_max_moves"):
            if name in d:
                setattr(out, name, int(d[name]))
                if getattr(out, name) < 1:
                    raise ConfigError(
                        f"experimental.{name} must be >= 1"
                    )
        if "balance_hot_ratio" in d:
            out.balance_hot_ratio = float(d["balance_hot_ratio"])
            if out.balance_hot_ratio <= 1.0:
                raise ConfigError(
                    "experimental.balance_hot_ratio must be > 1.0 (a "
                    "ratio at/below the mean would trigger constantly)"
                )
        if "async_islands" in d:
            out.async_islands = bool(d["async_islands"])
        if d.get("async_spread") is not None:
            out.async_spread = units.parse_time_ns(
                d["async_spread"], default_unit="ns"
            )
            if out.async_spread < 0:
                raise ConfigError(
                    "experimental.async_spread must be >= 0 ns"
                )
        if "island_mode" in d:
            v = str(d["island_mode"]).lower()
            if v not in ("vmap", "shard_map"):
                raise ConfigError(f"unknown island_mode {v!r}")
            out.island_mode = v
        if d.get("exclude_chips") is not None:
            v = d["exclude_chips"]
            if (not isinstance(v, (list, tuple))
                    or not all(isinstance(c, int) and c >= 0 for c in v)):
                raise ConfigError(
                    "experimental.exclude_chips must be a list of "
                    "non-negative chip indices"
                )
            out.exclude_chips = tuple(int(c) for c in v)
        if "mesh_exchange" in d:
            v = str(d["mesh_exchange"]).lower()
            if v not in ("ppermute", "all_gather"):
                raise ConfigError(f"unknown mesh_exchange {v!r}")
            out.mesh_exchange = v
        if "placement" in d:
            v = str(d["placement"]).lower()
            if v not in ("block", "min_cut"):
                raise ConfigError(f"unknown placement {v!r}")
            out.placement = v
        if "use_perf_timers" in d:
            out.use_perf_timers = bool(d["use_perf_timers"])
        if "use_shim_log_stamps" in d:
            out.use_shim_log_stamps = bool(d["use_shim_log_stamps"])
        if "lazy_paths" in d and d["lazy_paths"] is not None:
            out.lazy_paths = bool(d["lazy_paths"])
        if "lazy_paths_threshold" in d:
            out.lazy_paths_threshold = int(d["lazy_paths_threshold"])
        if "packet_trails" in d:
            out.packet_trails = bool(d["packet_trails"])
        if "router_queue_variant" in d:
            v = str(d["router_queue_variant"]).lower()
            if v not in ("codel", "static", "single"):
                raise ConfigError(f"unknown router_queue_variant {v!r}")
            out.router_queue_variant = v
        if "worker_threads" in d and d["worker_threads"] is not None:
            out.worker_threads = int(d["worker_threads"])
        if "interface_qdisc" in d:
            q = str(d["interface_qdisc"]).lower()
            if q not in ("fifo", "roundrobin", "rr"):
                raise ConfigError(f"unknown interface_qdisc {q!r}")
            out.interface_qdisc = "roundrobin" if q in ("roundrobin", "rr") else "fifo"
        if "interpose_method" in d:
            out.interpose_method = str(d["interpose_method"])
        return out


@dataclasses.dataclass
class ProcessOptions:
    """hosts.<name>.processes[*] (configuration.rs:471-515)."""

    path: str = ""
    args: list[str] = dataclasses.field(default_factory=list)
    environment: dict[str, str] = dataclasses.field(default_factory=dict)
    quantity: int = 1
    start_time: int = 0  # ns
    stop_time: Optional[int] = None  # ns

    @classmethod
    def from_dict(cls, d: dict) -> "ProcessOptions":
        _check_fields(
            "process", d,
            {"path", "args", "environment", "quantity", "start_time", "stop_time"},
        )
        if "path" not in d:
            raise ConfigError("process.path is required")
        args = d.get("args", [])
        if isinstance(args, str):
            args = args.split()
        env = d.get("environment", {}) or {}
        if isinstance(env, str):
            env = dict(kv.split("=", 1) for kv in env.split(";") if kv)
        out = cls(
            path=str(d["path"]),
            args=[str(a) for a in args],
            environment={str(k): str(v) for k, v in env.items()},
            quantity=int(d.get("quantity", 1)),
            start_time=units.parse_time_ns(d.get("start_time", 0)),
            stop_time=(
                units.parse_time_ns(d["stop_time"])
                if d.get("stop_time") is not None
                else None
            ),
        )
        if out.stop_time is not None and out.stop_time <= out.start_time:
            raise ConfigError(
                f"process {out.path}: stop_time must be after start_time"
            )
        return out


@dataclasses.dataclass
class HostOptions:
    """hosts.<name> merged with host_defaults (configuration.rs:386-431,498+)."""

    name: str = ""
    bandwidth_down: Optional[int] = None  # bits/sec; None = from graph vertex
    bandwidth_up: Optional[int] = None
    ip_address_hint: Optional[str] = None
    country_code_hint: Optional[str] = None
    city_code_hint: Optional[str] = None
    log_level: Optional[str] = None
    pcap_directory: Optional[str] = None
    network_node_id: Optional[int] = None
    quantity: int = 1
    processes: list[ProcessOptions] = dataclasses.field(default_factory=list)
    # Device-side app model (shadow_tpu extension): workloads that run fully
    # on-device with no managed process — "phold", "udp_flood", "tcp_bulk",
    # "udp_echo_server", ... with model-specific options.
    app_model: Optional[str] = None
    app_options: dict[str, Any] = dataclasses.field(default_factory=dict)
    # Device-plane CPU model (host/cpu.c analog): simulated processing cost
    # per device event; a loaded host's events serialize on its virtual CPU.
    cpu_ns_per_event: int = 0

    @classmethod
    def from_dict(cls, name: str, d: dict, defaults: dict) -> "HostOptions":
        allowed = {
            "bandwidth_down", "bandwidth_up", "options", "quantity", "processes",
            "ip_address_hint", "country_code_hint", "city_code_hint",
            "log_level", "pcap_directory", "network_node_id",
            "app_model", "app_options", "heartbeat_interval",
            "heartbeat_log_info", "heartbeat_log_level", "cpu_ns_per_event",
        }
        _check_fields(f"hosts.{name}", d, allowed)
        merged = dict(defaults)
        merged.update(d.get("options", {}) or {})
        merged.update({k: v for k, v in d.items() if k not in ("processes", "options")})
        out = cls(name=name)
        if merged.get("bandwidth_down") is not None:
            out.bandwidth_down = units.parse_bits(merged["bandwidth_down"])
        if merged.get("bandwidth_up") is not None:
            out.bandwidth_up = units.parse_bits(merged["bandwidth_up"])
        for f in (
            "ip_address_hint", "country_code_hint", "city_code_hint",
            "log_level", "pcap_directory",
        ):
            if merged.get(f) is not None:
                setattr(out, f, str(merged[f]))
        if merged.get("network_node_id") is not None:
            out.network_node_id = int(merged["network_node_id"])
        out.quantity = int(merged.get("quantity", 1))
        out.processes = [ProcessOptions.from_dict(p) for p in d.get("processes", [])]
        if merged.get("app_model") is not None:
            out.app_model = str(merged["app_model"])
        out.app_options = dict(merged.get("app_options", {}) or {})
        if merged.get("cpu_ns_per_event") is not None:
            out.cpu_ns_per_event = units.parse_time_ns(
                merged["cpu_ns_per_event"], default_unit="ns"
            )
        return out

    def expand(self) -> list["HostOptions"]:
        """quantity: N>1 → N hosts named name1..nameN (reference:
        controller.c:277-280 appends i+1 for every host when quantity > 1)."""
        if self.quantity <= 1:
            return [self]
        out = []
        for i in range(1, self.quantity + 1):
            h = dataclasses.replace(self, quantity=1)
            h.name = f"{self.name}{i}"
            out.append(h)
        return out


@dataclasses.dataclass
class FaultOptions:
    """`faults` section: deterministic fault injection + recovery policy
    (shadow_tpu/faults; no reference analog — Shadow dies whole-run on any
    plugin failure)."""

    # fault-plan JSON file (same schema as --fault-plan), merged with the
    # inline `inject` list; both are virtual-time-keyed injection lists
    plan: Optional[str] = None
    inject: list[dict] = dataclasses.field(default_factory=list)
    # what the supervisor does when a managed process wedges (IPC-timeout
    # escalation ladder exhausted) — abort the run, or quarantine the
    # simulated host (mark it dead, drain its events, keep running)
    on_proc_failure: str = "abort"
    # escalation ladder: extra timed waits (doubling backoff) before a
    # non-responsive managed process is declared wedged
    ipc_timeout_retries: int = 1
    # what the backend supervisor (core/supervisor.py) does when the
    # ACCELERATOR is lost mid-run: wait (drain to checkpoint, re-probe
    # until it returns, hot-resume), cpu (drain, re-lower the kernels on
    # the CPU backend and keep advancing, upshift back on recovery), or
    # abort (drain, then raise — resume with --resume). None = supervision
    # only arms when the fault plan carries backend ops (then abort).
    on_backend_loss: Optional[str] = None

    @classmethod
    def from_dict(cls, d: dict) -> "FaultOptions":
        _check_fields(
            "faults", d,
            {"plan", "inject", "on_proc_failure", "ipc_timeout_retries",
             "on_backend_loss"},
        )
        out = cls()
        if d.get("plan") is not None:
            out.plan = str(d["plan"])
        if d.get("inject"):
            out.inject = list(d["inject"])
            # fail at config time, not mid-run: entries must parse
            from shadow_tpu.faults import plan as plan_mod

            try:
                plan_mod.parse_fault_plan(out.inject)
            except plan_mod.FaultPlanError as e:
                raise ConfigError(f"faults.inject: {e}") from e
        if "on_proc_failure" in d:
            v = str(d["on_proc_failure"]).lower()
            if v not in ("abort", "quarantine"):
                raise ConfigError(
                    f"faults.on_proc_failure must be abort|quarantine, "
                    f"got {v!r}"
                )
            out.on_proc_failure = v
        if "ipc_timeout_retries" in d:
            out.ipc_timeout_retries = int(d["ipc_timeout_retries"])
            if out.ipc_timeout_retries < 0:
                raise ConfigError("faults.ipc_timeout_retries must be >= 0")
        if d.get("on_backend_loss") is not None:
            v = str(d["on_backend_loss"]).lower()
            if v not in ("wait", "cpu", "abort", "relayout"):
                raise ConfigError(
                    f"faults.on_backend_loss must be "
                    f"wait|cpu|abort|relayout, "
                    f"got {v!r}"
                )
            out.on_backend_loss = v
        return out

    def load_faults(self) -> list:
        """Materialize the merged injection list (plan file + inline),
        ordered by (at, declaration)."""
        from shadow_tpu.faults import plan as plan_mod

        faults = []
        if self.plan:
            faults.extend(plan_mod.load_fault_plan(self.plan))
        if self.inject:
            inline = plan_mod.parse_fault_plan(self.inject)
            base = len(faults)
            for f in inline:
                f.seq += base  # plan-file entries order before inline ones
            faults.extend(inline)
        faults.sort(key=lambda f: (f.at_ns, f.seq))
        return faults


@dataclasses.dataclass
class FleetOptions:
    """`fleet` section: batched multi-experiment execution knobs
    (shadow_tpu/fleet; consumed by the `sweep` CLI subcommand). These are
    scheduler-plane values — they never compile into the window kernel,
    so sweep jobs may carry them without breaking kernel sharing."""

    lanes: int = 0  # device lanes; 0 = one lane per job
    deadline_s: Optional[float] = None  # wall-clock budget per job
    sync: str = "conservative"  # "conservative" | "optimistic"
    windows_per_dispatch: int = 32
    checkpoint_every: int = 0  # ns of fleet frontier; 0 = off
    checkpoint_dir: Optional[str] = None

    @classmethod
    def from_dict(cls, d: dict) -> "FleetOptions":
        _check_fields(
            "fleet", d,
            {"lanes", "deadline_s", "sync", "windows_per_dispatch",
             "checkpoint_every", "checkpoint_dir"},
        )
        out = cls()
        if "lanes" in d:
            out.lanes = int(d["lanes"])
            if out.lanes < 0:
                raise ConfigError("fleet.lanes must be >= 0")
        if d.get("deadline_s") is not None:
            out.deadline_s = float(d["deadline_s"])
            if out.deadline_s <= 0:
                raise ConfigError("fleet.deadline_s must be > 0")
        if "sync" in d:
            v = str(d["sync"]).lower()
            if v not in ("conservative", "optimistic"):
                raise ConfigError(
                    f"fleet.sync must be conservative|optimistic, got {v!r}"
                )
            out.sync = v
        if "windows_per_dispatch" in d:
            out.windows_per_dispatch = int(d["windows_per_dispatch"])
            if out.windows_per_dispatch < 1:
                raise ConfigError("fleet.windows_per_dispatch must be >= 1")
        if d.get("checkpoint_every") is not None:
            out.checkpoint_every = units.parse_time_ns(d["checkpoint_every"])
        if d.get("checkpoint_dir") is not None:
            out.checkpoint_dir = str(d["checkpoint_dir"])
        return out


@dataclasses.dataclass
class QdiscOptions:
    """`qdisc` section: the per-interface scheduling plane
    (shadow_tpu/net/qdisc). `discipline: fifo` (the default) keeps the
    NIC's plain send ring — runs with no qdisc section are bit-identical
    to pre-qdisc builds. pifo/eiffel own a device-resident `[H, Q]` queue
    plane stepped inside the window kernel; every knob here shapes that
    kernel, so sweep jobs may NOT vary this section (fleet/sweep
    DATA_PATHS excludes it, same as experimental)."""

    # fifo | roundrobin | pifo | eiffel ("fifo" defers to the legacy
    # experimental.interface_qdisc string so old configs keep working)
    discipline: str = "fifo"
    rank: str = "fifo"  # fifo | prio | wfq
    queue_slots: int = 64  # per-host queue capacity Q
    buckets: int = 16  # eiffel: bucket count B
    bucket_width: int = 1  # eiffel: rank units per bucket
    classes: int = 4  # wfq/shaping flow classes
    weights: Optional[list] = None  # per-class wfq weights (len == classes)
    # per-class token-bucket shaping rates, class index → bandwidth
    # (e.g. {0: "10 Mbit"}); empty = unshaped
    shaping: dict = dataclasses.field(default_factory=dict)
    drop: str = "none"  # none | red | codel
    red_min_frac: float = 0.25
    red_max_frac: float = 0.75
    red_max_p: float = 0.1
    # host-name-prefix → flow class pin (applies to every expanded host
    # whose name starts with the prefix); unpinned hosts classify
    # per-packet by socket slot
    overrides: dict = dataclasses.field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "QdiscOptions":
        _check_fields(
            "qdisc", d,
            {"discipline", "rank", "queue_slots", "buckets", "bucket_width",
             "classes", "weights", "shaping", "drop", "red_min_frac",
             "red_max_frac", "red_max_p", "overrides"},
        )
        out = cls()
        if "discipline" in d:
            v = str(d["discipline"]).lower()
            if v not in ("fifo", "roundrobin", "pifo", "eiffel"):
                raise ConfigError(
                    f"qdisc.discipline must be fifo|roundrobin|pifo|eiffel, "
                    f"got {v!r}"
                )
            out.discipline = v
        if "rank" in d:
            v = str(d["rank"]).lower()
            if v not in ("fifo", "prio", "wfq"):
                raise ConfigError(
                    f"qdisc.rank must be fifo|prio|wfq, got {v!r}"
                )
            out.rank = v
        for k in ("queue_slots", "buckets", "bucket_width", "classes"):
            if k in d:
                setattr(out, k, int(d[k]))
        if out.queue_slots < 1:
            raise ConfigError("qdisc.queue_slots must be >= 1")
        if out.buckets < 2:
            raise ConfigError("qdisc.buckets must be >= 2")
        if out.bucket_width < 1:
            raise ConfigError("qdisc.bucket_width must be >= 1")
        if out.classes < 1:
            raise ConfigError("qdisc.classes must be >= 1")
        if d.get("weights") is not None:
            out.weights = [float(w) for w in d["weights"]]
            if len(out.weights) != out.classes:
                raise ConfigError(
                    f"qdisc.weights length {len(out.weights)} != classes "
                    f"{out.classes}"
                )
            if any(w <= 0 for w in out.weights):
                raise ConfigError("qdisc.weights must be > 0")
        for c, bw in (d.get("shaping") or {}).items():
            ci = int(c)
            if not (0 <= ci < out.classes):
                raise ConfigError(
                    f"qdisc.shaping class {ci} out of range [0, "
                    f"{out.classes})"
                )
            out.shaping[ci] = units.parse_bits(bw)
        if "drop" in d:
            v = str(d["drop"]).lower()
            if v not in ("none", "red", "codel"):
                raise ConfigError(
                    f"qdisc.drop must be none|red|codel, got {v!r}"
                )
            out.drop = v
        for k in ("red_min_frac", "red_max_frac", "red_max_p"):
            if k in d:
                setattr(out, k, float(d[k]))
        if not (0.0 <= out.red_min_frac < out.red_max_frac <= 1.0):
            raise ConfigError(
                "qdisc red thresholds need "
                "0 <= red_min_frac < red_max_frac <= 1"
            )
        if not (0.0 < out.red_max_p <= 1.0):
            raise ConfigError("qdisc.red_max_p must be in (0, 1]")
        for prefix, c in (d.get("overrides") or {}).items():
            ci = int(c)
            if not (0 <= ci < out.classes):
                raise ConfigError(
                    f"qdisc.overrides[{prefix!r}] class {ci} out of range "
                    f"[0, {out.classes})"
                )
            out.overrides[str(prefix)] = ci
        if out.discipline in ("fifo", "roundrobin"):
            for k in ("rank", "drop"):
                if getattr(out, k) != cls.__dataclass_fields__[k].default:
                    raise ConfigError(
                        f"qdisc.{k} requires discipline pifo|eiffel"
                    )
        return out


@dataclasses.dataclass
class Config:
    general: GeneralOptions
    network: NetworkOptions
    experimental: ExperimentalOptions
    hosts: list[HostOptions]
    faults: FaultOptions = dataclasses.field(default_factory=FaultOptions)
    fleet: FleetOptions = dataclasses.field(default_factory=FleetOptions)
    qdisc: QdiscOptions = dataclasses.field(default_factory=QdiscOptions)
    # raw `sweep:` section, if present: expanded by shadow_tpu/fleet/sweep
    # (the `sweep` CLI subcommand); the single-run CLI refuses such files
    # with a pointer there instead of silently running only the base config
    sweep_raw: Optional[dict] = None

    @classmethod
    def from_dict(cls, d: dict) -> "Config":
        _check_fields(
            "config", d,
            {"general", "network", "experimental", "host_defaults", "hosts",
             "faults", "fleet", "qdisc", "sweep"},
        )
        if "general" not in d:
            raise ConfigError("general section is required")
        if "network" not in d:
            raise ConfigError("network section is required")
        general = GeneralOptions.from_dict(d["general"] or {})
        network = NetworkOptions.from_dict(d["network"] or {})
        experimental = ExperimentalOptions.from_dict(d.get("experimental") or {})
        faults = FaultOptions.from_dict(d.get("faults") or {})
        fleet = FleetOptions.from_dict(d.get("fleet") or {})
        qdisc = QdiscOptions.from_dict(d.get("qdisc") or {})
        defaults = d.get("host_defaults") or {}
        hosts: list[HostOptions] = []
        for name, hd in (d.get("hosts") or {}).items():
            hosts.extend(HostOptions.from_dict(str(name), hd or {}, defaults).expand())
        # Deterministic host ordering regardless of YAML dict order, matching
        # the reference's BTreeMap iteration (configuration.rs:75-76).
        hosts.sort(key=lambda h: h.name)
        return cls(general, network, experimental, hosts, faults, fleet,
                   qdisc, d.get("sweep"))

    def graph_gml(self) -> str:
        g = self.network.graph
        if g.type == "1_gbit_switch":
            return ONE_GBIT_SWITCH_GML
        if g.inline is not None:
            return g.inline
        assert g.path is not None
        with open(g.path) as f:
            return f.read()


def load_config(source) -> Config:
    """Load from a YAML path, file object, or string, or a raw dict."""
    if isinstance(source, dict):
        return Config.from_dict(source)
    if isinstance(source, io.IOBase):
        return Config.from_dict(yaml.safe_load(source))
    text = str(source)
    if "\n" in text or text.strip().startswith("{"):
        return Config.from_dict(yaml.safe_load(text))
    with open(text) as f:
        return Config.from_dict(yaml.safe_load(f))
