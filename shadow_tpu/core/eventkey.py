"""Deterministic total order over simulation events.

The reference guarantees a total, repeatable event order with the 4-tuple
(time, dst host id, src host id, per-source sequence number)
(src/main/core/work/event.c:109-152). We keep exactly that key, as four
sortable device arrays, and sort lexicographically with ``jax.lax.sort``
(num_keys=4) — no u128 packing needed, and int64 time stays exact.

Empty event slots carry time == simtime.NEVER so they sort to the end.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sort_events(time, dst, src, seq, *payload):
    """Sort event columns by the deterministic key (time, dst, src, seq).

    Returns the same tuple of arrays, reordered. ``payload`` arrays are
    carried through the sort (values, not keys).
    """
    operands = [time, dst, src, seq, *payload]
    out = jax.lax.sort(operands, num_keys=4, is_stable=True)
    return tuple(out)


def argsort_events(time, dst, src, seq):
    """Permutation that sorts events by the deterministic key."""
    idx = jnp.arange(time.shape[0], dtype=jnp.int32)
    *_, perm = jax.lax.sort([time, dst, src, seq, idx], num_keys=4, is_stable=True)
    return perm


def argsort_events_by_dst(time, dst, src, seq):
    """Permutation sorting by (dst, time, src, seq).

    Used to build the per-host [H, K] window matrix: events group by
    destination host, ordered by the deterministic key within each host.
    """
    idx = jnp.arange(time.shape[0], dtype=jnp.int32)
    *_, perm = jax.lax.sort([dst, time, src, seq, idx], num_keys=4, is_stable=True)
    return perm
