"""Host-spill overflow tier: the event pool never silently drops.

The reference never loses an event — its per-host queues grow on the heap
(scheduler.c:232-255). The TPU engine's pool is a static device array, and
until round 3 its only pressure valve was drop-on-overflow with per-workload
capacity hand-tuning (VERDICT r3 weak #6). This module replaces that with a
driver-level spill tier:

  * the fused window loop exits early when any shard's pool occupancy
    crosses a red-zone mark (one compare per window — no extra device
    sorts, no lax.cond, vmap/shard_map-safe);
  * the driver drains the LATEST-timestamped rows to UNBOUNDED host memory
    (numpy), keyed deterministically by the full event key;
  * subsequent dispatches clamp their stop time below the earliest spilled
    row's time, so no shard can process past an event that is parked on
    the host — the conservative invariant holds;
  * rows re-inject into free pool slots once processing frees them.

Slow under sustained over-capacity (host round-trips per episode), but
BIT-IDENTICAL to an oversized-pool run: processing order is governed by the
extraction's full-key sort, which never sees a spilled row before its
window, and pool slot order is immaterial.

A genuine drop remains possible only if a SINGLE window's merge inflow
exceeds the whole pool (red zone too small for one window's emissions);
that is counted in pool_overflow_dropped and asserted zero by the benches.
"""

from __future__ import annotations

import numpy as np

from shadow_tpu.core import simtime

NEVER = simtime.NEVER


def red_zone(capacity: int) -> int:
    """Rows reserved above the drain mark — headroom for one window's
    merge inflow (the engine's pool-headroom stall bounds that inflow to
    whatever still fits, so this is a perf margin, not a correctness
    bound). Never more than a quarter of the pool: tiny test pools must
    keep a working region."""
    return max(min(64, capacity // 4), capacity // 8)


def marks(capacity: int) -> tuple[int, int]:
    """(pressure mark, rebalance fill mark) for a pool of `capacity` rows:
    the red-zone geometry every capacity-holder shares. The marks are
    PER-GEAR under pool gearing (core/gearbox.py): each tier of the
    capacity ladder carries its own marks, so the fused loop's early exit
    and the drain target always describe the pool the kernel actually
    compiled against. Pressure must fire while the merge can still absorb
    one window's inflow; the fill mark sits below pressure so a rebalance
    exits the red zone and the fused loop keeps running windows."""
    hi = capacity - red_zone(capacity)
    return hi, max(1, (3 * hi) // 4)


class HostSpill:
    """Per-shard unbounded host-side overflow store.

    Rows are (time, dst, src, seq, kind, payload[PP]) numpy columns; the
    store keeps them sorted by (time, dst, src, seq) — the engine's total
    order (event.c:109-152) — so drains and injections are deterministic.
    """

    def __init__(self, num_shards: int, payload_cols: int):
        self.S = num_shards
        self.PP = payload_cols
        self._rows: list[tuple] = [
            self._empty() for _ in range(num_shards)
        ]
        # per-shard: earliest parked key-time of a PARTIALLY-resident host
        # (NEVER when every parked host is fully parked). Windows must end
        # strictly below this — see manage().
        self._partial_min: list[int] = [int(NEVER)] * num_shards
        self.drained_total = 0
        self.injected_total = 0
        self.rerouted_total = 0  # foreign in-transit rows shipped host-side
        self.episodes = 0

    def _empty(self):
        return (
            np.empty((0,), np.int64), np.empty((0,), np.int32),
            np.empty((0,), np.int32), np.empty((0,), np.int32),
            np.empty((0,), np.int32), np.empty((0, self.PP), np.int64),
        )

    @property
    def count(self) -> int:
        return sum(r[0].shape[0] for r in self._rows)

    @property
    def min_time(self) -> int:
        if self.count == 0:
            return int(NEVER)
        return int(min(
            r[0][0] for r in self._rows if r[0].shape[0]
        ))

    @staticmethod
    def _order(t, d, s, q):
        # np.lexsort: last key is primary
        return np.lexsort((q, s, d, t))

    def rebalance(self, shard: int, cols, fill: int):
        """Restore the tier invariant for one shard, HOST-GRANULAR: hosts
        claim pool space in order of their earliest event key, and a host
        is resident ALL-OR-NOTHING — a parked host has every one of its
        pending events on the host side and processes nothing until it is
        re-admitted. That makes the spill tier exactly order-preserving:
        a resident host's self-emissions only ever compete with its own
        fully-resident rows (identical to the oversized-pool run), and a
        parked host emits nothing. Deliveries from parked events land at
        >= spill_min + runahead, so the driver clamp (manage) keeps every
        resident host short of them. cols = (t, d, s, q, k, p[PP]) numpy
        arrays of the shard's pool; returns modified copies."""
        t, d, s, q, k, p = (np.array(c) for c in cols)
        st, sd, ss, sq, sk, sp = self._rows[shard]
        live = np.where(t != NEVER)[0]
        at = np.concatenate([t[live], st])
        ad = np.concatenate([d[live], sd])
        as_ = np.concatenate([s[live], ss])
        aq = np.concatenate([q[live], sq])
        ak = np.concatenate([k[live], sk])
        ap = np.concatenate([p[live], sp])
        order = self._order(at, ad, as_, aq)
        srt_d = ad[order]
        # hosts in order of first appearance (= earliest event key)
        uniq, first = np.unique(srt_d, return_index=True)
        host_rank = uniq[np.argsort(first)]
        counts = np.bincount(
            srt_d, minlength=(int(srt_d.max()) + 1 if srt_d.size else 1)
        )
        csum = np.cumsum(counts[host_rank])
        self._partial_min[shard] = int(NEVER)
        if csum.size and csum[0] > fill:
            # The earliest host alone exceeds the fill mark: admit its
            # earliest `fill` rows (it must be resident for progress —
            # and no more, or occupancy would sit in the red zone and the
            # fused loop's pressure gate would never run a window).
            # manage() clamps windows STRICTLY below its first parked row
            # — a partially-resident host must never process or emit
            # at/past its own parked backlog, or order could diverge from
            # the oversized-pool run.
            h0 = host_rank[0]
            h0_rows = order[srt_d == h0]
            keep = h0_rows[:fill]
            rest_mask = np.ones(order.shape[0], bool)
            pos = np.flatnonzero(srt_d == h0)[:fill]
            rest_mask[pos] = False
            rest = order[rest_mask]
            self._partial_min[shard] = int(at[h0_rows[fill]])
        else:
            # whole hosts while the total fits the fill mark (always >= 1)
            n_hosts = int(np.searchsorted(csum, fill, side="right"))
            n_hosts = max(n_hosts, 1) if csum.size else 0
            kept_hosts = host_rank[:n_hosts]
            member = np.isin(srt_d, kept_hosts)
            keep = order[member]
            rest = order[~member]
        n_pool = keep.shape[0]
        t[:] = NEVER
        t[:n_pool] = at[keep]
        d[:n_pool] = ad[keep]
        s[:n_pool] = as_[keep]
        q[:n_pool] = aq[keep]
        k[:n_pool] = ak[keep]
        p[:n_pool] = ap[keep]
        moved_out = rest.shape[0] - st.shape[0]
        if moved_out > 0:
            self.drained_total += moved_out
        else:
            self.injected_total += -moved_out
        self._rows[shard] = (
            at[rest], ad[rest], as_[rest], aq[rest], ak[rest], ap[rest]
        )
        return t, d, s, q, k, p

    def park(self, shard: int, rows) -> int:
        """Fault plane (engine.skew_hosts overflow): merge externally
        built (t, d, s, q, k, p[PP]) columns into one shard's parked set,
        re-establishing the (time, dst, src, seq) order invariant — the
        rows re-enter the pool through the normal rebalance path, late
        but never lost. Returns rows parked."""
        n = rows[0].shape[0]
        if n == 0:
            return 0
        merged = [
            np.concatenate([a, b]) for a, b in zip(self._rows[shard], rows)
        ]
        order = self._order(merged[0], merged[1], merged[2], merged[3])
        self._rows[shard] = tuple(c[order] for c in merged)
        self.drained_total += n
        return n

    def drain_hosts(self, dead) -> int:
        """Fault plane (engine.quarantine_host): drop every parked row
        destined to a dead host, all shards. Returns rows dropped. The
        stale `_partial_min` of a killed partial host only over-clamps the
        next window (conservative) — the following rebalance resets it."""
        dead_arr = np.asarray(sorted(int(h) for h in dead), np.int64)
        if dead_arr.size == 0:
            return 0
        dropped = 0
        for sh in range(self.S):
            t, d, s, q, k, p = self._rows[sh]
            mask = np.isin(d, dead_arr)
            n = int(mask.sum())
            if n:
                keep = ~mask
                self._rows[sh] = (
                    t[keep], d[keep], s[keep], q[keep], k[keep], p[keep]
                )
                dropped += n
        return dropped

    def stats(self) -> dict:
        return {
            "spill_resident": self.count,
            "spill_drained_total": self.drained_total,
            "spill_injected_total": self.injected_total,
            "spill_rerouted_total": self.rerouted_total,
            "spill_episodes": self.episodes,
        }


def manage(sim, spill: HostSpill, stop: int) -> int:
    """One spill-management pass for a Simulation (global or islands):
    rebalance any shard whose occupancy crossed the red zone — and every
    shard currently holding spilled rows — then return the stop time for
    the next dispatch, clamped below the earliest still-spilled row so no
    shard processes past an event parked on the host.

    Pool layout: global engine [C] (treated as one shard); islands
    [S, C_shard].
    """
    import jax

    pool = sim.state.pool
    island = getattr(pool.time, "ndim", 1) == 2
    import jax.numpy as jnp

    S = pool.time.shape[0] if island else 1
    hi, fill = sim._spill_marks()[:2]
    # occupancy reduces ON DEVICE — the full pool transfers to host only
    # when a shard actually needs a rebalance
    occ = np.atleast_1d(np.asarray(jax.device_get(
        jnp.sum(pool.time != NEVER, axis=-1)
    )))
    # fault plane (shadow_tpu/faults force_spill): one injected episode
    # rebalances EVERY shard regardless of occupancy — exercises the
    # drain/clamp/re-inject machinery under test control. One-shot.
    force = bool(getattr(sim, "_force_spill", False))
    if force:
        sim._force_spill = False
    act = [
        sh for sh in range(S)
        if force or occ[sh] >= hi or spill._rows[sh][0].shape[0]
    ]
    if not act:
        return stop

    cols_all = [
        np.array(jax.device_get(c))  # writable copies
        for c in (pool.time, pool.dst, pool.src, pool.seq, pool.kind,
                  pool.payload)
    ]
    if island:
        # A FOREIGN in-transit row (an exchange deferral whose destination
        # host lives on another shard) is protected by the STRICT
        # exch_deferred_min window-end clamp only while it sits in the
        # pool; letting rebalance() park it would downgrade that to the
        # spill clamp (min_time + runahead) and the destination host could
        # process its own events in [T, T+runahead) before the delivery
        # re-injects — diverging from the oversized-pool run (ADVICE r4,
        # high). Never park them: before rebalancing a shard, ship its
        # foreign rows host-side to the DESTINATION shard's spill store
        # (the locked-queue push of scheduler.c:232-255, done by the
        # driver), and rebalance the destination in the same pass so the
        # row is pool-resident — and ordinarily clamped — again before
        # the next window runs.
        Hl = sim.num_hosts // S
        slot_of = getattr(sim.params, "slot_of", None)
        slot_np = (
            np.asarray(jax.device_get(slot_of))
            if getattr(sim, "rebalance_enabled", False) and slot_of is not None
            else None
        )
        # The worklist GROWS: a destination shard appended here must have
        # its own foreign rows shipped out before ITS rebalance runs, or
        # rebalance() would park them (rerouted rows themselves are
        # local-dst at their owner, so each shard needs one pass — the
        # loop is bounded by S).
        worklist = list(act)
        qi = 0
        while qi < len(worklist):
            sh = worklist[qi]
            qi += 1
            t_sh = cols_all[0][sh]
            live = np.where(t_sh != NEVER)[0]
            d_live = cols_all[1][sh][live]
            owner = (
                slot_np[d_live] // Hl if slot_np is not None
                else d_live // Hl
            )
            fmask = owner != sh
            if not fmask.any():
                continue
            frows, fown = live[fmask], owner[fmask]
            for dst_sh in np.unique(fown):
                sel = frows[fown == dst_sh]
                add = tuple(c[sh][sel] for c in cols_all)
                merged = tuple(
                    np.concatenate([a, b])
                    for a, b in zip(spill._rows[int(dst_sh)], add)
                )
                order = spill._order(*merged[:4])
                spill._rows[int(dst_sh)] = tuple(m[order] for m in merged)
                spill.rerouted_total += sel.shape[0]
                if int(dst_sh) not in worklist:
                    worklist.append(int(dst_sh))
            t_sh[frows] = NEVER
        act = worklist
    for sh in act:
        spill.episodes += 1
        view = (
            tuple(c[sh] for c in cols_all) if island
            else tuple(cols_all)
        )
        view = spill.rebalance(sh, view, fill)
        if island:
            for c, v in zip(cols_all, view):
                c[sh] = v
        else:
            cols_all = [np.array(v) for v in view]
    import jax.numpy as jnp

    from shadow_tpu.core.state import EventPool

    sim.state = sim.state.replace(pool=EventPool(
        time=jnp.asarray(cols_all[0]), dst=jnp.asarray(cols_all[1]),
        src=jnp.asarray(cols_all[2]), seq=jnp.asarray(cols_all[3]),
        kind=jnp.asarray(cols_all[4]), payload=jnp.asarray(cols_all[5]),
    ))
    from shadow_tpu.obs import counters as obs_mod

    # telemetry: one spill-tier fire per rebalanced shard (the pool is
    # being rewritten on the host anyway — no extra sync)
    sim.state = obs_mod.bump_win(
        sim.state, obs_mod.WIN_SPILL_FIRES, len(act)
    )
    # Clamp: resident hosts may run up to spill_min + runahead — a parked
    # event at spill_min emits deliveries no earlier than that (the
    # conservative bound), and parked hosts themselves process nothing
    # (whole-host residency), so windows under spill stay FULL length and
    # results stay bit-exact. REQUIRES one manage() between consecutive
    # windows while the spill is active (drivers force single-window
    # dispatches then): an emission landing on a parked host mid-dispatch
    # would otherwise be processed ahead of that host's parked backlog.
    # A PARTIALLY-resident host additionally clamps windows strictly
    # below its first parked row.
    partial = min(spill._partial_min)
    return min(stop, spill.min_time + sim.runahead, partial)
