"""Simulated time as int64 nanoseconds.

Mirrors the reference's ``SimulationTime`` newtype (u64 ns,
src/main/core/support/simulation_time.rs) with the conventions the event
engine needs: an explicit "invalid/never" sentinel used as the empty-slot
marker in device-side event pools, and emulated-time epoch offset used when
reporting clock_gettime to managed processes.
"""

from __future__ import annotations

import numpy as np

NS_PER_US = 1_000
NS_PER_MS = 1_000_000
NS_PER_SEC = 1_000_000_000
NS_PER_MIN = 60 * NS_PER_SEC
NS_PER_HOUR = 3600 * NS_PER_SEC

# Empty-slot / "no event" sentinel. Max int64 so min-reductions naturally
# ignore empty slots (reference: EMUTIME_INVALID / SIMTIME_INVALID).
NEVER = np.iinfo(np.int64).max

# Unix-epoch offset reported to managed processes so that wall-clock syscalls
# (clock_gettime etc.) return plausible dates. The reference boots its
# simulation at an arbitrary fixed epoch; we use 2000-01-01T00:00:00Z.
EMULATED_EPOCH_NS = 946_684_800 * NS_PER_SEC

DTYPE = np.int64


def from_seconds(s: float) -> int:
    return int(round(s * NS_PER_SEC))


def from_millis(ms: float) -> int:
    return int(round(ms * NS_PER_MS))


def from_micros(us: float) -> int:
    return int(round(us * NS_PER_US))


def to_seconds(t: int) -> float:
    return t / NS_PER_SEC


def is_never(t) -> bool:
    return t == NEVER
