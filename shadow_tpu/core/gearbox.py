"""Occupancy-adaptive pool gearing: tiered window kernels.

The window kernel's dominant cost is its multi-operand stable sorts
(_dense_extract and the merge, core/engine.py), and sort cost on TPU
scales with rows × comparator stages. Pool capacity C is a STATIC shape
compiled into the kernel, sized for the burst worst case — but PHOLD-class
steady states occupy a small fraction of it (≈ H·msgload live events), so
a pool sized 8× above occupancy wastes most of a window's wall time
sorting empty filler rows. Eiffel (arXiv:1810.03060) makes the same
observation for packet schedulers: cost must track LIVE queue occupancy,
not configured capacity; PARSIR (arXiv:2410.00644) wins by keeping
per-worker event-set working sizes small.

This module is the gearbox: a small ladder of (capacity, dense width)
tiers — e.g. C/4, C/2, C — each compiling its own window kernel, plus the
hysteresis decision rule the drivers consult at every dispatch boundary:

  * UPSHIFT immediately when occupancy (plus the headroom band) no longer
    fits under the gear's upshift mark — which sits BELOW the spill
    red-zone pressure mark, so a growing workload changes gear before the
    spill tier would have to fire;
  * DOWNSHIFT one gear only after `down_after` consecutive low-occupancy
    dispatches (oscillating workloads stay in the big gear rather than
    paying a re-sort per wave).

A gear change moves the pool between capacities with ONE truncating or
padding re-sort at the handoff boundary (resize_pool) — never inside the
jitted window loop. Semantics are exactly preserved: capacity only bounds
what fits, never the order (the pool is an unordered bag; extraction
re-sorts by the full event key every window), and the decision rule never
downshifts below live occupancy, so the truncation drops nothing. A
geared run commits the same events, counters, and final state digest as a
fixed-capacity run (tests/test_gearbox.py).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

from shadow_tpu.core import simtime
from shadow_tpu.core.state import EventPool

NEVER = simtime.NEVER

# Dense-width floor: gears never shrink the per-host window below this
# (a vanishing K would defer every wave into extra window passes —
# correct, but the opposite of a perf gear).
MIN_K = 4

# Downshift hysteresis: consecutive low-occupancy dispatches required
# before dropping one gear.
DOWN_AFTER = 4


class GearSpec(NamedTuple):
    """One tier of the ladder. `capacity` is pool rows as the kernel sees
    them (per shard under islands); `K` the dense window width; `hi`/`fill`
    the spill-tier marks AT THIS CAPACITY (red-zone marks are per-gear);
    `up` the upshift threshold — occupancy at/above it wants a bigger
    gear, and it sits below `hi` so the shift happens before the spill
    red zone."""

    level: int
    capacity: int
    K: int
    hi: int
    fill: int
    up: int


def build_ladder(
    tiers: int,
    capacity: int,
    K: int,
    hosts: int,
    marks_fn: Callable[[int], tuple[int, int]],
    capacity_map: Callable[[int], int] | None = None,
) -> list[GearSpec]:
    """Build the gear ladder, ascending: tier i covers capacity >> (tiers-1-i),
    so e.g. tiers=3 gives [C/4, C/2, C]. The top gear is EXACTLY the
    configured (capacity, K) — a pool_gears=1 build is bit-identical to
    the pre-gearbox kernel. Lower gears get a matching dense width:
    K >> shift, floored so the window still covers the per-host share of a
    full pool at that gear (capacity/hosts + slack) — occupancy low enough
    to select the gear implies per-host windows that small.

    `marks_fn(capacity) -> (hi, fill)` supplies the spill marks per tier
    (spill.marks for the global engine; the islands runner passes its
    exchange-block-aware variant). Tiers whose marks are infeasible (pool
    too small for its red zone / exchange block) are skipped — except the
    top tier, whose failure propagates exactly as an ungeared build's
    would. `capacity_map` translates the global capacity of a tier into
    what the kernel actually compiles against (the islands per-shard pool
    with its structural exchange block).
    """
    if tiers < 1:
        raise ValueError("pool_gears must be >= 1")
    rows: list[tuple[int, int, int, int]] = []
    seen: set[int] = set()
    for i in range(tiers):
        shift = tiers - 1 - i
        C_g = capacity >> shift
        if capacity_map is not None:
            C_g = capacity_map(C_g)
        if C_g <= 0 or C_g in seen:
            continue
        if shift == 0:
            K_g = K
            hi, fill = marks_fn(C_g)
        else:
            K_g = min(K, max(MIN_K, K >> shift, -(-C_g // hosts) + 4))
            try:
                hi, fill = marks_fn(C_g)
            except ValueError:
                continue
        if hi <= 0:
            if shift == 0:
                raise ValueError(
                    f"pool capacity {C_g} leaves no working region above "
                    f"its red zone"
                )
            continue
        seen.add(C_g)
        rows.append((C_g, K_g, hi, fill))
    return [
        GearSpec(level=lvl, capacity=c, K=k, hi=hi, fill=fill,
                 up=(7 * hi) // 8)
        for lvl, (c, k, hi, fill) in enumerate(rows)
    ]


def target_level(ladder: list[GearSpec], occ: int, margin: int = 1) -> int:
    """Smallest gear whose upshift mark covers `occ` (× `margin` extra
    headroom — the optimistic drivers pass 2: a speculative window of
    factor F can absorb several windows' inflow between decision points).
    Falls through to the top gear when nothing smaller fits."""
    for spec in ladder:
        if occ * margin < spec.up:
            return spec.level
    return ladder[-1].level


class GearShifter:
    """The hysteresis state machine the drivers consult at dispatch
    boundaries. Pure decision logic — the Simulation owns the active
    level and performs the actual shift (pool re-sort + kernel rebind).

    Upshifts are immediate (running out of headroom risks the spill
    red zone); downshifts require `down_after` consecutive dispatches
    whose occupancy fits a smaller gear, and move ONE level at a time.
    """

    def __init__(self, ladder: list[GearSpec], down_after: int = DOWN_AFTER):
        self.ladder = ladder
        self.down_after = int(down_after)
        self._streak = 0

    def reset(self) -> None:
        self._streak = 0

    def observe(
        self, level: int, occ: int, press: bool = False, margin: int = 1
    ) -> int | None:
        """One dispatch-boundary observation; returns the level to shift
        to, or None to stay. `press` marks a red-zone early exit from the
        fused window loop — an unconditional upshift demand while a
        bigger gear exists (the gear absorbs the pressure the spill tier
        would otherwise pay host round-trips for)."""
        want = target_level(self.ladder, occ, margin)
        top = self.ladder[-1].level
        if press and level < top:
            want = max(want, level + 1)
        if want > level:
            return want
        if want < level:
            self._streak += 1
            if self._streak >= self.down_after:
                return level - 1
        else:
            self._streak = 0
        return None


class ShardGearShifter:
    """Per-shard gearing for the ASYNC islands driver (parallel/islands):
    each shard carries its own hysteresis ladder state — occupancy target,
    red-zone demand, and downshift streak — updated at its own dispatch
    boundaries from the per-shard occupancy vector the async kernel
    returns, instead of one fleet-wide state fed the pmax'd occupancy.

    The compiled tier is the ENVELOPE (max of the per-shard levels):
    under vmap every shard shares one compiled pool shape, so a single
    hot shard still sizes the batch — but a burst on one shard no longer
    resets every other shard's downshift streak, and the envelope drops
    as soon as EVERY shard's own ladder state allows it (the fleet-wide
    shifter had to watch the max-occupancy signal cross the threshold
    for `down_after` consecutive dispatches regardless of which shard
    produced each sample).
    """

    def __init__(self, ladder: list[GearSpec], num_shards: int,
                 down_after: int = DOWN_AFTER):
        self.ladder = ladder
        self.S = int(num_shards)
        self.down_after = int(down_after)
        self.levels = [ladder[-1].level] * self.S
        self._streak = [0] * self.S

    def reset(self) -> None:
        self._streak = [0] * self.S

    def seed(self, level: int) -> None:
        """Align every shard's ladder state to the bound envelope (build
        time / layout permutation / fallback checkpoint restore)."""
        self.levels = [int(level)] * self.S
        self.reset()

    def restore(self, levels, envelope: int) -> bool:
        """Re-arm the PER-SHARD ladder states a checkpoint header
        recorded (`__meta__.async.gear_levels`): a resumed mesh run keeps
        each chip's own level instead of hoisting every cool shard to the
        envelope and forgetting its downshift progress (the flat-seed
        behavior). Returns False — caller should seed() — when the
        recorded vector is absent, the wrong width, or inconsistent with
        the restored envelope (its max must equal the bound tier, or the
        compiled pool shape would disagree with the decision state).
        The width check is also the MESH-RESIZE re-seed rule: an elastic
        relayout (parallel/elastic.py) restores an S_old-chip checkpoint
        onto an S_new-chip build, whose header vector no longer describes
        this shard set — the rebuilt mesh seeds flat and re-learns its
        per-chip levels (tests/test_mesh_resilience.py exercises the
        4→3→4 round trip under a multi-tier ladder)."""
        if not levels or len(levels) != self.S:
            return False
        lv = [int(x) for x in levels]
        top = self.ladder[-1].level
        if any(x < 0 or x > top for x in lv) or max(lv) != int(envelope):
            return False
        self.levels = lv
        self.reset()
        return True

    def observe(self, level: int, occs, press=None,
                margin: int = 1) -> int | None:
        """One dispatch-boundary decision from the [S] occupancy vector
        (and optional [S] red-zone press flags). Returns the envelope
        level to shift the compiled tier to, or None to stay."""
        top = self.ladder[-1].level
        for s in range(self.S):
            want = target_level(self.ladder, int(occs[s]), margin)
            if press is not None and bool(press[s]) and self.levels[s] < top:
                want = max(want, self.levels[s] + 1)
            if want > self.levels[s]:
                self.levels[s] = want
                self._streak[s] = 0
            elif want < self.levels[s]:
                self._streak[s] += 1
                if self._streak[s] >= self.down_after:
                    self.levels[s] -= 1
                    self._streak[s] = 0
            else:
                self._streak[s] = 0
        envelope = max(self.levels)
        return envelope if envelope != level else None


def resize_pool(pool: EventPool, capacity: int):
    """Move an event pool between gear capacities at a handoff boundary.

    Growing pads free (time NEVER) rows — no sort: the pool is an
    unordered bag, slot order is immaterial (extraction re-sorts by the
    full key every window). Shrinking keeps the earliest rows by the SAME
    rule the window merge truncates with (one 1-key stable sort by time,
    free rows last), so a shrink is indistinguishable from the merge
    having run at the smaller capacity all along. Handles both the global
    [C] and the islands [S, C] layouts.

    Returns (pool, dropped) where dropped counts real rows lost to the
    truncation per leading dim — structurally zero when the caller's gear
    selection held (occupancy below the new capacity), and accounted into
    pool_overflow_dropped regardless so a decision-rule bug can never
    silently lose events.
    """
    # capacity axis is the LAST one: this runs on the host-side batched
    # layouts ([S, C] islands, [L, ..., C] fleet), where EventPool's
    # .capacity property (shape[0] — the kernel-side per-shard contract)
    # would read the batch dim instead. With that bug every islands or
    # fleet gear shift "grew" toward a capacity compared against S/L, so
    # pools inflated on every shift in either direction — bit-exact
    # (extra NEVER rows) but re-growing the sort volume the gearbox
    # exists to shrink (caught by the ISSUE-10 per-shard-gear retrace
    # test: the inflated pool shape forced a kernel re-lowering).
    C = pool.time.shape[-1]
    if capacity == C:
        return pool, jnp.zeros(pool.time.shape[:-1], jnp.int64)
    PP = pool.payload.shape[-1]
    ax = pool.time.ndim - 1  # the capacity axis (also payload's -2)
    if capacity > C:
        pad = capacity - C

        def padc(x, fill):
            cfg = [(0, 0)] * x.ndim
            cfg[ax] = (0, pad)
            return jnp.pad(x, cfg, constant_values=fill)

        grown = EventPool(
            time=padc(pool.time, NEVER),
            dst=padc(pool.dst, 0),
            src=padc(pool.src, 0),
            seq=padc(pool.seq, 0),
            kind=padc(pool.kind, 0),
            payload=padc(pool.payload, 0),
        )
        return grown, jnp.zeros(pool.time.shape[:-1], jnp.int64)
    cols = [pool.time, pool.dst, pool.src, pool.seq, pool.kind] + [
        pool.payload[..., w] for w in range(PP)
    ]
    ops = jax.lax.sort(cols, num_keys=1, is_stable=True)
    dropped = jnp.sum(
        ops[0][..., capacity:] != NEVER, axis=-1, dtype=jnp.int64
    )
    sl = (Ellipsis, slice(0, capacity))
    shrunk = EventPool(
        time=ops[0][sl], dst=ops[1][sl], src=ops[2][sl],
        seq=ops[3][sl], kind=ops[4][sl],
        payload=jnp.stack([o[sl] for o in ops[5:]], axis=-1),
    )
    return shrunk, dropped
