"""Typed unit parsing for config values ("10 Mbit", "50 ms", "81920 Kibit").

Mirrors the reference's unit system (src/main/core/support/units.rs): a
numeric value, an optional SI (k/K/M/G/T = powers of 1000) or IEC
(Ki/Mi/Gi/Ti = powers of 1024) prefix, and a base unit for time, bits, or
bytes. Bare integers are accepted where the reference accepts them (e.g.
``stop_time: 10`` means seconds; ``socket_recv_buffer: 174760`` means bytes).
"""

from __future__ import annotations

import re

from shadow_tpu.core import simtime

_SI = {"": 1, "k": 10**3, "K": 10**3, "M": 10**6, "G": 10**9, "T": 10**12}
_IEC = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40}
_PREFIXES = {**_SI, **_IEC}

_TIME_BASE_NS = {
    "ns": 1,
    "nanosecond": 1,
    "nanoseconds": 1,
    "us": simtime.NS_PER_US,
    "μs": simtime.NS_PER_US,
    "microsecond": simtime.NS_PER_US,
    "microseconds": simtime.NS_PER_US,
    "ms": simtime.NS_PER_MS,
    "millisecond": simtime.NS_PER_MS,
    "milliseconds": simtime.NS_PER_MS,
    "s": simtime.NS_PER_SEC,
    "sec": simtime.NS_PER_SEC,
    "secs": simtime.NS_PER_SEC,
    "second": simtime.NS_PER_SEC,
    "seconds": simtime.NS_PER_SEC,
    "min": simtime.NS_PER_MIN,
    "mins": simtime.NS_PER_MIN,
    "minute": simtime.NS_PER_MIN,
    "minutes": simtime.NS_PER_MIN,
    "h": simtime.NS_PER_HOUR,
    "hr": simtime.NS_PER_HOUR,
    "hrs": simtime.NS_PER_HOUR,
    "hour": simtime.NS_PER_HOUR,
    "hours": simtime.NS_PER_HOUR,
}

_NUM_RE = re.compile(r"^\s*([0-9]+(?:\.[0-9]+)?)\s*([A-Za-zμ]*)\s*$")


class UnitParseError(ValueError):
    pass


def _split(text: str) -> tuple[float, str]:
    m = _NUM_RE.match(text)
    if not m:
        raise UnitParseError(f"cannot parse unit value: {text!r}")
    return float(m.group(1)), m.group(2)


def _prefixed(suffix: str, bases: tuple[str, ...]) -> int | None:
    """Return the multiplier if suffix = [prefix] + one of bases, else None."""
    for base in bases:
        if suffix.endswith(base):
            prefix = suffix[: len(suffix) - len(base)]
            if prefix in _PREFIXES:
                return _PREFIXES[prefix]
    return None


def parse_time_ns(value, default_unit: str = "s") -> int:
    """Parse a time value to int64 nanoseconds. Bare numbers use default_unit."""
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return int(round(value * _TIME_BASE_NS[default_unit]))
    num, suffix = _split(str(value))
    if suffix == "":
        return int(round(num * _TIME_BASE_NS[default_unit]))
    if suffix not in _TIME_BASE_NS:
        raise UnitParseError(f"unknown time unit {suffix!r} in {value!r}")
    return int(round(num * _TIME_BASE_NS[suffix]))


def parse_bits(value) -> int:
    """Parse a bit quantity (bandwidths) to bits. Bare numbers are bits."""
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return int(value)
    num, suffix = _split(str(value))
    if suffix == "":
        return int(round(num))
    mult = _prefixed(suffix, ("bit", "bits"))
    if mult is None:
        # Also accept byte units for bandwidth, converting to bits.
        bytes_mult = _prefixed(suffix, ("B", "byte", "bytes"))
        if bytes_mult is None:
            raise UnitParseError(f"unknown bit unit {suffix!r} in {value!r}")
        return int(round(num * bytes_mult * 8))
    return int(round(num * mult))


def parse_bytes(value) -> int:
    """Parse a byte quantity (buffer sizes) to bytes. Bare numbers are bytes."""
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return int(value)
    num, suffix = _split(str(value))
    if suffix == "":
        return int(round(num))
    mult = _prefixed(suffix, ("B", "byte", "bytes"))
    if mult is None:
        raise UnitParseError(f"unknown byte unit {suffix!r} in {value!r}")
    return int(round(num * mult))
