"""The batched PDES window kernel and simulation driver.

Reference execution model (src/main/core/manager.c:543-577,
scheduler/scheduler.c:77-94, controller.c:390-422): time advances in
conservative windows bounded by the minimum topology latency ("runahead");
within a window each worker pops its hosts' events in deterministic order
(time, dst, src, seq — event.c:109-152) and runs them; a barrier plus a
min-next-event-time reduction ends the round.

TPU-first re-architecture (one jitted pure function per window):

1. SORT — one sort of the event pool by (dst, time, src, seq) groups this
   window's events into consecutive per-host runs. This replaces all
   per-host priority queues and their locks.
2. MICRO-STEP LOOP — a `lax.while_loop` whose body processes AT MOST ONE
   event per host, fully vectorized across all hosts: candidate = key-min of
   (run head at a per-host cursor, self-inbox); handlers apply masked SoA
   updates. Per-host event order is preserved exactly; hosts are
   data-parallel, which is the same parallelism the reference exploits with
   worker threads (P1 in SURVEY.md §2.5) — but over lanes instead of
   pthreads.
3. The conservative-window invariant (window length ≤ min path latency,
   controller.c:125-153) guarantees cross-host emissions land at or after
   window end, so only SELF-emissions (short timers, NIC refills) can need
   intra-window processing — they go to a small per-host inbox. Everything
   else accumulates in a per-host outbox (no scatter collisions).
4. MERGE — unconsumed sorted rows + outbox + inbox leftovers merge into the
   next pool with one sort by time, truncating to capacity (drops counted).
   The next window start is the min pool time — the reference's min-reduce
   barrier (worker.c:332-363) becomes a jnp.min.

Everything is sorts, gathers, and elementwise selects: XLA scatters
serialize element-by-element on TPU and are banned from this module.

The whole multi-window run can itself be a `lax.while_loop` on device
(`Simulation.run_compiled`), so a complete simulation is ONE XLA program.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from shadow_tpu.core import gearbox
from shadow_tpu.core import hostplane as hostplane_mod
from shadow_tpu.core import pipeline as pipeline_mod
from shadow_tpu.core import pressure as pressure_mod
from shadow_tpu.core.supervisor import PendingDispatch
from shadow_tpu.core import rng as rng_mod
from shadow_tpu.core import simtime, soa
from shadow_tpu.core import spill as spill_mod
from shadow_tpu.obs import audit as audit_mod
from shadow_tpu.obs import counters as obs_mod
from shadow_tpu.obs import flight as flight_mod
from shadow_tpu.obs import metrics as metrics_mod
from shadow_tpu.core.state import (
    PAYLOAD_WORDS,
    Counters,
    EventPool,
    HostState,
    NetParams,
    SimState,
    make_host_state,
)

NEVER = simtime.NEVER


# ---------------------------------------------------------------------------
# Event view + emission interface for handlers
# ---------------------------------------------------------------------------


@struct.dataclass
class EventView:
    """The (at most one) event each host is processing this micro-step.

    All arrays are [H]-indexed; the destination host of event i IS host i.
    ``mask`` is set per handler: valid event AND kind match.
    """

    mask: jnp.ndarray  # [H] bool
    time: jnp.ndarray  # [H] i64
    src: jnp.ndarray  # [H] i32
    seq: jnp.ndarray  # [H] i32
    kind: jnp.ndarray  # [H] i32
    payload: jnp.ndarray  # [H, P] i32


class Emission(NamedTuple):
    mask: jnp.ndarray  # [H] bool — which hosts emit
    time: jnp.ndarray  # [H] i64
    dst: jnp.ndarray  # [H] i32
    kind: jnp.ndarray  # [H] i32 (may be per-host)
    payload: jnp.ndarray  # [H, P] i32


class Emitter:
    """Collects handler emissions; the engine routes them (inbox/outbox)
    in collection order, which fixes the per-source sequence numbering."""

    def __init__(self):
        self.records: list[Emission] = []

    def emit(self, mask, time, dst, kind, payload):
        kind = jnp.broadcast_to(jnp.asarray(kind, jnp.int32), mask.shape)
        self.records.append(
            Emission(mask, time.astype(jnp.int64), dst.astype(jnp.int32), kind, payload)
        )


# handler(state, ev, emitter, params) -> state
Handler = Callable[[SimState, EventView, Emitter, NetParams], SimState]


@struct.dataclass
class MatrixEventView:
    """A whole window of same-kind events per host, [H, K]-shaped, for the
    vectorized fast path (engine `run_matrix`). Column order is per-host
    key order; mask marks real events."""

    mask: jnp.ndarray  # [H, K] bool
    time: jnp.ndarray  # [H, K] i64
    src: jnp.ndarray  # [H, K] i32
    seq: jnp.ndarray  # [H, K] i32
    payload: jnp.ndarray  # [H, K, P] i32


class MatrixRecord(NamedTuple):
    mask: jnp.ndarray  # [H, K] bool
    time: jnp.ndarray  # [H, K] i64
    dst: jnp.ndarray  # [H, K] i32
    kind: jnp.ndarray  # [H, K] i32
    payload: jnp.ndarray  # [H, K, P] i32


class MatrixEmitter:
    """Collects [H, K]-shaped emissions from a matrix handler; the engine
    assigns per-source sequence numbers in (column-major, record-minor)
    order — identical to the loop path's per-event emission order."""

    def __init__(self):
        self.records: list[MatrixRecord] = []

    def emit(self, mask, time, dst, kind, payload):
        kind = jnp.broadcast_to(jnp.asarray(kind, jnp.int32), mask.shape)
        self.records.append(
            MatrixRecord(
                mask, time.astype(jnp.int64), dst.astype(jnp.int32), kind,
                payload,
            )
        )


def draw_uniform(state: SimState, mask):
    """One deterministic uniform draw per masked host; bumps draw counters
    only where masked (so inactive hosts' streams don't advance — matching a
    per-host sequential RNG)."""
    u = rng_mod.uniform_per_host(state.rng_keys, state.host.rng_counter)
    new_c = jnp.where(mask, state.host.rng_counter + 1, state.host.rng_counter)
    state = state.replace(host=state.host.replace(rng_counter=new_c))
    return state, u


# ---------------------------------------------------------------------------
# Window data structures
# ---------------------------------------------------------------------------


@struct.dataclass
class _Inbox:
    time: jnp.ndarray  # [H, B] i64
    src: jnp.ndarray
    seq: jnp.ndarray
    kind: jnp.ndarray
    payload: jnp.ndarray  # [H, B, P]

    @classmethod
    def empty(cls, H, B, PP):
        # payload PACKED (soa.pack_words): PP i64 columns, halving the
        # box-write traffic and the merge-sort operand count
        return cls(
            time=jnp.full((H, B), NEVER, dtype=jnp.int64),
            src=jnp.zeros((H, B), dtype=jnp.int32),
            seq=jnp.zeros((H, B), dtype=jnp.int32),
            kind=jnp.zeros((H, B), dtype=jnp.int32),
            payload=jnp.zeros((H, B, PP), dtype=jnp.int64),
        )


@struct.dataclass
class _Outbox:
    time: jnp.ndarray  # [H, O] i64
    dst: jnp.ndarray
    src: jnp.ndarray
    seq: jnp.ndarray
    kind: jnp.ndarray
    payload: jnp.ndarray  # [H, O, P]
    count: jnp.ndarray  # [H] i32

    @classmethod
    def empty(cls, H, O, PP):
        return cls(
            time=jnp.full((H, O), NEVER, dtype=jnp.int64),
            dst=jnp.zeros((H, O), dtype=jnp.int32),
            src=jnp.zeros((H, O), dtype=jnp.int32),
            seq=jnp.zeros((H, O), dtype=jnp.int32),
            kind=jnp.zeros((H, O), dtype=jnp.int32),
            payload=jnp.zeros((H, O, PP), dtype=jnp.int64),
            count=jnp.zeros((H,), dtype=jnp.int32),
        )


class _DenseWindow(NamedTuple):
    """Dense per-host window matrix: column j holds host h's j-th in-window
    event in (time, src, seq) key order; unused cells carry time NEVER.
    Shapes [H, Kc] (payload [H, Kc, P])."""

    time: jnp.ndarray
    src: jnp.ndarray
    seq: jnp.ndarray
    kind: jnp.ndarray
    payload: jnp.ndarray


class _Tail(NamedTuple):
    """Rows not extracted into the dense matrix: out-of-window events,
    per-host deferred leftovers (rank >= Kc), and spent filler rows (time
    NEVER). Flat [N - H*Kc] arrays; payload is a list of P word columns so
    it can ride merge sorts as operands."""

    time: jnp.ndarray
    src: jnp.ndarray
    seq: jnp.ndarray
    kind: jnp.ndarray
    dst: jnp.ndarray
    payload: list


# window-relative time field width in the packed sort key: 2^44 ns ≈ 4.9 h
# bounds a single window's span (runahead), far beyond any real runahead
_DT_BITS = 44
_DT_MAX = (1 << _DT_BITS) - 1


class IslandSpec(NamedTuple):
    """Per-shard ("island") execution of the window kernel.

    The reference's parallel design is per-worker locality: each worker owns
    a set of hosts and their event queues, and cross-host pushes go straight
    into the owner's queue (scheduler.c:329-353, worker.c:517-576). The TPU
    equivalent built here: the host axis is split into `num_shards`
    contiguous blocks, each owning a LOCAL event pool and a LOCAL dense
    window (so per-shard sort volume drops num_shards×); cross-shard
    emissions ride a bounded all_to_all exchange at the merge; the round
    barrier is a pmin over the shard axis. Runs identically under
    jax.vmap(axis_name=...) (virtual shards on one chip — batched local
    sorts) and jax.shard_map (real devices).
    """

    axis: str  # mesh/vmap axis name
    num_shards: int  # S
    exchange_slots: int  # X rows per destination shard per window
    # route by params.slot_of table instead of dst//H arithmetic —
    # required once the rebalancer may permute host→shard assignment
    # (compiled in from the start so a rebalance never recompiles)
    use_slot_table: bool = False
    # compile the speculation-violation checks for optimistic windows:
    # LOCAL-dst emissions check against the shard's own done_t progress
    # clocks at the merge (exactly the global engine's check), and
    # FOREIGN emissions are checked at ARRIVAL on the destination shard —
    # after the all_to_all, against the receiver's done_t — so no
    # per-emission collective is ever needed (the exchange the rows
    # already ride IS the collective). The per-shard xmit_min signals
    # combine with one pmin in the attempt loop (parallel/islands.py).
    optimistic: bool = False


def _island_route(
    m_t, m_d, m_s, m_q, m_k, m_p, *,
    win_start, H, C, spec: IslandSpec, slot_of=None,
):
    """Merge-stage routing for the islands engine: one grouping sort sends
    each row toward (destination shard 0..S-1 | local pool), a bounded
    [S, X] block per operand rides ONE all_to_all, and the local pool is
    assembled by concatenation — no third sort (the pool is an unordered
    bag; extraction re-sorts by the full key every window, and truncation
    overflow is handled by the caller's drop/spill accounting).

    Reference analog: scheduler_push into the destination host's locked
    queue (scheduler.c:232-255) — here the destination SHARD's pool, with
    the lock replaced by the collective.

    Rows that miss the bounded exchange (more than X rows for one
    destination shard) stay in the local pool and retry next window; their
    min time is returned so the driver can clamp the next window's END
    below it (the destination must not process past an in-transit event).

    Returns (pool_cols, dropped, sent, deferred, deferred_min) where
    pool_cols = (t, d, s, q, k, plist) each [C].
    """
    S, X = spec.num_shards, spec.exchange_slots
    SX = S * X
    if C <= SX:
        raise ValueError(
            f"per-shard pool capacity {C} must exceed exchange block "
            f"{SX} (= num_shards x exchange_slots)"
        )
    my_shard = jax.lax.axis_index(spec.axis).astype(jnp.int64)
    real = m_t != NEVER
    if slot_of is not None:
        slot = slot_of[jnp.clip(m_d, 0, slot_of.shape[0] - 1)]
    else:
        slot = m_d
    dshard = jnp.clip(slot // H, 0, S - 1).astype(jnp.int64)
    foreign = real & (dshard != my_shard)
    group = jnp.where(foreign, dshard, jnp.int64(S))
    dt = jnp.clip(m_t - win_start, 0, _DT_MAX)
    # Packed (group, dt) key. dt saturates at 2^44 ns (~4.9 h) past the
    # window start: rows beyond that tie and fall back to stable input
    # order — deterministic, and lossless once overflow spills instead of
    # drops; sub-horizon rows (every realistic sim span) order exactly.
    k1_r = (group << _DT_BITS) | dt
    gf = jnp.repeat(jnp.arange(S, dtype=jnp.int64), X)
    k1_f = (gf << _DT_BITS) | _DT_MAX
    z32 = jnp.zeros((SX,), jnp.int32)
    cat = [
        jnp.concatenate([k1_r, k1_f]),
        jnp.concatenate([m_t, jnp.full((SX,), NEVER, jnp.int64)]),
        jnp.concatenate([m_d, z32]),
        jnp.concatenate([m_s, z32]),
        jnp.concatenate([m_q, z32]),
        jnp.concatenate([m_k, z32]),
    ] + [jnp.concatenate([p, jnp.zeros((SX,), jnp.int64)]) for p in m_p]
    ops = jax.lax.sort(cat, num_keys=1, is_stable=True)
    s_k1 = ops[0]
    N = s_k1.shape[0]
    s_group = s_k1 >> _DT_BITS
    iota = jnp.arange(N, dtype=jnp.int32)
    boundary = jnp.concatenate(
        [jnp.ones((1,), bool), s_group[1:] != s_group[:-1]]
    )
    run_start = jax.lax.cummax(jnp.where(boundary, iota, -1))
    rank = iota - run_start
    # X fillers per group guarantee every exchange slot is claimed (filler
    # rows carry time NEVER; receivers mask them) — the [S, X] block is a
    # plain reshape after the slot sort, exactly the dense-window trick.
    extract = (s_group < S) & (rank < X)
    slot = jnp.where(extract, (s_group * X + rank.astype(jnp.int64)), SX)
    k2 = (slot << _DT_BITS) | (s_k1 & _DT_MAX)
    ops2 = jax.lax.sort([k2] + list(ops[1:]), num_keys=1, is_stable=True)
    cols = ops2[1:]  # t, d, s, q, k, p...
    sent = jnp.sum(extract & (ops[1] != NEVER), dtype=jnp.int64)

    recv_cols = []
    for c in cols:
        blk = c[:SX].reshape((S, X) + c.shape[1:])
        r = jax.lax.all_to_all(blk, spec.axis, 0, 0)
        recv_cols.append(r.reshape((SX,) + c.shape[1:]))

    C_keep = C - SX
    rem = [c[SX:] for c in cols]
    rem_t, rem_d = rem[0], rem[1]
    dropped = jnp.sum(rem_t[C_keep:] != NEVER, dtype=jnp.int64)
    rd = rem_d[:C_keep]
    if slot_of is not None:
        rslot = slot_of[jnp.clip(rd, 0, slot_of.shape[0] - 1)]
    else:
        rslot = rd
    def_mask = (rem_t[:C_keep] != NEVER) & (
        jnp.clip(rslot // H, 0, S - 1).astype(jnp.int64) != my_shard
    )
    deferred = jnp.sum(def_mask, dtype=jnp.int64)
    deferred_min = jnp.min(
        jnp.where(def_mask, rem_t[:C_keep], NEVER)
    )
    pool_cols = [
        jnp.concatenate([r[:C_keep], rc])
        for r, rc in zip(rem, recv_cols)
    ]
    return pool_cols, dropped, sent, deferred, deferred_min


def _dense_extract(pool: EventPool, win_start, win_end, H: int, Kc: int,
                   PP: int, lrow=None):
    """Extract the window into a dense [H, Kc] matrix with SORTS AND SCANS
    ONLY (profiled on v5e: large gathers serialize at ~9 ns/element while
    multi-operand sorts run near memory bandwidth — so every event column
    and payload word rides the sorts as an operand).

    Sort cost on TPU scales with rows × comparator stages (measured:
    payload-operand packing barely moved it, key count does), so the
    4-component key (dst | H-sentinel, time, src, seq) is PACKED into two
    i64 keys:

        k1 = run_key << 44 | clip(time - win_start, 0, 2^44-1)
        k2 = src << 32 | seq (zero-extended)

    Exact for every in-window row: run_key < H only for in-window rows,
    whose time ∈ [win_start, win_end) with win_end - win_start ≤ runahead
    ≪ 2^44; out-of-window rows (run_key = H) may clip dt, but their order
    is irrelevant — the next merge re-sorts everything by time. Filler
    rows (Kc per host, dt = 2^44-1 > any real in-window dt) sort after
    every real row of their host.

    A cummax scan derives each row's rank within its host run (no
    searchsorted — its method="sort" lowers to a scatter). Sort 2 by dense
    slot id (h*Kc + rank) lands extracted rows so the window matrix is a
    plain reshape; everything else becomes the merge leftovers.

    Replaces per-host priority queues (scheduler_policy_host_single.c:
    18-54) and their locks with two sorts shared by all hosts."""
    C = pool.capacity
    HK = H * Kc
    N = C + HK
    hosts = jnp.arange(H, dtype=jnp.int32)
    # Local row of each event's destination: dst itself on the global
    # engine; the caller passes the shard-relative row under islands
    # (contiguous-block arithmetic or the slot_of rebalance table).
    # Foreign rows (in-transit exchange deferrals) fall outside [0, H) and
    # must not extract — they ride the tail into the merge, where
    # _island_route retries them.
    if lrow is None:
        lrow = pool.dst
    local = (lrow >= 0) & (lrow < H)
    inwin = (pool.time < win_end) & local
    run_key = jnp.where(inwin, lrow, jnp.int32(H)).astype(jnp.int64)
    dt = jnp.clip(pool.time - win_start, 0, _DT_MAX)
    k1_r = (run_key << _DT_BITS) | dt
    k2_r = (pool.src.astype(jnp.int64) << 32) | (
        pool.seq.astype(jnp.int64) & 0xFFFFFFFF
    )
    key_f = jnp.repeat(hosts, Kc)  # [HK] filler host ids
    k1_f = (key_f.astype(jnp.int64) << _DT_BITS) | _DT_MAX
    cat_k1 = jnp.concatenate([k1_r, k1_f])
    cat_k2 = jnp.concatenate([k2_r, jnp.zeros((HK,), jnp.int64)])
    cat_t = jnp.concatenate([pool.time, jnp.full((HK,), NEVER, jnp.int64)])
    zf = jnp.zeros((HK,), jnp.int32)
    cat_d = jnp.concatenate([pool.dst, key_f])  # TRUE dst rides along
    cat_k = jnp.concatenate([pool.kind, zf])
    zf64 = jnp.zeros((HK,), jnp.int64)
    pcols = [jnp.concatenate([pool.payload[:, w], zf64]) for w in range(PP)]
    ops = jax.lax.sort(
        [cat_k1, cat_k2, cat_t, cat_k, cat_d] + pcols,
        num_keys=2, is_stable=True,
    )
    s_k1, s_k2, s_t, s_k, s_d = ops[:5]
    s_p = ops[5:]
    s_key = (s_k1 >> _DT_BITS).astype(jnp.int32)
    iota = jnp.arange(N, dtype=jnp.int32)
    boundary = jnp.concatenate(
        [jnp.ones((1,), bool), s_key[1:] != s_key[:-1]]
    )
    run_start = jax.lax.cummax(jnp.where(boundary, iota, -1))
    rank = iota - run_start
    extract = (s_key < H) & (rank < Kc)
    slot = jnp.where(extract, s_key * Kc + rank, jnp.int32(N))
    ops2 = jax.lax.sort(
        [slot, s_t, s_k2, s_k, s_d] + list(s_p),
        num_keys=1, is_stable=True,
    )
    o_t, o_k2, o_k, o_d = ops2[1], ops2[2], ops2[3], ops2[4]
    o_s = (o_k2 >> 32).astype(jnp.int32)
    o_q = o_k2.astype(jnp.int32)  # low 32 bits (seq is nonnegative)
    d_t = o_t[:HK].reshape(H, Kc)
    d_s = o_s[:HK].reshape(H, Kc)
    d_q = o_q[:HK].reshape(H, Kc)
    d_k = o_k[:HK].reshape(H, Kc)
    d_p = jnp.stack([o[:HK].reshape(H, Kc) for o in ops2[5:]], axis=-1)
    dense = _DenseWindow(time=d_t, src=d_s, seq=d_q, kind=d_k, payload=d_p)
    tail = _Tail(
        time=o_t[HK:], src=o_s[HK:], seq=o_q[HK:],
        kind=o_k[HK:], dst=o_d[HK:],
        payload=[o[HK:] for o in ops2[5:]],
    )
    return dense, tail


def _read_col(dense: _DenseWindow, col, Kc: int):
    """Read event fields at per-host column `col` via one-hot masked
    reduces (soa.get_at) — NOT take_along_axis, whose gather serializes per
    element on TPU; the [H, Kc] compare+select runs at full vector
    bandwidth (XLA CSE merges the repeated hit masks). `col` must lie in
    [0, Kc). Returns (time, src, seq, kind, payload)."""
    return (
        soa.get_at(dense.time, col),
        soa.get_at(dense.src, col),
        soa.get_at(dense.seq, col),
        soa.get_at(dense.kind, col),
        soa.get_at(dense.payload, col),
    )


def _inbox_min(inbox: _Inbox):
    """Per-host lexicographic min of the inbox by (time, src, seq).
    Returns (time, src, seq, slot) each [H].

    Tournament reduction (log2 B rounds of elementwise compares) instead of
    a lax.sort: B is tiny and TPU's bitonic sort costs ~ms at H=8k where
    this costs microseconds."""
    t, s, q = inbox.time, inbox.src, inbox.seq
    B = t.shape[1]
    slot = jnp.broadcast_to(jnp.arange(B, dtype=jnp.int32), t.shape)
    while B > 1:
        half = (B + 1) // 2
        t1, s1, q1, i1 = t[:, :half], s[:, :half], q[:, :half], slot[:, :half]
        t2 = t[:, half:]
        pad = half - t2.shape[1]
        if pad:
            t2 = jnp.pad(t2, ((0, 0), (0, pad)), constant_values=NEVER)
            s2 = jnp.pad(s[:, half:], ((0, 0), (0, pad)))
            q2 = jnp.pad(q[:, half:], ((0, 0), (0, pad)))
            i2 = jnp.pad(slot[:, half:], ((0, 0), (0, pad)))
        else:
            s2, q2, i2 = s[:, half:], q[:, half:], slot[:, half:]
        take2 = _key_lt(t2, s2, q2, t1, s1, q1)
        t = jnp.where(take2, t2, t1)
        s = jnp.where(take2, s2, s1)
        q = jnp.where(take2, q2, q1)
        slot = jnp.where(take2, i2, i1)
        B = half
    return t[:, 0], s[:, 0], q[:, 0], slot[:, 0]


def _key_lt(t1, s1, q1, t2, s2, q2):
    """(t1,s1,q1) < (t2,s2,q2) lexicographically (same dst implied)."""
    return (t1 < t2) | ((t1 == t2) & ((s1 < s2) | ((s1 == s2) & (q1 < q2))))


def _set_col(arr, col, mask, val):
    """arr[h, col[h]] = val[h] for masked hosts, as a pure elementwise
    select over [H, B(, P)] — avoids XLA scatter, which serializes on TPU.
    `val` may be scalar, [H], or [H, P] matching arr's trailing dims."""
    B = arr.shape[1]
    cols = jnp.arange(B, dtype=jnp.int32)
    hit = mask[:, None] & (cols[None, :] == col[:, None])  # [H, B]
    val = jnp.asarray(val, arr.dtype)
    if arr.ndim == 3:
        if val.ndim == 2:
            val = val[:, None, :]
        return jnp.where(hit[:, :, None], val, arr)
    if val.ndim == 1:
        val = val[:, None]
    return jnp.where(hit, val, arr)


# ---------------------------------------------------------------------------
# The window step factory
# ---------------------------------------------------------------------------


def make_window_step(
    handlers: dict[int, Handler],
    num_hosts: int,
    K: int = 32,
    B: int = 8,
    O: int = 64,
    max_iters: int | None = None,
    bulk_kinds: dict[int, int] | None = None,
    matrix_handlers: dict[int, Callable] | None = None,
    with_cpu_model: bool = False,
    bulk_gate: Callable | None = None,
    bulk_self_excluded: bool = False,
    payload_words: int = PAYLOAD_WORDS,
    island: IslandSpec | None = None,
    audit: bool = True,
    _force_path: str | None = None,  # "matrix"|"loop": testing/profiling only
):
    """Build step(state, params, win_start, win_end) -> (state, min_next).

    ``handlers`` maps event kind → handler; handler order within a micro-step
    follows ascending kind (fixed, deterministic).

    ``bulk_kinds`` maps kind → G: a host whose candidate event has that kind
    may consume up to G CONSECUTIVE same-kind run events in one iteration
    (the handler is invoked once per taken column, in key order), dividing
    the iteration count for kinds that dominate a host's window. SAFETY
    CONTRACT: a bulk kind's handler must never emit a SELF event with
    time < win_end — such an emission could carry a key between two bulked
    events and would deserve to interleave, which the batch forecloses.
    (Cross-host emissions always land >= win_end under conservative
    windows; PHOLD's message kind satisfies this by construction.)
    At most one bulk kind is supported currently.

    ``bulk_gate(state, params, win_start, win_end) -> [H] i32`` makes the
    contract DYNAMIC for kinds that are only conditionally bulk-safe (the
    net stack's packet arrivals): it returns, per host, how many EXTRA
    same-kind events may be batched this micro-step — 0 disables batching
    for hosts whose handler might emit a sub-window self event (queued
    router, exhausted tokens, armed pumps). ``bulk_self_excluded`` further
    restricts batches to events whose src differs from the host (loopback
    arrivals reply to self at the same timestamp).
    """
    H = num_hosts
    if max_iters is None:
        max_iters = K + 4 * B + 16
    kinds = sorted(handlers)
    if bulk_kinds and len(bulk_kinds) > 1:
        raise ValueError("at most one bulk kind is supported")
    bulk_kind, G = (
        next(iter(bulk_kinds.items())) if bulk_kinds else (None, 1)
    )
    if bulk_kind is not None and bulk_kind not in handlers:
        raise ValueError(f"bulk kind {bulk_kind} has no handler")
    matrix_handlers = matrix_handlers or {}

    def step(state: SimState, params: NetParams, win_start, win_end):
        P = payload_words  # logical payload words (per-sim sized)
        PP = soa.packed_words(P)  # packed i64 columns actually carried
        win_start = jnp.asarray(win_start, jnp.int64)
        win_end = jnp.asarray(win_end, jnp.int64)
        state = state.replace(now=win_start)
        # GLOBAL host id per local row: arange on the global engine, the
        # shard's contiguous block (or rebalanced permutation) under
        # islands. Every "my host id" use below (self-routing, emission
        # src stamping) is gid, never arange.
        gid = state.host.gid
        def _box_lrow(bd):
            """dst → shard-local row for any dst column (pool, box, or
            exchange-received rows); foreign dsts land outside [0, H)."""
            if island.use_slot_table:
                b = jax.lax.axis_index(island.axis).astype(jnp.int32) * H
                return params.slot_of[
                    jnp.clip(bd, 0, params.slot_of.shape[0] - 1)
                ] - b
            return bd - gid[0]

        _lrow = None if island is None else _box_lrow(state.pool.dst)

        def _obs_win_bump(state, *slots):
            """One fused add to the telemetry block's window-plane row.
            Under islands the bump is scaled by (axis_index == 0) so the
            summed-at-fetch counts equal the global engine's. Compiled out
            entirely when the block is disabled."""
            if state.obs is None:
                return state
            vec = obs_mod.win_bump_vec(*slots)
            if island is not None:
                vec = vec * (
                    jax.lax.axis_index(island.axis) == 0
                ).astype(jnp.int64)
            return state.replace(
                obs=state.obs.replace(win=state.obs.win + vec)
            )

        # Static per-kind emission bound: probe the handlers once at trace
        # time with an all-masked-off event and count emit() calls per
        # kind. A host processes exactly ONE event (of one kind) per
        # iteration, so its worst-case outbox demand is the emit-call count
        # of THAT kind's handler. The backpressure below stalls a host
        # whose outbox can't absorb that demand — nothing is ever dropped.
        # The probe's state/ops are discarded (XLA dead-code-eliminates).
        probe = Emitter()
        pv = EventView(
            mask=jnp.zeros((H,), jnp.bool_),
            time=jnp.zeros((H,), jnp.int64),
            src=jnp.zeros((H,), jnp.int32),
            seq=jnp.zeros((H,), jnp.int32),
            kind=jnp.zeros((H,), jnp.int32),
            payload=jnp.zeros((H, P), jnp.int32),
        )
        E_by_kind = np.zeros(max(kinds) + 1 if kinds else 1, dtype=np.int32)
        pstate = state
        for k in kinds:
            before = len(probe.records)
            pstate = handlers[k](pstate, pv, probe, params)
            E_by_kind[k] = len(probe.records) - before
        del pstate
        if int(E_by_kind.max()) > O:
            worst = int(E_by_kind.argmax())
            raise ValueError(
                f"outbox_slots O={O} cannot absorb kind {worst}'s worst-"
                f"case emissions E={int(E_by_kind.max())}; raise "
                f"experimental.outbox_slots"
            )
        G_run = G
        if bulk_kind is not None and int(E_by_kind[bulk_kind]) * G > O:
            if bulk_gate is None:
                raise ValueError(
                    f"outbox_slots O={O} cannot absorb a full bulk batch "
                    f"(kind {bulk_kind}: {int(E_by_kind[bulk_kind])} "
                    f"emissions x G={G}); raise outbox_slots or lower the "
                    f"bulk width"
                )
            # gated batching degrades gracefully: clamp the batch width so
            # a full batch always fits the outbox (the gate already makes
            # batching best-effort per host)
            G_run = max(1, O // max(1, int(E_by_kind[bulk_kind])))

        def assemble(state, m_t, m_d, m_s, m_q, m_k, m_p):
            """Merge candidates → next pool. Global engine: ONE 1-key stable
            sort by time, truncate to capacity. Islands: route through
            _island_route (grouping sort + bounded all_to_all + concat
            assembly) — cross-shard rows land in their owner's pool here,
            the TPU form of scheduler_push (scheduler.c:232-255).

            Returns (state, arrival_viol_min): the second value is the
            optimistic-islands ARRIVAL check — the earliest exchange-
            received row that lands at/behind its destination host's
            done_t progress clock (NEVER otherwise, and always NEVER for
            the global engine, where emissions are checked before the
            merge instead)."""
            C = state.pool.capacity
            arrival_min = jnp.asarray(NEVER, jnp.int64)
            if island is None:
                ops3 = jax.lax.sort(
                    [m_t, m_d, m_s, m_q, m_k] + m_p, num_keys=1,
                    is_stable=True,
                )
                dropped = jnp.sum(ops3[0][C:] != NEVER, dtype=jnp.int64)
                new_pool = EventPool(
                    time=ops3[0][:C], dst=ops3[1][:C], src=ops3[2][:C],
                    seq=ops3[3][:C], kind=ops3[4][:C],
                    payload=jnp.stack([o[:C] for o in ops3[5:]], axis=-1),
                )
                return state.replace(
                    pool=new_pool,
                    exch_deferred_min=jnp.asarray(NEVER, jnp.int64),
                    counters=state.counters.replace(
                        pool_overflow_dropped=(
                            state.counters.pool_overflow_dropped + dropped
                        )
                    ),
                ), arrival_min
            cols, dropped, sent, deferred, dmin = _island_route(
                m_t, m_d, m_s, m_q, m_k, m_p,
                win_start=win_start, H=H, C=C, spec=island,
                slot_of=params.slot_of if island.use_slot_table else None,
            )
            if island.optimistic:
                # Arrival check: rows just received through the exchange
                # occupy the pool tail block [C_keep:). One received row
                # behind its destination's progress clock means this
                # shard speculated past an in-flight delivery — surface
                # its time so the attempt loop rolls the window back.
                # Covers rows that DEFERRED in earlier sub-steps too:
                # they re-arrive here, and done_t only grows within an
                # attempt, so a missed ordering is still caught.
                C_keep = C - island.num_shards * island.exchange_slots
                recv_t, recv_d = cols[0][C_keep:], cols[1][C_keep:]
                lr = _box_lrow(recv_d)
                dst_last = state.host.done_t[jnp.clip(lr, 0, H - 1)]
                vio = (recv_t != NEVER) & (recv_t <= dst_last)
                arrival_min = jnp.min(jnp.where(vio, recv_t, NEVER))
            new_pool = EventPool(
                time=cols[0], dst=cols[1], src=cols[2],
                seq=cols[3], kind=cols[4],
                payload=jnp.stack(cols[5:], axis=-1),
            )
            c = state.counters
            return state.replace(
                pool=new_pool,
                exch_deferred_min=dmin,
                counters=c.replace(
                    pool_overflow_dropped=c.pool_overflow_dropped + dropped,
                    exchange_sent=c.exchange_sent + sent,
                    exchange_deferred=c.exchange_deferred + deferred,
                ),
            ), arrival_min

        # Merge-absorption budget for the pool-headroom stall: the merge
        # truncates at capacity (minus the islands' reserved exchange
        # block), so a window may generate at most C_keep − occupancy new
        # box rows without dropping. Computed once per window (the pool
        # does not change until the merge).
        _C_keep = state.pool.capacity - (
            island.num_shards * island.exchange_slots if island else 0
        )
        pool_budget = jnp.int32(_C_keep) - jnp.sum(
            state.pool.time != NEVER, dtype=jnp.int32
        )

        # The loop path's machinery closes over the dense window extraction;
        # building it in a factory keeps the extraction sorts INSIDE the
        # run_loop cond branch, so the matrix fast path never pays for them.
        # Kc = K + 1 columns: column K is never consumed (the cursor gate is
        # ptr < K) — it exists purely to expose the earliest DEFERRED
        # event's full key per host. A self-emission whose key (time,
        # emitting host, seq) is >= that deferred key must bypass the inbox
        # and go to the pool, otherwise it could be processed ahead of the
        # deferred leftover; the full-key compare keeps that routing exact
        # under nanosecond ties.
        def make_loop_fns(dense: _DenseWindow, tail: _Tail):
            Kc = K + 1
            defer_time = dense.time[:, K]
            defer_src = dense.src[:, K]
            defer_seq = dense.seq[:, K]
            carry0 = (
                jnp.zeros((H,), dtype=jnp.int32),  # ptr (consumed per host)
                _Inbox.empty(H, B, PP),
                _Outbox.empty(H, O, PP),
                jnp.int32(0),  # iteration counter
                jnp.bool_(True),  # work remaining
            )

            def cond(carry):
                _, _, _, _, it, work = carry
                return work & (it < max_iters)

            def body(carry):
                state, ptr, inbox, outbox, it, _ = carry

                # --- candidate per host: dense-matrix head vs inbox min ---
                # (one-hot reads; ptr <= K < Kc always in range)
                m_t_raw, m_src, m_seq, m_kind, m_payload = _read_col(
                    dense, ptr, Kc
                )
                in_run = (ptr < K) & (m_t_raw != NEVER)
                m_time = jnp.where(in_run, m_t_raw, NEVER)
                i_time, i_src, i_seq, i_slot = _inbox_min(inbox)
                use_inbox = _key_lt(i_time, i_src, i_seq, m_time, m_src, m_seq)
                ev_time = jnp.where(use_inbox, i_time, m_time)

                i_kind = soa.get_at(inbox.kind, i_slot)
                ev_kind = jnp.where(use_inbox, i_kind, m_kind)

                # --- bulk batch planning (before the room check, which must
                # cover the whole batch's emissions): extend the run take with
                # up to G-1 further CONSECUTIVE events of the bulk kind, each
                # required to precede the inbox head in key order so nothing
                # that deserves to interleave is foreclosed. ---
                bulk_t, bulk_s, bulk_q, bulk_p, bulk_m = [], [], [], [], []
                if bulk_kind is not None and G_run > 1:
                    prev = (
                        (ev_time < win_end) & ~use_inbox & (ev_kind == bulk_kind)
                    )
                    if bulk_self_excluded:
                        # the HEAD is part of the batch too: a loopback
                        # head may emit a same-time self reply that
                        # deserves to interleave before any batched extra
                        prev = prev & (m_src != gid)
                    if bulk_gate is not None:
                        gate = bulk_gate(state, params, win_start, win_end)
                        prev = prev & (gate > 0)
                    for g in range(1, G_run):
                        ing = ptr + g < K
                        tg_r, sg, qg, kg, pg = _read_col(
                            dense, jnp.where(ing, ptr + g, 0), Kc
                        )
                        ing = ing & (tg_r != NEVER)
                        tg = jnp.where(ing, tg_r, NEVER)
                        okg = (
                            prev & ing & (kg == bulk_kind) & (tg < win_end)
                            & _key_lt(tg, sg, qg, i_time, i_src, i_seq)
                        )
                        if bulk_self_excluded:
                            okg = okg & (sg != gid)
                        if bulk_gate is not None:
                            okg = okg & (gate >= g)
                        bulk_t.append(tg)
                        bulk_s.append(sg)
                        bulk_q.append(qg)
                        bulk_p.append(pg)
                        bulk_m.append(okg)
                        prev = okg
                    g_extra = jnp.sum(
                        jnp.stack(bulk_m, axis=1), axis=1, dtype=jnp.int32
                    )
                else:
                    g_extra = jnp.zeros((H,), dtype=jnp.int32)

                # Outbox backpressure: a host whose outbox cannot absorb this
                # event-kind's worst-case emissions (times the batch width)
                # stalls — its events stay queued and defer to the next window
                # via the merge (never dropped). Per-kind worst cases are
                # static python ints, so the lookup is an unrolled select —
                # not an [H]-gather.
                need_base = jnp.zeros((H,), dtype=jnp.int32)
                for k in kinds:
                    e_k = int(E_by_kind[k])
                    if e_k:
                        need_base = jnp.where(ev_kind == k, e_k, need_base)
                need = need_base * (1 + g_extra)
                room = (outbox.count + need) <= O
                # Pool-headroom backpressure (the never-drop invariant,
                # scheduler.c:232-255): the merge can only absorb
                # C − occupancy new box rows, so hosts whose emissions
                # would overflow the pool STALL this window (defer, never
                # drop). Budget is claimed in host-index order via an
                # exclusive cumsum — deterministic. NOT a progress
                # guarantee: box rows accumulated by earlier micro
                # -iterations (box_used) already count against the budget,
                # so with occupancy deep in the red zone even host 0 can
                # fail the gate and the window commits nothing; the driver
                # surfaces that as the headroom-stall RuntimeError in the
                # run loops (the spill tier then needs a larger pool to
                # place even one window's inflow). Common case (ample
                # headroom): every host passes, the gate folds away.
                hot = ev_time < win_end
                box_used = (
                    jnp.sum(outbox.count)
                    + jnp.sum(inbox.time != NEVER, dtype=jnp.int32)
                )
                need_hot = jnp.where(hot, need, 0)
                cum = jnp.cumsum(need_hot) - need_hot  # exclusive
                fits = (box_used + cum + need_hot) <= pool_budget
                valid = hot & room & fits
                stalled = hot & ~(room & fits)

                # --- CPU model (host/cpu.c analog): a loaded host's events
                # serialize on its virtual CPU — event at t EXECUTES at
                # max(t, cpu_avail), advancing cpu_avail by cpu_cost.
                # Selection/ordering stay keyed on the ORIGINAL times; only
                # execution (and thus emission) timestamps shift. Compiled
                # out entirely when the model is off.
                if with_cpu_model:
                    cost = state.host.cpu_cost
                    exec_t = jnp.maximum(ev_time, state.host.cpu_avail)
                    bulk_exec = []
                    prev_e = exec_t
                    for bt in bulk_t:
                        e = jnp.maximum(bt, prev_e + cost)
                        bulk_exec.append(e)
                        prev_e = e
                else:
                    exec_t = ev_time
                    bulk_exec = bulk_t

                i_payload = soa.get_at(inbox.payload, i_slot)
                ev = EventView(
                    mask=valid,
                    time=exec_t,
                    src=jnp.where(use_inbox, i_src, m_src),
                    seq=jnp.where(use_inbox, i_seq, m_seq),
                    kind=ev_kind,
                    # handlers see the unpacked i32 view (payloads travel
                    # packed through sorts/boxes — soa.pack_words)
                    payload=soa.unpack_words(
                        jnp.where(use_inbox[:, None], i_payload, m_payload),
                        P,
                    ),
                )

                # --- consume the chosen event(s) ---
                bulk_valid = [bm & valid for bm in bulk_m]  # [H] per extra col
                taken_extra = (
                    jnp.sum(jnp.stack(bulk_valid, axis=1), axis=1,
                            dtype=jnp.int32)
                    if bulk_valid else jnp.zeros((H,), dtype=jnp.int32)
                )
                last_t = exec_t
                for bt, bv in zip(bulk_exec, bulk_valid):
                    last_t = jnp.where(bv, bt, last_t)
                state = state.replace(
                    host=state.host.replace(
                        done_t=jnp.where(valid, last_t, state.host.done_t)
                    )
                )
                if with_cpu_model:
                    delay = jnp.where(valid, exec_t - ev_time, 0)
                    for bt, be, bv in zip(bulk_t, bulk_exec, bulk_valid):
                        delay = delay + jnp.where(bv, be - bt, 0)
                    state = state.replace(
                        host=state.host.replace(
                            cpu_avail=jnp.where(
                                valid, last_t + cost, state.host.cpu_avail
                            )
                        ),
                        counters=state.counters.replace(
                            cpu_delay_applied=state.counters.cpu_delay_applied
                            + jnp.sum(delay, dtype=jnp.int64)
                        ),
                    )
                ptr = jnp.where(valid & ~use_inbox, ptr + 1 + taken_extra, ptr)
                inbox = inbox.replace(
                    time=_set_col(inbox.time, i_slot, valid & use_inbox, NEVER)
                )

                # --- run handlers (ascending kind; masked SoA updates); the
                # bulk kind's handler runs once per taken column, in key order
                emitter = Emitter()
                for k in kinds:
                    hev = ev.replace(mask=valid & (ev.kind == k))
                    state = handlers[k](state, hev, emitter, params)
                    if k == bulk_kind:
                        for g in range(len(bulk_valid)):
                            gev = EventView(
                                mask=bulk_valid[g],
                                time=bulk_exec[g],
                                src=bulk_s[g],
                                seq=bulk_q[g],
                                kind=jnp.full((H,), k, dtype=jnp.int32),
                                payload=soa.unpack_words(bulk_p[g], P),
                            )
                            state = handlers[k](state, gev, emitter, params)

                state = state.replace(
                    counters=state.counters.replace(
                        events_committed=state.counters.events_committed
                        + jnp.sum(valid, dtype=jnp.int64)
                        + jnp.sum(taken_extra, dtype=jnp.int64),
                        outbox_stall_deferred=state.counters.outbox_stall_deferred
                        + jnp.sum(stalled, dtype=jnp.int64),
                        micro_steps=state.counters.micro_steps + 1,
                    )
                )
                if state.obs is not None:
                    # telemetry block: per-host committed count + the
                    # virtual-time frontier (events process in key order
                    # per host, so a where-select IS the running max)
                    ob = state.obs
                    hd = ob.host_digest
                    if audit:
                        # determinism-audit chain (obs/audit.py): fold the
                        # head event then each bulk column — per-host key
                        # order, the order every engine layout commits in.
                        # Keys use the ORIGINAL event time (not the CPU
                        # model's exec shift), so chains are model-stable.
                        hd = audit_mod.fold(
                            hd, valid, ev_time, ev.src, gid, ev_kind
                        )
                        for bt, bs, bv in zip(bulk_t, bulk_s, bulk_valid):
                            hd = audit_mod.fold(
                                hd, bv, bt, bs, gid, bulk_kind
                            )
                    state = state.replace(obs=ob.replace(
                        host_events=ob.host_events
                        + valid.astype(jnp.int64)
                        + taken_extra.astype(jnp.int64),
                        host_last_t=jnp.where(valid, last_t, ob.host_last_t),
                        host_digest=hd,
                    ))
                if state.flight is not None:
                    # flight recorder (obs/flight.py): append the committed
                    # records at each host's ring cursor, head then bulk
                    # columns — the same commit order the digest folds in
                    fl = flight_mod.record(
                        state.flight, valid, ev_time, ev.src, ev.seq,
                        ev_kind,
                    )
                    for bt, bs, bq, bv in zip(
                        bulk_t, bulk_s, bulk_q, bulk_valid
                    ):
                        fl = flight_mod.record(fl, bv, bt, bs, bq, bulk_kind)
                    state = state.replace(flight=fl)

                # --- route emissions (order fixes per-source seq numbers) ---
                for em in emitter.records:
                    emp = soa.pack_words(em.payload)  # [H, PP]
                    seq = state.host.seq_next
                    state = state.replace(
                        host=state.host.replace(
                            seq_next=jnp.where(em.mask, seq + 1, seq)
                        )
                    )
                    # Self-emissions at or past the host's earliest deferred
                    # leftover (full-key compare: exact under time ties) must
                    # not jump the queue: route them through the pool.
                    is_self = (
                        em.mask
                        & (em.dst == gid)
                        & (em.time < win_end)
                        & _key_lt(em.time, gid, seq,
                                  defer_time, defer_src, defer_seq)
                    )

                    free = inbox.time == NEVER  # [H, B]
                    ff = jnp.argmax(free, axis=1).astype(jnp.int32)
                    has_free = jnp.any(free, axis=1)
                    ins = is_self & has_free
                    # Inbox overflow DEFERS to the pool via the outbox (processed
                    # next window, late but never lost — a lost NIC pump event
                    # would wedge its queue); the counter records the deferral.
                    to_out = em.mask & ~ins
                    inbox = inbox.replace(
                        time=_set_col(inbox.time, ff, ins, em.time),
                        src=_set_col(inbox.src, ff, ins, gid),
                        seq=_set_col(inbox.seq, ff, ins, seq),
                        kind=_set_col(inbox.kind, ff, ins, em.kind),
                        payload=_set_col(inbox.payload, ff, ins, emp),
                    )

                    ocol = outbox.count  # next free outbox column per host
                    put = to_out & (ocol < O)
                    outbox = outbox.replace(
                        time=_set_col(outbox.time, ocol, put, em.time),
                        dst=_set_col(outbox.dst, ocol, put, em.dst),
                        src=_set_col(outbox.src, ocol, put, gid),
                        seq=_set_col(outbox.seq, ocol, put, seq),
                        kind=_set_col(outbox.kind, ocol, put, em.kind),
                        payload=_set_col(outbox.payload, ocol, put, emp),
                        count=outbox.count + put.astype(jnp.int32),
                    )
                    state = state.replace(
                        counters=state.counters.replace(
                            events_emitted=state.counters.events_emitted
                            + jnp.sum(em.mask, dtype=jnp.int64),
                            inbox_overflow_deferred=state.counters.inbox_overflow_deferred
                            + jnp.sum(is_self & ~has_free, dtype=jnp.int64),
                            outbox_overflow_dropped=state.counters.outbox_overflow_dropped
                            + jnp.sum(to_out & ~put, dtype=jnp.int64),
                        )
                    )

                work = jnp.any(valid)
                return (state, ptr, inbox, outbox, it + 1, work)

            def finish(state, ptr, bt, bd, bs, bq, bk, bp):
                """Merge: unconsumed dense cells ∪ tail rows ∪ box rows
                (flattened outbox + inbox leftovers) with ONE 1-key stable
                sort by time carrying every event column and payload word as
                operands — no scatters and no payload-indirection gathers
                (both serialize on TPU). A dense cell is consumed iff its
                column is below the host's final cursor — pure elementwise.
                Also derives the speculation-violation signal: a cross-host
                box emission targeting time t violates iff its DESTINATION
                host already processed an event at time >= t since the
                optimistic synchronizer's window began (host.done_t) —
                impossible under conservative windows, so xmit_min stays
                NEVER there."""
                pool = state.pool
                C = pool.capacity
                dcols = jnp.arange(Kc, dtype=jnp.int32)
                left = dcols[None, :] >= ptr[:, None]  # unconsumed cells
                l_t = jnp.where(left, dense.time, NEVER).reshape(-1)
                l_d = jnp.broadcast_to(gid[:, None], (H, Kc)).reshape(-1)
                l_s = dense.src.reshape(-1)
                l_q = dense.seq.reshape(-1)
                l_k = dense.kind.reshape(-1)

                m_t = jnp.concatenate([l_t, tail.time, bt])
                m_d = jnp.concatenate([l_d, tail.dst, bd])
                m_s = jnp.concatenate([l_s, tail.src, bs])
                m_q = jnp.concatenate([l_q, tail.seq, bq])
                m_k = jnp.concatenate([l_k, tail.kind, bk])
                m_p = [
                    jnp.concatenate(
                        [dense.payload[:, :, w].reshape(-1), tail.payload[w],
                         bp[:, w]]
                    )
                    for w in range(PP)
                ]
                if bt.shape[0] and (island is None or island.optimistic):
                    cross = (bd != bs) & (bt != NEVER)
                    if island is None:
                        dst_last = state.host.done_t[jnp.clip(bd, 0, H - 1)]
                        violates = cross & (bt <= dst_last)
                    else:
                        # islands: only LOCAL-dst emissions can be checked
                        # against this shard's progress clocks; foreign
                        # ones are checked at ARRIVAL on their owner
                        # (assemble's arrival_min) — no per-row collective
                        lr = _box_lrow(bd)
                        loc = (lr >= 0) & (lr < H)
                        dst_last = state.host.done_t[jnp.clip(lr, 0, H - 1)]
                        violates = cross & loc & (bt <= dst_last)
                    xmit_min = jnp.min(jnp.where(violates, bt, NEVER))
                else:
                    xmit_min = jnp.asarray(NEVER, jnp.int64)
                state, arrival_min = assemble(
                    state, m_t, m_d, m_s, m_q, m_k, m_p
                )
                state = state.replace(
                    xmit_min=jnp.minimum(xmit_min, arrival_min)
                )
                return state, jnp.min(state.pool.time)

            return carry0, cond, body, finish

        def run_loop(state):
            state = _obs_win_bump(
                state, obs_mod.WIN_WINDOWS, obs_mod.WIN_LOOP
            )
            dense, tail = _dense_extract(
                state.pool, win_start, win_end, H, K + 1, PP, lrow=_lrow,
            )
            carry0, cond, body, finish = make_loop_fns(dense, tail)
            state, ptr, inbox, outbox, _, _ = jax.lax.while_loop(
                cond, body, (state,) + carry0
            )
            hostsB = jnp.broadcast_to(
                gid[:, None], inbox.time.shape
            ).reshape(-1)
            return finish(
                state, ptr,
                jnp.concatenate(
                    [outbox.time.reshape(-1), inbox.time.reshape(-1)]
                ),
                jnp.concatenate([outbox.dst.reshape(-1), hostsB]),
                jnp.concatenate(
                    [outbox.src.reshape(-1), inbox.src.reshape(-1)]
                ),
                jnp.concatenate(
                    [outbox.seq.reshape(-1), inbox.seq.reshape(-1)]
                ),
                jnp.concatenate(
                    [outbox.kind.reshape(-1), inbox.kind.reshape(-1)]
                ),
                jnp.concatenate(
                    [outbox.payload.reshape(-1, PP),
                     inbox.payload.reshape(-1, PP)]
                ),
            )

        def run_matrix(state):
            """Whole-window vectorized path: when EVERY in-window event has
            the bulk kind, there is no intra-window feedback (the bulk
            safety contract forbids self-emissions below win_end), so the
            full [H, K] window matrix is processed in ONE handler pass —
            no micro-step loop at all. PHOLD-class models hit this every
            window; it is the PDES "superstep" optimization.

            TPU note (profiled on v5e): large GATHERS serialize (~9 ns per
            element) while multi-operand sorts and scans run at memory
            bandwidth, so this path is built from sorts, cumulative scans,
            and reshapes ONLY (_dense_extract)."""
            state = _obs_win_bump(
                state, obs_mod.WIN_WINDOWS, obs_mod.WIN_MATRIX
            )
            pool = state.pool
            dense, tail = _dense_extract(
                pool, win_start, win_end, H, K, PP, lrow=_lrow
            )
            d_t, d_s, d_q = dense.time, dense.src, dense.seq
            d_p = dense.payload
            # fillers interleave with real same-host rows only at time
            # NEVER, so a dense cell is real iff its time is set
            valid = d_t != NEVER
            nvalid = jnp.sum(valid, axis=1, dtype=jnp.int32)
            if with_cpu_model:
                # CPU serialization as a scan (same semantics as the loop
                # path's per-event chain): exec_k = max(t_k, exec_{k-1} +
                # cost). With u_k = exec_k - k*cost this is a cummax of
                # (t_k - k*cost) floored at cpu_avail.
                cost = state.host.cpu_cost[:, None]  # [H, 1]
                ks = jnp.arange(valid.shape[1], dtype=jnp.int64)[None, :]
                shifted = jnp.where(
                    valid, d_t - ks * cost, jnp.int64(-(1 << 62))
                )
                u = jax.lax.cummax(shifted, axis=1)
                u = jnp.maximum(u, state.host.cpu_avail[:, None])
                exec_t = jnp.where(valid, u + ks * cost, d_t)
                last_exec = soa.get_at(
                    exec_t, jnp.maximum(nvalid - 1, 0)
                )
                state = state.replace(
                    host=state.host.replace(
                        cpu_avail=jnp.where(
                            nvalid > 0,
                            last_exec + state.host.cpu_cost,
                            state.host.cpu_avail,
                        )
                    ),
                    counters=state.counters.replace(
                        cpu_delay_applied=state.counters.cpu_delay_applied
                        + jnp.sum(
                            jnp.where(valid, exec_t - d_t, 0),
                            dtype=jnp.int64,
                        )
                    ),
                )
            else:
                exec_t = d_t
            mv = MatrixEventView(
                mask=valid, time=exec_t, src=d_s, seq=d_q,
                payload=soa.unpack_words(d_p, P),
            )
            memit = MatrixEmitter()
            state = matrix_handlers[bulk_kind](state, mv, memit, params)
            last_t = jnp.max(jnp.where(valid, exec_t, jnp.int64(-1)), axis=1)
            state = state.replace(
                host=state.host.replace(
                    done_t=jnp.where(nvalid > 0, last_t, state.host.done_t)
                )
            )
            # per-source sequence numbers: per host, emissions are ordered
            # column-major (event order), record-minor within a column —
            # identical to the loop path's per-event record order
            base = state.host.seq_next
            masks = [r.mask.astype(jnp.int32) for r in memit.records]
            per_col = sum(masks) if masks else jnp.zeros((H, K), jnp.int32)
            col_excl = jnp.cumsum(per_col, axis=1) - per_col
            seen = jnp.zeros((H, K), dtype=jnp.int32)
            em_rows = []  # per record: (time, dst, src, seq, kind, pcols)
            hostsK = jnp.broadcast_to(gid[:, None], (H, K))
            for j, r in enumerate(memit.records):
                seqj = base[:, None] + col_excl + seen
                seen = seen + masks[j]
                rp = soa.pack_words(r.payload)  # [H, K, PP]
                em_rows.append((
                    jnp.where(r.mask, r.time, NEVER).reshape(-1),
                    r.dst.reshape(-1),
                    hostsK.reshape(-1),
                    seqj.reshape(-1),
                    r.kind.reshape(-1),
                    [rp[:, :, w].reshape(-1) for w in range(PP)],
                ))
            total = jnp.sum(per_col, axis=1, dtype=jnp.int32)
            state = state.replace(
                host=state.host.replace(seq_next=base + total)
            )
            # bulk-contract check (make_window_step docstring): the matrix
            # path is only sound if no emission targets SELF below win_end —
            # such an emission would deserve to interleave with this
            # window's batched events. Count violations loudly.
            viol = jnp.zeros((), jnp.int64)
            for r in memit.records:
                viol = viol + jnp.sum(
                    r.mask & (r.dst == hostsK) & (r.time < win_end),
                    dtype=jnp.int64,
                )
            state = state.replace(
                counters=state.counters.replace(
                    bulk_contract_violations=(
                        state.counters.bulk_contract_violations + viol
                    )
                )
            )
            state = state.replace(
                counters=state.counters.replace(
                    events_committed=state.counters.events_committed
                    + jnp.sum(valid, dtype=jnp.int64),
                    events_emitted=state.counters.events_emitted
                    + jnp.sum(per_col, dtype=jnp.int64),
                    micro_steps=state.counters.micro_steps + 1,
                )
            )
            if state.obs is not None:
                ob = state.obs
                hd = ob.host_digest
                if audit:
                    # audit chain over the dense window, column by column —
                    # per-host key order, identical to the loop path's
                    # micro-step commit order, so either dispatch path of
                    # the same window folds the same chain
                    for j in range(K):
                        hd = audit_mod.fold(
                            hd, valid[:, j], d_t[:, j], d_s[:, j], gid,
                            dense.kind[:, j],
                        )
                state = state.replace(obs=ob.replace(
                    host_events=ob.host_events
                    + jnp.sum(valid, axis=1, dtype=jnp.int64),
                    host_last_t=jnp.where(
                        nvalid > 0, last_t, ob.host_last_t
                    ),
                    host_digest=hd,
                ))
            if state.flight is not None:
                fl = state.flight
                for j in range(K):
                    fl = flight_mod.record(
                        fl, valid[:, j], d_t[:, j], d_s[:, j], d_q[:, j],
                        dense.kind[:, j],
                    )
                state = state.replace(flight=fl)
            # --- merge (sort 3): tail leftovers ∪ emissions, ONE 1-key
            # stable sort by time carrying every column; no payload
            # indirection gathers. Output truncates to pool capacity
            # (fillers sit at time NEVER and fall off first). ---
            m_t = jnp.concatenate([tail.time] + [e[0] for e in em_rows])
            m_d = jnp.concatenate([tail.dst] + [e[1] for e in em_rows])
            m_s = jnp.concatenate([tail.src] + [e[2] for e in em_rows])
            m_q = jnp.concatenate([tail.seq] + [e[3] for e in em_rows])
            m_k = jnp.concatenate([tail.kind] + [e[4] for e in em_rows])
            m_p = [
                jnp.concatenate([tail.payload[w]] + [e[5][w] for e in em_rows])
                for w in range(PP)
            ]
            state, arrival_min = assemble(state, m_t, m_d, m_s, m_q, m_k, m_p)
            # speculation-violation signal (optimistic synchronizer): the
            # one place a by-dst lookup is unavoidable; emissions are the
            # only candidate violators (leftovers already lived in the pool)
            if em_rows and (island is None or island.optimistic):
                e_t = jnp.concatenate([e[0] for e in em_rows])
                e_d = jnp.concatenate([e[1] for e in em_rows])
                e_s = jnp.concatenate([e[2] for e in em_rows])

                def _exact(_):
                    # the one unavoidable by-dst lookup (a serialized
                    # gather on TPU) — only reached when a violation is
                    # even possible, i.e. under optimistic long windows
                    if island is None:
                        dst_last = state.host.done_t[jnp.clip(e_d, 0, H - 1)]
                        viol = (
                            (e_d != e_s) & (e_t != NEVER) & (e_t <= dst_last)
                        )
                    else:
                        # local-dst only; foreign emissions are covered by
                        # assemble's arrival check on the owner shard
                        lr = _box_lrow(e_d)
                        loc = (lr >= 0) & (lr < H)
                        dst_last = state.host.done_t[jnp.clip(lr, 0, H - 1)]
                        viol = (
                            (e_d != e_s) & loc & (e_t != NEVER)
                            & (e_t <= dst_last)
                        )
                    return jnp.min(jnp.where(viol, e_t, NEVER))

                possible = jnp.min(e_t) <= jnp.max(state.host.done_t)

                def _never(_):
                    never = jnp.asarray(NEVER, jnp.int64)
                    pcast = getattr(jax.lax, "pcast", None)
                    if island is not None and pcast is not None:
                        # under shard_map the true branch's output varies
                        # over the islands axis; the constant must be cast
                        # to the same varying type or cond rejects it
                        # (jax < 0.7 has no varying-type checker and no
                        # lax.pcast — the bare constant is already valid)
                        never = pcast(
                            never, (island.axis,), to="varying"
                        )
                    return never

                xmit_min = jax.lax.cond(possible, _exact, _never, 0)
            else:
                xmit_min = jnp.asarray(NEVER, jnp.int64)
            state = state.replace(
                xmit_min=jnp.minimum(xmit_min, arrival_min)
            )
            return state, jnp.min(state.pool.time)

        if bulk_kind is None or bulk_kind not in matrix_handlers:
            return run_loop(state)
        if _force_path == "matrix":
            return run_matrix(state)
        if _force_path == "loop":
            return run_loop(state)
        pool0 = state.pool
        inwin = pool0.time < win_end
        all_bulk = jnp.all(~inwin | (pool0.kind == bulk_kind))
        return jax.lax.cond(all_bulk, run_matrix, run_loop, state)

    return step


# ---------------------------------------------------------------------------
# Driver kernel factories
# ---------------------------------------------------------------------------
#
# Module-level so the fleet runner (shadow_tpu/fleet) can vmap them over a
# leading JOB axis: every argument that varies per job (runahead, stop) is a
# traced value, never a closed-over Python constant. The Simulation methods
# below delegate here with their own runahead baked in.


def make_run_to(step, hi: int):
    """Build run_to(state, params, runahead, stop, max_windows) ->
    (state, min_next, pressed, occupancy): the fused conservative window
    loop of the single-pool engine. `runahead` and `stop` are traced (the
    fleet passes per-job values); `hi` is the bound gear's red-zone mark
    (a compile-time int — every fleet lane shares the compiled pool
    shape, so it is shared too)."""

    def run_to(state: SimState, params: NetParams, runahead, stop,
               max_windows):
        """Advance up to max_windows windows (or until stop). Bounding
        the on-device while_loop keeps each dispatch short — long single
        dispatches can trip accelerator-runtime watchdogs.

        Exits early (third return value True) when pool occupancy
        crosses the spill red zone — the mark is PER-GEAR (`hi` is the
        bound gear's) — so the driver can upshift, or drain overflow to
        host memory BEFORE the merge would drop rows (core/spill.py) —
        one compare per window, no extra sorts. The final occupancy
        rides back as the fourth value: it is the gearing decision
        signal, fetched on the sync the driver already pays."""
        runahead = jnp.asarray(runahead, jnp.int64)
        stop = jnp.asarray(stop, jnp.int64)
        max_windows = jnp.asarray(max_windows, jnp.int32)

        def cond(c):
            state, mn, w = c
            occ = jnp.sum(state.pool.time != NEVER)
            return (mn < stop) & (w < max_windows) & (occ < hi)

        def body(c):
            state, mn, w = c
            ws = mn
            we = jnp.minimum(ws + runahead, stop)
            state, mn = step(state, params, ws, we)
            return state, mn, w + 1

        mn0 = jnp.min(state.pool.time)
        state, mn, _ = jax.lax.while_loop(
            cond, body, (state, mn0, jnp.int32(0))
        )
        occ = jnp.sum(state.pool.time != NEVER)
        return state, mn, occ >= hi, occ

    return run_to


def make_attempt(step):
    """Build attempt(state, params, ws, we) -> (state, min_next, viol):
    one optimistic window processed to completion ON DEVICE. All four
    arguments are traced, so the factory is directly vmappable over a
    leading job axis (the fleet's per-lane speculative windows)."""

    def attempt(state: SimState, params: NetParams, ws, we):
        """Process the window [ws, we) to completion: sub-step until no
        pool events remain below we, or a speculation violation surfaces
        (state.xmit_min != NEVER). One dispatch per attempt."""
        ws = jnp.asarray(ws, jnp.int64)
        we = jnp.asarray(we, jnp.int64)

        def cond(c):
            _, mn, v = c
            return (mn < we) & (v == simtime.NEVER)

        def body(c):
            st, mn, _ = c
            st2, mn2 = step(st, params, jnp.maximum(mn, ws), we)
            return st2, mn2, st2.xmit_min

        mn0 = jnp.min(state.pool.time)
        return jax.lax.while_loop(
            cond, body, (state, mn0, jnp.asarray(simtime.NEVER, jnp.int64))
        )

    return attempt


# ---------------------------------------------------------------------------
# Simulation driver (controller/manager analog)
# ---------------------------------------------------------------------------


class Simulation:
    """Owns the built state + jitted kernels and plays the round loop.

    Construct via shadow_tpu.sim.build_simulation (from a Config) or directly
    with prebuilt pieces for tests.
    """

    def __init__(
        self,
        *,
        num_hosts: int,
        handlers: dict[int, Handler],
        params: NetParams,
        host_vertex: np.ndarray,
        seed: int,
        stop_time: int,
        runahead: int,
        event_capacity: int = 1 << 14,
        K: int = 32,
        B: int = 8,
        O: int = 64,
        subs: dict | None = None,
        initial_events: list[tuple[int, int, int, int, list[int]]] | None = None,
        bulk_kinds: dict[int, int] | None = None,
        matrix_handlers: dict[int, Callable] | None = None,
        payload_words: int = PAYLOAD_WORDS,
        cpu_ns_per_event: np.ndarray | None = None,
        bulk_gate: Callable | None = None,
        bulk_self_excluded: bool = False,
        obs_counters: bool = True,
        pool_gears: int = 1,
        audit_digest: bool = True,
        flight_capacity: int = 0,
        pipelined_dispatch: bool = True,
        host_workers: int = 1,
    ):
        # initial_events: (time, dst, src, kind, payload words)
        self.num_hosts = num_hosts
        self.stop_time = int(stop_time)
        self.runahead = int(runahead)
        if self.runahead <= 0:
            raise ValueError("runahead must be > 0 (min topology latency)")
        self.params = params
        n0 = len(initial_events or [])
        if n0 > event_capacity:
            raise ValueError("initial events exceed event pool capacity")
        # Occupancy-adaptive pool gearing (core/gearbox.py): a ladder of
        # (capacity, dense width) tiers, each compiling its own window
        # kernel; drivers shift at dispatch boundaries. pool_gears=1 keeps
        # a single tier at the configured shapes — the pre-gearbox build.
        self.pool_gears = int(pool_gears)
        self._gear_ladder = gearbox.build_ladder(
            self.pool_gears, event_capacity, K, num_hosts, spill_mod.marks
        )
        self._gear = (
            gearbox.target_level(self._gear_ladder, n0)
            if len(self._gear_ladder) > 1
            else self._gear_ladder[-1].level
        )
        self._shifter = (
            gearbox.GearShifter(self._gear_ladder)
            if len(self._gear_ladder) > 1
            else None
        )
        self._gear_shifts = 0
        self._gear_dispatches: dict[int, int] = {}
        pool = EventPool.empty(
            self._gear_ladder[self._gear].capacity, payload_words
        )
        if initial_events:
            # Assign per-source sequence numbers in list order, like the
            # reference assigns per-source event IDs at push time.
            seq_ctr: dict[int, int] = {}
            times, dsts, srcs, seqs, kinds_, pls = [], [], [], [], [], []
            for (t, d, s, k, pl) in initial_events:
                q = seq_ctr.get(s, 0)
                seq_ctr[s] = q + 1
                times.append(t)
                dsts.append(d)
                srcs.append(s)
                seqs.append(q)
                kinds_.append(k)
                row = list(pl) + [0] * (payload_words - len(pl))
                pls.append(row[:payload_words])
            sl = slice(0, n0)
            pool = pool.replace(
                time=pool.time.at[sl].set(jnp.asarray(times, jnp.int64)),
                dst=pool.dst.at[sl].set(jnp.asarray(dsts, jnp.int32)),
                src=pool.src.at[sl].set(jnp.asarray(srcs, jnp.int32)),
                seq=pool.seq.at[sl].set(jnp.asarray(seqs, jnp.int32)),
                kind=pool.kind.at[sl].set(jnp.asarray(kinds_, jnp.int32)),
                payload=pool.payload.at[sl].set(
                    soa.pack_words(jnp.asarray(pls, jnp.int32))
                ),
            )
            seq_init = np.zeros(num_hosts, dtype=np.int32)
            for s, q in sorted(seq_ctr.items()):
                seq_init[s] = q
        else:
            seq_init = np.zeros(num_hosts, dtype=np.int32)

        self.handlers = handlers
        self.K, self.B, self.O = K, B, O
        with_cpu = cpu_ns_per_event is not None and bool(
            np.any(np.asarray(cpu_ns_per_event) > 0)
        )
        # Stash the kernel build config so parallel/islands.py (and any
        # other re-wiring subclass) can rebuild the window step with a
        # different execution layout.
        self._bulk_kinds = bulk_kinds
        self._matrix_handlers = matrix_handlers
        self._with_cpu = with_cpu
        self._bulk_gate = bulk_gate
        self._bulk_self_excluded = bulk_self_excluded
        self._payload_words = payload_words
        # Determinism audit plane (obs/audit.py): the digest chain folds
        # ride the obs block; False compiles the folds out — the control
        # arm of bench.py --audit-smoke.
        self._audit_digest = bool(audit_digest)
        host = make_host_state(
            num_hosts, host_vertex,
            cpu_cost=cpu_ns_per_event if with_cpu else None,
        )
        host = host.replace(seq_next=jnp.asarray(seq_init))
        self.state = SimState(
            now=jnp.int64(0),
            pool=pool,
            host=host,
            counters=Counters.zeros(),
            rng_keys=rng_mod.host_keys(seed, num_hosts),
            subs=subs or {},
            obs=obs_mod.ObsBlock.zeros(num_hosts) if obs_counters else None,
            flight=(
                flight_mod.FlightRing.zeros(num_hosts, int(flight_capacity))
                if flight_capacity else None
            ),
        )
        # Telemetry session (obs/metrics.ObsSession): attached by the CLI
        # (--metrics-out/--trace-out) or bench; None keeps the run loops on
        # their zero-instrumentation path.
        self.obs_session = None
        # Determinism-audit trail + flight spool (obs/audit.py /
        # obs/flight.py): attached by --digest-out / --flight-out; None
        # keeps every handoff free of the extra obs-block fetch.
        self.audit = None
        self.flight_spool = None
        # Fault-tolerance plane (shadow_tpu/faults): device/file injections
        # execute at handoff boundaries via _handoff_tick; quarantined
        # (dead) hosts have their pending pool/spill events drained at
        # every subsequent handoff — the crashed-host semantic. Auto-
        # checkpointing (--checkpoint-every) rides the same tick.
        self.fault_injector = None
        self._dead_hosts: set[int] = set()
        self._force_spill = False
        # Backend supervision (core/supervisor.py): every driver dispatch
        # routes through _sv(); with no supervisor attached that is a
        # direct call — zero overhead, pre-supervisor behavior. The
        # failover flag re-lowers kernels on the CPU backend (_jit).
        self.supervisor = None
        self._cpu_failover = False
        # Pipelined CPU↔TPU handoff (core/pipeline.py): the drivers
        # double-buffer dispatches — issue window N+1 asynchronously
        # while the host drains window N — synchronizing only at the
        # fetch point. experimental.pipelined_dispatch: false restores
        # the strictly-serial loop (the bench comparison arm). Stats are
        # created lazily so serial runs emit no pipeline.* keys.
        self.pipelined_dispatch = bool(pipelined_dispatch)
        self._pipeline_stats: dict | None = None
        # Host handoff hooks: called as fn(sim, frontier_ns) inside every
        # driver's host-drain phase (after the fault/checkpoint tick) —
        # the seam for host-side per-handoff work the pipeline overlaps
        # (the managed-plane syscall-drain analog; bench models it here).
        # Entries are (fn, sharded): sharded hooks take (sim, frontier_ns,
        # gid) per owning host and drain through the multi-worker host
        # plane below.
        self._handoff_hooks: list = []
        # PARSIR-style multi-worker host plane (core/hostplane.py): with
        # experimental.host_workers > 1 the per-host handoff actions
        # (sharded hooks, flight-spool extraction) fan out to pinned
        # workers and merge in canonical (virtual-time, host-gid) order —
        # bit-exact vs the serial drain by construction, and the drain
        # runs inside the pipeline's issue->await overlap window. 1 (the
        # default) keeps today's strictly-serial inline drain: no
        # threads, and no hostplane.* stats keys.
        self.host_workers = max(1, int(host_workers))
        self._hostplane_obj = None
        self._hostplane_stats: dict | None = None
        self._hostplane_slot_cache: tuple | None = None
        # Elastic mesh resilience (parallel/elastic.py): the runner's
        # dispatch-boundary hook — probes lost chips and signals the
        # relayout-back-up. None = one attribute check per dispatch.
        self.elastic = None
        # Resource-pressure plane (core/pressure.py): None until the
        # first pressure signal (a stall, an XLA RESOURCE_EXHAUSTED, or a
        # saturate_pool injection) lazily attaches the default ladder —
        # the no-pressure path stays attribute-check cheap. Reshaping
        # ladder rungs (gear downshift) are forbidden while an optimistic
        # attempt holds a rollback snapshot of the current shapes.
        self.pressure = None
        self._pressure_reshape_ok = True
        self.checkpoint_dir: str | None = None
        self.checkpoint_every_ns = 0
        self.checkpoint_retain = 3
        self._ckpt_next_t = 0
        self._ckpt_seq = 0
        self.fault_counters = {
            "hosts_quarantined": 0,
            "events_drained": 0,
            "files_corrupted": 0,
            "checkpoints_written": 0,
            "checkpoints_pruned": 0,
            "resume_fallbacks": 0,
        }
        self._gear_fns: dict[int, dict] = {}
        self._bind_gear()

    # -- gearbox plumbing (core/gearbox.py): one compiled kernel set per
    # active gear, bound into the attributes every driver (and test, and
    # procs.bridge) already reads --
    def _build_gear_fns(self, spec: gearbox.GearSpec) -> dict:
        step = make_window_step(
            self.handlers, self.num_hosts, K=spec.K, B=self.B, O=self.O,
            bulk_kinds=self._bulk_kinds,
            matrix_handlers=self._matrix_handlers,
            with_cpu_model=self._with_cpu,
            bulk_gate=self._bulk_gate,
            bulk_self_excluded=self._bulk_self_excluded,
            payload_words=self._payload_words,
            audit=self._audit_digest,
        )
        return {
            "step_fn": step,
            "step": self._jit(step),
            "run_to": self._jit(self._make_run_to(step, spec.hi)),
            "attempt": self._jit(self._make_attempt(step)),
        }

    def _jit(self, fn):
        """jit honoring degraded-mode failover (core/supervisor.py): with
        the supervisor in CPU failover, kernels re-lower on the CPU
        backend so the simulation keeps advancing while the accelerator
        is gone; the default path is a plain jax.jit."""
        jf = jax.jit(fn)
        if not getattr(self, "_cpu_failover", False):
            return jf
        try:
            dev = jax.devices("cpu")[0]
        except RuntimeError:
            return jf

        def on_cpu(*args):
            with jax.default_device(dev):
                return jf(*args)

        return on_cpu

    def _bind_gear(self) -> None:
        spec = self._gear_ladder[self._gear]
        fns = self._gear_fns.get(spec.level)
        if fns is None:
            fns = self._gear_fns[spec.level] = self._build_gear_fns(spec)
        # raw (unjitted) step for callers composing their own fused device
        # loops (e.g. procs.bridge's run-until-output sync loop)
        self._step_fn = fns["step_fn"]
        self._step = fns["step"]
        self._run_to = fns["run_to"]
        self._attempt = fns["attempt"]

    def _shift_gear(self, level: int) -> None:
        """Move the pool to `level`'s capacity (one truncating/padding
        re-sort — gearbox.resize_pool) and rebind that gear's compiled
        kernels. Handoff-boundary only: never inside a jitted window loop,
        and never inside an optimistic attempt (rollback snapshots must
        keep their shapes)."""
        spec = self._gear_ladder[level]
        pool, dropped = gearbox.resize_pool(self.state.pool, spec.capacity)
        self.state = self.state.replace(
            pool=pool,
            counters=self.state.counters.replace(
                pool_overflow_dropped=(
                    self.state.counters.pool_overflow_dropped + dropped
                )
            ),
        )
        self._gear = level
        self._gear_shifts += 1
        if self._shifter is not None:
            self._shifter.reset()
        self.state = obs_mod.bump_win(self.state, obs_mod.WIN_GEAR_SHIFTS)
        obs = getattr(self, "obs_session", None)
        if obs is not None and obs.tracer:
            obs.tracer.instant(
                "gear_shift", level=level, capacity=spec.capacity
            )
        self._bind_gear()

    def _gear_tick(self, occ: int, press: bool = False,
                   margin: int = 1) -> bool:
        """One dispatch-boundary gearing decision; returns True iff the
        gear changed. No-op (and no occupancy math) on ungeared builds."""
        if self._shifter is None:
            return False
        if self.pressure is not None and self.pressure.hold_gear:
            # forced-downshift hold (pressure ladder): the red-zone
            # upshift rule is overridden while device memory is tight —
            # the spill tier absorbs the occupancy instead
            return False
        new = self._shifter.observe(
            self._gear, int(occ), press=press, margin=margin
        )
        if new is None:
            return False
        self._shift_gear(new)
        return True

    def _gear_note_dispatch(self) -> None:
        self._gear_dispatches[self._gear] = (
            self._gear_dispatches.get(self._gear, 0) + 1
        )

    def _live_spill_clamp(self, stop_at: int, wpd: int) -> tuple[int, int]:
        """Call-time spill clamp for SUPERVISED dispatch thunks: a
        pressure-ladder rung (forced downshift) can engage the spill tier
        BETWEEN attempts of one dispatch, after the driver computed its
        stop time — the retry must then clamp below the earliest parked
        row (and drop to single-window dispatches) or resident hosts
        would process past host-parked events and diverge from the
        oversized-pool run. Identity while the spill tier is empty."""
        sp = getattr(self, "_spill", None)
        if sp is None or not sp.count:
            return stop_at, wpd
        return (
            min(stop_at, sp.min_time + self.runahead, min(sp._partial_min)),
            1,
        )

    def _pool_occupancy(self) -> int:
        """Live pool rows — the gearing decision signal for the stepwise
        and optimistic drivers (the fused driver gets it for free on the
        run_to sync). One small reduce + fetch per dispatch boundary, paid
        only on geared builds."""
        return int(jnp.sum(self.state.pool.time != NEVER))

    def gear_stats(self) -> dict:
        """Gearbox telemetry for bench rows / metrics dumps: active level,
        ladder shape, shift count, and the per-gear dispatch histogram."""
        spec = self._gear_ladder[self._gear]
        return {
            "gear_level": self._gear,
            "gear_tiers": len(self._gear_ladder),
            "gear_capacity": spec.capacity,
            "gear_k": spec.K,
            "gear_shifts": self._gear_shifts,
            "gear_dispatches": {
                str(lvl): n for lvl, n in sorted(self._gear_dispatches.items())
            },
        }

    def _import_foreign_layout(self, foreign, meta) -> None:
        """checkpoint.restore_relayout hook: adopt a checkpoint taken in
        the islands [S, ...] layout into this GLOBAL build — the
        partition collapses (host rows land by gid, pool rows compact,
        per-shard counters sum). Per-host order, RNG streams and the
        audit digest key on global host ids, so the resumed run extends
        the checkpointed chain exactly. Routes into the CURRENT gear's
        pool; overflow raises with the capacity hint."""
        from shadow_tpu.parallel import islands as islands_mod

        self.state = islands_mod.globalize_state(
            foreign, int(self.state.pool.time.shape[-1])
        )

    def _make_run_to(self, step, hi: int):
        lane = make_run_to(step, hi)
        runahead = jnp.int64(self.runahead)

        def run_to(state: SimState, params: NetParams, stop, max_windows):
            return lane(state, params, runahead, stop, max_windows)

        return run_to

    # -- host-driven round loop (one device sync per window; debuggable) --

    def _step_halves(self, ws: int, we: int):
        """(issue_fn, fetch_fn) halves of one stepwise window dispatch.
        issue enqueues the jitted step (async — device futures); fetch
        performs the blocking frontier read. A supervised retry re-runs
        both halves, re-reading the bound kernel and re-clamping the
        spill stop per attempt — exactly what the fused thunk did."""

        def issue(ws=ws, we=we):
            we, _ = self._live_spill_clamp(we, 1)
            return self._step(self.state, self.params, ws, max(ws, we))

        def fetch(out):
            st, mn = out
            return st, int(mn)

        return issue, fetch

    def run_stepwise(self, until: int | None = None) -> int:
        stop = self.stop_time if until is None else min(until, self.stop_time)
        spill = self._spill_store()
        obs = self.obs_session
        pipe = self._pipeline()
        windows = 0
        stall = 0
        # Committed frontier carried from the dispatch's own return value:
        # re-deriving it with a fresh jnp.min per handoff dispatched one
        # tiny reduce kernel per window for nothing. None = must derive
        # from the pool (startup, or after a tick mutated it).
        min_next = None
        try:
            while True:
                if self._shifter is not None:
                    # gear decision BEFORE spill manage: an upshift absorbs
                    # red-zone pressure without a host drain episode
                    self._gear_tick(self._pool_occupancy())
                with metrics_mod.span(obs, "spill"):
                    tok = self.state
                    stop_at = spill_mod.manage(self, spill, stop)
                if self.state is not tok or min_next is None:
                    min_next = int(jnp.min(self.state.pool.time))
                if self._fault_plane_active():
                    tok = self.state
                    self._handoff_tick(min_next)
                    if self.state is not tok:
                        # a drain may have removed the frontier event
                        min_next = int(jnp.min(self.state.pool.time))
                if min_next >= stop_at:
                    if min_next >= stop and spill.min_time >= stop:
                        break
                    stall += 1
                    if stall > 2:
                        occ = self._pool_occupancy()
                        cap = self._gear_ladder[self._gear].capacity
                        if self._pressure_stall(window=min_next,
                                                occupancy=occ,
                                                capacity=cap):
                            stall = 0  # a ladder rung reshaped the tier
                            continue
                        raise self._pool_exhausted(
                            "spill tier cannot make progress: either a "
                            "single timestamp holds more events than the "
                            "pool fill mark, or pool occupancy leaves too "
                            "little headroom for even one window's "
                            "emissions (the pool-headroom gate stalled "
                            "every host); raise "
                            "experimental.event_capacity",
                            window=min_next, occupancy=occ, capacity=cap,
                        )
                    continue
                stall = 0
                if self.pressure is not None:
                    self.pressure.note_progress()
                ws = min_next
                we = min(ws + self.runahead, stop_at)
                # adopt the issued-ahead window iff the committed state
                # and args are exactly what the serial loop would pass
                # (core/pipeline.py recompute rule)
                pending = (
                    pipe.take(self.state, (ws, we))
                    if pipe is not None else None
                )
                if pending is None:
                    with metrics_mod.span(obs, "dispatch", windows=1):
                        p = self._sv_issue(
                            "step", *self._step_halves(ws, we)
                        )
                        self.state, mn = self._sv_await(p)
                else:
                    with metrics_mod.span(obs, "await", windows=1):
                        self.state, mn = self._sv_await(pending)
                self._gear_note_dispatch()
                min_next = mn
                # two-slot pipeline: issue window N+1 before draining
                # window N's handoff — only across a quiet boundary
                if pipe is not None and mn < stop:
                    if (not spill.count and not self._force_spill
                            and self._handoff_quiet(mn)
                            and not self._sv_disrupted()):
                        ws2, we2 = mn, min(mn + self.runahead, stop)
                        with metrics_mod.span(obs, "issue", windows=1):
                            pipe.put(
                                self._sv_issue(
                                    "step", *self._step_halves(ws2, we2)
                                ),
                                self.state, (ws2, we2),
                            )
                    else:
                        pipe.forced_drain()
                with metrics_mod.span(obs, "host_drain"):
                    if self._audit_active():
                        self._audit_tick(mn)
                    self._run_handoff_hooks(mn)
                if pipe is not None:
                    if self._sv_disrupted():
                        pipe.discard()
                    else:
                        pipe.invalidate(self.state)
                windows += 1
        finally:
            if pipe is not None:
                pipe.close()
        return windows

    def _make_attempt(self, step):
        return make_attempt(step)

    # -- optimistic synchronization: speculate long windows, roll back on
    # violation (SURVEY §7.6). Pure-array state makes rollback free: the
    # pre-window state is just the previous pytree. --
    @staticmethod
    def adapt_window_factor(
        factor: int, streak: int, rolled_back: bool, cap: int
    ) -> tuple[int, int]:
        """The Time-Warp throttling policy shared by the global and
        islands optimistic drivers: halve the speculation factor on a
        rolled-back window, double it after four clean windows in a row.
        Per-run deterministic (depends only on sim state, never wall
        time)."""
        if rolled_back:
            return max(1, factor // 2), 0
        streak += 1
        if streak >= 4 and factor < cap:
            return min(cap, factor * 2), 0
        return factor, streak

    def run_optimistic(
        self,
        until: int | None = None,
        window_factor: int = 8,
        adaptive: bool = True,
    ) -> tuple[int, int]:
        """Advance with speculative windows of window_factor × runahead.

        A window [ws, we) is processed to completion by repeated sub-steps
        (each processes all pool events < we in per-host key order; newly
        generated cross-host deliveries inside the window are picked up by
        the following sub-step). `host.done_t` tracks each host's processed
        progress across sub-steps; a sub-step reports a violation
        (state.xmit_min < NEVER) when it emitted a delivery behind its
        destination's progress clock. On violation the WHOLE window rolls
        back to the snapshot (pure arrays — rollback is just dropping the
        speculated pytree) and retries with the window shrunk to the
        violation time, never below the conservative runahead, which is
        violation-free by construction (emission time >= now + min_latency
        >= ws + runahead >= any processed time).

        With ``adaptive`` (BASELINE config 4's "optimistic PDES windows"
        tuning), the factor self-regulates between 1 and window_factor: a
        rolled-back window halves it (speculation is outrunning the
        workload's lookahead), four clean windows in a row double it —
        the standard Time-Warp throttling shape, per-run deterministic
        (the schedule depends only on sim state, never wall time).

        Returns (windows_committed, rollbacks). Produces the conservative
        schedule's results; wins when the pool holds work spanning many
        runaheads (fewer barriers/dispatches per simulated second).
        """
        stop = self.stop_time if until is None else min(until, self.stop_time)
        cons = self.runahead
        windows = rollbacks = 0
        factor = window_factor
        streak = 0
        neg1 = jnp.full((self.num_hosts,), -1, dtype=jnp.int64)
        self.state = self.state.replace(
            host=self.state.host.replace(done_t=neg1)
        )
        obs = self.obs_session
        pipe = self._pipeline()
        min_next = int(jnp.min(self.state.pool.time))
        try:
            while min_next < stop:
                if self._shifter is not None:
                    # margin=2: a speculative window absorbs several
                    # windows' inflow between decision points, so gear
                    # selection keeps double headroom
                    # (core/gearbox.target_level)
                    self._gear_tick(self._pool_occupancy(), margin=2)
                ws = min_next
                we = min(ws + factor * cons, stop)
                base = self.state  # rollback snapshot (done_t reset)
                rb0 = rollbacks
                # pressure-ladder rungs that reshape the pool (gear
                # downshift) are forbidden while `base` pins the compiled
                # shapes; non-reshaping rungs (spill-fill escalation) stay
                # available to the supervisor's RESOURCE_EXHAUSTED retries
                self._pressure_reshape_ok = False
                # adopt the issued-ahead first attempt iff base + window
                # bounds are exactly the serial loop's (recompute rule)
                first = (
                    pipe.take(base, (ws, we)) if pipe is not None else None
                )
                with metrics_mod.span(obs, "window", factor=factor):
                    while True:  # attempt [ws, we); shrink on violation
                        if first is not None:
                            with metrics_mod.span(obs, "await"):
                                st, mn, viol = self._sv_await(first)
                            first = None
                        else:
                            with metrics_mod.span(obs, "dispatch"):
                                p = self._sv_issue(
                                    "attempt",
                                    *self._attempt_halves(base, ws, we),
                                )
                                st, mn, viol = self._sv_await(p)
                        self._gear_note_dispatch()
                        if we <= ws + cons and viol < int(simtime.NEVER):
                            # A conservative-width window is violation-free
                            # BY CONSTRUCTION (emission time >= ws +
                            # runahead >= any processed time). A violation
                            # here means the conservative-width invariant
                            # itself is broken — committing would silently
                            # accept a causally-violated window (ADVICE
                            # round-5 finding).
                            raise RuntimeError(
                                f"speculation violation at t={viol} inside "
                                f"a conservative-width window [{ws}, {we}): "
                                f"the conservative-width invariant is "
                                f"broken — runahead {cons} ns exceeds a "
                                f"real path latency "
                                f"({self._runahead_bound_hint()}), or a "
                                f"handler emitted into the past; refusing "
                                f"to commit"
                            )
                        if viol >= int(simtime.NEVER) or we <= ws + cons:
                            break
                        rollbacks += 1
                        if obs is not None and obs.tracer:
                            obs.tracer.instant("rollback", viol_ns=viol)
                        we = max(viol, ws + cons)
                # driver-plane telemetry bumps ride the state replace the
                # loop does anyway (handoff boundary — no sync added);
                # each rollback shrank the window once
                self._pressure_reshape_ok = True
                st = obs_mod.bump_win(
                    st, obs_mod.WIN_ROLLBACKS, rollbacks - rb0
                )
                st = obs_mod.bump_win(
                    st, obs_mod.WIN_SHRINKS, rollbacks - rb0
                )
                self.state = st.replace(host=st.host.replace(done_t=neg1))
                min_next = int(mn)
                windows += 1
                if adaptive:
                    # pure host arithmetic — computed at commit (before
                    # the speculative issue needs the next factor); the
                    # schedule is identical to the serial loop's
                    factor, streak = self.adapt_window_factor(
                        factor, streak, rollbacks > rb0, window_factor
                    )
                # two-slot pipeline: issue window N+1's first attempt
                # from the committed state before draining this handoff
                if pipe is not None and min_next < stop:
                    if (self._handoff_quiet(min_next)
                            and not self._sv_disrupted()):
                        ws2 = min_next
                        we2 = min(ws2 + factor * cons, stop)
                        with metrics_mod.span(obs, "issue"):
                            pipe.put(
                                self._sv_issue(
                                    "attempt",
                                    *self._attempt_halves(
                                        self.state, ws2, we2
                                    ),
                                ),
                                self.state, (ws2, we2),
                            )
                    else:
                        pipe.forced_drain()
                with metrics_mod.span(obs, "host_drain"):
                    if self.pressure is not None:
                        self.pressure.note_progress()
                    if obs is not None:
                        obs.round_done(self, min_next)
                    self._audit_tick(min_next)
                    if self._fault_plane_active():
                        self._handoff_tick(min_next)
                        min_next = int(jnp.min(self.state.pool.time))
                    self._run_handoff_hooks(min_next)
                if pipe is not None:
                    if self._sv_disrupted():
                        pipe.discard()
                    else:
                        pipe.invalidate(self.state)
        finally:
            if pipe is not None:
                pipe.close()
        return windows, rollbacks

    def _attempt_halves(self, base, ws: int, we: int):
        """(issue_fn, fetch_fn) halves of one optimistic attempt from
        the rollback snapshot `base` (captured explicitly — a supervised
        retry must re-speculate the same window from the same
        snapshot)."""

        def issue(base=base, ws=ws, we=we):
            return self._attempt(base, self.params, ws, we)

        def fetch(out):
            st, mn, viol = out
            return st, int(mn), int(viol)

        return issue, fetch

    def _runahead_bound_hint(self) -> str:
        """The actually-safe runahead bound for conservative-width
        violation errors: the minimum finite baked path latency. The
        islands engine overrides this with the partition-derived
        cross-shard lookahead (parallel/lookahead.py), naming the
        critical shard link."""
        lat = np.asarray(jax.device_get(self.params.latency_vv))
        finite = lat[lat < int(simtime.NEVER)]
        if finite.size == 0:
            return "the topology bakes no finite path latency"
        return (
            f"minimum baked topology path latency is {int(finite.min())} "
            f"ns; set experimental.runahead <= {int(finite.min())} ns"
        )

    # -- host-spill tier (core/spill.py): the pool never silently drops --
    def _spill_marks(self) -> tuple[int, int]:
        """(pressure mark, rebalance fill mark) in pool rows per shard —
        PER-GEAR: the active gear's capacity defines the red zone.
        Pressure must fire while the merge can still absorb one window's
        inflow; the fill mark sits below pressure so a rebalance —
        including a partially-resident giant host's admission — exits the
        red zone and the fused loop keeps running windows. The pressure
        plane (core/pressure.py) scales both marks: injected saturation
        shrinks them, and memory-ladder escalation halves the fill per
        notch — identity until a pressure event actually engaged."""
        spec = self._gear_ladder[self._gear]
        hi, fill = spec.hi, spec.fill
        cap = getattr(self, "_pressure_fill_cap", None)
        if cap is not None:
            # transient override during a forced downshift: park down to
            # the TARGET gear's fill before the pool re-sorts smaller
            fill = min(fill, cap)
        if self.pressure is not None:
            hi, fill = self.pressure.scaled_marks(hi, fill)
        return hi, fill

    def _spill_store(self):
        if getattr(self, "_spill", None) is None:
            from shadow_tpu.core import spill as spill_mod2

            t = self.state.pool.time
            S = t.shape[0] if t.ndim == 2 else 1
            self._spill = spill_mod2.HostSpill(
                S, self.state.pool.payload.shape[-1]
            )
        return self._spill

    def spill_stats(self) -> dict:
        return self._spill_store().stats()

    # -- fused run: windows execute in on-device while_loop chunks --

    def _run_to_halves(self, stop_at: int, wpd: int):
        """(issue_fn, fetch_fn) halves of one fused-loop dispatch. issue
        enqueues the run_to program (jax async dispatch — futures); fetch
        performs the blocking host reads. The supervisor re-runs BOTH for
        a retry: issue re-reads the bound kernels and re-clamps the spill
        stop per attempt, so recovery rebinds and mid-dispatch pressure
        rungs behave exactly as under the fused thunk."""

        def issue(stop_at=stop_at, wpd=wpd):
            # per-attempt clamp: a pressure rung may have engaged the
            # spill tier since the driver computed stop_at
            stop_at, wpd = self._live_spill_clamp(stop_at, wpd)
            return self._run_to(self.state, self.params, stop_at, wpd)

        def fetch(out):
            st, mn, press, occ = out
            # blocking fetches INSIDE the supervised await: async-
            # dispatch errors must surface here, not at a later
            # unsupervised sync
            return st, int(mn), bool(press), int(occ)

        return issue, fetch

    def run(
        self, until: int | None = None, windows_per_dispatch: int = 64
    ) -> None:
        stop = self.stop_time if until is None else min(until, self.stop_time)
        spill = self._spill_store()
        obs = self.obs_session
        pipe = self._pipeline()
        last = None
        try:
            while True:
                active = (
                    (last is not None and last[2]) or spill.count
                    or self._force_spill  # injected force_spill fault
                )
                if active:
                    if pipe is not None:
                        # spill manage mutates the pool: a barrier point
                        # (the boundary was already tallied as a forced
                        # drain when speculation was skipped)
                        pipe.close()
                    with metrics_mod.span(obs, "spill"):
                        stop_at = spill_mod.manage(self, spill, stop)
                else:
                    stop_at = stop
                # whole-host spill residency is only exact with a manage
                # pass between consecutive windows (core/spill.py manage)
                wpd = 1 if spill.count else windows_per_dispatch
                if self._fault_plane_active():
                    # hand off at the next injection/checkpoint mark
                    stop_at = min(stop_at, self._fault_mark())
                # adopt the issued-ahead dispatch iff the committed state
                # and recomputed args are exactly what the serial loop
                # would pass now (core/pipeline.py recompute rule)
                pending = (
                    pipe.take(self.state, (stop_at, wpd))
                    if pipe is not None else None
                )
                if pending is None:
                    with metrics_mod.span(obs, "dispatch", windows=wpd):
                        p = self._sv_issue(
                            "run_to", *self._run_to_halves(stop_at, wpd)
                        )
                        self.state, mn, press, occ = self._sv_await(p)
                else:
                    with metrics_mod.span(obs, "await", windows=wpd):
                        self.state, mn, press, occ = self._sv_await(pending)
                # two-slot pipeline: issue dispatch N+1 asynchronously
                # BEFORE draining dispatch N's handoff — the device
                # computes while the host drains; state-mutating ticks
                # stay barrier points (forced_drain), and a drain that
                # mutates anyway discards the issue (recompute, never
                # reuse — the invalidate below)
                if pipe is not None and mn < stop:
                    if (not press and not spill.count
                            and not self._force_spill
                            and self._handoff_quiet(mn)
                            and not self._sv_disrupted()):
                        nxt = stop
                        if self._fault_plane_active():
                            nxt = min(nxt, self._fault_mark())
                        with metrics_mod.span(
                            obs, "issue", windows=windows_per_dispatch
                        ):
                            pipe.put(
                                self._sv_issue(
                                    "run_to",
                                    *self._run_to_halves(
                                        nxt, windows_per_dispatch
                                    ),
                                ),
                                self.state,
                                (nxt, windows_per_dispatch),
                            )
                    else:
                        pipe.forced_drain()
                with metrics_mod.span(obs, "host_drain"):
                    self._gear_note_dispatch()
                    if obs is not None:
                        obs.round_done(self, mn)
                    self._audit_tick(mn)
                    # gearing: a red-zone early exit upshifts (one pool
                    # re-sort) before the spill tier would pay host drain
                    # round-trips
                    shifted = self._gear_tick(occ, press=press)
                    if self._fault_plane_active():
                        self._handoff_tick(mn)
                    self._run_handoff_hooks(mn)
                if pipe is not None:
                    if self._sv_disrupted():
                        pipe.discard()
                    else:
                        pipe.invalidate(self.state)
                if mn >= stop and spill.min_time >= stop and not press:
                    break
                if self.elastic is not None:
                    # elastic re-expansion probe (parallel/elastic.py):
                    # may raise MeshReexpand at this committed boundary —
                    # the runner drains and relayouts onto the recovered
                    # mesh
                    self.elastic.on_dispatch(self, mn)
                cur = (mn, spill.count, press)
                if cur == last and mn >= stop_at and not shifted:
                    cap = self._gear_ladder[self._gear].capacity
                    if self._pressure_stall(window=mn, occupancy=occ,
                                            capacity=cap):
                        last = None  # a ladder rung reshaped the tier
                        continue
                    raise self._pool_exhausted(
                        "spill tier cannot make progress: either a single "
                        "timestamp holds more events than the pool fill "
                        "mark, or pool occupancy leaves too little "
                        "headroom for even one window's emissions (the "
                        "pool-headroom gate stalled every host); raise "
                        "experimental.event_capacity",
                        window=mn, occupancy=occ, capacity=cap,
                    )
                elif self.pressure is not None:
                    self.pressure.note_progress()
                last = cur
        finally:
            if pipe is not None:
                pipe.close()

    # -- fault-tolerance plane (shadow_tpu/faults) + auto-checkpointing --

    def attach_faults(self, faults) -> None:
        """Arm a parsed fault plan (list of faults.plan.Fault). Device and
        file ops execute at handoff boundaries; proc ops are not valid on
        the device plane (the builder/CLI routes those to ProcessDriver).
        Backend ops (kill_backend / stall_backend) drive the supervision
        state machine — a default supervisor (policy `abort`) is attached
        when the plan carries them and none is armed yet."""
        from shadow_tpu.faults import FaultInjector
        from shadow_tpu.faults import plan as plan_mod

        self.fault_injector = FaultInjector(faults) if faults else None
        if faults and self.supervisor is None and any(
            f.op in plan_mod.BACKEND_OPS for f in faults
        ):
            from shadow_tpu.core.supervisor import BackendSupervisor

            self.attach_supervisor(BackendSupervisor())

    # -- backend supervision (core/supervisor.py) --

    def attach_supervisor(self, supervisor) -> None:
        """Arm backend supervision: every subsequent driver dispatch goes
        through supervisor.call — deadline watchdog, classified retries,
        and drain-to-checkpoint + wait/cpu/abort recovery on loss."""
        supervisor.bind(self)
        self.supervisor = supervisor

    def _sv(self, label: str, thunk):
        """Run one dispatch thunk, supervised when a supervisor is
        attached (a direct call otherwise — the zero-overhead default)."""
        if self.supervisor is None:
            return thunk()
        return self.supervisor.call(label, thunk)

    def _sv_issue(self, label: str, issue_fn, fetch_fn):
        """The ISSUE half of a split dispatch: enqueue the device work
        (jax async dispatch — futures, no blocking) and return the
        ticket. Supervised when a supervisor is attached; a direct
        launch otherwise."""
        if self.supervisor is None:
            return PendingDispatch.direct(label, issue_fn, fetch_fn)
        return self.supervisor.issue(label, issue_fn, fetch_fn)

    def _sv_await(self, pending):
        """The AWAIT half: block on the ticket's host fetches. With a
        supervisor attached this runs the classified retry ladder,
        pressure rungs, watchdog and loss policies — all operating on
        the awaited half, so pipelining never re-serializes them."""
        if self.supervisor is None:
            return pending.await_direct()
        return self.supervisor.await_result(pending)

    def _sv_disrupted(self) -> bool:
        """True when the supervisor already knows the next dispatch will
        not run clean (injected kill/stall/exhaust, failover) — the
        pipelined drivers drain instead of issuing ahead so injected
        faults keep their serial-schedule ordering."""
        sup = self.supervisor
        return sup is not None and sup.pending_disruption

    # -- pipelined CPU↔TPU handoff (core/pipeline.py) --

    def _pipeline(self):
        """The two-slot pipeline for one driver-loop invocation, or None
        when the serial arm is configured. Stats accumulate across loops
        on the same sim (the dict is shared)."""
        if not self.pipelined_dispatch:
            return None
        if self._pipeline_stats is None:
            self._pipeline_stats = pipeline_mod.new_stats()
        return pipeline_mod.TwoSlotPipeline(self._pipeline_stats)

    def pipeline_stats(self) -> dict:
        """Pipelined-handoff telemetry for the metrics `pipeline.*`
        namespace (schema v14); {} until a pipelined driver loop ran
        (serial runs emit no pipeline keys)."""
        st = self._pipeline_stats
        return dict(st) if st is not None else {}

    def add_handoff_hook(self, fn, sharded: bool = False) -> None:
        """Register per-handoff host work, called inside every driver's
        host-drain phase (after the fault/checkpoint tick) — the
        managed-plane syscall-drain analog — which the pipelined loop
        overlaps with the in-flight dispatch. Hooks must not assume the
        next dispatch has not been issued; state mutations they make are
        detected and discard any in-flight speculation (the recompute
        rule).

        sharded=False: fn(sim, frontier_ns), one whole-sim call, always
        on the coordinator. sharded=True: fn(sim, frontier_ns, gid), one
        call per live host, partitioned by owning host across the
        multi-worker host plane (core/hostplane.py) — the call must only
        touch that host's partition-local state. With host_workers == 1
        sharded hooks run inline in the same canonical (frontier, gid)
        order the parallel merge uses, so both paths are bit-exact."""
        self._handoff_hooks.append((fn, bool(sharded)))

    # -- PARSIR-style multi-worker host plane (core/hostplane.py) --

    def _hostplane(self):
        """The drain-worker pool, or None on the serial path (host_workers
        == 1). Stats are created lazily so serial runs emit no
        hostplane.* keys."""
        if self.host_workers <= 1:
            return None
        if self._hostplane_obj is None:
            if self._hostplane_stats is None:
                self._hostplane_stats = hostplane_mod.new_stats(
                    self.host_workers
                )
            self._hostplane_obj = hostplane_mod.HostPlane(
                self.host_workers, self._hostplane_stats
            )
        return self._hostplane_obj

    def hostplane_stats(self) -> dict:
        """Host-plane telemetry for the metrics `hostplane.*` namespace
        (schema v15); {} until a multi-worker drain ran (host_workers ==
        1 emits no hostplane keys)."""
        st = self._hostplane_stats
        return dict(st) if st is not None else {}

    def _hostplane_slot_map(self):
        """The placement seam's host->slot table for worker pinning, read
        once per layout epoch (islands bump `rebalances` on every
        migration/relayout, which invalidates the cache — so a moved host
        re-pins deterministically). None = identity pinning."""
        slot = getattr(self.params, "slot_of", None)
        if slot is None:
            return None
        epoch = int(getattr(self, "rebalances", 0))
        cached = self._hostplane_slot_cache
        if cached is not None and cached[0] == epoch:
            return cached[1]
        m = np.asarray(jax.device_get(slot)).reshape(-1)
        self._hostplane_slot_cache = (epoch, m)
        return m

    def _run_handoff_hooks(self, mn: int) -> None:
        if not self._handoff_hooks:
            return
        sharded = [fn for fn, sh in self._handoff_hooks if sh]
        if sharded:
            hp = self._hostplane()
            if hp is None:
                # serial path: inline, in the same canonical (frontier,
                # gid, registration) order the parallel merge produces
                for gid in range(self.num_hosts):
                    for fn in sharded:
                        fn(self, mn, gid)
            else:
                hp.set_slot_map(self._hostplane_slot_map())
                obs = self.obs_session
                hp.drain(
                    [
                        hostplane_mod.HostAction(
                            mn, gid, (lambda f=fn, g=gid: f(self, mn, g))
                        )
                        for gid in range(self.num_hosts)
                        for fn in sharded
                    ],
                    tracer=obs.tracer if obs is not None else None,
                )
        for fn, sh in self._handoff_hooks:
            if not sh:
                fn(self, mn)

    def _handoff_quiet(self, mn: int) -> bool:
        """True when the upcoming handoff tick at committed frontier
        `mn` cannot mutate state: no due injection or checkpoint mark at
        or below the frontier, no quarantined-host recurring drain, no
        forced/sustained spill episode. The pipelined drivers only issue
        ahead across QUIET boundaries — state-mutating ticks are barrier
        points (docs/architecture.md §Pipelined handoff)."""
        if self._dead_hosts or self._force_spill:
            return False
        pc = self.pressure
        if (pc is not None and pc.saturate_frac is not None
                and pc.saturate_frac < 1.0):
            return False
        if self._fault_plane_active() and self._fault_mark() <= mn:
            return False
        return True

    def _rebind_kernels(self) -> None:
        """Drop every compiled kernel and rebind the active gear — the
        hot-resume step after a backend returns (stale executables point
        at the dead client) and the re-lowering step entering/leaving CPU
        failover. The optimistic attempt kernel is re-ensured when a
        lazily-compiling engine (islands) had one bound."""
        had_attempt = getattr(self, "_attempt", None) is not None
        self._gear_fns = {}
        self._bind_gear()
        ensure = getattr(self, "_ensure_optimistic", None)
        if had_attempt and self._attempt is None and ensure is not None:
            ensure()

    def _enter_cpu_failover(self) -> None:
        """Degraded-mode failover: move state/params to the CPU backend
        and re-lower the window kernels there. The simulation keeps
        advancing (slower); results are bit-identical — the kernels are
        pure integer programs, and the audit chain proves it."""
        if getattr(self, "mode", None) == "shard_map":
            raise RuntimeError(
                "CPU failover is not available under shard_map islands "
                "(the mesh IS the lost device set); use --on-backend-loss "
                "wait or abort"
            )
        try:
            dev = jax.devices("cpu")[0]
        except RuntimeError as e:
            raise RuntimeError(f"no CPU backend to fail over to: {e}") from e
        self.state = jax.device_put(jax.device_get(self.state), dev)
        self.params = jax.device_put(jax.device_get(self.params), dev)
        self._cpu_failover = True
        self._rebind_kernels()

    def _exit_cpu_failover(self) -> None:
        """Upshift back to the recovered primary backend: move state home
        and rebind the primary kernels."""
        self._cpu_failover = False
        self.state = jax.device_put(jax.device_get(self.state))
        self.params = jax.device_put(jax.device_get(self.params))
        self._rebind_kernels()

    def _drain_to_checkpoint(self, reason: str,
                             ckpt_dir: str | None = None) -> str | None:
        """Flush the committed frontier to a crash-consistent ring
        checkpoint with drain-reason metadata (the supervisor's first act
        on backend loss). `self.state` at a dispatch boundary is the last
        committed pytree — the failed dispatch never assigned — so the
        drain is exactly the crash-consistent checkpoint path, audit
        chain included. Returns the path, or None when no checkpoint
        directory is configured (in-memory recovery still proceeds)."""
        from shadow_tpu.core import checkpoint as ckpt_mod

        d = ckpt_dir or self.checkpoint_dir
        if not d:
            return None
        mn = int(np.min(np.asarray(jax.device_get(self.state.pool.time))))
        t = max(0, min(mn, self.stop_time))
        sup = self.supervisor
        # drains live in their own `drain-*` ring namespace: a burst of
        # backend/chip losses rotates drains against drains only, never
        # the periodic ring (core/checkpoint.save_ring prefix rule)
        path, pruned = ckpt_mod.save_ring(
            self, d, self._ckpt_seq, t, self.checkpoint_retain,
            extra_meta={"drain": {
                "reason": reason,
                "policy": sup.policy if sup is not None else "abort",
                "frontier_ns": t,
            }},
            prefix="drain",
        )
        self._ckpt_seq += 1
        self.fault_counters["checkpoints_written"] += 1
        self.fault_counters["checkpoints_pruned"] += pruned
        obs = self.obs_session
        if obs is not None and obs.tracer:
            obs.tracer.fault("drain_checkpoint", sim_ns=t, reason=reason)
        return path

    def resilience_stats(self) -> dict:
        """Supervisor telemetry for the metrics `resilience.*` namespace
        (schema v6); {} when no supervisor is attached."""
        sup = self.supervisor
        return sup.stats() if sup is not None else {}

    # -- resource-pressure plane (core/pressure.py) --

    def attach_pressure(self, controller) -> None:
        """Arm a custom pressure controller/policy; the drivers attach
        the default ladder lazily on the first pressure signal."""
        self.pressure = controller

    def _pressure(self):
        if self.pressure is None:
            self.pressure = pressure_mod.PressureController()
        return self.pressure

    def _pressure_ladder_step(self, label: str) -> bool:
        """One memory-ladder rung for a classified RESOURCE_EXHAUSTED
        dispatch failure (called by the supervisor between attempts)."""
        return self._pressure().on_backend_exhausted(self, label)

    def _pressure_stall(self, *, window=None, occupancy=None,
                        capacity=None) -> bool:
        """One pool-ladder consultation at a driver stall; True = retry
        the driver loop (a rung reshaped something)."""
        return self._pressure().on_pool_exhausted(
            self, window=window, occupancy=occupancy, capacity=capacity
        )

    def _pool_exhausted(self, message: str, window=None,
                        occupancy=None, capacity=None):
        """Terminal pool exhaustion: drain the committed frontier to the
        checkpoint ring (when one is configured — the run is resumable at
        a reshaped config, docs/fault_tolerance.md §5) and build the
        typed error every driver raises instead of a bare RuntimeError."""
        path = self._drain_to_checkpoint("pool_exhausted")
        if path:
            message += f" (drained to {path}; resume with --resume)"
        return pressure_mod.PoolExhausted(
            message, window=window, occupancy=occupancy, capacity=capacity
        )

    def _pressure_relieve_pool(self, step: int):
        """The pool-exhaustion rungs, in ladder order. Returns the action
        name or None when exhausted (core/pressure.py counts them)."""
        pc = self._pressure()
        pol = pc.policy
        # rung 1: forced upshift — more usable pool, unless a memory hold
        # pins the gear down or no bigger gear exists
        if (self._shifter is not None and not pc.hold_gear
                and self._gear < self._gear_ladder[-1].level):
            self._shift_gear(self._gear + 1)
            return "upshift"
        # rung 2 (saturation yield) lives in the controller
        # rung 3: force one spill episode — the stall may predate any
        # red-zone crossing (occupancy under the mark can still leave too
        # little merge headroom for a whole window's inflow)
        if pol.allow_spill_escalation and not self._force_spill \
                and step < 1 + pol.max_fill_shrink:
            self._force_spill = True
            return "spill_escalation"
        return None

    def _pressure_relieve_memory(self, step: int):
        """The memory-exhaustion rungs, in ladder order: forced gear
        downshift (red-zone rule overridden), then spill-fill escalation.
        The fleet adds lane eviction; the supervisor's drain + policy is
        the rung after None."""
        pc = self._pressure()
        pol = pc.policy
        if (pol.allow_downshift and self._pressure_reshape_ok
                and len(self._gear_ladder) > 1
                and self._gear > self._gear_ladder[0].level
                and self._pressure_downshift()):
            pc.hold_gear = True
            return "downshift"
        if pol.allow_spill_escalation and pc.fill_shrink < pol.max_fill_shrink:
            pc.fill_shrink += 1
            self._force_spill = True
            return "spill_escalation"
        return None

    def _pressure_downshift(self) -> bool:
        """Forced downshift one gear under memory pressure: park rows
        beyond the TARGET gear's fill mark on the host spill tier (one
        manage pass — foreign-row re-routing and whole-host ordering
        included), then re-sort the pool into the smaller capacity. The
        resize drops nothing (occupancy <= fill < capacity after the
        park), so results stay bit-identical — the spill tier's
        guarantee."""
        target = self._gear - 1
        spec = self._gear_ladder[target]
        spill = self._spill_store()
        self._force_spill = True
        self._pressure_fill_cap = max(1, min(spec.fill, spec.hi))
        try:
            spill_mod.manage(self, spill, self.stop_time)
        finally:
            self._pressure_fill_cap = None
        self._shift_gear(target)
        return True

    def pressure_stats(self) -> dict:
        """Pressure-plane telemetry for the metrics `pressure.*`
        namespace (schema v8); {} until a pressure signal engaged."""
        pc = self.pressure
        return pc.stats() if pc is not None else {}

    def configure_auto_checkpoint(
        self, ckpt_dir: str, every_ns: int, retain: int = 3
    ) -> None:
        """Arm crash-consistent ring checkpoints every `every_ns` of sim
        time, written at handoff boundaries (core/checkpoint.save_ring).
        Safe to call after resume: ring numbering continues past existing
        entries and the next boundary is derived from the restored clock."""
        from shadow_tpu.core import checkpoint as ckpt_mod

        self.checkpoint_dir = str(ckpt_dir)
        self.checkpoint_every_ns = int(every_ns)
        self.checkpoint_retain = max(1, int(retain))
        now = int(np.max(np.asarray(jax.device_get(self.state.now))))
        if self.checkpoint_every_ns > 0:
            self._ckpt_next_t = (
                (now // self.checkpoint_every_ns) + 1
            ) * self.checkpoint_every_ns
        entries = ckpt_mod.ring_entries(self.checkpoint_dir)
        self._ckpt_seq = entries[-1][0] + 1 if entries else 0

    def resume_from(self, ckpt_dir: str) -> dict:
        """Restore the newest checkpoint in `ckpt_dir` that passes
        integrity validation, falling back past corrupt entries."""
        from shadow_tpu.core import checkpoint as ckpt_mod

        info = ckpt_mod.resume_latest(self, ckpt_dir)
        self.fault_counters["resume_fallbacks"] += info["fallbacks"]
        # Backend injections at or before the restored frontier already
        # happened — the outage was the very reason this run is resuming.
        # Marking them fired stops a re-attached plan from re-draining the
        # resumed run the moment it dispatches. skew_hosts joins them:
        # its effect (the replicated pool rows) is IN the restored state,
        # so re-firing would double-inject and diverge from the
        # uninterrupted chain. kill_host deliberately stays re-fireable —
        # quarantine is idempotent, and re-firing rebuilds the dead-host
        # set (runtime state no checkpoint carries).
        inj = self.fault_injector
        if inj is not None:
            from shadow_tpu.faults import plan as plan_mod

            replayed = plan_mod.BACKEND_OPS | {"skew_hosts"}
            for f in inj.faults:
                if (not f.fired and f.op in replayed
                        and f.at_ns <= info["sim_ns"]):
                    inj.mark_fired(f)
        return info

    def _resolve_host_id(self, host) -> int:
        if isinstance(host, (int, np.integer)):
            hid = int(host)
        else:
            cfg = getattr(self, "config", None)
            names = [h.name for h in cfg.hosts] if cfg is not None else []
            if host not in names:
                raise ValueError(
                    f"kill_host: unknown host {host!r} (named lookup needs "
                    f"a config-built sim; known: {names[:8]})"
                )
            hid = names.index(host)
        if not 0 <= hid < self.num_hosts:
            raise ValueError(
                f"kill_host: host id {hid} out of range [0, {self.num_hosts})"
            )
        return hid

    def quarantine_host(self, host) -> int:
        """Mark a simulated host dead (crashed-host semantic): its pending
        device-plane events are drained now and at every subsequent
        handoff — exchange-deferred rows that arrive later are caught by
        the recurring drain, which is what makes quarantine compose with
        the islands shard exchange. Events it already emitted remain in
        flight (a crashed host's packets still arrive). Idempotent;
        returns rows drained by this call."""
        hid = self._resolve_host_id(host)
        if hid in self._dead_hosts:
            return 0
        self._dead_hosts.add(hid)
        self.fault_counters["hosts_quarantined"] += 1
        obs = self.obs_session
        if obs is not None and obs.tracer:
            obs.tracer.fault("quarantine_host", host=hid)
        return self._drain_dead()

    def _drain_dead(self) -> int:
        """Cancel pool + spill rows destined to quarantined hosts. Runs at
        handoff boundaries only (the pool is about to be re-sorted by the
        next window's merge; a freed NEVER row is just a free slot)."""
        pool = self.state.pool
        dead = jnp.asarray(sorted(self._dead_hosts), pool.dst.dtype)
        mask = jnp.isin(pool.dst, dead) & (pool.time != NEVER)
        n = int(jnp.sum(mask))
        if n:
            self.state = self.state.replace(
                pool=pool.replace(time=jnp.where(mask, NEVER, pool.time))
            )
        sp = getattr(self, "_spill", None)
        if sp is not None:
            n += sp.drain_hosts(self._dead_hosts)
        if n:
            self.fault_counters["events_drained"] += n
            self.state = obs_mod.bump_win(self.state, obs_mod.WIN_FAULTS)
        return n

    def skew_hosts(self, hosts, factor: int) -> int:
        """Deterministic traffic-skew injection (the ``skew_hosts`` fault
        op): multiply the selected hosts' event rates by `factor` from
        this handoff boundary on, by replicating each host's pending pool
        rows `factor - 1` times (copies one nanosecond apart — a strict
        total order on every engine layout; faults/injector.skew_pool_np).
        Runs at handoff boundaries only, where the dispatch clamp
        (_fault_mark) has pinned every frontier — including the async
        islands per-shard frontiers — at or below the injection time, so
        a copy (which inherits a pending event's time, at or after its
        owner shard's frontier) can never violate causality. Copies that
        do not fit the pool park on the spill tier (late, never lost).
        Quarantined hosts are skipped. Returns rows injected."""
        from shadow_tpu.faults import injector as inj_mod

        if factor < 2:
            return 0
        ids = [self._resolve_host_id(h) for h in hosts]
        pool = self.state.pool
        cols = [
            np.array(jax.device_get(c)) for c in (
                pool.time, pool.dst, pool.src, pool.seq, pool.kind,
                pool.payload,
            )
        ]
        flat = cols[0].ndim == 1  # global [C] layout vs islands [S, C]
        if flat:
            cols = [c[None] for c in cols]
        (t, d, s, q, k, p), made, overflow = inj_mod.skew_pool_np(
            cols, ids, factor, dead=self._dead_hosts
        )
        parked = 0
        if overflow:
            sp = self._spill_store()
            for r, rows in sorted(overflow.items()):
                parked += sp.park(r, rows)
            self._force_spill = True  # manage() re-admits parked rows
        if flat:
            t, d, s, q, k, p = (c[0] for c in (t, d, s, q, k, p))
        self.state = self.state.replace(pool=pool.replace(
            time=jnp.asarray(t), dst=jnp.asarray(d), src=jnp.asarray(s),
            seq=jnp.asarray(q), kind=jnp.asarray(k),
            payload=jnp.asarray(p),
        ))
        self.fault_counters["events_skewed"] = (
            self.fault_counters.get("events_skewed", 0) + made + parked
        )
        self.state = obs_mod.bump_win(self.state, obs_mod.WIN_FAULTS)
        obs = self.obs_session
        if obs is not None and obs.tracer:
            obs.tracer.fault(
                "skew_hosts", hosts=len(ids), factor=int(factor),
                injected=made + parked,
            )
        return made + parked

    def _skew_fault_ids(self, f) -> list:
        """Resolve a skew_hosts fault's host selection (id/name list or
        [first, count] span) against this sim's host table."""
        if f.span is not None:
            first, count = f.span
            if first >= self.num_hosts:
                raise ValueError(
                    f"skew_hosts: span start {first} out of range "
                    f"[0, {self.num_hosts})"
                )
            return list(range(first, min(first + count, self.num_hosts)))
        return list(f.hosts or [])

    def _handoff_tick(self, mn: int) -> None:
        """The fault-plane + auto-checkpoint hook every driver calls at
        its handoff boundary (state synced, `mn` = committed frontier):
        fire due device/file injections, drain quarantined hosts' events,
        and write a ring checkpoint when the frontier crosses the next
        checkpoint mark. Zero work — four attribute checks — when neither
        faults nor checkpointing are configured."""
        inj = self.fault_injector
        obs = self.obs_session
        drained_this_tick = False
        if inj is not None and inj.pending:
            from shadow_tpu.faults import injector as inj_mod
            from shadow_tpu.faults import plan as plan_mod

            for f in inj.due(mn, plan_mod.DEVICE_OPS | plan_mod.FILE_OPS):
                if f.op == "kill_host":
                    self.quarantine_host(f.host)
                    drained_this_tick = True
                elif f.op == "skew_hosts":
                    self.skew_hosts(self._skew_fault_ids(f), f.factor)
                elif f.op == "force_spill":
                    self._force_spill = True
                    self.state = obs_mod.bump_win(
                        self.state, obs_mod.WIN_FAULTS
                    )
                elif f.op == "saturate_pool":
                    # injected pool saturation (core/pressure.py): scale
                    # the spill marks by frac from this frontier on; the
                    # sustained re-force below keeps the episodes coming
                    self._pressure().saturate(f.frac)
                    self._force_spill = True
                    self.state = obs_mod.bump_win(
                        self.state, obs_mod.WIN_FAULTS
                    )
                elif f.op == "corrupt_file":
                    touched = inj_mod.corrupt_file(
                        f, default_dir=self.checkpoint_dir
                    )
                    self.fault_counters["files_corrupted"] += len(touched)
                    self.state = obs_mod.bump_win(
                        self.state, obs_mod.WIN_FAULTS
                    )
                else:
                    # every DEVICE/FILE op must carry an explicit arm —
                    # the contract auditor (analysis/contracts.py SLC003)
                    # checks each registered op is named here, so a new
                    # plan op cannot silently fall through
                    raise RuntimeError(
                        f"fault op {f.op!r} has no device-plane handler"
                    )
                if obs is not None and obs.tracer:
                    obs.tracer.fault(
                        "fault_injection", op=f.op, at_ns=f.at_ns
                    )
            for f in inj.due(mn, plan_mod.BACKEND_OPS):
                # backend ops drive the supervisor's state machine; the
                # NEXT supervised dispatch sees the simulated loss/stall
                sup = self.supervisor
                if sup is None:
                    from shadow_tpu.core.supervisor import BackendSupervisor

                    sup = BackendSupervisor()
                    self.attach_supervisor(sup)
                if f.op == "kill_backend":
                    sup.inject_kill(f.recover_after)
                elif f.op == "kill_chip":
                    sup.inject_kill_chip(f.chip, f.recover_after)
                elif f.op == "exhaust_backend":
                    sup.inject_exhaust(f.recover_after)
                elif f.op == "stall_backend":
                    sup.inject_stall(f.count)
                else:
                    # explicit arms only (contracts.py SLC003, as above)
                    raise RuntimeError(
                        f"fault op {f.op!r} has no backend handler"
                    )
                if obs is not None and obs.tracer:
                    obs.tracer.fault(
                        "fault_injection", op=f.op, at_ns=f.at_ns
                    )
        if (self.pressure is not None
                and self.pressure.saturate_frac is not None
                and self.pressure.saturate_frac < 1.0):
            # sustained saturation: keep the spill tier engaged so the
            # scaled marks keep parking rows every handoff
            self._force_spill = True
        if self._dead_hosts and not drained_this_tick:
            # recurring drain: exchange-deferred / late-emitted rows for
            # dead hosts are cancelled before the next window runs
            self._drain_dead()
        if self.checkpoint_every_ns and mn >= self._ckpt_next_t:
            from shadow_tpu.core import checkpoint as ckpt_mod

            t = min(int(mn), self.stop_time)
            with metrics_mod.span(obs, "checkpoint"):
                path, pruned = ckpt_mod.save_ring(
                    self, self.checkpoint_dir, self._ckpt_seq, t,
                    self.checkpoint_retain,
                )
            self._ckpt_seq += 1
            self.fault_counters["checkpoints_written"] += 1
            self.fault_counters["checkpoints_pruned"] += pruned
            if obs is not None and obs.tracer:
                obs.tracer.fault("checkpoint", sim_ns=t)
            self._ckpt_next_t = (
                (t // self.checkpoint_every_ns) + 1
            ) * self.checkpoint_every_ns

    def _fault_plane_active(self) -> bool:
        """True when a handoff tick has work to do — the drivers skip the
        tick (and any re-sync it would force) entirely otherwise."""
        return (
            self.fault_injector is not None
            or bool(self._dead_hosts)
            or bool(self.checkpoint_every_ns)
        )

    def _fault_mark(self) -> int:
        """Earliest virtual time the fused drivers must create a handoff
        boundary at: the next unfired device/file injection or the next
        checkpoint mark. Multi-window dispatches clamp their stop time
        here — otherwise a 64-window dispatch would sail seconds past a
        scheduled injection and both the checkpoint cadence and the fault
        plan's timing would degrade to dispatch granularity."""
        mark = int(NEVER)
        inj = self.fault_injector
        if inj is not None:
            from shadow_tpu.faults import plan as plan_mod

            ops = (
                plan_mod.DEVICE_OPS | plan_mod.FILE_OPS
                | plan_mod.BACKEND_OPS
            )
            for f in inj.faults:
                if not f.fired and f.op in ops:
                    mark = min(mark, f.at_ns)
        if self.checkpoint_every_ns:
            mark = min(mark, self._ckpt_next_t)
        return mark

    def fault_stats(self) -> dict:
        """Fault-plane telemetry for metrics dumps (faults.* namespace,
        schema v3) and bench rows."""
        d = dict(self.fault_counters)
        if self.fault_injector is not None:
            d.update(self.fault_injector.stats())
        return d

    def counters(self) -> dict[str, int]:
        c = jax.device_get(self.state.counters)
        return {k: int(v) for k, v in sorted(c.__dict__.items())}

    def obs_snapshot(self) -> dict:
        """The device telemetry block (obs/counters.py), normalized across
        engine layouts; {} when built with obs_counters=False. Read at
        handoff boundaries only — it device_gets the block."""
        return obs_mod.snapshot(self.state)

    # -- determinism audit plane (obs/audit.py, obs/flight.py) --

    def attach_audit(self, meta: dict | None = None):
        """Arm per-handoff digest-chain recording (--digest-out). Needs
        the obs block (the chain lives in it)."""
        if self.state.obs is None:
            raise ValueError(
                "digest auditing needs the obs block "
                "(experimental.obs_counters: true)"
            )
        self.audit = audit_mod.AuditTrail(meta)
        return self.audit

    def attach_flight_spool(self, path: str):
        """Arm flight-ring spooling to `path` (--flight-out). Needs the
        ring compiled in (experimental.flight_recorder)."""
        if self.state.flight is None:
            raise ValueError(
                "flight spooling needs experimental.flight_recorder "
                "(the ring compiles into the kernel)"
            )
        self.flight_spool = flight_mod.FlightSpool(
            path, self.num_hosts, self.state.flight.capacity
        )
        return self.flight_spool

    def audit_chain(self) -> int:
        """The current global digest-chain value: one obs-block fetch plus
        the order-independent per-host combine. 0 when the block is off."""
        snap = self.obs_snapshot()
        if not snap or "host_digest" not in snap:
            return 0
        return audit_mod.combine(snap["host_digest"])

    def write_digest(self, path: str) -> dict:
        """Dump the digest document (--digest-out): chain records, final
        per-host sub-chains, final combined chain."""
        if self.audit is None:
            raise ValueError("no audit trail attached (attach_audit first)")
        return self.audit.dump(path, self.obs_snapshot())

    def _audit_active(self) -> bool:
        return self.audit is not None or self.flight_spool is not None

    def _audit_tick(self, mn: int) -> None:
        """Record the digest chain and flush the flight ring at a handoff
        boundary the driver already synced at. Zero work unless a trail
        or spool is attached."""
        if not self._audit_active():
            return
        frontier = min(int(mn), self.stop_time)
        if self.audit is not None:
            snap = self.obs_snapshot()
            if snap:
                self.audit.record(snap, frontier)
        if self.flight_spool is not None:
            # per-host ring extraction shards across the host plane's
            # pinned workers (core/hostplane.py); bytes identical either
            # way (canonical-order merge + the sort below)
            self.flight_spool.flush(self, frontier,
                                    plane=self._hostplane())

    def save_checkpoint(self, path: str) -> None:
        """Snapshot the full device state to disk (resume is bit-exact)."""
        from shadow_tpu.core import checkpoint

        checkpoint.save(self, path)

    def load_checkpoint(self, path: str) -> None:
        """Restore state saved by save_checkpoint; this Simulation must be
        built from the same config."""
        from shadow_tpu.core import checkpoint

        checkpoint.restore(self, path)

    def host_trackers(self) -> dict[str, "np.ndarray"]:
        """Per-host byte/packet counters from the device NIC state
        (tracker.c analog); empty if the sim has no network stack."""
        sub = self.state.subs.get("nic")
        if sub is None:
            return {}
        return {
            k: np.asarray(jax.device_get(getattr(sub, k)))
            for k in ("tx_packets", "tx_bytes", "rx_packets", "rx_bytes")
        }
