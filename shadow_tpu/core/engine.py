"""The batched PDES window kernel and simulation driver.

Reference execution model (src/main/core/manager.c:543-577,
scheduler/scheduler.c:77-94, controller.c:390-422): time advances in
conservative windows bounded by the minimum topology latency ("runahead");
within a window each worker pops its hosts' events in deterministic order
(time, dst, src, seq — event.c:109-152) and runs them; a barrier plus a
min-next-event-time reduction ends the round.

TPU-first re-architecture (one jitted pure function per window):

1. EXTRACT — one sort of the event pool by (dst, time, src, seq) builds a
   per-host ordered matrix [H, K] of this window's events. This replaces all
   per-host priority queues and their locks.
2. MICRO-STEP LOOP — a `lax.while_loop` whose body processes AT MOST ONE
   event per host, fully vectorized across all hosts: candidate = key-min of
   (matrix head, self-inbox); handlers apply masked SoA updates. Per-host
   event order is preserved exactly; hosts are data-parallel, which is the
   same parallelism the reference exploits with worker threads (P1 in
   SURVEY.md §2.5) — but over lanes instead of pthreads.
3. The conservative-window invariant (window length ≤ min path latency,
   controller.c:125-153) guarantees cross-host emissions land at or after
   window end, so only SELF-emissions (short timers, NIC refills) can need
   intra-window processing — they go to a small per-host inbox. Everything
   else accumulates in a per-host outbox (no scatter collisions).
4. MERGE — outbox + any spilled leftovers are merged into the pool with one
   sort by time, truncating to capacity (drops counted). The next window
   start is the min pool time — the reference's min-reduce barrier
   (worker.c:332-363) becomes a jnp.min.

The whole multi-window run can itself be a `lax.while_loop` on device
(`Simulation.run_compiled`), so a complete simulation is ONE XLA program.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from shadow_tpu.core import rng as rng_mod
from shadow_tpu.core import simtime
from shadow_tpu.core.state import (
    PAYLOAD_WORDS,
    Counters,
    EventPool,
    HostState,
    NetParams,
    SimState,
    make_host_state,
)

NEVER = simtime.NEVER


# ---------------------------------------------------------------------------
# Event view + emission interface for handlers
# ---------------------------------------------------------------------------


@struct.dataclass
class EventView:
    """The (at most one) event each host is processing this micro-step.

    All arrays are [H]-indexed; the destination host of event i IS host i.
    ``mask`` is set per handler: valid event AND kind match.
    """

    mask: jnp.ndarray  # [H] bool
    time: jnp.ndarray  # [H] i64
    src: jnp.ndarray  # [H] i32
    seq: jnp.ndarray  # [H] i32
    kind: jnp.ndarray  # [H] i32
    payload: jnp.ndarray  # [H, P] i32


class Emission(NamedTuple):
    mask: jnp.ndarray  # [H] bool — which hosts emit
    time: jnp.ndarray  # [H] i64
    dst: jnp.ndarray  # [H] i32
    kind: jnp.ndarray  # [H] i32 (may be per-host)
    payload: jnp.ndarray  # [H, P] i32


class Emitter:
    """Collects handler emissions; the engine routes them (inbox/outbox)
    in collection order, which fixes the per-source sequence numbering."""

    def __init__(self):
        self.records: list[Emission] = []

    def emit(self, mask, time, dst, kind, payload):
        kind = jnp.broadcast_to(jnp.asarray(kind, jnp.int32), mask.shape)
        self.records.append(
            Emission(mask, time.astype(jnp.int64), dst.astype(jnp.int32), kind, payload)
        )


# handler(state, ev, emitter, params) -> state
Handler = Callable[[SimState, EventView, Emitter, NetParams], SimState]


def draw_uniform(state: SimState, mask):
    """One deterministic uniform draw per masked host; bumps draw counters
    only where masked (so inactive hosts' streams don't advance — matching a
    per-host sequential RNG)."""
    u = rng_mod.uniform_per_host(state.rng_keys, state.host.rng_counter)
    new_c = jnp.where(mask, state.host.rng_counter + 1, state.host.rng_counter)
    state = state.replace(host=state.host.replace(rng_counter=new_c))
    return state, u


# ---------------------------------------------------------------------------
# Window data structures
# ---------------------------------------------------------------------------


@struct.dataclass
class _Matrix:
    time: jnp.ndarray  # [H, K] i64 (NEVER padded)
    src: jnp.ndarray  # [H, K] i32
    seq: jnp.ndarray  # [H, K] i32
    kind: jnp.ndarray  # [H, K] i32
    payload: jnp.ndarray  # [H, K, P] i32


@struct.dataclass
class _Inbox:
    time: jnp.ndarray  # [H, B] i64
    src: jnp.ndarray
    seq: jnp.ndarray
    kind: jnp.ndarray
    payload: jnp.ndarray  # [H, B, P]

    @classmethod
    def empty(cls, H, B):
        return cls(
            time=jnp.full((H, B), NEVER, dtype=jnp.int64),
            src=jnp.zeros((H, B), dtype=jnp.int32),
            seq=jnp.zeros((H, B), dtype=jnp.int32),
            kind=jnp.zeros((H, B), dtype=jnp.int32),
            payload=jnp.zeros((H, B, PAYLOAD_WORDS), dtype=jnp.int32),
        )


@struct.dataclass
class _Outbox:
    time: jnp.ndarray  # [H, O] i64
    dst: jnp.ndarray
    src: jnp.ndarray
    seq: jnp.ndarray
    kind: jnp.ndarray
    payload: jnp.ndarray  # [H, O, P]
    count: jnp.ndarray  # [H] i32

    @classmethod
    def empty(cls, H, O):
        return cls(
            time=jnp.full((H, O), NEVER, dtype=jnp.int64),
            dst=jnp.zeros((H, O), dtype=jnp.int32),
            src=jnp.zeros((H, O), dtype=jnp.int32),
            seq=jnp.zeros((H, O), dtype=jnp.int32),
            kind=jnp.zeros((H, O), dtype=jnp.int32),
            payload=jnp.zeros((H, O, PAYLOAD_WORDS), dtype=jnp.int32),
            count=jnp.zeros((H,), dtype=jnp.int32),
        )


def _extract_window(pool: EventPool, win_end, H: int, K: int):
    """One sort by (dst, time, src, seq) → per-host ordered [H, K] matrix.

    Events beyond K per host stay in the pool; their keys are strictly larger
    than every extracted event's, so deferring them to the next window keeps
    per-host order. Also returns defer_time[H]: the earliest LEFTOVER event
    time per host (NEVER if none) — self-emissions at or past it must bypass
    the inbox and go to the pool, otherwise they could be processed ahead of
    the deferred leftover. (Known tie edge: a leftover and an extracted event
    at the exact same nanosecond can still invert against a same-time
    self-emission; requires K overflow + an exact time tie, and K is
    configurable — tracked for an exact re-extraction fix.)"""
    C = pool.capacity
    inwin = pool.time < win_end
    sort_dst = jnp.where(inwin, pool.dst, jnp.int32(H))
    idx = jnp.arange(C, dtype=jnp.int32)
    s_dst, s_time, s_src, s_seq, s_idx = jax.lax.sort(
        [sort_dst, pool.time, pool.src, pool.seq, idx], num_keys=4, is_stable=True
    )
    starts = jnp.searchsorted(s_dst, jnp.arange(H, dtype=jnp.int32)).astype(jnp.int32)
    pos = jnp.arange(C, dtype=jnp.int32)
    rank = pos - starts[jnp.clip(s_dst, 0, H - 1)]
    valid = s_dst < H
    extract = valid & (rank < K)
    # Scatter into the matrix; invalid rows target index H → dropped.
    mrow = jnp.where(extract, s_dst, jnp.int32(H))
    mcol = jnp.where(extract, rank, 0)
    gathered_kind = pool.kind[s_idx]
    gathered_payload = pool.payload[s_idx]

    def scat(init, vals):
        return init.at[mrow, mcol].set(vals, mode="drop")

    mat = _Matrix(
        time=scat(jnp.full((H, K), NEVER, dtype=jnp.int64), s_time),
        src=scat(jnp.zeros((H, K), dtype=jnp.int32), s_src),
        seq=scat(jnp.zeros((H, K), dtype=jnp.int32), s_seq),
        kind=scat(jnp.zeros((H, K), dtype=jnp.int32), gathered_kind),
        payload=jnp.zeros((H, K, PAYLOAD_WORDS), dtype=jnp.int32)
        .at[mrow, mcol]
        .set(gathered_payload, mode="drop"),
    )
    # Earliest leftover (rank == K) per host; NEVER if the host fit in K.
    defer_row = jnp.where(valid & (rank == K), s_dst, jnp.int32(H))
    defer_time = (
        jnp.full((H,), NEVER, dtype=jnp.int64)
        .at[defer_row]
        .set(s_time, mode="drop")
    )
    # Free the extracted slots in the pool.
    clear_idx = jnp.where(extract, s_idx, jnp.int32(C))
    new_time = pool.time.at[clear_idx].set(NEVER, mode="drop")
    return mat, pool.replace(time=new_time), defer_time


def _inbox_min(inbox: _Inbox):
    """Per-host lexicographic min of the inbox by (time, src, seq).
    Returns (time, src, seq, slot) each [H]."""
    B = inbox.time.shape[1]
    slot = jnp.broadcast_to(jnp.arange(B, dtype=jnp.int32), inbox.time.shape)
    t, s, q, i = jax.lax.sort(
        [inbox.time, inbox.src, inbox.seq, slot], num_keys=3, is_stable=True, dimension=1
    )
    return t[:, 0], s[:, 0], q[:, 0], i[:, 0]


def _key_lt(t1, s1, q1, t2, s2, q2):
    """(t1,s1,q1) < (t2,s2,q2) lexicographically (same dst implied)."""
    return (t1 < t2) | ((t1 == t2) & ((s1 < s2) | ((s1 == s2) & (q1 < q2))))


# ---------------------------------------------------------------------------
# The window step factory
# ---------------------------------------------------------------------------


def make_window_step(
    handlers: dict[int, Handler],
    num_hosts: int,
    K: int = 32,
    B: int = 8,
    O: int = 64,
    max_iters: int | None = None,
):
    """Build step(state, params, win_start, win_end) -> (state, min_next).

    ``handlers`` maps event kind → handler; handler order within a micro-step
    follows ascending kind (fixed, deterministic).
    """
    H = num_hosts
    if max_iters is None:
        max_iters = K + 4 * B + 16
    hosts = jnp.arange(H, dtype=jnp.int32)
    kinds = sorted(handlers)

    def step(state: SimState, params: NetParams, win_start, win_end):
        win_start = jnp.asarray(win_start, jnp.int64)
        win_end = jnp.asarray(win_end, jnp.int64)
        mat, pool, defer_time = _extract_window(state.pool, win_end, H, K)
        state = state.replace(pool=pool, now=win_start)
        carry0 = (
            state,
            mat,
            jnp.zeros((H,), dtype=jnp.int32),  # ptr
            _Inbox.empty(H, B),
            _Outbox.empty(H, O),
            jnp.int32(0),  # iteration counter
            jnp.bool_(True),  # work remaining
        )

        def cond(carry):
            _, _, _, _, _, it, work = carry
            return work & (it < max_iters)

        def body(carry):
            state, mat, ptr, inbox, outbox, it, _ = carry

            # --- candidate per host: matrix head vs inbox min ---
            p = jnp.clip(ptr, 0, K - 1)
            m_time = jnp.take_along_axis(mat.time, p[:, None], axis=1)[:, 0]
            m_time = jnp.where(ptr < K, m_time, NEVER)
            m_src = jnp.take_along_axis(mat.src, p[:, None], axis=1)[:, 0]
            m_seq = jnp.take_along_axis(mat.seq, p[:, None], axis=1)[:, 0]
            i_time, i_src, i_seq, i_slot = _inbox_min(inbox)
            use_inbox = _key_lt(i_time, i_src, i_seq, m_time, m_src, m_seq)
            ev_time = jnp.where(use_inbox, i_time, m_time)
            valid = ev_time < win_end

            m_kind = jnp.take_along_axis(mat.kind, p[:, None], axis=1)[:, 0]
            m_payload = jnp.take_along_axis(mat.payload, p[:, None, None], axis=1)[
                :, 0, :
            ]
            i_kind = jnp.take_along_axis(inbox.kind, i_slot[:, None], axis=1)[:, 0]
            i_payload = jnp.take_along_axis(
                inbox.payload, i_slot[:, None, None], axis=1
            )[:, 0, :]
            ev = EventView(
                mask=valid,
                time=ev_time,
                src=jnp.where(use_inbox, i_src, m_src),
                seq=jnp.where(use_inbox, i_seq, m_seq),
                kind=jnp.where(use_inbox, i_kind, m_kind),
                payload=jnp.where(use_inbox[:, None], i_payload, m_payload),
            )

            # --- consume the chosen event ---
            ptr = jnp.where(valid & ~use_inbox, ptr + 1, ptr)
            clear_slot = jnp.where(valid & use_inbox, i_slot, jnp.int32(B))
            inbox = inbox.replace(
                time=inbox.time.at[hosts, clear_slot].set(NEVER, mode="drop")
            )

            # --- run handlers (ascending kind; masked SoA updates) ---
            emitter = Emitter()
            for k in kinds:
                hev = ev.replace(mask=valid & (ev.kind == k))
                state = handlers[k](state, hev, emitter, params)

            state = state.replace(
                counters=state.counters.replace(
                    events_committed=state.counters.events_committed
                    + jnp.sum(valid, dtype=jnp.int64)
                )
            )

            # --- route emissions (order fixes per-source seq numbers) ---
            for em in emitter.records:
                seq = state.host.seq_next
                state = state.replace(
                    host=state.host.replace(
                        seq_next=jnp.where(em.mask, seq + 1, seq)
                    )
                )
                # Self-emissions past the host's earliest deferred leftover
                # must not jump the queue: route them through the pool.
                is_self = (
                    em.mask
                    & (em.dst == hosts)
                    & (em.time < win_end)
                    & (em.time < defer_time)
                )

                free = inbox.time == NEVER  # [H, B]
                ff = jnp.argmax(free, axis=1).astype(jnp.int32)
                has_free = jnp.any(free, axis=1)
                ins = is_self & has_free
                # Inbox overflow DEFERS to the pool via the outbox (processed
                # next window, late but never lost — a lost NIC pump event
                # would wedge its queue); the counter records the deferral.
                to_out = em.mask & ~ins
                ins_slot = jnp.where(ins, ff, jnp.int32(B))
                inbox = inbox.replace(
                    time=inbox.time.at[hosts, ins_slot].set(em.time, mode="drop"),
                    src=inbox.src.at[hosts, ins_slot].set(hosts, mode="drop"),
                    seq=inbox.seq.at[hosts, ins_slot].set(seq, mode="drop"),
                    kind=inbox.kind.at[hosts, ins_slot].set(em.kind, mode="drop"),
                    payload=inbox.payload.at[hosts, ins_slot].set(
                        em.payload, mode="drop"
                    ),
                )

                oslot = jnp.where(
                    to_out & (outbox.count < O), outbox.count, jnp.int32(O)
                )
                outbox = outbox.replace(
                    time=outbox.time.at[hosts, oslot].set(em.time, mode="drop"),
                    dst=outbox.dst.at[hosts, oslot].set(em.dst, mode="drop"),
                    src=outbox.src.at[hosts, oslot].set(hosts, mode="drop"),
                    seq=outbox.seq.at[hosts, oslot].set(seq, mode="drop"),
                    kind=outbox.kind.at[hosts, oslot].set(em.kind, mode="drop"),
                    payload=outbox.payload.at[hosts, oslot].set(
                        em.payload, mode="drop"
                    ),
                    count=outbox.count + (oslot < O).astype(jnp.int32),
                )
                state = state.replace(
                    counters=state.counters.replace(
                        events_emitted=state.counters.events_emitted
                        + jnp.sum(em.mask, dtype=jnp.int64),
                        inbox_overflow_deferred=state.counters.inbox_overflow_deferred
                        + jnp.sum(is_self & ~has_free, dtype=jnp.int64),
                        outbox_overflow_dropped=state.counters.outbox_overflow_dropped
                        + jnp.sum(to_out & (outbox.count >= O) & (oslot >= O),
                                  dtype=jnp.int64),
                    )
                )

            work = jnp.any(valid)
            return (state, mat, ptr, inbox, outbox, it + 1, work)

        state, mat, ptr, inbox, outbox, _, _ = jax.lax.while_loop(
            cond, body, carry0
        )

        # --- merge: pool ∪ outbox ∪ spilled leftovers (inbox/matrix) ---
        # Leftovers are only non-empty if max_iters capped the loop; their
        # keys exceed everything processed, so deferring them is still a
        # correct (if slower) schedule.
        pool = state.pool
        C = pool.capacity
        col = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32), (H, K))
        mat_left = col >= ptr[:, None]
        mat_time_left = jnp.where(mat_left, mat.time, NEVER)

        all_time = jnp.concatenate(
            [pool.time, outbox.time.reshape(-1), inbox.time.reshape(-1),
             mat_time_left.reshape(-1)]
        )
        hostsK = jnp.broadcast_to(hosts[:, None], (H, K)).reshape(-1)
        hostsB = jnp.broadcast_to(hosts[:, None], inbox.time.shape).reshape(-1)
        all_dst = jnp.concatenate(
            [pool.dst, outbox.dst.reshape(-1), hostsB, hostsK]
        )
        all_src = jnp.concatenate(
            [pool.src, outbox.src.reshape(-1), inbox.src.reshape(-1),
             mat.src.reshape(-1)]
        )
        all_seq = jnp.concatenate(
            [pool.seq, outbox.seq.reshape(-1), inbox.seq.reshape(-1),
             mat.seq.reshape(-1)]
        )
        all_kind = jnp.concatenate(
            [pool.kind, outbox.kind.reshape(-1), inbox.kind.reshape(-1),
             mat.kind.reshape(-1)]
        )
        all_payload = jnp.concatenate(
            [pool.payload, outbox.payload.reshape(-1, PAYLOAD_WORDS),
             inbox.payload.reshape(-1, PAYLOAD_WORDS),
             mat.payload.reshape(-1, PAYLOAD_WORDS)]
        )
        idx = jnp.arange(all_time.shape[0], dtype=jnp.int32)
        s_time, s_idx = jax.lax.sort([all_time, idx], num_keys=1, is_stable=True)
        keep = s_idx[:C]
        dropped = jnp.sum(s_time[C:] != NEVER, dtype=jnp.int64)
        new_pool = EventPool(
            time=s_time[:C],
            dst=all_dst[keep],
            src=all_src[keep],
            seq=all_seq[keep],
            kind=all_kind[keep],
            payload=all_payload[keep],
        )
        state = state.replace(
            pool=new_pool,
            counters=state.counters.replace(
                pool_overflow_dropped=state.counters.pool_overflow_dropped + dropped
            ),
        )
        min_next = jnp.min(new_pool.time)
        return state, min_next

    return step


# ---------------------------------------------------------------------------
# Simulation driver (controller/manager analog)
# ---------------------------------------------------------------------------


class Simulation:
    """Owns the built state + jitted kernels and plays the round loop.

    Construct via shadow_tpu.sim.build_simulation (from a Config) or directly
    with prebuilt pieces for tests.
    """

    def __init__(
        self,
        *,
        num_hosts: int,
        handlers: dict[int, Handler],
        params: NetParams,
        host_vertex: np.ndarray,
        seed: int,
        stop_time: int,
        runahead: int,
        event_capacity: int = 1 << 14,
        K: int = 32,
        B: int = 8,
        O: int = 64,
        subs: dict | None = None,
        initial_events: list[tuple[int, int, int, int, list[int]]] | None = None,
    ):
        # initial_events: (time, dst, src, kind, payload words)
        self.num_hosts = num_hosts
        self.stop_time = int(stop_time)
        self.runahead = int(runahead)
        if self.runahead <= 0:
            raise ValueError("runahead must be > 0 (min topology latency)")
        self.params = params
        pool = EventPool.empty(event_capacity)
        n0 = len(initial_events or [])
        if n0 > event_capacity:
            raise ValueError("initial events exceed event pool capacity")
        if initial_events:
            # Assign per-source sequence numbers in list order, like the
            # reference assigns per-source event IDs at push time.
            seq_ctr: dict[int, int] = {}
            times, dsts, srcs, seqs, kinds_, pls = [], [], [], [], [], []
            for (t, d, s, k, pl) in initial_events:
                q = seq_ctr.get(s, 0)
                seq_ctr[s] = q + 1
                times.append(t)
                dsts.append(d)
                srcs.append(s)
                seqs.append(q)
                kinds_.append(k)
                row = list(pl) + [0] * (PAYLOAD_WORDS - len(pl))
                pls.append(row[:PAYLOAD_WORDS])
            sl = slice(0, n0)
            pool = pool.replace(
                time=pool.time.at[sl].set(jnp.asarray(times, jnp.int64)),
                dst=pool.dst.at[sl].set(jnp.asarray(dsts, jnp.int32)),
                src=pool.src.at[sl].set(jnp.asarray(srcs, jnp.int32)),
                seq=pool.seq.at[sl].set(jnp.asarray(seqs, jnp.int32)),
                kind=pool.kind.at[sl].set(jnp.asarray(kinds_, jnp.int32)),
                payload=pool.payload.at[sl].set(jnp.asarray(pls, jnp.int32)),
            )
            seq_init = np.zeros(num_hosts, dtype=np.int32)
            for s, q in seq_ctr.items():
                seq_init[s] = q
        else:
            seq_init = np.zeros(num_hosts, dtype=np.int32)

        host = make_host_state(num_hosts, host_vertex)
        host = host.replace(seq_next=jnp.asarray(seq_init))
        self.state = SimState(
            now=jnp.int64(0),
            pool=pool,
            host=host,
            counters=Counters.zeros(),
            rng_keys=rng_mod.host_keys(seed, num_hosts),
            subs=subs or {},
        )
        step = make_window_step(handlers, num_hosts, K=K, B=B, O=O)
        self._step = jax.jit(step)
        self._run_to = jax.jit(self._make_run_to(step))

    def _make_run_to(self, step):
        runahead = jnp.int64(self.runahead)

        def run_to(state: SimState, params: NetParams, stop, max_windows):
            """Advance up to max_windows windows (or until stop). Bounding
            the on-device while_loop keeps each dispatch short — long single
            dispatches can trip accelerator-runtime watchdogs."""
            stop = jnp.asarray(stop, jnp.int64)
            max_windows = jnp.asarray(max_windows, jnp.int32)

            def cond(c):
                state, mn, w = c
                return (mn < stop) & (w < max_windows)

            def body(c):
                state, mn, w = c
                ws = mn
                we = jnp.minimum(ws + runahead, stop)
                state, mn = step(state, params, ws, we)
                return state, mn, w + 1

            mn0 = jnp.min(state.pool.time)
            state, mn, _ = jax.lax.while_loop(
                cond, body, (state, mn0, jnp.int32(0))
            )
            return state, mn

        return run_to

    # -- host-driven round loop (one device sync per window; debuggable) --
    def run_stepwise(self, until: int | None = None) -> int:
        stop = self.stop_time if until is None else min(until, self.stop_time)
        windows = 0
        min_next = int(jnp.min(self.state.pool.time))
        while min_next < stop:
            ws = min_next
            we = min(ws + self.runahead, stop)
            self.state, mn = self._step(self.state, self.params, ws, we)
            min_next = int(mn)
            windows += 1
        return windows

    # -- fused run: windows execute in on-device while_loop chunks --
    def run(
        self, until: int | None = None, windows_per_dispatch: int = 64
    ) -> None:
        stop = self.stop_time if until is None else min(until, self.stop_time)
        while True:
            self.state, mn = self._run_to(
                self.state, self.params, stop, windows_per_dispatch
            )
            if int(mn) >= stop:
                break

    def counters(self) -> dict[str, int]:
        c = jax.device_get(self.state.counters)
        return {k: int(v) for k, v in c.__dict__.items()}
