"""The batched PDES window kernel and simulation driver.

Reference execution model (src/main/core/manager.c:543-577,
scheduler/scheduler.c:77-94, controller.c:390-422): time advances in
conservative windows bounded by the minimum topology latency ("runahead");
within a window each worker pops its hosts' events in deterministic order
(time, dst, src, seq — event.c:109-152) and runs them; a barrier plus a
min-next-event-time reduction ends the round.

TPU-first re-architecture (one jitted pure function per window):

1. SORT — one sort of the event pool by (dst, time, src, seq) groups this
   window's events into consecutive per-host runs. This replaces all
   per-host priority queues and their locks.
2. MICRO-STEP LOOP — a `lax.while_loop` whose body processes AT MOST ONE
   event per host, fully vectorized across all hosts: candidate = key-min of
   (run head at a per-host cursor, self-inbox); handlers apply masked SoA
   updates. Per-host event order is preserved exactly; hosts are
   data-parallel, which is the same parallelism the reference exploits with
   worker threads (P1 in SURVEY.md §2.5) — but over lanes instead of
   pthreads.
3. The conservative-window invariant (window length ≤ min path latency,
   controller.c:125-153) guarantees cross-host emissions land at or after
   window end, so only SELF-emissions (short timers, NIC refills) can need
   intra-window processing — they go to a small per-host inbox. Everything
   else accumulates in a per-host outbox (no scatter collisions).
4. MERGE — unconsumed sorted rows + outbox + inbox leftovers merge into the
   next pool with one sort by time, truncating to capacity (drops counted).
   The next window start is the min pool time — the reference's min-reduce
   barrier (worker.c:332-363) becomes a jnp.min.

Everything is sorts, gathers, and elementwise selects: XLA scatters
serialize element-by-element on TPU and are banned from this module.

The whole multi-window run can itself be a `lax.while_loop` on device
(`Simulation.run_compiled`), so a complete simulation is ONE XLA program.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from shadow_tpu.core import rng as rng_mod
from shadow_tpu.core import simtime
from shadow_tpu.core.state import (
    PAYLOAD_WORDS,
    Counters,
    EventPool,
    HostState,
    NetParams,
    SimState,
    make_host_state,
)

NEVER = simtime.NEVER


# ---------------------------------------------------------------------------
# Event view + emission interface for handlers
# ---------------------------------------------------------------------------


@struct.dataclass
class EventView:
    """The (at most one) event each host is processing this micro-step.

    All arrays are [H]-indexed; the destination host of event i IS host i.
    ``mask`` is set per handler: valid event AND kind match.
    """

    mask: jnp.ndarray  # [H] bool
    time: jnp.ndarray  # [H] i64
    src: jnp.ndarray  # [H] i32
    seq: jnp.ndarray  # [H] i32
    kind: jnp.ndarray  # [H] i32
    payload: jnp.ndarray  # [H, P] i32


class Emission(NamedTuple):
    mask: jnp.ndarray  # [H] bool — which hosts emit
    time: jnp.ndarray  # [H] i64
    dst: jnp.ndarray  # [H] i32
    kind: jnp.ndarray  # [H] i32 (may be per-host)
    payload: jnp.ndarray  # [H, P] i32


class Emitter:
    """Collects handler emissions; the engine routes them (inbox/outbox)
    in collection order, which fixes the per-source sequence numbering."""

    def __init__(self):
        self.records: list[Emission] = []

    def emit(self, mask, time, dst, kind, payload):
        kind = jnp.broadcast_to(jnp.asarray(kind, jnp.int32), mask.shape)
        self.records.append(
            Emission(mask, time.astype(jnp.int64), dst.astype(jnp.int32), kind, payload)
        )


# handler(state, ev, emitter, params) -> state
Handler = Callable[[SimState, EventView, Emitter, NetParams], SimState]


def draw_uniform(state: SimState, mask):
    """One deterministic uniform draw per masked host; bumps draw counters
    only where masked (so inactive hosts' streams don't advance — matching a
    per-host sequential RNG)."""
    u = rng_mod.uniform_per_host(state.rng_keys, state.host.rng_counter)
    new_c = jnp.where(mask, state.host.rng_counter + 1, state.host.rng_counter)
    state = state.replace(host=state.host.replace(rng_counter=new_c))
    return state, u


# ---------------------------------------------------------------------------
# Window data structures
# ---------------------------------------------------------------------------


@struct.dataclass
class _Inbox:
    time: jnp.ndarray  # [H, B] i64
    src: jnp.ndarray
    seq: jnp.ndarray
    kind: jnp.ndarray
    payload: jnp.ndarray  # [H, B, P]

    @classmethod
    def empty(cls, H, B):
        return cls(
            time=jnp.full((H, B), NEVER, dtype=jnp.int64),
            src=jnp.zeros((H, B), dtype=jnp.int32),
            seq=jnp.zeros((H, B), dtype=jnp.int32),
            kind=jnp.zeros((H, B), dtype=jnp.int32),
            payload=jnp.zeros((H, B, PAYLOAD_WORDS), dtype=jnp.int32),
        )


@struct.dataclass
class _Outbox:
    time: jnp.ndarray  # [H, O] i64
    dst: jnp.ndarray
    src: jnp.ndarray
    seq: jnp.ndarray
    kind: jnp.ndarray
    payload: jnp.ndarray  # [H, O, P]
    count: jnp.ndarray  # [H] i32

    @classmethod
    def empty(cls, H, O):
        return cls(
            time=jnp.full((H, O), NEVER, dtype=jnp.int64),
            dst=jnp.zeros((H, O), dtype=jnp.int32),
            src=jnp.zeros((H, O), dtype=jnp.int32),
            seq=jnp.zeros((H, O), dtype=jnp.int32),
            kind=jnp.zeros((H, O), dtype=jnp.int32),
            payload=jnp.zeros((H, O, PAYLOAD_WORDS), dtype=jnp.int32),
            count=jnp.zeros((H,), dtype=jnp.int32),
        )


@struct.dataclass
class _SortedWindow:
    """The pool sorted by (dst, time, src, seq) for one window.

    In-window events of host h occupy consecutive rows [starts[h], ends[h]);
    out-of-window rows sort to the end (dst key = H sentinel). The loop
    consumes rows via per-host cursors — no [H, K] matrix is materialized;
    per-iteration [H]-gathers read the head rows directly, and unconsumed
    rows flow straight into the merge."""

    dst: jnp.ndarray  # [C] i32 original dst (sentinel-free)
    time: jnp.ndarray  # [C] i64
    src: jnp.ndarray  # [C] i32
    seq: jnp.ndarray  # [C] i32
    kind: jnp.ndarray  # [C] i32
    idx: jnp.ndarray  # [C] i32 original pool slot (payload indirection)
    starts: jnp.ndarray  # [H] i32
    ends: jnp.ndarray  # [H] i32


def _sort_window(pool: EventPool, win_end, H: int, K: int):
    """Sort the pool by (dst, time, src, seq) and locate per-host runs.

    Events beyond K per host are deferred to the next window (their keys are
    strictly larger than every extracted event's, so per-host order holds).
    Also returns the FULL key (time, src, seq), each [H], of the earliest
    DEFERRED event per host (time NEVER if none): a self-emission whose own
    key (time, emitting host, seq) is >= that deferred key must bypass the
    inbox and go to the pool, otherwise it could be processed ahead of the
    deferred leftover. Comparing the full key (not just the time) makes the
    routing exact under nanosecond ties: an emission tied on time with the
    deferred leftover still interleaves correctly against the extracted
    same-time events via the (src, seq) tiebreak — the order the pool sort
    would produce.

    TPU note: sorts and gathers only — XLA scatters serialize
    element-by-element on TPU (~0.5 µs each), so a single [C]-row scatter
    would cost more than the entire window step."""
    C = pool.capacity
    inwin = pool.time < win_end
    sort_dst = jnp.where(inwin, pool.dst, jnp.int32(H))
    idx = jnp.arange(C, dtype=jnp.int32)
    s_key, s_time, s_src, s_seq, s_idx = jax.lax.sort(
        [sort_dst, pool.time, pool.src, pool.seq, idx], num_keys=4,
        is_stable=True,
    )
    # One sort-method searchsorted over H+1 boundaries (the default binary
    # scan costs ~3x more here).
    bounds = jnp.searchsorted(
        s_key, jnp.arange(H + 1, dtype=jnp.int32), method="sort"
    ).astype(jnp.int32)
    starts, ends = bounds[:H], bounds[1:]
    sw = _SortedWindow(
        dst=pool.dst[s_idx],
        time=s_time,
        src=s_src,
        seq=s_seq,
        kind=pool.kind[s_idx],
        idx=s_idx,
        starts=starts,
        ends=ends,
    )
    # Earliest deferred (rank >= K) per host; time NEVER if the host fit.
    has_defer = (starts + K) < ends
    didx = jnp.where(has_defer, starts + K, 0)
    defer_time = jnp.where(has_defer, s_time[didx], NEVER)
    defer_src = jnp.where(has_defer, s_src[didx], 0)
    defer_seq = jnp.where(has_defer, s_seq[didx], 0)
    return sw, (defer_time, defer_src, defer_seq)


def _inbox_min(inbox: _Inbox):
    """Per-host lexicographic min of the inbox by (time, src, seq).
    Returns (time, src, seq, slot) each [H].

    Tournament reduction (log2 B rounds of elementwise compares) instead of
    a lax.sort: B is tiny and TPU's bitonic sort costs ~ms at H=8k where
    this costs microseconds."""
    t, s, q = inbox.time, inbox.src, inbox.seq
    B = t.shape[1]
    slot = jnp.broadcast_to(jnp.arange(B, dtype=jnp.int32), t.shape)
    while B > 1:
        half = (B + 1) // 2
        t1, s1, q1, i1 = t[:, :half], s[:, :half], q[:, :half], slot[:, :half]
        t2 = t[:, half:]
        pad = half - t2.shape[1]
        if pad:
            t2 = jnp.pad(t2, ((0, 0), (0, pad)), constant_values=NEVER)
            s2 = jnp.pad(s[:, half:], ((0, 0), (0, pad)))
            q2 = jnp.pad(q[:, half:], ((0, 0), (0, pad)))
            i2 = jnp.pad(slot[:, half:], ((0, 0), (0, pad)))
        else:
            s2, q2, i2 = s[:, half:], q[:, half:], slot[:, half:]
        take2 = _key_lt(t2, s2, q2, t1, s1, q1)
        t = jnp.where(take2, t2, t1)
        s = jnp.where(take2, s2, s1)
        q = jnp.where(take2, q2, q1)
        slot = jnp.where(take2, i2, i1)
        B = half
    return t[:, 0], s[:, 0], q[:, 0], slot[:, 0]


def _key_lt(t1, s1, q1, t2, s2, q2):
    """(t1,s1,q1) < (t2,s2,q2) lexicographically (same dst implied)."""
    return (t1 < t2) | ((t1 == t2) & ((s1 < s2) | ((s1 == s2) & (q1 < q2))))


def _set_col(arr, col, mask, val):
    """arr[h, col[h]] = val[h] for masked hosts, as a pure elementwise
    select over [H, B(, P)] — avoids XLA scatter, which serializes on TPU.
    `val` may be scalar, [H], or [H, P] matching arr's trailing dims."""
    B = arr.shape[1]
    cols = jnp.arange(B, dtype=jnp.int32)
    hit = mask[:, None] & (cols[None, :] == col[:, None])  # [H, B]
    val = jnp.asarray(val, arr.dtype)
    if arr.ndim == 3:
        if val.ndim == 2:
            val = val[:, None, :]
        return jnp.where(hit[:, :, None], val, arr)
    if val.ndim == 1:
        val = val[:, None]
    return jnp.where(hit, val, arr)


# ---------------------------------------------------------------------------
# The window step factory
# ---------------------------------------------------------------------------


def make_window_step(
    handlers: dict[int, Handler],
    num_hosts: int,
    K: int = 32,
    B: int = 8,
    O: int = 64,
    max_iters: int | None = None,
):
    """Build step(state, params, win_start, win_end) -> (state, min_next).

    ``handlers`` maps event kind → handler; handler order within a micro-step
    follows ascending kind (fixed, deterministic).
    """
    H = num_hosts
    if max_iters is None:
        max_iters = K + 4 * B + 16
    hosts = jnp.arange(H, dtype=jnp.int32)
    kinds = sorted(handlers)

    def step(state: SimState, params: NetParams, win_start, win_end):
        win_start = jnp.asarray(win_start, jnp.int64)
        win_end = jnp.asarray(win_end, jnp.int64)
        sw, (defer_time, defer_src, defer_seq) = _sort_window(
            state.pool, win_end, H, K
        )
        pool_payload = state.pool.payload
        state = state.replace(now=win_start)

        # Static per-kind emission bound: probe the handlers once at trace
        # time with an all-masked-off event and count emit() calls per
        # kind. A host processes exactly ONE event (of one kind) per
        # iteration, so its worst-case outbox demand is the emit-call count
        # of THAT kind's handler. The backpressure below stalls a host
        # whose outbox can't absorb that demand — nothing is ever dropped.
        # The probe's state/ops are discarded (XLA dead-code-eliminates).
        probe = Emitter()
        pv = EventView(
            mask=jnp.zeros((H,), jnp.bool_),
            time=jnp.zeros((H,), jnp.int64),
            src=jnp.zeros((H,), jnp.int32),
            seq=jnp.zeros((H,), jnp.int32),
            kind=jnp.zeros((H,), jnp.int32),
            payload=jnp.zeros((H, PAYLOAD_WORDS), jnp.int32),
        )
        E_by_kind = np.zeros(max(kinds) + 1 if kinds else 1, dtype=np.int32)
        pstate = state
        for k in kinds:
            before = len(probe.records)
            pstate = handlers[k](pstate, pv, probe, params)
            E_by_kind[k] = len(probe.records) - before
        del pstate
        if int(E_by_kind.max()) > O:
            worst = int(E_by_kind.argmax())
            raise ValueError(
                f"outbox_slots O={O} cannot absorb kind {worst}'s worst-"
                f"case emissions E={int(E_by_kind.max())}; raise "
                f"experimental.outbox_slots"
            )
        E_arr = jnp.asarray(E_by_kind, jnp.int32)
        carry0 = (
            state,
            jnp.zeros((H,), dtype=jnp.int32),  # ptr (consumed per host)
            _Inbox.empty(H, B),
            _Outbox.empty(H, O),
            jnp.int32(0),  # iteration counter
            jnp.bool_(True),  # work remaining
        )

        def cond(carry):
            _, _, _, _, it, work = carry
            return work & (it < max_iters)

        def body(carry):
            state, ptr, inbox, outbox, it, _ = carry

            # --- candidate per host: sorted-run head vs inbox min ---
            hp = jnp.clip(sw.starts + ptr, 0, sw.time.shape[0] - 1)
            in_run = (ptr < K) & ((sw.starts + ptr) < sw.ends)
            m_time = jnp.where(in_run, sw.time[hp], NEVER)
            m_src = sw.src[hp]
            m_seq = sw.seq[hp]
            i_time, i_src, i_seq, i_slot = _inbox_min(inbox)
            use_inbox = _key_lt(i_time, i_src, i_seq, m_time, m_src, m_seq)
            ev_time = jnp.where(use_inbox, i_time, m_time)

            m_kind = sw.kind[hp]
            i_kind = jnp.take_along_axis(inbox.kind, i_slot[:, None], axis=1)[:, 0]
            ev_kind = jnp.where(use_inbox, i_kind, m_kind)
            # Outbox backpressure: a host whose outbox cannot absorb this
            # event-kind's worst-case emissions stalls — its events stay
            # queued and defer to the next window via the merge (never
            # dropped).
            need = E_arr[jnp.clip(ev_kind, 0, E_arr.shape[0] - 1)]
            room = (outbox.count + need) <= O
            valid = (ev_time < win_end) & room
            stalled = (ev_time < win_end) & ~room

            m_payload = pool_payload[sw.idx[hp]]
            i_payload = jnp.take_along_axis(
                inbox.payload, i_slot[:, None, None], axis=1
            )[:, 0, :]
            ev = EventView(
                mask=valid,
                time=ev_time,
                src=jnp.where(use_inbox, i_src, m_src),
                seq=jnp.where(use_inbox, i_seq, m_seq),
                kind=ev_kind,
                payload=jnp.where(use_inbox[:, None], i_payload, m_payload),
            )

            # --- consume the chosen event ---
            state = state.replace(
                host=state.host.replace(
                    done_t=jnp.where(valid, ev_time, state.host.done_t)
                )
            )
            ptr = jnp.where(valid & ~use_inbox, ptr + 1, ptr)
            inbox = inbox.replace(
                time=_set_col(inbox.time, i_slot, valid & use_inbox, NEVER)
            )

            # --- run handlers (ascending kind; masked SoA updates) ---
            emitter = Emitter()
            for k in kinds:
                hev = ev.replace(mask=valid & (ev.kind == k))
                state = handlers[k](state, hev, emitter, params)

            state = state.replace(
                counters=state.counters.replace(
                    events_committed=state.counters.events_committed
                    + jnp.sum(valid, dtype=jnp.int64),
                    outbox_stall_deferred=state.counters.outbox_stall_deferred
                    + jnp.sum(stalled, dtype=jnp.int64),
                )
            )

            # --- route emissions (order fixes per-source seq numbers) ---
            for em in emitter.records:
                seq = state.host.seq_next
                state = state.replace(
                    host=state.host.replace(
                        seq_next=jnp.where(em.mask, seq + 1, seq)
                    )
                )
                # Self-emissions at or past the host's earliest deferred
                # leftover (full-key compare: exact under time ties) must
                # not jump the queue: route them through the pool.
                is_self = (
                    em.mask
                    & (em.dst == hosts)
                    & (em.time < win_end)
                    & _key_lt(em.time, hosts, seq,
                              defer_time, defer_src, defer_seq)
                )

                free = inbox.time == NEVER  # [H, B]
                ff = jnp.argmax(free, axis=1).astype(jnp.int32)
                has_free = jnp.any(free, axis=1)
                ins = is_self & has_free
                # Inbox overflow DEFERS to the pool via the outbox (processed
                # next window, late but never lost — a lost NIC pump event
                # would wedge its queue); the counter records the deferral.
                to_out = em.mask & ~ins
                inbox = inbox.replace(
                    time=_set_col(inbox.time, ff, ins, em.time),
                    src=_set_col(inbox.src, ff, ins, hosts),
                    seq=_set_col(inbox.seq, ff, ins, seq),
                    kind=_set_col(inbox.kind, ff, ins, em.kind),
                    payload=_set_col(inbox.payload, ff, ins, em.payload),
                )

                ocol = outbox.count  # next free outbox column per host
                put = to_out & (ocol < O)
                outbox = outbox.replace(
                    time=_set_col(outbox.time, ocol, put, em.time),
                    dst=_set_col(outbox.dst, ocol, put, em.dst),
                    src=_set_col(outbox.src, ocol, put, hosts),
                    seq=_set_col(outbox.seq, ocol, put, seq),
                    kind=_set_col(outbox.kind, ocol, put, em.kind),
                    payload=_set_col(outbox.payload, ocol, put, em.payload),
                    count=outbox.count + put.astype(jnp.int32),
                )
                state = state.replace(
                    counters=state.counters.replace(
                        events_emitted=state.counters.events_emitted
                        + jnp.sum(em.mask, dtype=jnp.int64),
                        inbox_overflow_deferred=state.counters.inbox_overflow_deferred
                        + jnp.sum(is_self & ~has_free, dtype=jnp.int64),
                        outbox_overflow_dropped=state.counters.outbox_overflow_dropped
                        + jnp.sum(to_out & ~put, dtype=jnp.int64),
                    )
                )

            work = jnp.any(valid)
            return (state, ptr, inbox, outbox, it + 1, work)

        state, ptr, inbox, outbox, _, _ = jax.lax.while_loop(
            cond, body, carry0
        )

        # --- merge: unconsumed sorted rows ∪ outbox ∪ inbox leftovers with
        # one sort by time (gathers only — no scatters, which serialize on
        # TPU). A sorted row is consumed iff its rank within its host's run
        # is below that host's final cursor — pure elementwise, no inverse
        # permutation needed. Inbox leftovers exist if max_iters capped the
        # loop or a host stalled on outbox backpressure; deferring them is a
        # correct (if slower) schedule.
        pool = state.pool
        C = pool.capacity
        spos = jnp.arange(C, dtype=jnp.int32)
        run_host = jnp.clip(sw.dst, 0, H - 1)
        rank = spos - sw.starts[run_host]
        in_run_row = (spos >= sw.starts[run_host]) & (spos < sw.ends[run_host])
        consumed = in_run_row & (rank < ptr[run_host])
        left_time = jnp.where(consumed, NEVER, sw.time)

        hostsB = jnp.broadcast_to(hosts[:, None], inbox.time.shape).reshape(-1)
        all_time = jnp.concatenate(
            [left_time, outbox.time.reshape(-1), inbox.time.reshape(-1)]
        )
        all_dst = jnp.concatenate([sw.dst, outbox.dst.reshape(-1), hostsB])
        all_src = jnp.concatenate(
            [sw.src, outbox.src.reshape(-1), inbox.src.reshape(-1)]
        )
        all_seq = jnp.concatenate(
            [sw.seq, outbox.seq.reshape(-1), inbox.seq.reshape(-1)]
        )
        all_kind = jnp.concatenate(
            [sw.kind, outbox.kind.reshape(-1), inbox.kind.reshape(-1)]
        )
        idx = jnp.arange(all_time.shape[0], dtype=jnp.int32)
        s_time, s_idx = jax.lax.sort([all_time, idx], num_keys=1, is_stable=True)
        keep = s_idx[:C]
        dropped = jnp.sum(s_time[C:] != NEVER, dtype=jnp.int64)
        # Payload indirection: rows from the sorted window read the ORIGINAL
        # pool payload via sw.idx; box rows read the box buffers.
        box_payload = jnp.concatenate(
            [outbox.payload.reshape(-1, PAYLOAD_WORDS),
             inbox.payload.reshape(-1, PAYLOAD_WORDS)]
        )
        from_pool = keep < C
        ppidx = sw.idx[jnp.where(from_pool, keep, 0)]
        bidx = jnp.clip(keep - C, 0, box_payload.shape[0] - 1)
        new_payload = jnp.where(
            from_pool[:, None], pool.payload[ppidx], box_payload[bidx]
        )
        new_pool = EventPool(
            time=s_time[:C],
            dst=all_dst[keep],
            src=all_src[keep],
            seq=all_seq[keep],
            kind=all_kind[keep],
            payload=new_payload,
        )
        # Speculation-violation signal for the optimistic synchronizer: a
        # cross-host emission targeting time t is a violation iff its
        # DESTINATION host already processed an event at time >= t since the
        # synchronizer's window began (host.done_t, reset by run_optimistic
        # per window) — the delivery should have interleaved before that
        # event. With a conservative window this is impossible
        # (t >= now + min_latency >= window end > every processed time), so
        # xmit_min stays NEVER there.
        cross = (outbox.dst != hosts[:, None]) & (outbox.time != NEVER)
        dst_last = state.host.done_t[jnp.clip(outbox.dst, 0, H - 1)]
        violates = cross & (outbox.time <= dst_last)
        xmit_min = jnp.min(jnp.where(violates, outbox.time, NEVER))
        state = state.replace(
            pool=new_pool,
            xmit_min=xmit_min,
            counters=state.counters.replace(
                pool_overflow_dropped=state.counters.pool_overflow_dropped + dropped
            ),
        )
        min_next = jnp.min(new_pool.time)
        return state, min_next

    return step


# ---------------------------------------------------------------------------
# Simulation driver (controller/manager analog)
# ---------------------------------------------------------------------------


class Simulation:
    """Owns the built state + jitted kernels and plays the round loop.

    Construct via shadow_tpu.sim.build_simulation (from a Config) or directly
    with prebuilt pieces for tests.
    """

    def __init__(
        self,
        *,
        num_hosts: int,
        handlers: dict[int, Handler],
        params: NetParams,
        host_vertex: np.ndarray,
        seed: int,
        stop_time: int,
        runahead: int,
        event_capacity: int = 1 << 14,
        K: int = 32,
        B: int = 8,
        O: int = 64,
        subs: dict | None = None,
        initial_events: list[tuple[int, int, int, int, list[int]]] | None = None,
    ):
        # initial_events: (time, dst, src, kind, payload words)
        self.num_hosts = num_hosts
        self.stop_time = int(stop_time)
        self.runahead = int(runahead)
        if self.runahead <= 0:
            raise ValueError("runahead must be > 0 (min topology latency)")
        self.params = params
        pool = EventPool.empty(event_capacity)
        n0 = len(initial_events or [])
        if n0 > event_capacity:
            raise ValueError("initial events exceed event pool capacity")
        if initial_events:
            # Assign per-source sequence numbers in list order, like the
            # reference assigns per-source event IDs at push time.
            seq_ctr: dict[int, int] = {}
            times, dsts, srcs, seqs, kinds_, pls = [], [], [], [], [], []
            for (t, d, s, k, pl) in initial_events:
                q = seq_ctr.get(s, 0)
                seq_ctr[s] = q + 1
                times.append(t)
                dsts.append(d)
                srcs.append(s)
                seqs.append(q)
                kinds_.append(k)
                row = list(pl) + [0] * (PAYLOAD_WORDS - len(pl))
                pls.append(row[:PAYLOAD_WORDS])
            sl = slice(0, n0)
            pool = pool.replace(
                time=pool.time.at[sl].set(jnp.asarray(times, jnp.int64)),
                dst=pool.dst.at[sl].set(jnp.asarray(dsts, jnp.int32)),
                src=pool.src.at[sl].set(jnp.asarray(srcs, jnp.int32)),
                seq=pool.seq.at[sl].set(jnp.asarray(seqs, jnp.int32)),
                kind=pool.kind.at[sl].set(jnp.asarray(kinds_, jnp.int32)),
                payload=pool.payload.at[sl].set(jnp.asarray(pls, jnp.int32)),
            )
            seq_init = np.zeros(num_hosts, dtype=np.int32)
            for s, q in seq_ctr.items():
                seq_init[s] = q
        else:
            seq_init = np.zeros(num_hosts, dtype=np.int32)

        self.handlers = handlers
        self.K, self.B, self.O = K, B, O
        host = make_host_state(num_hosts, host_vertex)
        host = host.replace(seq_next=jnp.asarray(seq_init))
        self.state = SimState(
            now=jnp.int64(0),
            pool=pool,
            host=host,
            counters=Counters.zeros(),
            rng_keys=rng_mod.host_keys(seed, num_hosts),
            subs=subs or {},
        )
        step = make_window_step(handlers, num_hosts, K=K, B=B, O=O)
        self._step = jax.jit(step)
        self._run_to = jax.jit(self._make_run_to(step))
        self._attempt = jax.jit(self._make_attempt(step))

    def _make_run_to(self, step):
        runahead = jnp.int64(self.runahead)

        def run_to(state: SimState, params: NetParams, stop, max_windows):
            """Advance up to max_windows windows (or until stop). Bounding
            the on-device while_loop keeps each dispatch short — long single
            dispatches can trip accelerator-runtime watchdogs."""
            stop = jnp.asarray(stop, jnp.int64)
            max_windows = jnp.asarray(max_windows, jnp.int32)

            def cond(c):
                state, mn, w = c
                return (mn < stop) & (w < max_windows)

            def body(c):
                state, mn, w = c
                ws = mn
                we = jnp.minimum(ws + runahead, stop)
                state, mn = step(state, params, ws, we)
                return state, mn, w + 1

            mn0 = jnp.min(state.pool.time)
            state, mn, _ = jax.lax.while_loop(
                cond, body, (state, mn0, jnp.int32(0))
            )
            return state, mn

        return run_to

    # -- host-driven round loop (one device sync per window; debuggable) --
    def run_stepwise(self, until: int | None = None) -> int:
        stop = self.stop_time if until is None else min(until, self.stop_time)
        windows = 0
        min_next = int(jnp.min(self.state.pool.time))
        while min_next < stop:
            ws = min_next
            we = min(ws + self.runahead, stop)
            self.state, mn = self._step(self.state, self.params, ws, we)
            min_next = int(mn)
            windows += 1
        return windows

    def _make_attempt(self, step):
        def attempt(state: SimState, params: NetParams, ws, we):
            """Process the window [ws, we) to completion ON DEVICE: sub-step
            until no pool events remain below we, or a speculation violation
            surfaces (state.xmit_min != NEVER). One dispatch per attempt."""
            ws = jnp.asarray(ws, jnp.int64)
            we = jnp.asarray(we, jnp.int64)

            def cond(c):
                _, mn, v = c
                return (mn < we) & (v == simtime.NEVER)

            def body(c):
                st, mn, _ = c
                st2, mn2 = step(st, params, jnp.maximum(mn, ws), we)
                return st2, mn2, st2.xmit_min

            mn0 = jnp.min(state.pool.time)
            return jax.lax.while_loop(
                cond, body, (state, mn0, jnp.asarray(simtime.NEVER, jnp.int64))
            )

        return attempt

    # -- optimistic synchronization: speculate long windows, roll back on
    # violation (SURVEY §7.6). Pure-array state makes rollback free: the
    # pre-window state is just the previous pytree. --
    def run_optimistic(
        self,
        until: int | None = None,
        window_factor: int = 8,
    ) -> tuple[int, int]:
        """Advance with speculative windows of window_factor × runahead.

        A window [ws, we) is processed to completion by repeated sub-steps
        (each processes all pool events < we in per-host key order; newly
        generated cross-host deliveries inside the window are picked up by
        the following sub-step). `host.done_t` tracks each host's processed
        progress across sub-steps; a sub-step reports a violation
        (state.xmit_min < NEVER) when it emitted a delivery behind its
        destination's progress clock. On violation the WHOLE window rolls
        back to the snapshot (pure arrays — rollback is just dropping the
        speculated pytree) and retries with the window shrunk to the
        violation time, never below the conservative runahead, which is
        violation-free by construction (emission time >= now + min_latency
        >= ws + runahead >= any processed time).

        Returns (windows_committed, rollbacks). Produces the conservative
        schedule's results; wins when the pool holds work spanning many
        runaheads (fewer barriers/dispatches per simulated second).
        """
        stop = self.stop_time if until is None else min(until, self.stop_time)
        cons = self.runahead
        windows = rollbacks = 0
        neg1 = jnp.full((self.num_hosts,), -1, dtype=jnp.int64)
        self.state = self.state.replace(
            host=self.state.host.replace(done_t=neg1)
        )
        min_next = int(jnp.min(self.state.pool.time))
        while min_next < stop:
            ws = min_next
            we = min(ws + window_factor * cons, stop)
            base = self.state  # rollback snapshot (done_t already reset)
            while True:  # attempt [ws, we) in ONE dispatch; shrink on violation
                st, mn, viol = self._attempt(base, self.params, ws, we)
                viol = int(viol)
                if viol >= int(simtime.NEVER) or we <= ws + cons:
                    break
                rollbacks += 1
                we = max(viol, ws + cons)
            self.state = st.replace(host=st.host.replace(done_t=neg1))
            min_next = int(mn)
            windows += 1
        return windows, rollbacks

    # -- fused run: windows execute in on-device while_loop chunks --
    def run(
        self, until: int | None = None, windows_per_dispatch: int = 64
    ) -> None:
        stop = self.stop_time if until is None else min(until, self.stop_time)
        while True:
            self.state, mn = self._run_to(
                self.state, self.params, stop, windows_per_dispatch
            )
            if int(mn) >= stop:
                break

    def counters(self) -> dict[str, int]:
        c = jax.device_get(self.state.counters)
        return {k: int(v) for k, v in c.__dict__.items()}

    def save_checkpoint(self, path: str) -> None:
        """Snapshot the full device state to disk (resume is bit-exact)."""
        from shadow_tpu.core import checkpoint

        checkpoint.save(self, path)

    def load_checkpoint(self, path: str) -> None:
        """Restore state saved by save_checkpoint; this Simulation must be
        built from the same config."""
        from shadow_tpu.core import checkpoint

        checkpoint.restore(self, path)

    def host_trackers(self) -> dict[str, "np.ndarray"]:
        """Per-host byte/packet counters from the device NIC state
        (tracker.c analog); empty if the sim has no network stack."""
        sub = self.state.subs.get("nic")
        if sub is None:
            return {}
        return {
            k: np.asarray(jax.device_get(getattr(sub, k)))
            for k in ("tx_packets", "tx_bytes", "rx_packets", "rx_bytes")
        }
