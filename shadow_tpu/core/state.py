"""Device-side simulation state: struct-of-arrays over fixed capacities.

The reference keeps pointer-rich per-host objects (Host owns interfaces,
router, processes; events live in per-host locked priority queues —
src/main/host/host.c:49-95, scheduler_policy_host_single.c:18-54). The TPU
design inverts this: ALL simulation state is flat arrays indexed by host /
pool-slot / socket, registered as pytrees, and a window step is a pure
function over them.

Capacities are static (compiled into the kernel):
    C  event-pool slots per shard
    K  max events extracted per host per window
    B  self-inbox slots (intra-window self-emitted events, e.g. short timers)
    O  outbox slots per host per window (emissions buffered until merge)
    P  payload words per event (packet header fields)
Overflow never corrupts the sim: inbox/outbox pressure DEFERS work to later
windows (backpressure stalls the host, nothing is lost); only event-pool
capacity overflow drops, and that is counted in `Counters` and asserted
zero by the benchmarks.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp
import numpy as np
from flax import struct

from shadow_tpu.core import simtime

# Payload words per event. Layout is defined by shadow_tpu.net.packet.
PAYLOAD_WORDS = 12

# Event kinds. Handlers register against these (engine.HandlerRegistry).
KIND_NONE = 0
KIND_APP_MSG = 1  # app-level message delivery (engine-v1 path, PHOLD)
KIND_APP_TIMER = 2  # app-defined timer
KIND_PKT_DELIVER = 3  # packet arrives at dst host's upstream router
KIND_NIC_REFILL = 4  # token-bucket refill retry (network_interface.c:127-193)
KIND_TCP_TIMER = 5  # TCP retransmit timeout
KIND_PROC_SYSCALL = 6  # CPU-plane syscall completion injection
NUM_KINDS = 7


@struct.dataclass
class EventPool:
    """Pending events, one row per slot; time == NEVER marks a free slot.

    The deterministic total order (event.c:109-152) is the tuple
    (time, dst, src, seq); seq is assigned from the emitting host's counter
    like the reference's per-source event ID.

    Payload words are stored PACKED, two i32 words per i64 column
    (core.soa.pack_words): every payload column rides the engine's window
    sorts as an operand, and packing halves that operand count — the sorts
    are the dominant window cost at netstack shapes (profiled on v5e).
    Handlers always see the unpacked [H, P] i32 view via EventView.
    """

    time: jnp.ndarray  # [C] i64 ns
    dst: jnp.ndarray  # [C] i32 global host index
    src: jnp.ndarray  # [C] i32
    seq: jnp.ndarray  # [C] i32
    kind: jnp.ndarray  # [C] i32
    payload: jnp.ndarray  # [C, ceil(P/2)] i64 PACKED (soa.pack_words)

    @classmethod
    def empty(cls, capacity: int,
              payload_words: int = PAYLOAD_WORDS) -> "EventPool":
        # payload_words is sizable per simulation: network sims need the
        # full packet-header layout (12 words, net/packet.py); pure-PDES
        # models like PHOLD carry 2 — payload columns are a dominant
        # per-window sort cost on TPU, so right-sizing is a direct speedup.
        from shadow_tpu.core import soa

        return cls(
            time=jnp.full((capacity,), simtime.NEVER, dtype=jnp.int64),
            dst=jnp.zeros((capacity,), dtype=jnp.int32),
            src=jnp.zeros((capacity,), dtype=jnp.int32),
            seq=jnp.zeros((capacity,), dtype=jnp.int32),
            kind=jnp.zeros((capacity,), dtype=jnp.int32),
            payload=jnp.zeros(
                (capacity, soa.packed_words(payload_words)), dtype=jnp.int64
            ),
        )

    @property
    def capacity(self) -> int:
        return self.time.shape[0]


@struct.dataclass
class Counters:
    """Device-side observability counters (reference: tracker.c, counter.rs).

    All [()] i64 scalars summed across the mesh at fetch time.
    """

    events_committed: jnp.ndarray
    events_emitted: jnp.ndarray
    packets_sent: jnp.ndarray
    packets_delivered: jnp.ndarray
    packets_dropped_loss: jnp.ndarray  # reliability roll failures (worker.c:539)
    packets_dropped_unreachable: jnp.ndarray
    pool_overflow_dropped: jnp.ndarray
    outbox_overflow_dropped: jnp.ndarray  # structurally 0 (backpressure)
    inbox_overflow_deferred: jnp.ndarray
    # iterations a host sat out because its outbox couldn't absorb one
    # iteration's worst-case emissions; the work defers, never drops
    outbox_stall_deferred: jnp.ndarray
    # engine-loop iterations executed (profiling: events_committed /
    # (micro_steps * H) = lane utilization; the per-iteration fixed cost
    # of the full handler suite is the throughput ceiling)
    micro_steps: jnp.ndarray
    bytes_sent: jnp.ndarray
    bytes_delivered: jnp.ndarray
    # matrix-path safety: count of bulk-kind emissions that targeted SELF
    # below win_end — forbidden by the bulk contract (engine.make_window_step
    # docstring); nonzero means the fast path may have corrupted event
    # order. Asserted zero by tests; always-on (the check is elementwise).
    bulk_contract_violations: jnp.ndarray
    # total ns of CPU-model execution deferral applied to device events
    # (tracker_addVirtualProcessingDelay analog); 0 when the model is off
    cpu_delay_applied: jnp.ndarray
    # islands engine (parallel/islands.py): cross-shard rows shipped
    # through the all_to_all exchange, and rows that missed the bounded
    # exchange window and deferred (retried next window under the
    # exch_deferred_min window-end clamp — late but never lost)
    exchange_sent: jnp.ndarray
    exchange_deferred: jnp.ndarray

    @classmethod
    def zeros(cls) -> "Counters":
        z = lambda: jnp.zeros((), dtype=jnp.int64)  # noqa: E731
        return cls(**{f.name: z() for f in dataclasses.fields(cls)})


@struct.dataclass
class HostState:
    """Per-host scalars the engine itself needs. [H] arrays."""

    seq_next: jnp.ndarray  # i32: next event sequence number for emissions
    rng_counter: jnp.ndarray  # u32: per-host RNG draw counter
    vertex: jnp.ndarray  # i32: used-vertex index in the baked topology
    # GLOBAL host id of each local row. On the global engine this is
    # arange(H); on the islands engine each shard holds the contiguous
    # block [shard*H_local, (shard+1)*H_local). Handlers MUST use this —
    # never jnp.arange(H) — wherever a value means "my host id" (packet
    # src fields, loopback compares, self-addressed timer emissions):
    # under islands arange would alias every shard onto shard 0's ids.
    gid: jnp.ndarray  # i32
    # Max event time processed since the optimistic synchronizer last reset
    # it (-1 = none): the per-host progress clock that speculation
    # violations are judged against. Unused by conservative runs.
    done_t: jnp.ndarray  # i64
    # Device-plane CPU model (host/cpu.c analog, deterministic form):
    # cpu_cost = simulated processing nanoseconds per event (0 = off);
    # cpu_avail = the host CPU's next-free time (timeCPUAvailable). An
    # event at t executes at max(t, cpu_avail) and advances cpu_avail by
    # cpu_cost — a loaded host's events serialize on its virtual CPU.
    cpu_cost: jnp.ndarray  # i64
    cpu_avail: jnp.ndarray  # i64


@struct.dataclass
class NetParams:
    """Immutable baked network model (broadcast to all shards)."""

    latency_vv: jnp.ndarray  # [U, U] i64 ns; NEVER = unreachable
    reliability_vv: jnp.ndarray  # [U, U] f32
    bootstrap_end: jnp.ndarray  # [] i64: no drops before this time
    # (configuration.rs:149-152, worker.c:536-545)
    # GLOBAL host→vertex table, replicated to every shard. Destination
    # host ids are global, so by-dst latency lookups under the islands
    # engine must not index the shard-local host.vertex rows. None on
    # single-vertex topologies (every lookup broadcasts) and legacy tests.
    vertex_g: jnp.ndarray | None = None
    # Islands re-sharding (scheduler_policy_host_steal.c analog): global
    # host id → SLOT in the permuted island layout (shard = slot // H_l,
    # local row = slot % H_l). None = static contiguous blocks (slot is
    # the identity, routing is pure arithmetic). A rebalance permutes host
    # rows across shards and rewrites this table — params are runtime
    # arguments, so no recompilation.
    slot_of: jnp.ndarray | None = None


@struct.dataclass
class SimState:
    """Everything a window step reads and writes."""

    now: jnp.ndarray  # [] i64: current window start
    pool: EventPool
    host: HostState
    counters: Counters
    rng_keys: jnp.ndarray  # [H] per-host PRNG key array (core.rng.host_keys)
    # Earliest cross-host emission time of the LAST window stepped (NEVER if
    # none). The optimistic synchronizer compares it against the window end
    # to detect speculation violations (SURVEY §7.6); conservative windows
    # satisfy xmit_min >= window end by construction.
    xmit_min: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.asarray(simtime.NEVER, jnp.int64)
    )
    # Islands engine: min event time among cross-shard rows that missed the
    # bounded exchange this window (NEVER if none). The driver clamps the
    # next window's END to this so the destination shard cannot process
    # past an in-transit event — the conservative invariant survives
    # exchange backpressure (see parallel/islands.py). Always NEVER on the
    # global engine.
    exch_deferred_min: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.asarray(simtime.NEVER, jnp.int64)
    )
    # Subsystem states keyed by name ("nic", "udp", "tcp", app models...).
    # A plain dict is a pytree node; handlers look up their own slice.
    subs: dict[str, Any] = struct.field(default_factory=dict)
    # Device telemetry counter block (shadow_tpu.obs.counters.ObsBlock):
    # window-plane counters + per-host committed-event/virtual-time rows,
    # updated inside the jitted step with fused adds and read only at
    # handoff boundaries. None compiles every update out (the bench's
    # obs-overhead control arm; experimental.obs_counters).
    obs: Any = None
    # Flight recorder (shadow_tpu.obs.flight.FlightRing): opt-in per-host
    # ring of the last R committed event records, written in-kernel by
    # masked one-hot updates and flushed to a binary spool at handoff
    # boundaries (experimental.flight_recorder). Rides the pytree like
    # obs: rollbacks discard speculated records, checkpoints capture the
    # ring, the fleet stacks it per lane. None compiles it out.
    flight: Any = None

    def with_sub(self, key: str, value) -> "SimState":
        """Functional sub-state update (dict copy; the pytree structure is
        unchanged so jit caches stay valid)."""
        subs = dict(self.subs)
        subs[key] = value
        return self.replace(subs=subs)


# ---------------------------------------------------------------------------
# Fleet batch axis (shadow_tpu/fleet): F independent jobs stacked along a
# NEW leading axis over every state/params leaf. The window kernel is
# vmapped over it — per-job halt comes from per-lane (runahead, stop)
# window bounds, so a finished job's lane freezes (its fused-loop cond is
# false) without mutating any other lane. These helpers are the only
# sanctioned way to build/read/replace a lane: they preserve pytree
# structure exactly, so the compiled fleet kernel never retraces on a
# lane swap.
# ---------------------------------------------------------------------------


def stack_pytrees(trees: list):
    """Stack identically-structured pytrees along a new leading axis.
    Leaf shape/dtype mismatches raise with the offending key path (the
    fleet's job-compatibility error surface)."""
    import jax

    flat0, treedef = jax.tree_util.tree_flatten_with_path(trees[0])
    cols = [[leaf for _, leaf in flat0]]
    for t in trees[1:]:
        flat, td = jax.tree_util.tree_flatten_with_path(t)
        if td != treedef:
            raise ValueError(
                "fleet jobs carry different state structures (subsystem "
                "or telemetry config differs); jobs sharing one kernel "
                "must be built from compatible configs"
            )
        for (path, a), b in zip(flat0, (leaf for _, leaf in flat)):
            ja, jb = jnp.asarray(a), jnp.asarray(b)
            if ja.shape != jb.shape or ja.dtype != jb.dtype:
                raise ValueError(
                    f"fleet leaf {jax.tree_util.keystr(path)}: "
                    f"{jb.shape}/{jb.dtype} vs template {ja.shape}/"
                    f"{ja.dtype} — jobs sharing one kernel must compile "
                    f"identical shapes"
                )
        cols.append([leaf for _, leaf in flat])
    stacked = [jnp.stack(col) for col in zip(*cols)]
    return jax.tree_util.tree_unflatten(treedef, stacked)


def slice_lane(tree, lane: int):
    """Read one job's slice out of a stacked fleet pytree (device-side
    views; the solo layout the lane was admitted with)."""
    import jax

    return jax.tree.map(lambda x: x[lane], tree)


def set_lane(tree, lane: int, solo):
    """Replace lane `lane` of a stacked fleet pytree with a solo-layout
    pytree (the lane-swap write). Structure must match the stack."""
    import jax

    return jax.tree.map(lambda s, n: s.at[lane].set(n), tree, solo)


def make_host_state(
    num_hosts: int, host_vertex: np.ndarray, cpu_cost: np.ndarray | None = None
) -> HostState:
    return HostState(
        seq_next=jnp.zeros((num_hosts,), dtype=jnp.int32),
        rng_counter=jnp.zeros((num_hosts,), dtype=jnp.uint32),
        vertex=jnp.asarray(host_vertex, dtype=jnp.int32),
        gid=jnp.arange(num_hosts, dtype=jnp.int32),
        done_t=jnp.full((num_hosts,), -1, dtype=jnp.int64),
        cpu_cost=(
            jnp.asarray(cpu_cost, dtype=jnp.int64)
            if cpu_cost is not None
            else jnp.zeros((num_hosts,), dtype=jnp.int64)
        ),
        cpu_avail=jnp.zeros((num_hosts,), dtype=jnp.int64),
    )
