"""Resource-pressure survival plane: degrade deterministically, never die.

Shadow's single worst production failure mode is resource exhaustion —
large runs die to OOM kills and event-queue saturation. The TPU port
inherits both flavors:

  * BACKEND pressure: XLA raises ``RESOURCE_EXHAUSTED`` when a dispatch
    cannot allocate its HBM working set (pool + dense window + sort
    temporaries);
  * POOL pressure: the event pool leaves too little merge headroom for
    even one window's inflow and the drivers stall — which, before this
    module, surfaced as a bare ``RuntimeError`` mid-run.

PR 6/PR 8 made backend *loss* survivable (drain → checkpoint → resume);
this module does the same for backend *pressure*. Both signals feed one
policy-driven degradation ladder executed at dispatch boundaries, where
every action is a host-side reshape of machinery that is already proven
bit-exact (gearbox re-sorts, spill-tier parking, fleet lane swaps), so a
degraded run commits the identical event schedule — the audit digest
chain (obs/audit.py) is the proof instrument:

  memory ladder (XLA ``RESOURCE_EXHAUSTED`` at a supervised dispatch):
    1. forced gear DOWNSHIFT — override the red-zone upshift rule: a
       smaller pool kernel needs less device memory; overflow rows park
       on the host spill tier (order-preserving) instead of the device.
       The gear holds down (``hold_gear``) until pressure clears.
    2. spill-tier ESCALATION — shrink the spill fill mark one notch per
       rung (``fill_shrink``), trading device residency for host memory.
    3. fleet lane EVICTION — requeue the heaviest running job
       (``FleetScheduler.requeue``); the freed lane shrinks the resident
       working set and admission holds until pressure clears.
    4. drain-to-checkpoint + the --on-backend-loss policy (the
       supervisor's existing wait/cpu/abort machinery).

  pool ladder (driver headroom stall):
    1. forced UPSHIFT when a bigger gear exists (and no memory hold
       pins the gear down).
    2. injected-saturation YIELD — ``saturate_pool`` pressure responds
       to the ladder like ``exhaust_backend``'s recover_after contract:
       each rung the spill tier absorbs relieves the simulated external
       pressure one notch (frac doubles toward 1.0).
    3. force one spill EPISODE (the stall may predate any red-zone
       crossing: occupancy under the mark can still leave too little
       merge headroom for a whole window's inflow).
    4. give up: drain-to-checkpoint, then raise the *typed*
       ``PoolExhausted`` (resume with --resume at a reshaped config).

Deterministic testing rides the fault plane (shadow_tpu/faults):
``exhaust_backend {at, recover_after}`` injects classified OOM failures
into supervised dispatches; ``saturate_pool {at, frac}`` scales the
spill marks. Both execute at virtual-time-keyed handoff boundaries, so
the chaos matrix (tests/test_pressure.py, bench.py --pressure-smoke)
asserts post-degradation digest chains bit-identical to uninterrupted
runs on CPU.

This is a HOST module: nothing here is ever traced into a kernel, and
every ladder action happens at a dispatch boundary with the state
synced (shadowlint classifies it host; tests/test_analysis.py pins it).
"""

from __future__ import annotations

import os


class PoolExhausted(RuntimeError):
    """The event pool cannot make progress and the pressure ladder is
    exhausted (or disabled). Carries the stall diagnostics so callers —
    and operators reading the message — know the shape that failed:
    ``window`` (the frozen virtual-time frontier, ns), ``occupancy``
    (live pool rows at the stall) and ``capacity`` (the active gear's
    pool rows). Classified RESOURCE_EXHAUSTED by the supervisor."""

    def __init__(self, message: str, *, window: int | None = None,
                 occupancy: int | None = None,
                 capacity: int | None = None):
        super().__init__(message)
        self.window = window
        self.occupancy = occupancy
        self.capacity = capacity


# ---------------------------------------------------------------------------
# HBM budget estimator
# ---------------------------------------------------------------------------
#
# The window kernel's peak working set is the live state plus the sort
# temporaries: XLA's multi-operand stable sorts materialize an output copy
# of every operand, and the dense-window extraction concatenates pool +
# filler rows before sorting, so the transient peak is a small multiple of
# the pool + dense bytes. The factor below is deliberately conservative
# (an over-estimate sheds a sweep the device could maybe have served; an
# under-estimate OOMs it mid-run).

SORT_TEMP_FACTOR = 2

# bytes per event row: time i64 + dst/src/seq/kind i32 + payload i64 cols
_EVENT_FIXED_BYTES = 8 + 4 * 4


def _row_bytes(payload_cols: int) -> int:
    return _EVENT_FIXED_BYTES + 8 * int(payload_cols)


def tree_bytes(tree) -> int:
    """Total array bytes of a pytree (the avals ARE the state leaves)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        dtype = getattr(leaf, "dtype", None)
        if dtype is not None:
            total += int(leaf.size) * int(dtype.itemsize)
    return total


def estimate_hbm_bytes(sim, level: int | None = None) -> dict:
    """Estimate the device-memory footprint of `sim` at gear `level`
    (default: the active gear): resident state + params plus the kernel's
    sort/dense temporaries, sized from the state avals.

    Works for Simulation, IslandSimulation and FleetSimulation — the
    leading lane/shard axes are already part of the state leaves' shapes.
    Returns a breakdown dict; ``total_bytes`` is the admission signal.
    """
    ladder = getattr(sim, "_gear_ladder", None) or getattr(sim, "_ladder", None)
    gear = getattr(sim, "_gear", 0)
    spec = None
    if ladder:
        spec = ladder[gear if level is None else level]
    state_b = tree_bytes(sim.state)
    params_b = tree_bytes(getattr(sim, "params", None))
    pool_b = tree_bytes(sim.state.pool)
    # rows per pool = the trailing axis (leading dims are lanes/shards;
    # gear capacities are per-shard, matching)
    cur_rows = int(sim.state.pool.time.shape[-1])
    if spec is not None and cur_rows:
        # rescale the pool component to the target gear's capacity
        pool_at = pool_b * spec.capacity // max(1, cur_rows)
    else:
        pool_at = pool_b
    # dense window matrix: one (K+1)-wide row block per host row (lanes
    # and shards ride the host leaf's leading dims, counted via gid.size)
    host_rows = int(sim.state.host.gid.size)
    K = spec.K if spec is not None else getattr(sim, "K", 32)
    PP = int(sim.state.pool.payload.shape[-1])
    dense_b = host_rows * (K + 1) * _row_bytes(PP)
    temp_b = SORT_TEMP_FACTOR * (pool_at + dense_b)
    total = state_b + params_b + (pool_at - pool_b) + dense_b + temp_b
    return {
        "state_bytes": int(state_b),
        "params_bytes": int(params_b),
        "pool_bytes": int(pool_at),
        "dense_bytes": int(dense_b),
        "temp_bytes": int(temp_b),
        "total_bytes": int(total),
        "gear_level": int(spec.level if spec is not None else gear),
    }


def estimate_config_bytes(cfg, lanes: int = 1) -> int:
    """Preflight footprint of a run described only by its Config — the
    serve daemon's admission estimator (no device state exists yet, so
    this sizes the avals analytically from the kernel-shaping fields):

        lanes x (pool + dense + host block + sort temporaries)

    Deliberately coarse and conservative; documented in docs/serving.md.
    """
    H = sum(int(getattr(h, "quantity", 1)) for h in cfg.hosts)
    exp = cfg.experimental
    C = int(getattr(exp, "event_capacity", 1 << 14))
    K = int(getattr(exp, "events_per_host_per_window", 32))
    O = int(getattr(exp, "outbox_slots", 64))
    B = int(getattr(exp, "inbox_slots", 8))
    PP = 2  # packed payload columns at the default 4 payload words
    row = _row_bytes(PP)
    pool_b = C * row
    dense_b = H * (K + 1) * row
    box_b = H * (O + B) * row
    # per-host SoA block (HostState + net subs): a generous flat estimate
    host_b = H * 256
    per_lane = pool_b + dense_b + box_b + host_b \
        + SORT_TEMP_FACTOR * (pool_b + dense_b)
    return int(max(1, lanes) * per_lane)


def device_memory_budget() -> int | None:
    """The accelerator's usable memory in bytes, or None when unknown
    (CPU backends report no limit — admission is then unbounded).
    ``SHADOW_TPU_HBM_BUDGET`` overrides for tests and capped deployments.
    """
    env = os.environ.get("SHADOW_TPU_HBM_BUDGET")
    if env:
        try:
            return max(0, int(env))
        except ValueError:
            return None
    try:
        import jax

        stats = jax.devices()[0].memory_stats() or {}
    except Exception:
        return None
    limit = stats.get("bytes_limit")
    return int(limit) if limit else None


def headroom_bytes(estimated: int, budget: int | None = None) -> int | None:
    """Live headroom gauge: budget − estimate (None when no budget)."""
    if budget is None:
        budget = device_memory_budget()
    if budget is None:
        return None
    return int(budget) - int(estimated)


def overflow_advice(sim, dropped: int) -> tuple[str, dict]:
    """Actionable sizing advice for a run that ended with
    ``pool_overflow_dropped > 0`` (the __main__ end-of-run warning):
    suggest a capacity that would have absorbed the overflow, and gearing
    when the build ran a single fixed tier."""
    ladder = getattr(sim, "_gear_ladder", None) or getattr(sim, "_ladder", None)
    cap = ladder[-1].capacity if ladder else int(sim.state.pool.capacity)
    need = cap + int(dropped) + cap // 2
    suggested = 1
    while suggested < need:
        suggested <<= 1
    advice = {
        "suggested_event_capacity": int(suggested),
        "suggested_pool_gears": max(2, int(getattr(sim, "pool_gears", 1))),
    }
    msg = (
        f"raise experimental.event_capacity to ~{suggested} "
        f"(top tier was {cap})"
    )
    if getattr(sim, "pool_gears", 1) <= 1:
        msg += (
            "; or run with experimental.pool_gears >= 2 so the red-zone "
            "upshift absorbs the burst before the merge drops"
        )
    return msg, advice


# ---------------------------------------------------------------------------
# the degradation ladder
# ---------------------------------------------------------------------------


class PressurePolicy:
    """Knobs for the degradation ladder (docs/fault_tolerance.md §5)."""

    def __init__(
        self,
        enabled: bool = True,
        allow_downshift: bool = True,
        allow_spill_escalation: bool = True,
        allow_lane_eviction: bool = True,
        max_fill_shrink: int = 3,
        recover_after_dispatches: int = 8,
        eviction_hold_dispatches: int = 4,
    ):
        self.enabled = bool(enabled)
        self.allow_downshift = bool(allow_downshift)
        self.allow_spill_escalation = bool(allow_spill_escalation)
        self.allow_lane_eviction = bool(allow_lane_eviction)
        self.max_fill_shrink = int(max_fill_shrink)
        self.recover_after_dispatches = max(1, int(recover_after_dispatches))
        self.eviction_hold_dispatches = max(1, int(eviction_hold_dispatches))


class PressureController:
    """Per-run ladder state + the ``pressure.*`` metrics namespace
    (schema v8). One per sim, attached lazily by the drivers on the
    first pressure signal (``Simulation._pressure()``) or explicitly via
    ``attach_pressure`` for a custom policy.

    The controller is pure host bookkeeping: the bound sim executes the
    actual reshapes through its ``_pressure_relieve_pool`` /
    ``_pressure_relieve_memory`` hooks, which return the action name
    taken (counted here) or None when their ladder is exhausted.
    Determinism: every action depends only on sim state and dispatch
    counts — never wall time.
    """

    def __init__(self, policy: PressurePolicy | None = None):
        self.policy = policy or PressurePolicy()
        # ladder posture (consulted by Simulation._spill_marks / _gear_tick)
        self.fill_shrink = 0  # spill fill mark halves per notch
        self.saturate_frac: float | None = None  # injected saturation
        self.hold_gear = False  # forced-downshift hold: no upshifts
        self._stall_steps = 0  # rungs taken since the last progress note
        self._clean = 0  # clean dispatches toward relaxation
        self.counters = {
            "pool_exhausted": 0,
            "backend_exhausted": 0,
            "ladder_steps": 0,
            "downshifts": 0,
            "upshifts": 0,
            "spill_escalations": 0,
            "lane_evictions": 0,
            "job_sheds": 0,
            "saturations": 0,
            "saturation_yields": 0,
            "relaxations": 0,
            "gave_up": 0,
        }

    # -- mark scaling (the one hook on the driver hot path; both scalings
    # are identity until a pressure event actually set them) --

    def scaled_marks(self, hi: int, fill: int) -> tuple[int, int]:
        if self.saturate_frac is not None:
            hi = max(1, int(hi * self.saturate_frac))
            fill = max(1, int(fill * self.saturate_frac))
        if self.fill_shrink:
            fill = max(1, fill >> self.fill_shrink)
        return hi, min(fill, hi)

    # -- signals --

    def saturate(self, frac: float) -> None:
        """Injected pool saturation (the ``saturate_pool`` fault op):
        scale the spill marks by `frac` from now on."""
        self.counters["saturations"] += 1
        self.saturate_frac = max(0.001, min(1.0, float(frac)))

    def on_pool_exhausted(self, sim, *, window=None, occupancy=None,
                          capacity=None) -> bool:
        """One pool-ladder consultation at a driver stall. True = a rung
        was taken and the driver should retry its loop; False = ladder
        exhausted (the driver drains and raises the typed error)."""
        self.counters["pool_exhausted"] += 1
        self._clean = 0
        if not self.policy.enabled:
            self.counters["gave_up"] += 1
            return False
        step = self._stall_steps
        act = sim._pressure_relieve_pool(step)
        if act is None and self.saturate_frac is not None \
                and self.saturate_frac < 1.0:
            # injected saturation yields a notch per absorbed rung —
            # the exhaust_backend recover_after contract, pool-side
            self.saturate_frac = min(1.0, self.saturate_frac * 2)
            act = "saturation_yield"
        if act is not None:
            self._stall_steps += 1
            self.counters["ladder_steps"] += 1
            self.counters[_ACTION_COUNTER[act]] += 1
            return True
        self.counters["gave_up"] += 1
        return False

    def on_backend_exhausted(self, sim, label: str = "") -> bool:
        """One memory-ladder consultation for a classified
        RESOURCE_EXHAUSTED dispatch failure (called by the supervisor).
        True = retry the dispatch; False = escalate to drain + policy."""
        self.counters["backend_exhausted"] += 1
        self._clean = 0
        if not self.policy.enabled:
            self.counters["gave_up"] += 1
            return False
        act = sim._pressure_relieve_memory(self._stall_steps)
        if act is not None:
            self._stall_steps += 1
            self.counters["ladder_steps"] += 1
            self.counters[_ACTION_COUNTER[act]] += 1
            return True
        self.counters["gave_up"] += 1
        return False

    def note_progress(self) -> None:
        """The driver observed forward progress: the current posture is
        sufficient. After `recover_after_dispatches` clean dispatches,
        relax ONE notch (shrink before gear hold — mirror of the ladder
        order) — the same hysteresis shape as GearShifter.down_after."""
        self._stall_steps = 0
        self._clean += 1
        if self._clean < self.policy.recover_after_dispatches:
            return
        self._clean = 0
        if self.fill_shrink > 0:
            self.fill_shrink -= 1
            self.counters["relaxations"] += 1
        elif self.hold_gear:
            self.hold_gear = False
            self.counters["relaxations"] += 1

    # -- telemetry --

    def stats(self) -> dict:
        """The ``pressure.*`` counters (schema v8). Integer-only — the
        float/None posture gauges ride `gauges()`."""
        d = dict(self.counters)
        d["fill_shrink"] = int(self.fill_shrink)
        d["hold_gear"] = int(self.hold_gear)
        return d

    def gauges(self) -> dict:
        return {
            "saturate_frac": (
                float(self.saturate_frac)
                if self.saturate_frac is not None else 1.0
            ),
        }


_ACTION_COUNTER = {
    "downshift": "downshifts",
    "upshift": "upshifts",
    "spill_escalation": "spill_escalations",
    "lane_eviction": "lane_evictions",
    "job_shed": "job_sheds",
    "saturation_yield": "saturation_yields",
}
