"""Two-slot pipelined CPU↔TPU handoff bookkeeping.

Every driver loop used to run the host-side handoff (spill/syscall
drains, fault injections, audit tick, checkpoint ring, scheduler work)
and the next device window strictly serially: dispatch N → block on its
scalar fetches → host drain → dispatch N+1. With jax's asynchronous
dispatch the device is IDLE through the whole host drain — the last
structural stall on the hot path now that the cross-shard barrier is
gone (asynchronous-conservative literature: hiding coordination latency
behind compute is where the remaining wall-clock lives, cs/0409032;
PARSIR's per-worker pipelining, arXiv:2410.00644).

The pipelined loop double-buffers instead: right after awaiting window
N's scalars (the committed frontier), the driver ISSUES window N+1
speculatively — jax enqueues it and returns futures — then performs
window N's host drain while the device computes. The host synchronizes
only at the next fetch point. Correctness is the serial schedule's,
enforced by two rules:

  * FORCED DRAINS — a handoff with state-mutating work pending (a due
    fault injection, an active spill episode, a checkpoint mark, a
    pressure rung, a balancer migration, an elastic relayout) never
    overlaps: the driver drains the in-flight dispatch first and stays
    serial through that boundary (`forced_drains`).
  * RECOMPUTE, NEVER REUSE — a speculative issue is adopted only if the
    drained handoff left the committed state UNTOUCHED (object identity
    on the pytree the dispatch was issued from) and the recomputed
    dispatch arguments match the predicted ones; otherwise it is
    discarded unobserved and re-issued from the mutated state
    (`recompute_discards`). An adopted dispatch is therefore a pure
    function of exactly the inputs the serial loop would have passed —
    audit chains stay bit-identical by construction.

This module holds only host bookkeeping (the slot, the validation
tokens, and the `pipeline.*` metrics tallies). The dispatch halves
themselves are `core/supervisor.PendingDispatch` tickets, so the retry
ladder, pressure rungs, stall watchdog, and loss policies all operate on
the awaited half without re-serializing the loop.
"""

from __future__ import annotations

import time


def new_stats() -> dict:
    """The `pipeline.*` metrics namespace (schema v14): monotonic host
    tallies of the two-slot pipeline's behavior."""
    return {
        # speculative dispatches issued ahead of the handoff drain
        "issued_ahead": 0,
        # wall ns of host-drain work performed while an (eventually
        # adopted) speculative dispatch was in flight — the hidden latency
        "overlap_ns": 0,
        # handoff boundaries where state-mutating tick work (or a known
        # supervisor disruption) forced the loop to stay serial
        "forced_drains": 0,
        # speculative issues discarded because the drained handoff
        # changed state or the recomputed dispatch args differed — the
        # dispatch was recomputed from the mutated state, never reused
        "recompute_discards": 0,
    }


class TwoSlotPipeline:
    """One speculative dispatch slot plus its validation tokens.

    The driver protocol per handoff boundary:

      1. adopt-or-recompute:  p = pipe.take(state_token, args)
         → the issued-ahead ticket if the committed state is the very
           pytree it was issued from AND the recomputed args match;
           None (after counting a discard) otherwise.
      2. await p (or issue+await fresh when None).
      3. speculate: when the upcoming handoff is quiet, issue N+1 and
         pipe.put(ticket, state_token, args); else pipe.forced_drain().
      4. after the host drain: pipe.invalidate(state_token) discards the
         slot if the drain replaced the committed state after all.
    """

    def __init__(self, stats: dict):
        self.stats = stats
        self._pending = None
        self._token = None
        self._args = None
        self._t_issue = 0.0

    @property
    def pending(self) -> bool:
        return self._pending is not None

    def put(self, pending, token, args) -> None:
        """Record a speculative issue: `token` is the committed state
        pytree the dispatch closes over (identity-compared at take),
        `args` the host-computed dispatch arguments it was issued with."""
        self._pending = pending
        self._token = token
        self._args = args
        self._t_issue = time.perf_counter()
        self.stats["issued_ahead"] += 1

    def take(self, token, args):
        """Adopt the issued-ahead dispatch iff its inputs are exactly
        what the serial loop would pass now; discard + count otherwise."""
        p = self._pending
        if p is None:
            return None
        if token is not self._token or args != self._args:
            self.discard()
            return None
        self._pending = None
        self._token = self._args = None
        self.stats["overlap_ns"] += int(
            (time.perf_counter() - self._t_issue) * 1e9
        )
        return p

    def invalidate(self, token) -> None:
        """Discard the slot when the host drain replaced the committed
        state the speculation was issued from (gear shift, fault drain,
        checkpoint-adjacent mutation, migration, pressure rung)."""
        if self._pending is not None and token is not self._token:
            self.discard()

    def discard(self) -> None:
        """Drop the in-flight speculative dispatch unobserved; the next
        dispatch is recomputed from the (possibly mutated) state."""
        if self._pending is not None:
            self._pending.abandon()
            self._pending = None
            self._token = self._args = None
            self.stats["recompute_discards"] += 1

    def close(self) -> None:
        """Abandon any in-flight speculation WITHOUT counting a discard —
        loop exit and exception unwind (the dispatch was neither adopted
        nor recomputed; it simply never happened)."""
        if self._pending is not None:
            self._pending.abandon()
            self._pending = None
            self._token = self._args = None

    def forced_drain(self) -> None:
        """A state-mutating handoff (or a known supervisor disruption)
        kept this boundary serial: drain any in-flight speculation and
        tally the barrier point."""
        self.discard()
        self.stats["forced_drains"] += 1
