"""Backend supervision: survive accelerator loss instead of aborting.

Three straight bench rounds (BENCH_r03-r05) died to `backend_unavailable`:
a flaky device at probe time aborted the whole run, and a device lost
MID-run lost everything since the last manual checkpoint. This module
converts every driver's hard-abort path into a supervised state machine:

    HEALTHY ──transient error──▶ RETRY (bounded, jittered exp. backoff)
       │                            │ retries exhausted
       │ deadline misses ≥ limit    ▼
       ├──────────▶ SUSPECT ──probe fails──▶ LOST
       │                └─probe ok─▶ HEALTHY
       │ RESOURCE_EXHAUSTED (XLA OOM / PoolExhausted)
       ├──────────▶ PRESSURE ──ladder rung taken──▶ retry dispatch
       │                └─ladder exhausted─▶ LOST (drain + policy);
       │                  the degradation ladder is core/pressure.py:
       │                  forced downshift → spill escalation → fleet
       │                  lane eviction (docs/fault_tolerance.md §5)
       ▼ classified backend loss
      LOST ──▶ DRAIN (flush state to a crash-consistent checkpoint,
       │        audit chain + drain-reason metadata riding the header)
       ▼
     policy `wait`  re-probe loop (jittered backoff) until the backend
                    answers, rebind the compiled kernels, re-dispatch —
                    hot resume, nothing lost;
     policy `cpu`   degraded-mode failover: move state to the CPU
                    backend, re-lower the window kernels there, keep the
                    simulation advancing; opportunistic probes upshift
                    back to the primary when it recovers;
     policy `abort` raise BackendLost AFTER the drain checkpoint — the
                    run dies but `--resume` finishes it bit-exactly;
     policy `relayout`
                    chip-scoped elastic recovery for multi-chip meshes:
                    after the drain, raise ChipLost carrying the dead
                    chip set — the elastic runner (parallel/elastic.py)
                    rebuilds the mesh over the surviving chips, resumes
                    via checkpoint.restore_relayout (audit chain
                    extended exactly), and relayouts back up when the
                    lost chips answer probes again.

Every dispatch goes through `BackendSupervisor.call(label, thunk)`. The
thunk re-reads the driver's bound kernels on each attempt, so a recovery
that rebinds (`sim._rebind_kernels()`) is picked up transparently; the
window step is a pure function of (state, params, window), so
re-executing an interrupted dispatch is always safe.

The deadline watchdog mirrors the bounded-lag stall detection of the
asynchronous conservative protocol (cs/0409032, PAPERS.md): a dispatch
that falls behind its deadline is a SIGNAL to act on (count it, probe the
backend after `stall_limit` consecutive misses), not something to hang
on. Watchdog jitter only perturbs wall-clock scheduling — simulation
results stay bit-identical because recovery replays pure functions, and
the audit digest chain (obs/audit.py) proves it.

Deterministic testing on CPU rides the fault plane (shadow_tpu/faults):
`kill_backend` / `stall_backend` injections fire at handoff boundaries
and drive this state machine without any real device dying
(tests/test_resilience.py chaos matrix, bench.py --resilience-smoke).
"""

from __future__ import annotations

import random
import time

from shadow_tpu.core.pressure import PoolExhausted

# ---------------------------------------------------------------------------
# failure classification
# ---------------------------------------------------------------------------

TRANSIENT = "transient"
BACKEND_LOST = "backend_lost"
RESOURCE_EXHAUSTED = "resource_exhausted"
FATAL = "fatal"

# Substrings (lowercased) that mark a dispatch error as a dead/unreachable
# backend: the PJRT client's UNAVAILABLE family, tunnel/worker drops, and
# the runtime watchdog's own verdicts. Deliberately conservative — an
# unrecognized error stays FATAL and propagates (misclassifying a real bug
# as backend loss would send the supervisor into a pointless drain loop).
_LOST_MARKERS = (
    "unavailable",
    "backend_unavailable",
    "failed to connect",
    "connection reset",
    "connection refused",
    "socket closed",
    "broken pipe",
    "device lost",
    "device or resource busy",
    "initialize backend",
    "core halted",
    "tpu driver",
    "worker exited",
    "heartbeat timeout",
)

# Mesh-collective failure markers: a cross-chip collective (the async
# driver's ppermute frontier exchange, the event-exchange all_to_all, a
# pmin all-reduce) died because ONE participant chip is gone, not the
# whole device set. Checked BEFORE the transient table — several runtimes
# phrase these as "ABORTED: collective ..." and a bounded retry would
# spin forever against the same dead peer — and classified BACKEND_LOST
# (chip-scoped: `chip_scoped` reports which family matched) so the drain
# + policy machinery runs with the surviving chips still healthy.
_CHIP_MARKERS = (
    "ppermute",
    "collective-permute",
    "collective_permute",
    "all-reduce",
    "all_reduce",
    "allreduce",
    "all-gather",
    "all_gather",
    "all-to-all",
    "all_to_all",
    "collective operation",
    "collective timeout",
    "peer failure",
    "peer unreachable",
    "remote device",
    "ici link",
    "dcn link",
    "nccl",
    "participant failed",
)

# Errors worth a bounded in-place retry before escalating: interrupted
# collectives and queue hiccups that a healthy backend shakes off.
_TRANSIENT_MARKERS = (
    "aborted",
    "cancelled",
    "temporarily",
    "try again",
    "retry",
)

# XLA memory-pressure markers: the allocator could not place the dispatch's
# working set. NOT transient (an identical retry re-OOMs identically) and
# NOT a loss (the backend is alive) — the pressure ladder (core/pressure.py)
# reshapes the working set, then the dispatch retries.
_EXHAUSTED_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "failed to allocate",
    "allocation failure",
    "hbm oom",
)


class BackendLost(RuntimeError):
    """The accelerator backend is gone and the active policy cannot (or
    chose not to) recover in-process. The drain checkpoint — when a
    checkpoint directory is configured — was written before this raise."""


class ChipLost(BackendLost):
    """CHIP-SCOPED backend loss under policy `relayout`: one (or a few)
    chips of a multi-chip mesh died, the surviving chips are healthy,
    and the drain checkpoint was written. `chips` is the frozenset of
    lost chip indices (mesh device order); `path` the drain checkpoint
    (None when no checkpoint directory is configured). The elastic
    runner (parallel/elastic.py) catches this, rebuilds the mesh over
    the survivors, and resumes via checkpoint.restore_relayout."""

    def __init__(self, message: str, *, chips=frozenset(),
                 path: str | None = None):
        super().__init__(message)
        self.chips = frozenset(int(c) for c in chips)
        self.path = path


def classify_failure(exc: BaseException) -> str:
    """TRANSIENT (bounded retry), RESOURCE_EXHAUSTED (pressure ladder),
    BACKEND_LOST (drain + policy), or FATAL (re-raise: a real bug, not an
    infrastructure failure). Mesh-collective failures (`chip_scoped`)
    classify BACKEND_LOST — checked before the transient table, so a
    dead ppermute peer is never retried forever."""
    if isinstance(exc, BackendLost):
        return BACKEND_LOST
    if isinstance(exc, PoolExhausted):
        return RESOURCE_EXHAUSTED
    msg = f"{type(exc).__name__}: {exc}".lower()
    for marker in _EXHAUSTED_MARKERS:
        if marker in msg:
            return RESOURCE_EXHAUSTED
    for marker in _CHIP_MARKERS:
        if marker in msg:
            return BACKEND_LOST
    for marker in _TRANSIENT_MARKERS:
        if marker in msg:
            return TRANSIENT
    for marker in _LOST_MARKERS:
        if marker in msg:
            return BACKEND_LOST
    return FATAL


def chip_scoped(exc: BaseException) -> bool:
    """True when `exc` names a mesh-collective failure — loss of ONE
    participant chip, not the whole device set. The relayout policy uses
    this (plus the kill_chip injection's explicit chip set, plus a
    MeshHealth probe sweep) to decide that degrading to the surviving
    mesh is sound where a whole-backend loss would not be."""
    if isinstance(exc, ChipLost):
        return True
    msg = f"{type(exc).__name__}: {exc}".lower()
    return any(marker in msg for marker in _CHIP_MARKERS)


def probe_backend() -> bool:
    """One trivial dispatch against the default backend — the shared
    liveness probe: the supervisor's SUSPECT→LOST check and the serve
    daemon's `/healthz` both use it, so a probe verdict means the same
    thing everywhere. Real deployments that fear a HANGING (not erroring)
    backend should pass a subprocess prober (bench.wait_for_backend is
    one); in-process keeps the library dependency-free."""
    try:
        import jax.numpy as jnp

        jnp.zeros((), jnp.int32).block_until_ready()
        return True
    except Exception:
        return False


_default_probe = probe_backend  # supervisor-internal historical name


# ---------------------------------------------------------------------------
# peer probe state: the SUSPECT→LOST ladder as reusable data
# ---------------------------------------------------------------------------

PEER_HEALTHY = "healthy"
PEER_SUSPECT = "suspect"
PEER_LOST = "lost"

PEER_STATES = (PEER_HEALTHY, PEER_SUSPECT, PEER_LOST)


class ProbeLadder:
    """The bounded-miss health ladder of the supervisor state machine
    (HEALTHY → SUSPECT → LOST, cs/0409032's bounded-lag signal) packaged
    as standalone peer-probe state: the serve federation's router
    (serve/federation.py) runs one ladder per serve daemon, exactly the
    classification discipline BackendSupervisor applies per backend —
    a single missed probe is a SIGNAL (SUSPECT), `lost_after`
    consecutive misses a verdict (LOST), and any success snaps the
    ladder back to HEALTHY (recovery is instant, loss is earned).

    `backoff_s()` is the jittered exponential wait before the NEXT
    probe of a non-healthy peer — the same ±50% decorrelation jitter
    the supervisor applies to its re-probe loop, seeded so tests are
    deterministic. Wall scheduling only; never simulation results.
    """

    def __init__(self, *, lost_after: int = 3,
                 backoff_base_s: float = 0.25,
                 backoff_cap_s: float = 5.0, seed: int = 0):
        self.lost_after = max(1, int(lost_after))
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self._rng = random.Random(seed)
        self.misses = 0
        self.probes = 0
        self.state = PEER_HEALTHY

    def record(self, ok: bool) -> str:
        """Fold one probe verdict; returns the post-probe state."""
        self.probes += 1
        if ok:
            self.misses = 0
            self.state = PEER_HEALTHY
        else:
            self.misses += 1
            self.state = (
                PEER_LOST if self.misses >= self.lost_after
                else PEER_SUSPECT
            )
        return self.state

    def backoff_s(self) -> float:
        """Jittered exponential wait before the next probe, keyed to the
        consecutive-miss count (0 misses → 0: healthy peers are probed
        on the caller's regular cadence)."""
        if self.misses == 0:
            return 0.0
        base = min(
            self.backoff_base_s * (2 ** (self.misses - 1)),
            self.backoff_cap_s,
        )
        return base * (0.5 + self._rng.random())


class PendingDispatch:
    """One device dispatch split into its two halves (the pipelined
    drivers' seam, core/pipeline.py):

      issue_fn()     enqueue the dispatch — returns device FUTURES (jax's
                     async dispatch), never blocks on results;
      fetch_fn(out)  the blocking host reads of those futures (the
                     `int()` / `device_get` scalar fetches).

    `BackendSupervisor.issue` launches the issue half immediately (when
    the backend is believed healthy) and hands back this ticket;
    `await_result` runs the fetch half under the full classified-retry +
    watchdog state machine — a retry re-runs BOTH halves, so recovery
    rebinds and per-attempt clamps behave exactly like the fused
    `call()` thunk did. A ticket also works unsupervised (the drivers'
    zero-overhead default): `direct()` + `await_direct()` reproduce a
    plain thunk call with errors propagating raw."""

    __slots__ = (
        "label", "issue_fn", "fetch_fn", "_out", "_error", "_t0",
        "_live",
    )

    def __init__(self, label: str, issue_fn, fetch_fn):
        self.label = label
        self.issue_fn = issue_fn
        self.fetch_fn = fetch_fn
        self._out = None
        self._error = None
        self._t0 = None
        self._live = False

    @classmethod
    def direct(cls, label: str, issue_fn, fetch_fn) -> "PendingDispatch":
        """Unsupervised ticket: issue now, fetch at await_direct."""
        p = cls(label, issue_fn, fetch_fn)
        p.launch(time.monotonic)
        return p

    def launch(self, clock) -> None:
        """Run the issue half now. An issue-time error (a tracing bug, an
        immediately-failing enqueue) is captured and re-raised inside the
        awaiter's classified try — never lost, never early."""
        self._t0 = clock()
        try:
            self._out = self.issue_fn()
            self._live = True
        except Exception as exc:  # noqa: BLE001 — classified at await
            self._error = exc
            self._live = True

    def claim(self):
        """Surrender the issued-ahead attempt ONCE: (t0, out, error), or
        None when nothing was launched (or it was already claimed /
        abandoned) — the awaiter then re-issues fresh."""
        if not self._live:
            return None
        self._live = False
        out, err = self._out, self._error
        self._out = self._error = None
        return (self._t0, out, err)

    def abandon(self) -> None:
        """Drop the issued futures without fetching (a pipelined driver
        discarding a speculative dispatch whose inputs a handoff
        invalidated). The device work is wasted, never observed; jax
        garbage-collects the result buffers."""
        self._live = False
        self._out = self._error = None

    def await_direct(self):
        """The unsupervised await half: fetch the issued futures (or
        re-run the halves if never launched); errors propagate raw —
        exactly a bare thunk call."""
        c = self.claim()
        if c is None:
            return self.fetch_fn(self.issue_fn())
        if c[2] is not None:
            raise c[2]
        return self.fetch_fn(c[1])

# _chips_down sentinel for probe-discovered (not injection-driven) dead
# chips: probing one consults the MeshHealth device prober, never an
# injection countdown
_REAL_CHIP = -1


class BackendSupervisor:
    """Wraps device dispatches in a deadline watchdog with classified
    failure handling. One per run; bind to the driving Simulation /
    IslandSimulation / FleetSimulation with ``sim.attach_supervisor``.

    The bound sim must duck-type four recovery hooks:
      _drain_to_checkpoint(reason, ckpt_dir=None)  flush state + metadata
      _rebind_kernels()                            fresh compiled kernels
      _enter_cpu_failover() / _exit_cpu_failover() degraded-mode swap

    ``sleep`` / ``clock`` are injectable for tests (wall scheduling only —
    never simulation results).
    """

    POLICIES = ("wait", "cpu", "abort", "relayout")

    def __init__(
        self,
        policy: str = "abort",
        *,
        max_retries: int = 3,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 5.0,
        dispatch_deadline_s: float = 300.0,
        stall_limit: int = 3,
        probe_budget_s: float = 900.0,
        probe_interval_s: float = 5.0,
        probe_interval_cap_s: float = 60.0,
        recheck_every: int = 8,
        max_drains: int = 16,
        drain_dir: str | None = None,
        probe_fn=None,
        seed: int = 0,
        sleep=time.sleep,
        clock=time.monotonic,
    ):
        if policy not in self.POLICIES:
            raise ValueError(
                f"on_backend_loss policy must be one of {self.POLICIES}, "
                f"got {policy!r}"
            )
        self.policy = policy
        self.max_retries = int(max_retries)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.dispatch_deadline_s = float(dispatch_deadline_s)
        self.stall_limit = max(1, int(stall_limit))
        self.probe_budget_s = float(probe_budget_s)
        self.probe_interval_s = float(probe_interval_s)
        self.probe_interval_cap_s = float(probe_interval_cap_s)
        self.recheck_every = max(1, int(recheck_every))
        self.max_drains = int(max_drains)
        self.drain_dir = drain_dir
        self._probe_fn = probe_fn or _default_probe
        # jitter decorrelates probe herds across a fleet of runs; wall
        # scheduling only — simulation results never depend on it
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._clock = clock
        self._sim = None
        self._dead = False
        self.failover = False  # running on the CPU fallback backend
        self._consec_stalls = 0
        self._since_recheck = 0
        self._down_since: float | None = None
        # injected faults (shadow_tpu/faults kill_backend / stall_backend /
        # exhaust_backend): None = no kill injection armed; an int counts
        # FAILED probes until the simulated backend answers again (-1 =
        # never recovers). _inject_exhausts counts dispatch attempts that
        # fail with a simulated XLA RESOURCE_EXHAUSTED before the
        # allocation "fits" again (the pressure ladder's reshapes are what
        # make the retries converge).
        self._inject_probes_left: int | None = None
        self._inject_stalls = 0
        self._inject_exhausts = 0
        # chip-scoped loss bookkeeping (policy `relayout`, and per-chip
        # probing under `wait`): chip index -> remaining FAILED probes
        # before the simulated chip answers again (None = stays down).
        # Real (non-injected) chips are probed through the bound
        # MeshHealth prober (parallel/mesh.py) when one is attached.
        self._chips_down: dict[int, int | None] = {}
        self.mesh_health = None
        self.counters = {
            "dispatches": 0,
            "retries": 0,
            "backoffs": 0,
            "stalls": 0,
            "probes": 0,
            "backend_losses": 0,
            "exhaustions": 0,
            "pressure_steps": 0,
            "drains": 0,
            "failovers": 0,
            "failbacks": 0,
            "hot_resumes": 0,
            "downtime_ns": 0,
            "chip_losses": 0,
        }

    # -- binding + fault-plane injection hooks --

    def bind(self, sim) -> None:
        self._sim = sim

    def attach_mesh_health(self, health) -> None:
        """Bind a per-chip prober (parallel/mesh.MeshHealth): chip
        probes that are not injection-driven dispatch against the
        individual device instead of the default backend."""
        self.mesh_health = health

    def inject_kill_chip(self, chip: int,
                         recover_after: int | None = None) -> None:
        """Simulate the loss of ONE mesh chip (the `kill_chip` fault
        op): the next supervised dispatch fails chip-scoped — under
        policy `relayout` the drain is followed by a ChipLost carrying
        the dead chip set (the elastic runner's rebuild signal); under
        `wait` the probe loop holds until every down chip answers.
        Probes of this chip fail `recover_after` times before the
        simulated chip recovers (None = stays down)."""
        self._dead = True
        self.counters["backend_losses"] += 1
        self.counters["chip_losses"] += 1
        self._chips_down[int(chip)] = (
            None if recover_after is None else max(0, int(recover_after))
        )

    def inject_kill(self, recover_after: int | None = None) -> None:
        """Simulate backend loss (the `kill_backend` fault op): the next
        supervised dispatch drains; probes fail `recover_after` times
        before the backend "answers" again (None = stays down)."""
        self._dead = True
        self.counters["backend_losses"] += 1
        self._inject_probes_left = (
            -1 if recover_after is None else max(0, int(recover_after))
        )

    def inject_stall(self, count: int = 1) -> None:
        """Simulate `count` dispatches missing the deadline (the
        `stall_backend` fault op) — exercises the stall→probe ladder
        without any real slowness."""
        self._inject_stalls += max(1, int(count))

    def inject_exhaust(self, recover_after: int | None = 1) -> None:
        """Simulate XLA memory exhaustion (the `exhaust_backend` fault
        op): the next `recover_after` supervised dispatch attempts fail
        with a classified RESOURCE_EXHAUSTED — each failure runs one
        pressure-ladder rung (core/pressure.py), modeling an allocation
        that fits only after the ladder reshaped the working set."""
        self._inject_exhausts += max(1, int(recover_after or 1))

    @property
    def degraded(self) -> bool:
        """True while the backend is lost or the run is on the CPU
        fallback — the interlock signal elective reshapes (the shard
        balancer's live migrations, parallel/balancer.py) consult: no
        optional work while survival machinery is driving."""
        return self._dead or self.failover

    # -- probing --

    def probe(self) -> bool:
        self.counters["probes"] += 1
        if self._chips_down:
            # chip-scoped outage: the backend answers when every down
            # chip does (the `wait` policy's hold-until-whole condition)
            for chip in sorted(self._chips_down):
                self._probe_chip_raw(chip)
            return not self._chips_down
        if self._inject_probes_left is not None:
            if self._inject_probes_left == 0:
                self._inject_probes_left = None  # simulated recovery
                return True
            if self._inject_probes_left > 0:
                self._inject_probes_left -= 1
            return False
        return bool(self._probe_fn())

    def probe_chip(self, chip: int) -> bool:
        """Probe ONE mesh chip — the elastic re-expansion loop's signal
        (parallel/elastic.py polls lost chips through this and relayouts
        back up after a hysteresis streak of successes)."""
        self.counters["probes"] += 1
        return self._probe_chip_raw(int(chip))

    def _probe_chip_raw(self, chip: int) -> bool:
        if chip in self._chips_down:
            left = self._chips_down[chip]
            if left == _REAL_CHIP:
                # probe-discovered (not injected) dead chip: ask the
                # actual device through the MeshHealth prober
                if self.mesh_health is not None and bool(
                    self.mesh_health.probe_chip(chip)
                ):
                    del self._chips_down[chip]
                    return True
                return False
            if left is None:
                return False
            if left <= 0:
                del self._chips_down[chip]  # simulated chip recovery
                return True
            self._chips_down[chip] = left - 1
            return False
        if self.mesh_health is not None:
            return bool(self.mesh_health.probe_chip(chip))
        return bool(self._probe_fn())

    @property
    def chips_down(self) -> frozenset[int]:
        """The currently-known dead chip set (injected or probe-found)."""
        return frozenset(self._chips_down)

    def mark_chip_down(self, chip: int) -> None:
        """Record a probe-discovered dead chip (MeshHealth sweep, real
        hardware path): subsequent probes go to the device itself."""
        self._chips_down.setdefault(int(chip), _REAL_CHIP)

    # -- the supervised dispatch --

    def call(self, label: str, thunk):
        """Run one device dispatch to completion under supervision.

        `thunk` takes no arguments, performs the dispatch INCLUDING the
        blocking host fetches (so async-dispatch errors surface here, not
        at a later unsupervised sync), and must re-read the driver's
        bound kernel attributes — recovery rebinds them.

        Implemented as issue()+await_result() with the whole thunk as the
        issue half — the fused form every pre-pipeline call site keeps.
        """
        return self.await_result(self.issue(label, thunk, lambda out: out))

    @property
    def pending_disruption(self) -> bool:
        """True when the NEXT supervised dispatch will not run clean: the
        backend is (injected-)dead, the run is on the CPU fallback, or an
        injected exhaust/stall is armed. The pipelined drivers consult
        this instead of issuing ahead — a speculative dispatch against a
        known disruption would only be discarded (and, for injections,
        would reorder the fault against the serial schedule)."""
        return (
            self._dead or self.failover or self._inject_exhausts > 0
            or self._inject_stalls > 0
        )

    def issue(self, label: str, issue_fn, fetch_fn) -> PendingDispatch:
        """The ISSUE half of a supervised dispatch: enqueue the device
        work asynchronously (jax dispatch returns futures) and hand back
        the ticket. Nothing blocks, nothing is classified yet — the full
        retry ladder, pressure rungs, watchdog, and loss policies all run
        in await_result, operating on the awaited half. When the backend
        is already known-disrupted the launch is skipped; await_result
        then recovers first and issues fresh, exactly like call() did."""
        p = PendingDispatch(label, issue_fn, fetch_fn)
        if not self.pending_disruption:
            p.launch(self._clock)
        return p

    def await_result(self, p: PendingDispatch):
        """The AWAIT half: block on the issued dispatch's host fetches
        under the classified state machine. First pass consumes the
        issued-ahead futures (deadline measured from their issue time);
        any retry re-runs BOTH halves — issue_fn re-reads the bound
        kernels and re-clamps, so recovery and mid-dispatch pressure
        rungs are picked up exactly as under the fused call()."""
        label = p.label
        retries = 0
        while True:
            if self._dead:
                p.abandon()
                self._recover(label)  # raises under policy `abort`
            if self.failover:
                self._maybe_failback()
            self.counters["dispatches"] += 1
            pre = p.claim()
            t0 = pre[0] if pre is not None else self._clock()
            try:
                if self._inject_exhausts > 0:
                    self._inject_exhausts -= 1
                    raise RuntimeError(
                        "RESOURCE_EXHAUSTED: out of memory allocating "
                        "window buffers (injected exhaust_backend)"
                    )
                if pre is not None:
                    if pre[2] is not None:
                        raise pre[2]
                    out = p.fetch_fn(pre[1])
                else:
                    out = p.fetch_fn(p.issue_fn())
            except Exception as exc:  # noqa: BLE001 — classified below
                kind = classify_failure(exc)
                if kind == TRANSIENT and retries < self.max_retries:
                    retries += 1
                    self.counters["retries"] += 1
                    self._backoff(retries)
                    continue
                if kind == FATAL:
                    raise
                if kind == RESOURCE_EXHAUSTED:
                    # memory pressure, not loss: the backend is alive but
                    # the working set does not fit. Run one degradation-
                    # ladder rung (core/pressure.py) and retry — the
                    # thunk re-reads the bound kernels, so a downshift's
                    # rebind is picked up transparently.
                    self.counters["exhaustions"] += 1
                    if self._pressure_step(label, exc):
                        continue
                    # ladder exhausted/unavailable: treat as a loss —
                    # drain to a checkpoint, then the configured policy
                # backend loss, or transient retries exhausted (a backend
                # that cannot absorb a bounded retry burst is not healthy)
                self._dead = True
                self.counters["backend_losses"] += 1
                if chip_scoped(exc) and not self._chips_down:
                    # a mesh collective died against one peer: find the
                    # dead participant(s) so the relayout policy can
                    # degrade to the survivors instead of declaring the
                    # whole device set gone
                    self.counters["chip_losses"] += 1
                    self._sweep_chips()
                self._note_down()
                continue
            elapsed = self._clock() - t0
            if self._inject_stalls > 0:
                self._inject_stalls -= 1
                elapsed = self.dispatch_deadline_s + elapsed
            if elapsed > self.dispatch_deadline_s:
                # bounded-lag signal (cs/0409032): a deadline miss is a
                # signal to act on, not to hang on — the result is valid
                # (the dispatch DID complete), but consecutive misses
                # trigger a probe, and a failed probe declares the
                # backend lost before the next dispatch wedges forever.
                self.counters["stalls"] += 1
                self._consec_stalls += 1
                if self._consec_stalls >= self.stall_limit:
                    self._consec_stalls = 0
                    if not self.probe():
                        self._dead = True
                        self.counters["backend_losses"] += 1
                        self._note_down()
                        continue
            else:
                self._consec_stalls = 0
            return out

    def _pressure_step(self, label: str, exc: BaseException) -> bool:
        """One memory-ladder rung via the bound sim's pressure plane;
        False when no sim is bound or its ladder is exhausted (the
        caller then escalates to the drain + loss-policy path)."""
        sim = self._sim
        step = getattr(sim, "_pressure_ladder_step", None)
        if step is None:
            return False
        if step(f"{label}: {exc}"):
            self.counters["pressure_steps"] += 1
            return True
        return False

    # -- loss handling: drain, then the configured policy --

    def _recover(self, label: str) -> None:
        sim = self._sim
        if sim is None:
            raise BackendLost(
                f"backend lost at dispatch {label!r} with no bound sim "
                f"(attach_supervisor first)"
            )
        self._note_down()
        if self.counters["drains"] >= self.max_drains:
            raise BackendLost(
                f"backend lost {self.counters['drains']} times; giving up "
                f"(max_drains={self.max_drains})"
            )
        self.counters["drains"] += 1
        path = sim._drain_to_checkpoint(
            f"backend_lost:{label}", ckpt_dir=self.drain_dir
        )
        if self.policy == "abort":
            note = f"; drained to {path}" if path else ""
            raise BackendLost(
                f"backend lost at dispatch {label!r} "
                f"(policy abort{note}; resume with --resume)"
            )
        if self.policy == "relayout":
            # chip-scoped elastic recovery: the drain checkpoint is on
            # disk; hand the dead chip set to the elastic runner
            # (parallel/elastic.py), which rebuilds the mesh over the
            # survivors and resumes via checkpoint.restore_relayout.
            # The survivors are healthy — clear the dead flag so the
            # re-bound supervisor serves the degraded mesh immediately;
            # the lost chips stay in _chips_down for re-expansion probes.
            chips = frozenset(self._chips_down)
            self._dead = False
            self._note_up()
            raise ChipLost(
                f"chip(s) {sorted(chips) if chips else '?'} lost at "
                f"dispatch {label!r} (policy relayout; drained to {path}); "
                f"relayout onto the surviving mesh and resume",
                chips=chips, path=path,
            )
        if self.policy == "cpu":
            sim._enter_cpu_failover()
            self.failover = True
            self.counters["failovers"] += 1
            self._since_recheck = 0
            self._dead = False
            return
        # policy `wait`: hot resume — re-probe with jittered backoff
        # until the backend returns, then rebind the compiled kernels
        deadline = self._clock() + self.probe_budget_s
        delay = self.probe_interval_s
        while not self.probe():
            if self._clock() >= deadline:
                raise BackendLost(
                    f"backend did not return within the "
                    f"{self.probe_budget_s:.0f}s probe budget at dispatch "
                    f"{label!r} (drained to {path}; resume with --resume)"
                )
            self.counters["backoffs"] += 1
            self._sleep(self._jitter(delay))
            delay = min(delay * 2, self.probe_interval_cap_s)
        sim._rebind_kernels()
        self._dead = False
        self.counters["hot_resumes"] += 1
        self._note_up()

    def _maybe_failback(self) -> None:
        """In CPU failover, opportunistically probe the primary every
        `recheck_every` dispatches; upshift back when it answers."""
        self._since_recheck += 1
        if self._since_recheck < self.recheck_every:
            return
        self._since_recheck = 0
        if self.probe():
            self._sim._exit_cpu_failover()
            self.failover = False
            self.counters["failbacks"] += 1
            self._note_up()

    def _sweep_chips(self) -> None:
        """Probe every mesh chip through the bound MeshHealth prober and
        mark the non-answering ones down. A no-op without a prober (the
        deterministic CPU path gets its chip set from kill_chip
        injections instead)."""
        mh = self.mesh_health
        if mh is None:
            return
        for chip, up in enumerate(mh.probe_all()):
            self.counters["probes"] += 1
            if not up:
                self.mark_chip_down(chip)

    # -- wall bookkeeping --

    def _note_down(self) -> None:
        if self._down_since is None:
            self._down_since = self._clock()

    def _note_up(self) -> None:
        if self._down_since is not None:
            self.counters["downtime_ns"] += int(
                (self._clock() - self._down_since) * 1e9
            )
            self._down_since = None

    def _backoff(self, attempt: int) -> None:
        self.counters["backoffs"] += 1
        delay = min(
            self.backoff_base_s * (2 ** (attempt - 1)), self.backoff_cap_s
        )
        self._sleep(self._jitter(delay))

    def _jitter(self, delay: float) -> float:
        """±50% decorrelation so a fleet of supervisors never probes a
        recovering worker in lockstep."""
        return delay * (0.5 + self._rng.random())

    def stats(self) -> dict:
        """The `resilience.*` metrics namespace (schema v6; v8 adds the
        exhaustions / pressure_steps memory-pressure tallies; v12 adds
        chip_losses — the chip-scoped subset of backend_losses)."""
        d = dict(self.counters)
        d["failover_active"] = int(self.failover)
        return d
