"""Deterministic seeded randomness hierarchy.

The reference derives per-host RNG streams from a single experiment seed
(controller seed → manager → per-host nodeSeed; src/main/utility/random.c:15-51,
src/main/core/manager.c:344, src/main/host/host.c:164) so results are
reproducible and independent of worker scheduling. We replicate the hierarchy
with ``jax.random.fold_in``:

    root  = PRNGKey(config seed)
    host  = fold_in(root, host_id)
    draw  = fold_in(host, per-host draw counter)

The per-host draw counter lives in device state, so every random decision
(packet drop rolls, jitter, app payload choices) is a pure function of
(seed, host_id, counter) — independent of sharding layout and event batching,
which is what makes the TPU engine bit-deterministic across runs AND across
mesh shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def root_key(seed: int):
    return jax.random.PRNGKey(seed)


def host_keys(seed: int, num_hosts: int):
    """[H] key array: one independent stream root per host."""
    root = root_key(seed)
    return jax.vmap(lambda i: jax.random.fold_in(root, i))(
        jnp.arange(num_hosts, dtype=jnp.uint32)
    )


def uniform_per_host(hkeys, counters):
    """One uniform [0,1) float32 draw per host at the given draw counters.

    hkeys: [H] key array from host_keys(); counters: [H] uint32 per-host draw
    counters (caller increments after use).
    """
    def draw(k, c):
        return jax.random.uniform(jax.random.fold_in(k, c), dtype=jnp.float32)

    return jax.vmap(draw)(hkeys, counters)


def uniform_matrix(hkeys, counters):
    """[H, K] uniform draws: element (h, k) is the draw host h's stream
    produces at counter counters[h, k] — the same pure function of
    (key, counter) as uniform_per_host, so matrix-path draws reproduce the
    sequential schedule bit-for-bit when given the same counters."""
    def draw(k, c):
        return jax.random.uniform(jax.random.fold_in(k, c), dtype=jnp.float32)

    return jax.vmap(jax.vmap(draw, in_axes=(None, 0)))(hkeys, counters)


def bits_per_host(hkeys, counters):
    """One uint32 draw per host at the given draw counters."""
    def draw(k, c):
        return jax.random.bits(jax.random.fold_in(k, c), dtype=jnp.uint32)

    return jax.vmap(draw)(hkeys, counters)
