"""Runtime half of the fault plane: injection bookkeeping + file corruptor.

The injector itself holds no plane-specific logic — the ProcessDriver and
the device Simulation each ask for the ops THEY execute (`due(...)`) at
their own deterministic points (event heap vs handoff boundary) and apply
them. Keeping execution in the owning plane keeps ordering identical run
to run: the managed plane fires at exactly `at` on the virtual clock, the
device plane at the first handoff whose committed frontier reaches `at`.
"""

from __future__ import annotations

import glob
import os

import numpy as np

from shadow_tpu.faults import plan as plan_mod


class FaultInjector:
    """Ordered, fire-once view over a parsed fault plan."""

    def __init__(self, faults: list[plan_mod.Fault]):
        self.faults = sorted(faults, key=lambda f: (f.at_ns, f.seq))
        self.fired: list[plan_mod.Fault] = []
        self.counts: dict[str, int] = {}

    def mark_fired(self, f: plan_mod.Fault) -> None:
        """Record an execution (callers that schedule faults themselves —
        the ProcessDriver's event heap — bypass due())."""
        if not f.fired:
            f.fired = True
            self.fired.append(f)
            self.counts[f.op] = self.counts.get(f.op, 0) + 1

    def due(self, now_ns: int, ops: frozenset[str] | set[str]) -> list:
        """Faults with at <= now whose op is in `ops`, not yet fired —
        marked fired and tallied on return (the caller MUST execute them)."""
        out = []
        for f in self.faults:
            if f.fired or f.op not in ops:
                continue
            if f.at_ns > now_ns:
                # sorted by at: nothing later can be due either, but keep
                # scanning — earlier entries of OTHER planes interleave
                continue
            self.mark_fired(f)
            out.append(f)
        return out

    @property
    def pending(self) -> int:
        return sum(1 for f in self.faults if not f.fired)

    def stats(self) -> dict[str, int]:
        d = {"injections_fired": len(self.fired),
             "injections_pending": self.pending}
        for op, n in sorted(self.counts.items()):
            d[f"injected_{op}"] = n
        return d


def skew_pool_np(cols, host_ids, factor: int, dead=frozenset()):
    """Execute one skew_hosts fault on host-side pool columns: replicate
    every pending row destined to a selected host `factor - 1` times, each
    copy one nanosecond after the last (a strict total order with the
    original — (time, dst, src, seq) keys never collide, so extraction
    order is unambiguous on every engine layout). Deterministic: pure
    array arithmetic, no RNG.

    `cols` is (time, dst, src, seq, kind, payload) numpy arrays with a
    leading row axis — [1, C] for the global pool, [S, C] per shard under
    islands (a copy stays in its original's row: same dst, same owner
    shard). Copies land in the row's free (NEVER) slots; rows that do not
    fit come back as per-leading-row overflow column tuples for the
    caller's spill tier (late, never lost — the engine parks them; the
    fleet, which has no spill tier, counts them dropped).

    Returns (cols, made, overflow) — the mutated columns, total copies
    placed in the pool, and {row_index: column-tuple} overflow.
    """
    from shadow_tpu.core import simtime

    NEVER = np.int64(simtime.NEVER)
    t, d, s, q, k, p = (np.array(c) for c in cols)
    ids = np.asarray(sorted(int(h) for h in set(host_ids) - set(dead)),
                     np.int64)
    made = 0
    overflow: dict[int, tuple] = {}
    if ids.size == 0 or factor < 2:
        return (t, d, s, q, k, p), made, overflow
    R = t.shape[0]
    for r in range(R):
        live = t[r] != NEVER
        sel = np.flatnonzero(live & np.isin(d[r], ids))
        if sel.size == 0:
            continue
        reps = np.repeat(sel, factor - 1)
        # copy k of a row sits at time + k: unique keys, same window-ish
        off = np.tile(np.arange(1, factor, dtype=np.int64), sel.size)
        new = (t[r][reps] + off, d[r][reps], s[r][reps], q[r][reps],
               k[r][reps], p[r][reps])
        free = np.flatnonzero(~live)
        n_fit = min(free.size, reps.size)
        if n_fit:
            slots = free[:n_fit]
            t[r][slots] = new[0][:n_fit]
            d[r][slots] = new[1][:n_fit]
            s[r][slots] = new[2][:n_fit]
            q[r][slots] = new[3][:n_fit]
            k[r][slots] = new[4][:n_fit]
            p[r][slots] = new[5][:n_fit]
            made += n_fit
        if n_fit < reps.size:
            overflow[r] = tuple(c[n_fit:] for c in new)
    return (t, d, s, q, k, p), made, overflow


def corrupt_file(f: plan_mod.Fault, default_dir: str | None = None) -> list[str]:
    """Execute one corrupt_file fault: apply `mode` to every file matching
    the glob (relative patterns resolve against f.dir or `default_dir`).
    Returns the paths touched. Deterministic: matches are sorted, and the
    flip mode XORs a fixed byte at a fixed offset — no RNG."""
    pat = f.path
    base = f.dir or default_dir
    if base and not os.path.isabs(pat):
        pat = os.path.join(base, pat)
    touched = []
    for path in sorted(glob.glob(pat)):
        if not os.path.isfile(path):
            continue
        if f.mode == "delete":
            os.unlink(path)
        elif f.mode == "truncate":
            size = os.path.getsize(path)
            with open(path, "r+b") as fh:
                fh.truncate(max(0, size // 2))
        else:  # flip: XOR a 64-byte span mid-file (archive payload, not
            # the zip end-of-central-directory, so the file still OPENS
            # and only content verification can catch it; a span — not a
            # single byte — so the damage cannot land entirely in zip
            # padding that readers never touch)
            size = os.path.getsize(path)
            if size == 0:
                continue
            off = size // 2
            n = min(64, size - off)
            with open(path, "r+b") as fh:
                fh.seek(off)
                b = fh.read(n)
                fh.seek(off)
                fh.write(bytes(x ^ 0xFF for x in b))
        touched.append(path)
    return touched
