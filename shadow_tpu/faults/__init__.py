"""Fault-tolerance plane: deterministic fault injection + supervised
recovery.

Shadow's core promise is replicable experiments; a fault path that only
ever runs by accident is a fault path that silently rots. This package
makes failure a first-class, *scheduled* input: a fault plan is a list of
virtual-time-keyed injections (kill/wedge a managed process, refuse an IPC
reply, corrupt a checkpoint file, force a pool-overflow spill, kill a
device host, kill or stall the ACCELERATOR BACKEND itself) executed at
deterministic points — the driver's event heap on the managed plane,
handoff boundaries on the device plane — so two runs with the same plan
are bit-identical. Backend ops drive the supervision state machine
(core/supervisor.py): device loss becomes deterministically testable on
CPU, and recovery is provably exact via the audit digest chain.

  plan.py      fault-plan schema: parse/validate JSON documents and the
               `faults:` config section's inline list
  injector.py  runtime side: ordered injection bookkeeping per plane,
               plus the file-corruption executor
"""

from shadow_tpu.faults.plan import (  # noqa: F401
    BACKEND_OPS,
    DEVICE_OPS,
    FILE_OPS,
    PROC_OPS,
    Fault,
    FaultPlanError,
    PLAN_KIND,
    PLAN_SCHEMA_VERSION,
    load_fault_plan,
    parse_fault_plan,
    validate_fault_plan_doc,
)
from shadow_tpu.faults.injector import (  # noqa: F401
    FaultInjector,
    corrupt_file,
)
