"""Fault-plan schema: virtual-time-keyed injections, validated up front.

A plan is a JSON document (``--fault-plan plan.json``) or the inline
``faults.inject`` list of the YAML config — same entry schema either way:

    {
      "kind": "shadow_tpu.fault_plan",
      "schema_version": 1,
      "faults": [
        {"at": "2 s", "op": "kill_proc",    "proc": "client.0"},
        {"at": "2 s", "op": "wedge_proc",   "proc": "client.0"},
        {"at": "1 s", "op": "refuse_ipc",   "proc": "client.0", "count": 1},
        {"at": "3 s", "op": "kill_host",    "host": 3},
        {"at": "4 s", "op": "skew_hosts",   "span": [0, 4], "factor": 6},
        {"at": "1 s", "op": "force_spill"},
        {"at": "2 s", "op": "kill_backend", "recover_after": 2},
        {"at": "2 s", "op": "kill_chip",    "chip": 3, "recover_after": 4},
        {"at": "2 s", "op": "stall_backend", "count": 3},
        {"at": "2 s", "op": "exhaust_backend", "recover_after": 1},
        {"at": "2 s", "op": "saturate_pool", "frac": 0.25},
        {"at": "4 s", "op": "corrupt_file", "path": "ckpt-*.npz",
         "mode": "flip"}
      ]
    }

``at`` accepts the config time grammar (core/units.py; bare numbers are
seconds). Ops are split by execution plane:

  PROC_OPS    executed by the ProcessDriver at sim time ``at`` exactly
              (scheduled on its event heap):
                kill_proc   SIGKILL the named managed process's native
                            image — the crashed-plugin case
                wedge_proc  SIGSTOP it — the wedged-plugin case (detected
                            by the IPC-timeout escalation ladder)
                refuse_ipc  drop the next `count` driver→shim IPC replies
                            (the shim blocks; same ladder detects it)
  DEVICE_OPS  executed by the device engine at the first handoff boundary
              whose committed frontier reaches ``at``:
                kill_host   quarantine the host id/name: its pending pool
                            events drain at every subsequent handoff
                skew_hosts  deterministic traffic skew: multiply the
                            selected hosts' event rates by `factor` from
                            virtual time `at` on, by replicating their
                            pending pool rows (factor−1 copies, each one
                            nanosecond apart — a strict total order, no
                            RNG). Select with `hosts` (id/name list) or
                            `span` ([first, count] of global host ids).
                            Fires at the handoff boundary whose committed
                            frontier reaches `at`, which the dispatch
                            clamp pins exactly — and under the async
                            islands driver every per-shard frontier is
                            clamped at or below `at` there, so the
                            injection is fleet-frontier-safe (copies
                            inherit pending-event times, which no shard
                            has run past). The chaos input the
                            self-balancing plane heals (bench.py
                            --balance-smoke), and usable standalone
                force_spill force one pool-overflow spill episode
                saturate_pool simulate sustained pool pressure: scale the
                            spill-tier marks by `frac` (0 < frac <= 1)
                            from the injection frontier on — drives the
                            degradation ladder (core/pressure.py) so
                            pool saturation is deterministically
                            testable on CPU
  BACKEND_OPS executed at the same device handoff boundaries, but
              targeting the ACCELERATOR rather than a simulated host —
              they drive the backend supervision state machine
              (core/supervisor.py) so device loss is deterministically
              testable on CPU:
                kill_backend   declare the backend dead; the next
                               supervised dispatch drains to a
                               checkpoint and the --on-backend-loss
                               policy takes over; `recover_after` = N
                               failed probes before the simulated
                               backend answers again (absent = stays
                               down)
                stall_backend  the next `count` dispatches appear to
                               miss the supervisor's deadline — the
                               bounded-lag stall ladder escalates to a
                               probe
                exhaust_backend the next `recover_after` supervised
                               dispatch attempts fail with a classified
                               XLA RESOURCE_EXHAUSTED — each failure
                               runs one pressure-ladder rung
                               (core/pressure.py), modeling an
                               allocation that fits only after the
                               ladder reshaped the working set
                kill_chip      declare ONE mesh chip dead (chip-scoped
                               loss, core/supervisor.inject_kill_chip):
                               under --on-backend-loss relayout the
                               drain is followed by an elastic relayout
                               onto the surviving mesh
                               (parallel/elastic.py); under wait the
                               probe loop holds until the chip answers.
                               `chip` = index into the deterministic
                               mesh device order; `recover_after` = N
                               failed probes before the simulated chip
                               answers again (absent = stays down)
  FILE_OPS    executed by whichever plane runs, at the same points:
                corrupt_file  truncate/flip/delete files matching a glob
                              (checkpoint or spill artifacts) — proves
                              resume integrity validation actually gates

Validation mirrors obs/metrics.validate_metrics_doc: a reference
validator (`validate_fault_plan_doc`) shared by the loader, the
tools/validate_fault_plan.py CLI, and the tier-1 tests.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

from shadow_tpu.core import units

PLAN_KIND = "shadow_tpu.fault_plan"
PLAN_SCHEMA_VERSION = 1

PROC_OPS = frozenset({"kill_proc", "wedge_proc", "refuse_ipc"})
DEVICE_OPS = frozenset(
    {"kill_host", "skew_hosts", "force_spill", "saturate_pool"}
)
BACKEND_OPS = frozenset(
    {"kill_backend", "stall_backend", "exhaust_backend", "kill_chip"}
)
FILE_OPS = frozenset({"corrupt_file"})
ALL_OPS = PROC_OPS | DEVICE_OPS | BACKEND_OPS | FILE_OPS

CORRUPT_MODES = ("truncate", "flip", "delete")

# per-op field contract: required / optional (beyond `at` + `op`)
_FIELDS = {
    "kill_proc": ({"proc"}, set()),
    "wedge_proc": ({"proc"}, set()),
    "refuse_ipc": ({"proc"}, {"count"}),
    "kill_host": ({"host"}, set()),
    "skew_hosts": (set(), {"hosts", "span", "factor"}),
    "force_spill": (set(), set()),
    "kill_backend": (set(), {"recover_after"}),
    "stall_backend": (set(), {"count"}),
    "exhaust_backend": (set(), {"recover_after"}),
    "kill_chip": ({"chip"}, {"recover_after"}),
    "saturate_pool": (set(), {"frac"}),
    "corrupt_file": ({"path"}, {"mode", "dir"}),
}


class FaultPlanError(ValueError):
    pass


@dataclasses.dataclass
class Fault:
    """One parsed injection. ``seq`` is the declaration index — the
    deterministic tiebreak for same-timestamp faults."""

    at_ns: int
    op: str
    seq: int = 0
    proc: Optional[str] = None
    host: Optional[int | str] = None
    count: int = 1
    # kill_backend: failed supervisor probes before the simulated backend
    # answers again; None = the outage never self-heals (abort/resume
    # path). exhaust_backend: dispatch attempts that fail RESOURCE_
    # EXHAUSTED before the allocation fits (None = one).
    recover_after: Optional[int] = None
    # saturate_pool: the factor the spill-tier marks scale by (smaller =
    # more severe simulated pressure)
    frac: float = 0.5
    # kill_chip: the mesh chip index (deterministic device order) to
    # declare dead
    chip: Optional[int] = None
    # skew_hosts: the selected hosts (id/name list, or [first, count]
    # span of global host ids) and the rate multiplier
    hosts: Optional[list] = None
    span: Optional[list] = None
    factor: int = 2
    path: Optional[str] = None
    mode: str = "truncate"
    dir: Optional[str] = None
    fired: bool = False

    def describe(self) -> str:
        tgt = self.proc or self.host or self.path or ""
        return f"{self.op}({tgt}) @ {self.at_ns}ns"


def _parse_entry(i: int, d: dict) -> Fault:
    if not isinstance(d, dict):
        raise FaultPlanError(f"faults[{i}] must be an object, got {d!r}")
    if "op" not in d:
        raise FaultPlanError(f"faults[{i}]: `op` is required")
    op = str(d["op"])
    if op not in ALL_OPS:
        raise FaultPlanError(
            f"faults[{i}]: unknown op {op!r} (known: {sorted(ALL_OPS)})"
        )
    if "at" not in d:
        raise FaultPlanError(f"faults[{i}] ({op}): `at` is required")
    required, optional = _FIELDS[op]
    allowed = {"at", "op"} | required | optional
    unknown = set(d) - allowed
    if unknown:
        raise FaultPlanError(
            f"faults[{i}] ({op}): unknown field(s) {sorted(unknown)}"
        )
    missing = required - set(d)
    if missing:
        raise FaultPlanError(
            f"faults[{i}] ({op}): missing field(s) {sorted(missing)}"
        )
    try:
        at_ns = units.parse_time_ns(d["at"])
    except ValueError as e:
        raise FaultPlanError(f"faults[{i}] ({op}): bad `at`: {e}") from e
    if at_ns < 0:
        raise FaultPlanError(f"faults[{i}] ({op}): `at` must be >= 0")
    f = Fault(at_ns=at_ns, op=op, seq=i)
    if "proc" in d:
        f.proc = str(d["proc"])
    if "host" in d:
        f.host = d["host"] if isinstance(d["host"], int) else str(d["host"])
    if "count" in d:
        f.count = int(d["count"])
        if f.count < 1:
            raise FaultPlanError(f"faults[{i}] ({op}): count must be >= 1")
    if "recover_after" in d and d["recover_after"] is not None:
        f.recover_after = int(d["recover_after"])
        if f.recover_after < 0:
            raise FaultPlanError(
                f"faults[{i}] ({op}): recover_after must be >= 0"
            )
    if "frac" in d:
        try:
            f.frac = float(d["frac"])
        except (TypeError, ValueError):
            raise FaultPlanError(
                f"faults[{i}] ({op}): frac must be a number, got "
                f"{d['frac']!r}"
            ) from None
        if not 0.0 < f.frac <= 1.0:
            raise FaultPlanError(
                f"faults[{i}] ({op}): frac must be in (0, 1], got {f.frac}"
            )
    if op == "skew_hosts":
        if ("hosts" in d) == ("span" in d):
            raise FaultPlanError(
                f"faults[{i}] (skew_hosts): exactly one of `hosts` "
                f"(id/name list) or `span` ([first, count]) is required"
            )
        if "hosts" in d:
            if not isinstance(d["hosts"], list) or not d["hosts"]:
                raise FaultPlanError(
                    f"faults[{i}] (skew_hosts): `hosts` must be a "
                    f"non-empty list of host ids/names"
                )
            f.hosts = [
                h if isinstance(h, int) else str(h) for h in d["hosts"]
            ]
        else:
            sp = d["span"]
            if (not isinstance(sp, list) or len(sp) != 2
                    or not all(isinstance(x, int) for x in sp)
                    or sp[0] < 0 or sp[1] < 1):
                raise FaultPlanError(
                    f"faults[{i}] (skew_hosts): `span` must be "
                    f"[first >= 0, count >= 1], got {sp!r}"
                )
            f.span = [int(sp[0]), int(sp[1])]
        if "factor" in d:
            try:
                f.factor = int(d["factor"])
            except (TypeError, ValueError):
                raise FaultPlanError(
                    f"faults[{i}] (skew_hosts): factor must be an "
                    f"integer, got {d['factor']!r}"
                ) from None
        if f.factor < 2:
            raise FaultPlanError(
                f"faults[{i}] (skew_hosts): factor must be >= 2 "
                f"(1 is a no-op), got {f.factor}"
            )
    if "chip" in d:
        if not isinstance(d["chip"], int) or isinstance(d["chip"], bool):
            raise FaultPlanError(
                f"faults[{i}] ({op}): chip must be an integer mesh chip "
                f"index, got {d['chip']!r}"
            )
        f.chip = int(d["chip"])
        if f.chip < 0:
            raise FaultPlanError(
                f"faults[{i}] ({op}): chip must be >= 0, got {f.chip}"
            )
    if "path" in d:
        f.path = str(d["path"])
    if "dir" in d and d["dir"] is not None:
        f.dir = str(d["dir"])
    if "mode" in d:
        f.mode = str(d["mode"])
        if f.mode not in CORRUPT_MODES:
            raise FaultPlanError(
                f"faults[{i}] ({op}): mode {f.mode!r} not in {CORRUPT_MODES}"
            )
    return f


def validate_fault_plan_doc(doc: dict) -> None:
    """Raise FaultPlanError unless `doc` conforms to the plan schema.
    The reference validator behind tools/validate_fault_plan.py."""
    if not isinstance(doc, dict):
        raise FaultPlanError("fault plan must be a JSON object")
    if doc.get("kind") != PLAN_KIND:
        raise FaultPlanError(
            f"fault plan kind {doc.get('kind')!r} != {PLAN_KIND!r}"
        )
    if doc.get("schema_version") != PLAN_SCHEMA_VERSION:
        raise FaultPlanError(
            f"fault plan schema_version {doc.get('schema_version')!r} != "
            f"{PLAN_SCHEMA_VERSION}"
        )
    unknown = set(doc) - {"kind", "schema_version", "faults", "meta"}
    if unknown:
        raise FaultPlanError(f"unknown top-level field(s) {sorted(unknown)}")
    faults = doc.get("faults")
    if not isinstance(faults, list):
        raise FaultPlanError("`faults` must be a list")
    for i, d in enumerate(faults):
        _parse_entry(i, d)


def parse_fault_plan(entries: list) -> list[Fault]:
    """Parse a bare injection list (a plan doc's `faults`, or the config's
    inline `faults.inject`) into Fault records ordered by (at, seq)."""
    if not isinstance(entries, list):
        raise FaultPlanError("fault injections must be a list")
    out = [_parse_entry(i, d) for i, d in enumerate(entries)]
    out.sort(key=lambda f: (f.at_ns, f.seq))
    return out


def check_backend_ops(faults: list[Fault],
                      mesh_size: int | None = None) -> list[Fault]:
    """Require every injection to be a BACKEND op (kill_backend /
    stall_backend / exhaust_backend / kill_chip) or saturate_pool — the
    classes a daemon-level chaos plan may carry (they target the shared
    accelerator / pressure plane, not one simulated host): proc/device/
    file ops are run-scoped and belong in a job's own config
    (shadow_tpu/serve validates submissions with this).

    With `mesh_size`, kill_chip targets are additionally bounds-checked
    against it (a chip index at/past the mesh would declare a chip that
    does not exist dead — a plan bug, refused up front)."""
    allowed = BACKEND_OPS | {"saturate_pool"}
    for f in faults:
        if f.op not in allowed:
            raise FaultPlanError(
                f"daemon-level fault plans support backend + pressure "
                f"ops only ({sorted(allowed)}); {f.op!r} belongs in a "
                f"job config's faults section"
            )
        if (f.op == "kill_chip" and mesh_size is not None
                and not 0 <= int(f.chip) < int(mesh_size)):
            raise FaultPlanError(
                f"kill_chip chip {f.chip} out of range for the "
                f"{mesh_size}-chip mesh (valid: 0..{int(mesh_size) - 1})"
            )
    return faults


def load_fault_plan(path: str) -> list[Fault]:
    """Load and validate a fault-plan JSON file."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except json.JSONDecodeError as e:
        raise FaultPlanError(f"{path}: not valid JSON: {e}") from e
    validate_fault_plan_doc(doc)
    return parse_fault_plan(doc["faults"])
