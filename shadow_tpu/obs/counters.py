"""Device counter block: fixed-layout i64 telemetry carried in SimState.

The engine's `Counters` struct (core/state.py) accounts events and drops;
this block adds the WINDOW-plane signals every perf PR needs to watch —
which kernel path ran, how often windows shrank or rolled back, how the
per-host virtual-time frontier spreads — in a single `[NUM_WIN]` i64 array
plus two `[H]` rows, all updated inside the jitted window step with fused
adds/selects. Nothing here ever forces a host<->device sync: the block is
read only at handoff boundaries via `snapshot()` (one device_get).

Layout is versioned by position: new slots append, existing indices never
move (docs/observability.md documents the layout; BLOCK_VERSION guards
consumers).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

BLOCK_VERSION = 4

# --- fixed window-plane slot indices (append-only; never renumber) ---
WIN_WINDOWS = 0  # window steps executed (one per step() call)
WIN_MATRIX = 1  # windows dispatched on the matrix fast path
WIN_LOOP = 2  # windows dispatched on the micro-step loop path
WIN_SHRINKS = 3  # optimistic windows shrunk after a violation
WIN_ROLLBACKS = 4  # optimistic whole-window rollbacks
WIN_OPT_STALLS = 5  # optimistic null-window exchange-retry stalls
WIN_SPILL_FIRES = 6  # spill-tier manage episodes (shard rebalances)
WIN_GEAR_SHIFTS = 7  # pool gear changes (core/gearbox.py re-sorts)
WIN_FAULTS = 8  # fault-plane actions applied at handoffs (shadow_tpu/faults)
NUM_WIN = 9

WIN_NAMES = (
    "windows_run",
    "matrix_dispatches",
    "loop_dispatches",
    "window_shrinks",
    "rollbacks",
    "opt_stalls",
    "spill_fires",
    "gear_shifts",
    "fault_actions",
)
assert len(WIN_NAMES) == NUM_WIN


def win_bump_vec(*indices: int) -> jnp.ndarray:
    """Trace-time constant [NUM_WIN] vector with 1 at each index — a step
    bumps several slots with ONE fused add (win + vec)."""
    v = np.zeros((NUM_WIN,), np.int64)
    for i in indices:
        v[i] = 1
    return jnp.asarray(v)


@struct.dataclass
class ObsBlock:
    """The device-resident telemetry block (a SimState SOA field).

    Shapes: global engine win=[NUM_WIN], host rows [H]; islands layout
    win=[S, NUM_WIN] (per-shard, summed at fetch — the kernel scales
    shard-shared bumps by axis_index==0 so sums match the global engine),
    host rows [S, H/S].
    """

    win: jnp.ndarray  # [NUM_WIN] i64 window-plane counters
    host_events: jnp.ndarray  # [H] i64 committed events per host
    # Per-host virtual-time frontier: max committed event time, -1 before
    # the first commit. Never reset (unlike host.done_t): its min/max
    # spread IS the desynchronization-roughness health metric.
    host_last_t: jnp.ndarray  # [H] i64
    # Determinism-audit digest chain (obs/audit.py, block v4): rolling-mix
    # hash of every committed event key (time, src, dst, kind) in per-host
    # commit order. Rides the pytree, so rollbacks discard speculated
    # digest state with the rest of the speculated window.
    host_digest: jnp.ndarray  # [H] i64

    @classmethod
    def zeros(cls, num_hosts: int) -> "ObsBlock":
        return cls(
            win=jnp.zeros((NUM_WIN,), jnp.int64),
            host_events=jnp.zeros((num_hosts,), jnp.int64),
            host_last_t=jnp.full((num_hosts,), -1, jnp.int64),
            host_digest=jnp.zeros((num_hosts,), jnp.int64),
        )


def bump_win(state, idx: int, n: int = 1):
    """Host-side bump of one window-plane slot (driver-plane events the
    kernel cannot see: rollbacks, shrinks, spill fires). Runs at handoff
    boundaries only — a tiny device add, never a sync. No-op when the
    block is disabled or n == 0."""
    if getattr(state, "obs", None) is None or n == 0:
        return state
    w = state.obs.win
    if w.ndim == 2:  # islands layout: shard 0 carries driver-plane bumps
        w = w.at[0, idx].add(n)
    else:
        w = w.at[idx].add(n)
    return state.replace(obs=state.obs.replace(win=w))


def snapshot(state) -> dict:
    """Read the block at a handoff boundary: ONE device_get, layouts
    normalized (islands win summed over shards, host rows flattened back
    to global [H] order). Returns {} when the block is disabled."""
    if getattr(state, "obs", None) is None:
        return {}
    blk = jax.device_get(state.obs)
    win = np.asarray(blk.win)
    if win.ndim == 2:
        win = win.sum(axis=0)
    # host rows come back in GLOBAL host-id order even after an islands
    # rebalance permuted the physical layout (host.gid maps row -> host)
    gid = np.asarray(jax.device_get(state.host.gid)).reshape(-1)
    he = np.empty_like(np.asarray(blk.host_events).reshape(-1))
    he[gid] = np.asarray(blk.host_events).reshape(-1)
    hl = np.empty_like(np.asarray(blk.host_last_t).reshape(-1))
    hl[gid] = np.asarray(blk.host_last_t).reshape(-1)
    hd = np.empty_like(np.asarray(blk.host_digest).reshape(-1))
    hd[gid] = np.asarray(blk.host_digest).reshape(-1)
    return {
        "block_version": BLOCK_VERSION,
        "win": {name: int(win[i]) for i, name in enumerate(WIN_NAMES)},
        "host_events": he,
        "host_last_t": hl,
        "host_digest": hd,
    }


def vtime_stats(host_last_t: np.ndarray) -> dict:
    """Virtual-time-roughness statistics over the per-host committed-time
    frontier (cond-mat/0302050's spread metric): hosts that committed
    nothing (-1) are excluded; empty frontier reports zeros."""
    t = np.asarray(host_last_t)
    t = t[t >= 0]
    if t.size == 0:
        return {"committed_hosts": 0, "min_ns": 0, "max_ns": 0,
                "spread_ns": 0, "mean_ns": 0.0}
    return {
        "committed_hosts": int(t.size),
        "min_ns": int(t.min()),
        "max_ns": int(t.max()),
        "spread_ns": int(t.max() - t.min()),
        "mean_ns": float(t.mean()),
    }
