"""Device-resident telemetry plane.

Three pieces (docs/observability.md):

  counters  fixed-layout int64 counter block carried in SimState and
            incremented inside the jitted window kernel — no host sync
            until a handoff boundary reads it
  metrics   host-side registry of counters/gauges/histograms the drivers
            snapshot at CPU<->TPU handoff boundaries; dumped as versioned
            JSON (--metrics-out)
  trace     nestable wall-time spans in Chrome trace-event JSON
            (--trace-out), loadable in Perfetto; fleet runs ride
            per-lane named tids
  audit     determinism-audit digest chains: in-kernel rolling-mix
            hashes of committed event keys (--digest-out), plus the
            divergence bisector behind tools/diff_digest.py
  flight    opt-in flight recorder: device ring of the last R committed
            events per host, spooled at handoffs (--flight-out) and
            rendered as a virtual-time Perfetto clock domain

Reference analog: tracker.c per-host byte/CPU accounting, lifted onto the
device plane; virtual-time-progress statistics follow the PDES literature
(desynchronization spread as the central health metric); per-LP run-audit
instrumentation follows PARSIR (arxiv 2410.00644).
"""
