"""Host-side metrics registry: counters / gauges / histograms, snapshotted
at CPU<->TPU handoff boundaries and dumped as versioned JSON.

Namespaces in the dumped document:
  engine.*  the engine Counters struct (core/state.py), fetched once
  obs.*     the device counter block (obs/counters.py): window plane,
            per-host event totals, virtual-time roughness
  net.*     device network-plane counters read from SimState subs
            (nic tx/rx, router CoDel drops, TCP retransmits/timeouts)
  wall.*    driver wall-time histograms (compile/dispatch/host phases)
  round.*   per-dispatch-round throughput series

The JSON schema (docs/observability.md) carries `schema_version`;
`validate_metrics_doc` is the reference validator used by the tier-1
smoke test and available to downstream consumers.
"""

from __future__ import annotations

import json
import time
from contextlib import contextmanager, nullcontext

import numpy as np

from shadow_tpu.obs import counters as obs_counters

# v18: prof.* profiling plane (obs/prof.py + obs/hist.py): mergeable
# log-bucketed latency histograms surfaced as p50/p90/p99/count gauges
# (dispatch_wall_ns / host_drain_wall_ns / window_width_ns, plus the
# serve plane's request_ns), the interval-ring posture counters
# (prof.intervals / prof.dropped), and the critical-path attribution
# gauges prof.critical_shard / prof.blocked_frac / prof.wall_frac;
# v17: qdisc.* per-interface scheduling plane (net/qdisc/):
# enqueues/dequeues plus the split drop tallies (drops_overflow /
# drops_red / drops_codel) for the PIFO and Eiffel-bucketed
# disciplines, and depth_max/depth_mean/sojourn_mean_ns occupancy
# gauges over the [H, Q] queue plane;
# v16: federation.* federated serve plane (serve/federation.py +
# serve/router.py): placements/steals/failovers/replayed_sweeps/
# probes/peers_lost/handoff_recoveries counters for the N-daemon
# router, plus peers_up/peers_total/peers_suspect membership gauges
# and the queue_depth_max/min spread the work stealer flattens;
# v15: hostplane.* multi-worker host plane (core/hostplane.py): worker
# pool width, sharded-drain count, canonical-merge wall, per-worker
# drain wall (drain_ns_w<i>), serial-fallback re-runs after a worker
# exception, and placement-derived host->worker re-pins;
# v14: pipeline.* pipelined-handoff namespace (core/pipeline.py + the
# driver loops: issued-ahead dispatch count, overlap_ns of host-drain
# time hidden behind in-flight device work, forced_drains at
# state-mutating barrier points, recompute_discards where a drained
# handoff invalidated a speculative issue);
# v13: dropped the never-emitted `bench` namespace from the closed
# table — the contract auditor (analysis/contracts.py SLC002) requires
# every registered namespace to have a statically-visible emitter, and
# no gate ever wrote a bench.* key;
# v12: elastic mesh resilience (parallel/elastic.py): mesh.chips_up/
# chips_total posture gauges, mesh.chips_lost/relayouts/re_expansions/
# relayout_downtime_ns/kernel_rebuilds/reexpand_holds counters for the
# drain → relayout → re-expand loop, and resilience.chip_losses (the
# chip-scoped subset of backend_losses, core/supervisor.py);
# v11: mesh.* multi-chip namespace (parallel/{mesh,islands}.py: per-chip
# committed-event balance, neighbor-only frontier-exchange collective
# volume + partner counts, placement cut-cost gauges, and exchange-
# schedule rebuild counters for the shard_map mesh execution plane);
# v10: balance.* self-balancing-fleet namespace (parallel/balancer.py:
# verified live migrations / rollbacks / interlock holds plus controller
# posture gauges, and the fleet scheduler's load-packing + lane-steal
# counters); v9: async.* asynchronous-conservative-sync namespace
# (parallel/islands.py + parallel/lookahead.py: superstep/shard-window/
# yield/blocked-on-neighbor counters plus frontier spread, spread-bound
# and lookahead gauges); v8: pressure.* resource-pressure namespace
# (core/pressure.py: degradation-ladder rungs — downshifts/upshifts/
# spill escalations/lane evictions/job sheds — plus HBM estimate +
# headroom gauges and memory-shed admission counters on the serve
# plane); v7: serve.*
# sim-as-a-service namespace (shadow_tpu/serve: journal records/replays,
# admission sheds, kernel-cache hits/misses/evictions, drains); v6:
# resilience.* backend-supervision namespace (core/supervisor.py:
# retries, backoffs, stalls, drains, failovers, downtime_ns, fleet lane
# reclaims); v5: audit.* determinism-audit namespace (digest chain,
# obs/audit.py) + optional per-job `audit` sub-object on fleet.jobs[*]
# rows; v4: optional top-level `fleet` section (fleet.jobs[*] per-job
# rows) + fleet.* counters; v3: faults.* recovery counters
SCHEMA_VERSION = 18
DOC_KIND = "shadow_tpu.metrics"

# metrics-doc `fleet.jobs[*]` rows must carry at least these keys
_FLEET_JOB_KEYS = {
    "name", "status", "events_committed", "windows", "frontier_ns", "wall_s",
}

# The closed set of dotted-key namespaces a metrics document may carry
# (docs/observability.md).  This is the single source of truth three
# consumers share: `validate_metrics_doc(strict_namespaces=True)` /
# `tools/validate_metrics.py --strict-namespaces` reject documents with
# keys outside it, and the shadowlint STL008 rule
# (shadow_tpu/analysis/rules.py) rejects the *emitting line* at lint
# time — so a new namespace lands here, with a schema-version bump and a
# docs row, before any code can emit it.
KNOWN_METRIC_NAMESPACES = frozenset({
    "engine",      # engine Counters struct (core/state.py)
    "obs",         # device counter block (obs/counters.py)
    "net",         # device network planes: net.nic/router/tcp.*
    "vtime",       # virtual-time roughness gauges
    "wall",        # driver wall-time histograms
    "round",       # per-dispatch-round throughput series
    "spill",       # spill-tier counters
    "gear",        # gearbox telemetry (schema v2)
    "faults",      # fault-tolerance plane (schema v3)
    "fleet",       # scenario-fleet scheduler plane (schema v4)
    "audit",       # determinism-audit plane (schema v5)
    "resilience",  # backend supervision (schema v6)
    "serve",       # sim-as-a-service daemon plane (schema v7)
    "pressure",    # resource-pressure degradation ladder (schema v8)
    "async",       # asynchronous conservative sync (schema v9)
    "balance",     # self-balancing fleet plane (schema v10)
    "mesh",        # multi-chip mesh execution plane (schema v11;
                   # elastic-resilience rows added in v12)
    "pipeline",    # pipelined CPU↔TPU handoff (schema v14)
    "hostplane",   # multi-worker host-plane drain (schema v15)
    "federation",  # federated serve plane / router (schema v16)
    "qdisc",       # per-interface scheduling plane (schema v17)
    "prof",        # profiling plane: histogram percentiles +
                   # critical-path posture (schema v18)
    "sim",         # build-level gauges (num_hosts, runahead)
})

# Histograms keep exact count/sum/min/max plus a bounded sample buffer for
# percentiles: past the cap, samples are kept with a deterministic stride
# (every k-th observation) — no RNG, reruns dump identical documents.
_SAMPLE_CAP = 4096


class Histogram:
    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._samples: list[float] = []
        self._stride = 1

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.total += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if (self.count - 1) % self._stride == 0:
            self._samples.append(v)
            if len(self._samples) >= _SAMPLE_CAP:
                # decimate in place, double the stride
                self._samples = self._samples[::2]
                self._stride *= 2

    def summary(self) -> dict:
        if self.count == 0:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                    "mean": 0.0, "p50": 0.0, "p90": 0.0, "p99": 0.0}
        s = np.asarray(self._samples, dtype=np.float64)
        p50, p90, p99 = np.percentile(s, [50, 90, 99])
        return {
            "count": int(self.count),
            "sum": float(self.total),
            "min": float(self.min),
            "max": float(self.max),
            "mean": float(self.total / self.count),
            "p50": float(p50),
            "p90": float(p90),
            "p99": float(p99),
        }


class MetricsRegistry:
    def __init__(self):
        self.counters: dict[str, int] = {}
        self.gauges: dict[str, float | int] = {}
        self._hists: dict[str, Histogram] = {}
        # structured top-level sections (schema v4: "fleet"); absent from
        # the doc until set, so solo-run documents are unchanged
        self.sections: dict[str, dict] = {}

    def section_set(self, name: str, value: dict) -> None:
        self.sections[name] = dict(value)

    def counter_set(self, name: str, value: int) -> None:
        self.counters[name] = int(value)

    def counter_add(self, name: str, delta: int) -> None:
        self.counters[name] = self.counters.get(name, 0) + int(delta)

    def gauge_set(self, name: str, value) -> None:
        self.gauges[name] = value

    def histogram(self, name: str) -> Histogram:
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = Histogram()
        return h

    def to_doc(self, meta: dict | None = None) -> dict:
        return {
            "kind": DOC_KIND,
            "schema_version": SCHEMA_VERSION,
            "created_unix": time.time(),
            "meta": dict(meta or {}),
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "histograms": {
                k: h.summary() for k, h in sorted(self._hists.items())
            },
            **{k: dict(v) for k, v in sorted(self.sections.items())},
        }

    def dump(self, path: str, meta: dict | None = None) -> dict:
        doc = self.to_doc(meta)
        dump_json_atomic(path, doc)
        return doc


def dump_json_atomic(path: str, doc: dict, indent: int | None = 1) -> None:
    """tmp + fsync + rename, the checkpoint plane's torn-write discipline
    (core/checkpoint.py): a poller, tpu_watch, or perf_compare reading
    `path` concurrently sees either the previous complete document or
    this one — never a truncated JSON."""
    import os

    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=indent)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


_HIST_KEYS = {"count", "sum", "min", "max", "mean", "p50", "p90", "p99"}


def validate_metrics_doc(doc: dict, strict_namespaces: bool = False) -> None:
    """Raise ValueError unless `doc` conforms to the documented schema
    (docs/observability.md). The tier-1 smoke test runs this on the
    --metrics-out output of the flagship tiny config.

    With `strict_namespaces`, every dotted counter/gauge/histogram key
    must additionally live in KNOWN_METRIC_NAMESPACES — the runtime twin
    of shadowlint's STL008 static check."""
    if not isinstance(doc, dict):
        raise ValueError("metrics doc must be a JSON object")
    if doc.get("kind") != DOC_KIND:
        raise ValueError(f"metrics doc kind {doc.get('kind')!r} != {DOC_KIND!r}")
    if doc.get("schema_version") != SCHEMA_VERSION:
        raise ValueError(
            f"metrics schema_version {doc.get('schema_version')!r} != "
            f"{SCHEMA_VERSION}"
        )
    for section in ("meta", "counters", "gauges", "histograms"):
        if not isinstance(doc.get(section), dict):
            raise ValueError(f"metrics doc section {section!r} missing or not an object")
    for k, v in doc["counters"].items():
        if not isinstance(v, int) or isinstance(v, bool):
            raise ValueError(f"counter {k!r} must be an integer, got {v!r}")
        if k.startswith("resilience.") and v < 0:
            # schema v6: backend-supervision counters are monotonic tallies
            raise ValueError(f"resilience counter {k!r} must be >= 0, got {v}")
        if k.startswith("serve.") and v < 0:
            # schema v7: daemon-plane counters are monotonic tallies too
            raise ValueError(f"serve counter {k!r} must be >= 0, got {v}")
        if k.startswith("pressure.") and v < 0:
            # schema v8: degradation-ladder counters are monotonic tallies
            raise ValueError(
                f"pressure counter {k!r} must be >= 0, got {v}"
            )
        if k.startswith("async.") and v < 0:
            # schema v9: async-sync counters are monotonic tallies
            raise ValueError(
                f"async counter {k!r} must be >= 0, got {v}"
            )
        if k.startswith("balance.") and v < 0:
            # schema v10: self-balancing counters are monotonic tallies
            raise ValueError(
                f"balance counter {k!r} must be >= 0, got {v}"
            )
        if k.startswith("mesh.") and v < 0:
            # schema v11: multi-chip counters are monotonic tallies
            raise ValueError(
                f"mesh counter {k!r} must be >= 0, got {v}"
            )
        if k.startswith("pipeline.") and v < 0:
            # schema v14: pipelined-handoff counters are monotonic tallies
            raise ValueError(
                f"pipeline counter {k!r} must be >= 0, got {v}"
            )
        if k.startswith("hostplane.") and v < 0:
            # schema v15: host-plane drain counters are monotonic tallies
            raise ValueError(
                f"hostplane counter {k!r} must be >= 0, got {v}"
            )
        if k.startswith("federation.") and v < 0:
            # schema v16: federated-serve counters are monotonic tallies
            raise ValueError(
                f"federation counter {k!r} must be >= 0, got {v}"
            )
        if k.startswith("qdisc.") and v < 0:
            # schema v17: qdisc counters are monotonic tallies
            raise ValueError(
                f"qdisc counter {k!r} must be >= 0, got {v}"
            )
        if k.startswith("prof.") and v < 0:
            # schema v18: profiling-plane counters are monotonic tallies
            raise ValueError(
                f"prof counter {k!r} must be >= 0, got {v}"
            )
    for k, v in doc["gauges"].items():
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            raise ValueError(f"gauge {k!r} must be a number, got {v!r}")
    for k, h in doc["histograms"].items():
        if not isinstance(h, dict) or not _HIST_KEYS <= set(h):
            raise ValueError(
                f"histogram {k!r} must carry keys {sorted(_HIST_KEYS)}"
            )
    if strict_namespaces:
        for section in ("counters", "gauges", "histograms"):
            for k in doc[section]:
                ns = k.split(".", 1)[0]
                if "." in k and ns not in KNOWN_METRIC_NAMESPACES:
                    raise ValueError(
                        f"{section} key {k!r}: namespace {ns!r} is not in "
                        f"KNOWN_METRIC_NAMESPACES (obs/metrics.py)"
                    )
    fleet = doc.get("fleet")
    if fleet is not None:
        # schema v4: fleet runs attach per-job rows (docs/observability.md)
        if not isinstance(fleet, dict) or not isinstance(
            fleet.get("jobs"), list
        ):
            raise ValueError(
                "fleet section must be an object with a jobs list"
            )
        for i, row in enumerate(fleet["jobs"]):
            if not isinstance(row, dict) or not _FLEET_JOB_KEYS <= set(row):
                raise ValueError(
                    f"fleet.jobs[{i}] must carry keys "
                    f"{sorted(_FLEET_JOB_KEYS)}"
                )
            audit = row.get("audit")
            if audit:
                # schema v5: a job's determinism-audit sub-object must at
                # least carry its integer digest chain (obs/audit.py)
                if not isinstance(audit, dict) or not isinstance(
                    audit.get("chain"), int
                ) or isinstance(audit.get("chain"), bool):
                    raise ValueError(
                        f"fleet.jobs[{i}].audit must carry an integer "
                        f"`chain` (the job's digest-chain value)"
                    )


def _sub_counter(reg: MetricsRegistry, sub, prefix: str, fields) -> None:
    for f in fields:
        v = getattr(sub, f, None)
        if v is not None:
            reg.counter_set(f"{prefix}.{f}", int(np.sum(np.asarray(v))))


def snapshot_device(sim, reg: MetricsRegistry) -> None:
    """Read every device-resident counter plane at a handoff boundary:
    engine Counters, the obs block, and the net-plane subs. One pass, no
    mid-window syncs — callers invoke this only between dispatches or at
    the end of a run."""
    import jax

    for k, v in sim.counters().items():
        reg.counter_set(f"engine.{k}", v)
    snap = obs_counters.snapshot(sim.state)
    if snap:
        for k, v in snap["win"].items():
            reg.counter_set(f"obs.{k}", v)
        he = snap["host_events"]
        reg.counter_set("obs.host_events_total", int(he.sum()))
        reg.gauge_set("obs.host_events_min", int(he.min()))
        reg.gauge_set("obs.host_events_max", int(he.max()))
        reg.gauge_set("obs.host_events_mean", float(he.mean()))
        for k, v in obs_counters.vtime_stats(snap["host_last_t"]).items():
            reg.gauge_set(f"vtime.{k}", v)
        if "host_digest" in snap:
            # determinism audit (schema v5): the combined digest chain +
            # block version; per-handoff records ride --digest-out
            from shadow_tpu.obs import audit as audit_mod

            reg.gauge_set(
                "audit.chain", audit_mod.combine(snap["host_digest"])
            )
            reg.gauge_set("audit.block_version", int(snap["block_version"]))
    trail = getattr(sim, "audit", None)
    if trail is not None:
        reg.counter_set("audit.records", len(trail.records))
    spool = getattr(sim, "flight_spool", None)
    if spool is not None:
        for k, v in spool.stats().items():
            reg.counter_set(f"audit.flight_{k}", int(v))
    subs = sim.state.subs
    nic = subs.get("nic")
    if nic is not None:
        nic = jax.device_get(nic)
        _sub_counter(reg, nic, "net.nic",
                     ("tx_packets", "tx_bytes", "rx_packets", "rx_bytes",
                      "sendq_dropped"))
    router = subs.get("router")
    if router is not None:
        _sub_counter(reg, jax.device_get(router), "net.router",
                     ("codel_dropped",))
    tcp = subs.get("tcp")
    if tcp is not None:
        _sub_counter(reg, jax.device_get(tcp), "net.tcp",
                     ("retransmits", "timeouts", "rtx_fast", "rtx_sack",
                      "rtx_walk", "drop_no_socket", "drop_ooo",
                      "accept_overflow"))
    reg.gauge_set("sim.num_hosts", int(sim.num_hosts))
    reg.gauge_set("sim.runahead_ns", int(sim.runahead))
    for k, v in sim.spill_stats().items():
        reg.counter_set(f"spill.{k}", int(v))
    gear_stats = getattr(sim, "gear_stats", None)
    if gear_stats is not None:
        g = gear_stats()
        reg.gauge_set("gear.level", int(g["gear_level"]))
        reg.gauge_set("gear.tiers", int(g["gear_tiers"]))
        reg.gauge_set("gear.capacity", int(g["gear_capacity"]))
        reg.counter_set("gear.shifts", int(g["gear_shifts"]))
        for lvl, n in g["gear_dispatches"].items():
            reg.counter_set(f"gear.dispatches.level{lvl}", int(n))
    # fault-tolerance plane (schema v3): injections fired, quarantines,
    # drained events, auto-checkpoint ring activity (shadow_tpu/faults)
    fault_stats = getattr(sim, "fault_stats", None)
    if fault_stats is not None:
        for k, v in fault_stats().items():
            reg.counter_set(f"faults.{k}", int(v))
    # backend supervision (schema v6): retries/backoffs/stalls/drains/
    # failovers/downtime from the attached supervisor (core/supervisor.py)
    res_stats = getattr(sim, "resilience_stats", None)
    if res_stats is not None:
        for k, v in res_stats().items():
            reg.counter_set(f"resilience.{k}", int(v))
    _snapshot_pressure(sim, reg)
    _snapshot_async(sim, reg)
    _snapshot_balance(sim, reg)
    _snapshot_mesh(sim, reg)
    _snapshot_pipeline(sim, reg)
    _snapshot_hostplane(sim, reg)
    _snapshot_qdisc(sim, reg)


def _snapshot_qdisc(sim, reg: MetricsRegistry) -> None:
    """Per-interface scheduling plane (schema v17): admission/service/
    drop tallies plus queue-occupancy gauges from the device queue
    discipline's [H]-leading counter plane (net/qdisc/). FIFO/round-robin
    runs carry no `qdisc` sub and emit no qdisc.* keys — pre-v17 docs
    stay valid."""
    import jax

    state = getattr(sim, "state", None)
    qd = state.subs.get("qdisc") if state is not None else None
    if qd is None:
        return
    qd = jax.device_get(qd)
    for f in ("enqueues", "dequeues", "drops_overflow", "drops_red",
              "drops_codel"):
        reg.counter_set(f"qdisc.{f}", int(np.sum(np.asarray(qd[f]))))
    depth = (
        np.asarray(qd["q_len"], np.int64)
        if "q_len" in qd
        else np.sum(np.asarray(qd["q_valid"], np.int64), axis=-1)
    )
    reg.gauge_set("qdisc.depth_max", int(depth.max()))
    reg.gauge_set("qdisc.depth_mean", float(depth.mean()))
    deq = int(np.sum(np.asarray(qd["dequeues"])))
    reg.gauge_set(
        "qdisc.sojourn_mean_ns",
        float(np.sum(np.asarray(qd["sojourn_sum"])) / deq) if deq else 0.0,
    )


def _snapshot_hostplane(sim, reg: MetricsRegistry) -> None:
    """Multi-worker host-plane plane (schema v15): pool width, sharded
    drains, canonical-merge wall, per-worker drain wall, serial
    fallbacks and re-pins from the drain pool (core/hostplane.py).
    Serial runs (experimental.host_workers: 1) report {} and emit no
    hostplane keys."""
    hs = getattr(sim, "hostplane_stats", None)
    if hs is None:
        return
    for k, v in hs().items():
        reg.counter_set(f"hostplane.{k}", int(v))


def _snapshot_pipeline(sim, reg: MetricsRegistry) -> None:
    """Pipelined-handoff plane (schema v14): issued-ahead / overlap /
    forced-drain / recompute-discard tallies from the two-slot dispatch
    pipeline (core/pipeline.py). Serial runs (experimental.
    pipelined_dispatch: false) report {} and emit no pipeline keys."""
    ps = getattr(sim, "pipeline_stats", None)
    if ps is None:
        return
    for k, v in ps().items():
        reg.counter_set(f"pipeline.{k}", int(v))


def _snapshot_mesh(sim, reg: MetricsRegistry) -> None:
    """Multi-chip mesh plane (schema v11): per-chip committed-event
    balance, neighbor-only frontier-exchange volume/partners, placement
    cut cost, and exchange-schedule rebuilds, from the islands runner
    (parallel/islands.py mesh_stats/mesh_gauges; None = single shard).
    Schema v12 adds the elastic-resilience posture from the attached
    ElasticMeshRunner (parallel/elastic.py): chips up/total gauges and
    the chip-loss / relayout / re-expansion / downtime counters — these
    also ride the sim the S→1 endpoint fell back to (the global engine
    has no mesh_stats, but its elastic hook still reports)."""
    ms = getattr(sim, "mesh_stats", None)
    if ms is not None:
        stats = ms()
        if stats:
            for k, v in stats.items():
                reg.counter_set(f"mesh.{k}", int(v))
    mg = getattr(sim, "mesh_gauges", None)
    if mg is not None:
        gauges = mg()
        if gauges:
            for k, v in gauges.items():
                reg.gauge_set(f"mesh.{k}", v)
    el = getattr(sim, "elastic", None)
    if el is not None:
        for k, v in el.stats().items():
            reg.counter_set(f"mesh.{k}", int(v))
        for k, v in el.gauges().items():
            reg.gauge_set(f"mesh.{k}", v)


def _snapshot_balance(sim, reg: MetricsRegistry) -> None:
    """Self-balancing plane (schema v10): migration / rollback / hold
    counters plus controller posture gauges, from the islands balancer
    (parallel/balancer.py) or the fleet scheduler's packing + stealing
    tallies (fleet/scheduler.py; None/absent = no balance plane)."""
    bs = getattr(sim, "balance_stats", None)
    if bs is not None:
        stats = bs()
        if stats:
            for k, v in stats.items():
                reg.counter_set(f"balance.{k}", int(v))
    bg = getattr(sim, "balance_gauges", None)
    if bg is not None:
        gauges = bg()
        if gauges:
            for k, v in gauges.items():
                reg.gauge_set(f"balance.{k}", v)


def _snapshot_async(sim, reg: MetricsRegistry) -> None:
    """Asynchronous-conservative-sync plane (schema v9): superstep /
    shard-window / yield / blocked-on-neighbor counters plus frontier
    spread and lookahead gauges, from the islands driver or the fleet
    (parallel/islands.py async_stats/async_gauges; None = barrier)."""
    ast = getattr(sim, "async_stats", None)
    if ast is not None:
        stats = ast()
        if stats:
            for k, v in stats.items():
                reg.counter_set(f"async.{k}", int(v))
    ag = getattr(sim, "async_gauges", None)
    if ag is not None:
        gauges = ag()
        if gauges:
            for k, v in gauges.items():
                reg.gauge_set(f"async.{k}", v)


def _snapshot_pressure(sim, reg: MetricsRegistry) -> None:
    """Resource-pressure plane (schema v8): ladder counters from the
    attached controller plus the HBM estimate/headroom gauges
    (core/pressure.py) — the preflight budget the serve daemon's
    admission compares against."""
    from shadow_tpu.core import pressure as pressure_mod

    ps = getattr(sim, "pressure_stats", None)
    if ps is not None:
        for k, v in ps().items():
            reg.counter_set(f"pressure.{k}", int(v))
    pc = getattr(sim, "pressure", None)
    if pc is not None:
        for k, v in pc.gauges().items():
            reg.gauge_set(f"pressure.{k}", v)
    try:
        est = pressure_mod.estimate_hbm_bytes(sim)
    except Exception:
        return  # estimator is best-effort telemetry, never a run failure
    reg.gauge_set("pressure.estimated_bytes", int(est["total_bytes"]))
    hb = pressure_mod.headroom_bytes(est["total_bytes"])
    if hb is not None:
        reg.gauge_set("pressure.headroom_bytes", int(hb))


def snapshot_fleet(fleet, reg: MetricsRegistry) -> None:
    """Read a FleetSimulation's scheduler-plane results into the registry
    (schema v4): fleet.* counters plus the top-level `fleet` section with
    one `jobs[*]` row per experiment (per-job events / windows / virtual-
    time frontier). Per-job device counters ride inside each row — the
    fleet harvested them at the job's own handoff boundary, so this call
    never forces a sync."""
    stats = fleet.fleet_stats()
    for k in ("jobs_total", "jobs_done", "jobs_failed", "jobs_timeout",
              "lane_swaps", "admission_upshifts", "kernel_traces",
              "gear_shifts"):
        reg.counter_set(f"fleet.{k}", int(stats.get(k, 0)))
    reg.gauge_set("fleet.lanes", int(stats.get("lanes", 0)))
    reg.gauge_set("fleet.gear_level", int(stats.get("gear_level", 0)))
    # backend supervision (schema v6): supervisor counters + the
    # scheduler's deadline lane reclaims / drain requeues
    res_stats = getattr(fleet, "resilience_stats", None)
    if res_stats is not None:
        for k, v in res_stats().items():
            reg.counter_set(f"resilience.{k}", int(v))
    _snapshot_pressure(fleet, reg)
    _snapshot_async(fleet, reg)
    _snapshot_balance(fleet, reg)
    _snapshot_mesh(fleet, reg)
    _snapshot_pipeline(fleet, reg)
    _snapshot_hostplane(fleet, reg)
    _snapshot_qdisc(fleet, reg)
    reg.section_set("fleet", {
        "lanes": int(stats.get("lanes", 0)),
        "lane_swaps": int(stats.get("lane_swaps", 0)),
        "kernel_traces": int(stats.get("kernel_traces", 0)),
        "jobs": fleet.results(),
    })


class ObsSession:
    """The driver-facing handle: one per run, attached as
    `sim.obs_session`. Bundles the metrics registry with an optional
    Chrome tracer; the engine drivers call `span()` around each phase and
    `round_done()` after each dispatch round's handoff sync."""

    def __init__(self, metrics: MetricsRegistry | None = None, tracer=None,
                 prof=None):
        self.metrics = metrics or MetricsRegistry()
        self.tracer = tracer
        self.prof = prof  # optional obs/prof.ProfRecorder
        self._last_events = 0
        self._last_wall = time.perf_counter()
        self._dispatches = 0

    @contextmanager
    def span(self, name: str, **args):
        """Wall-time span: observed into `wall.{name}_s` and (when tracing)
        emitted as a Chrome complete event. The FIRST dispatch span also
        lands in `wall.first_dispatch_s` — it includes XLA compilation."""
        t0 = time.perf_counter()
        tr = self.tracer.span(name, **args) if self.tracer else nullcontext()
        with tr:
            yield
        dt = time.perf_counter() - t0
        self.metrics.histogram(f"wall.{name}_s").observe(dt)
        if self.prof is not None:
            # mergeable int64 twins (obs/hist.py): `dispatch` and the
            # pipelined driver's `await` are both device-wait wall
            if name in ("dispatch", "await"):
                self.prof.observe_wall("dispatch_wall_ns", dt)
            elif name == "host_drain":
                self.prof.observe_wall("host_drain_wall_ns", dt)
        if name == "dispatch":
            self._dispatches += 1
            if self._dispatches == 1:
                self.metrics.gauge_set("wall.first_dispatch_s", dt)

    def round_done(self, sim, frontier_ns: int | None = None) -> None:
        """Per-round throughput sample, taken at the handoff boundary the
        driver already synced at (the scalar frontier fetch). Drivers
        pass the committed frontier they fetched anyway; the profiling
        recorder stamps its interval ring with it."""
        now = time.perf_counter()
        ev = sim.counters()["events_committed"]
        dt = now - self._last_wall
        if dt > 0 and ev > self._last_events:
            self.metrics.histogram("round.events_per_sec").observe(
                (ev - self._last_events) / dt
            )
        if self.tracer:
            self.tracer.counter(
                "progress", {"events_committed": int(ev)}
            )
        self._last_events, self._last_wall = ev, now
        if self.prof is not None:
            self.prof.tick_from(sim, frontier_ns=frontier_ns)

    def finalize(self, sim) -> None:
        snapshot_device(sim, self.metrics)
        if self.prof is not None:
            snapshot_prof(self.prof, self.metrics)


def snapshot_prof(prof, reg: MetricsRegistry) -> None:
    """Profiling plane (schema v18): fold the recorder's mergeable
    histograms into prof.* percentile gauges, the interval-ring posture
    counters, and — when the run carried per-shard async data — the
    critical-path attribution posture (obs/prof.critical_path)."""
    from shadow_tpu.obs import prof as prof_mod

    for name, h in sorted(prof._hists.items()):
        if not h.count:
            continue
        s = h.summary()
        reg.counter_set(f"prof.{name}_count", s["count"])
        for q in ("p50", "p90", "p99", "max"):
            reg.gauge_set(f"prof.{name}_{q}", int(s[q]))
    reg.counter_set("prof.intervals", int(prof.recorded))
    reg.counter_set("prof.dropped", int(prof.dropped))
    cp = prof_mod.critical_path(prof.to_doc())
    if cp is not None:
        reg.gauge_set("prof.critical_shard", int(cp["critical_shard"]))
        reg.gauge_set("prof.blocked_frac", float(cp["blocked_frac"]))
        reg.gauge_set("prof.wall_frac", float(cp["wall_frac"]))


def span(session: ObsSession | None, name: str, **args):
    """Null-safe span: drivers call this unconditionally; with no session
    attached it is a nullcontext — zero overhead on the default path."""
    return session.span(name, **args) if session is not None else nullcontext()
