"""Chrome trace-event export: nestable wall-time spans, Perfetto-loadable.

Format: the Trace Event JSON object form — {"traceEvents": [...],
"displayTimeUnit": "ms", "metadata": {...}} — with complete ("X") events
for spans, instant ("i") events for markers, and counter ("C") events for
progress series. Timestamps are microseconds since tracer creation.

The tracer is driver-plane only (wall time, host process); device-plane
telemetry lives in obs/counters.py. Spans nest by call structure:
round -> window -> dispatch / host-exchange / spill.
"""

from __future__ import annotations

import json
import time
from contextlib import contextmanager

FORMAT = "chrome-trace-events"
VERSION = 1


class ChromeTracer:
    """Collects trace events in memory; write() dumps the JSON document.

    Single-threaded by design (the drivers are): every span lands on one
    tid and nests by strict LIFO, which is exactly what the complete-event
    renderer expects.
    """

    def __init__(self, process_name: str = "shadow_tpu"):
        self._t0 = time.perf_counter()
        self.events: list[dict] = []
        self._depth = 0
        self.events.append({
            "name": "process_name", "ph": "M", "pid": 0, "tid": 0,
            "args": {"name": process_name},
        })

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    @contextmanager
    def span(self, name: str, cat: str = "sim", **args):
        """Nestable wall-time span emitted as one complete ("X") event."""
        t0 = self._now_us()
        self._depth += 1
        try:
            yield
        finally:
            self._depth -= 1
            ev = {
                "name": name, "cat": cat, "ph": "X", "pid": 0, "tid": 0,
                "ts": t0, "dur": self._now_us() - t0,
            }
            if args:
                ev["args"] = args
            self.events.append(ev)

    def instant(self, name: str, cat: str = "sim", **args) -> None:
        ev = {
            "name": name, "cat": cat, "ph": "i", "s": "t",
            "pid": 0, "tid": 0, "ts": self._now_us(),
        }
        if args:
            ev["args"] = args
        self.events.append(ev)

    def fault(self, name: str, **args) -> None:
        """Fault-plane marker (injection fired, quarantine, checkpoint
        fallback): an instant event under its own category so Perfetto
        can filter recovery actions from the sim timeline."""
        self.instant(name, cat="fault", **args)

    def counter(self, name: str, values: dict) -> None:
        """Counter ("C") sample: Perfetto draws each key as a series."""
        self.events.append({
            "name": name, "ph": "C", "pid": 0, "tid": 0,
            "ts": self._now_us(), "args": dict(values),
        })

    def to_doc(self) -> dict:
        return {
            "displayTimeUnit": "ms",
            "metadata": {"format": FORMAT, "version": VERSION},
            "traceEvents": list(self.events),
        }

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_doc(), f)
            f.write("\n")
