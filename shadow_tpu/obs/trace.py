"""Chrome trace-event export: nestable wall-time spans, Perfetto-loadable.

Format: the Trace Event JSON object form — {"traceEvents": [...],
"displayTimeUnit": "ms", "metadata": {...}} — with complete ("X") events
for spans, instant ("i") events for markers, and counter ("C") events for
progress series. Timestamps are microseconds since tracer creation.

The tracer is driver-plane only (wall time, host process); device-plane
telemetry lives in obs/counters.py, and virtual-time tracks come from the
flight recorder (obs/flight.py + tools/flight_to_trace.py, which emits a
second clock domain on its own pid). Spans nest by call structure:
round -> window -> dispatch / host-exchange / spill.

Thread ids: solo drivers emit everything on tid 0. Fleet runs give every
lane its own tid (lane index + 1; tid 0 is the driver) and name the
threads via "M" metadata events, so a sweep's per-job lifecycles render
as separate rows instead of interleaving into one.
"""

from __future__ import annotations

import time
from contextlib import contextmanager

FORMAT = "chrome-trace-events"
VERSION = 3  # v3: t0_unix metadata (tools/trace_merge.py clock alignment)


class ChromeTracer:
    """Collects trace events in memory; write() dumps the JSON document.

    Single-threaded by design (the drivers are): spans nest by strict
    LIFO per tid, which is exactly what the complete-event renderer
    expects. `tid` routes events onto named rows (fleet lanes)."""

    def __init__(self, process_name: str = "shadow_tpu"):
        self._t0 = time.perf_counter()
        # wall-clock anchor of ts=0: tools/trace_merge.py shifts peer
        # traces onto one timeline by t0_unix deltas
        self.t0_unix = time.time()
        self.events: list[dict] = []
        self._depth = 0
        self._named_tids: set[tuple[int, int]] = set()
        self.events.append({
            "name": "process_name", "ph": "M", "pid": 0, "tid": 0,
            "args": {"name": process_name},
        })

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def now_us(self) -> float:
        """The tracer's relative-µs clock — for callers (the host plane's
        drain workers) that time work off-thread with perf_counter and
        emit it later through `complete`."""
        return self._now_us()

    def thread_name(self, tid: int, name: str, pid: int = 0) -> None:
        """Name a thread row once via an "M" metadata event (the fleet
        names tid 0 "driver" and each lane "lane <j>")."""
        if (pid, tid) in self._named_tids:
            return
        self._named_tids.add((pid, tid))
        self.events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": name},
        })

    @contextmanager
    def span(self, name: str, cat: str = "sim", tid: int = 0, **args):
        """Nestable wall-time span emitted as one complete ("X") event."""
        t0 = self._now_us()
        self._depth += 1
        try:
            yield
        finally:
            self._depth -= 1
            ev = {
                "name": name, "cat": cat, "ph": "X", "pid": 0, "tid": tid,
                "ts": t0, "dur": self._now_us() - t0,
            }
            if args:
                ev["args"] = args
            self.events.append(ev)

    def complete(self, name: str, ts_us: float, dur_us: float,
                 cat: str = "sim", tid: int = 0, **args) -> None:
        """An explicit complete ("X") event with caller-supplied bounds —
        the fleet emits one per job residency (admit -> harvest) on the
        lane's tid."""
        ev = {
            "name": name, "cat": cat, "ph": "X", "pid": 0, "tid": tid,
            "ts": float(ts_us), "dur": float(dur_us),
        }
        if args:
            ev["args"] = args
        self.events.append(ev)

    def instant(self, name: str, cat: str = "sim", tid: int = 0,
                **args) -> None:
        ev = {
            "name": name, "cat": cat, "ph": "i", "s": "t",
            "pid": 0, "tid": tid, "ts": self._now_us(),
        }
        if args:
            ev["args"] = args
        self.events.append(ev)

    def fault(self, name: str, tid: int = 0, **args) -> None:
        """Fault-plane marker (injection fired, quarantine, checkpoint
        fallback): an instant event under its own category so Perfetto
        can filter recovery actions from the sim timeline."""
        self.instant(name, cat="fault", tid=tid, **args)

    def counter(self, name: str, values: dict, tid: int = 0) -> None:
        """Counter ("C") sample: Perfetto draws each key as a series."""
        self.events.append({
            "name": name, "ph": "C", "pid": 0, "tid": tid,
            "ts": self._now_us(), "args": dict(values),
        })

    def to_doc(self) -> dict:
        return {
            "displayTimeUnit": "ms",
            "metadata": {
                "format": FORMAT, "version": VERSION,
                "t0_unix": round(self.t0_unix, 6),
            },
            "traceEvents": list(self.events),
        }

    def write(self, path: str) -> None:
        from shadow_tpu.obs.metrics import dump_json_atomic

        dump_json_atomic(path, self.to_doc(), indent=None)
