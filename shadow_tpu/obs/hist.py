"""Log-bucketed fixed-bin integer histograms (HDR-style).

The snapshot registry's Histogram (obs/metrics.py) keeps a stride-decimated
float sample buffer — fine for one run's summary, useless for federation:
two sample buffers don't merge into the histogram either run would have
produced. This module is the mergeable twin: a FIXED bucket layout shared
by every producer (log-linear, ``SUB_BITS`` sub-buckets per octave) holding
pure int64 counts, so

  merge(a, b) == merge(b, a)            (commutative)
  merge(merge(a, b), c) == merge(a, merge(b, c))   (associative)

and a histogram accumulated across fleet lanes, federation peers, or a
checkpoint-resume boundary is EXACTLY the histogram one uninterrupted
observer would have built. Values are non-negative integers (nanoseconds
throughout the profiling plane); the relative quantization error is bounded
by 2**-SUB_BITS (25% with the default layout) — the HDR trade: coarse
absolute precision, exact mergeable counts.

Bucket layout (``SUB_BITS = 2``):
  idx 0..3            exact: value == idx
  idx 4..             log-linear: octave ``(idx >> 2) - 1`` split into 4
                      sub-buckets; ``bucket_lo/hi`` give inclusive bounds
  idx NUM_BINS - 1    overflow: values past the last bounded bucket clamp
                      here (unbounded above; percentiles report ``max``)

With NUM_BINS = 256 every int64 value has its own bounded bucket — the
overflow bin only catches arbitrary-precision outliers — but the bin is
part of the contract: producers with different layouts refuse to merge.
"""

from __future__ import annotations

SUB_BITS = 2
NUM_BINS = 256

_SUB = 1 << SUB_BITS  # sub-buckets per octave


def bucket_index(v: int) -> int:
    """Bucket of non-negative integer ``v`` (negatives clamp to 0)."""
    v = int(v)
    if v < 0:
        v = 0
    if v < _SUB:
        return v
    shift = v.bit_length() - 1 - SUB_BITS
    idx = ((shift + 1) << SUB_BITS) + ((v >> shift) - _SUB)
    return idx if idx < NUM_BINS - 1 else NUM_BINS - 1


def bucket_lo(idx: int) -> int:
    """Inclusive lower bound of bucket ``idx``."""
    if idx < _SUB:
        return idx
    shift = (idx >> SUB_BITS) - 1
    base = (idx & (_SUB - 1)) + _SUB
    return base << shift


def bucket_hi(idx: int) -> int | None:
    """Inclusive upper bound of bucket ``idx``; None for the unbounded
    overflow bucket (callers clamp to the observed max)."""
    if idx < _SUB:
        return idx
    if idx >= NUM_BINS - 1:
        return None
    shift = (idx >> SUB_BITS) - 1
    base = (idx & (_SUB - 1)) + _SUB
    return ((base + 1) << shift) - 1


class LogHistogram:
    """Fixed-layout int64 histogram: observe / merge / percentile.

    State is five integers plus a sparse bucket->count map — everything
    merges by elementwise addition (count, sum, buckets) or min/max, so
    merge order can never matter.
    """

    __slots__ = ("count", "sum", "min", "max", "buckets")

    def __init__(self):
        self.count = 0
        self.sum = 0
        self.min = None  # None until the first observation
        self.max = None
        self.buckets: dict[int, int] = {}

    def observe(self, v: int) -> None:
        v = int(v)
        if v < 0:
            v = 0
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        i = bucket_index(v)
        self.buckets[i] = self.buckets.get(i, 0) + 1

    def merge(self, other: "LogHistogram") -> None:
        """Fold ``other`` into self (elementwise adds + min/max)."""
        self.count += other.count
        self.sum += other.sum
        if other.min is not None:
            self.min = other.min if self.min is None \
                else min(self.min, other.min)
        if other.max is not None:
            self.max = other.max if self.max is None \
                else max(self.max, other.max)
        for i, n in other.buckets.items():
            self.buckets[i] = self.buckets.get(i, 0) + n

    def percentile(self, q: float) -> int:
        """Value at percentile ``q`` in [0, 100]: the upper bound of the
        bucket holding the rank-``ceil(q/100 * count)`` observation,
        clamped to the exact observed max (so p100 == max and the
        overflow bucket never reports an invented bound). Empty
        histogram: 0."""
        if self.count == 0:
            return 0
        rank = max(1, min(self.count, -(-int(q * self.count) // 100)))
        seen = 0
        for i in sorted(self.buckets):
            seen += self.buckets[i]
            if seen >= rank:
                hi = bucket_hi(i)
                return self.max if hi is None else min(hi, self.max)
        return self.max  # unreachable when counts are consistent

    def summary(self) -> dict:
        """count/sum/min/max/mean + p50/p90/p99 — the same key set the
        snapshot-registry histograms dump, so both render alike."""
        if self.count == 0:
            return {"count": 0, "sum": 0, "min": 0, "max": 0, "mean": 0.0,
                    "p50": 0, "p90": 0, "p99": 0}
        return {
            "count": int(self.count), "sum": int(self.sum),
            "min": int(self.min), "max": int(self.max),
            "mean": float(self.sum / self.count),
            "p50": int(self.percentile(50)),
            "p90": int(self.percentile(90)),
            "p99": int(self.percentile(99)),
        }

    def to_doc(self) -> dict:
        """JSON form. The layout constants travel with the counts so a
        consumer with a different build refuses to merge instead of
        silently mis-binning."""
        return {
            "sub_bits": SUB_BITS,
            "num_bins": NUM_BINS,
            "count": int(self.count),
            "sum": int(self.sum),
            "min": 0 if self.min is None else int(self.min),
            "max": 0 if self.max is None else int(self.max),
            "buckets": {str(i): int(n)
                        for i, n in sorted(self.buckets.items())},
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "LogHistogram":
        if (doc.get("sub_bits") != SUB_BITS
                or doc.get("num_bins") != NUM_BINS):
            raise ValueError(
                f"histogram layout mismatch: doc carries sub_bits="
                f"{doc.get('sub_bits')} num_bins={doc.get('num_bins')}, "
                f"this build uses {SUB_BITS}/{NUM_BINS} — counts from "
                f"different layouts do not merge"
            )
        h = cls()
        h.count = int(doc.get("count", 0))
        h.sum = int(doc.get("sum", 0))
        if h.count:
            h.min = int(doc.get("min", 0))
            h.max = int(doc.get("max", 0))
        h.buckets = {int(i): int(n)
                     for i, n in doc.get("buckets", {}).items() if int(n)}
        return h

    def __eq__(self, other) -> bool:
        if not isinstance(other, LogHistogram):
            return NotImplemented
        return (self.count == other.count and self.sum == other.sum
                and self.min == other.min and self.max == other.max
                and {i: n for i, n in self.buckets.items() if n}
                == {i: n for i, n in other.buckets.items() if n})


def merge_docs(a: dict, b: dict) -> dict:
    """Merge two histogram JSON docs (router /timez roll-up): decode,
    fold, re-encode. Raises ValueError on layout mismatch."""
    h = LogHistogram.from_doc(a)
    h.merge(LogHistogram.from_doc(b))
    return h.to_doc()
