"""Virtual-time flight recorder: a device-resident ring of the last R
committed event records per host.

The digest chain (obs/audit.py) tells you THAT two runs diverged and at
which window; the flight recorder tells you WHAT the engine was committing
around that point. Opt-in (`experimental.flight_recorder: {capacity: R}`):
the ring is a `SimState` field of [H, R] arrays written inside the jitted
window step by masked one-hot updates — the same select-over-columns write
the engine's inbox/outbox use (`engine._set_col`); XLA scatters serialize
on TPU and stay banned, and the masked update IS the per-host
dynamic-slice write expressed in that idiom. Nothing syncs mid-window: the
ring is read only at handoff boundaries, where `FlightSpool` flushes the
records committed since the previous flush to a binary spool file.
`tools/flight_to_trace.py` converts the spool into a second Perfetto clock
domain — virtual-time tracks per host — alongside the wall-time spans of
`--trace-out`.

Because the ring rides the state pytree it also: rolls back with
speculated state (the spool only ever sees committed records), stacks
under the fleet's lane axis, shards under islands ([S, H/S, R]), and is
captured inside every checkpoint — a crashed run's last R events per host
are in the newest ring entry.
"""

from __future__ import annotations

import struct as binstruct

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

SPOOL_MAGIC = 0x53544652  # "STFR"
SPOOL_VERSION = 1

_HDR = binstruct.Struct("<IIII")  # magic, version, num_hosts, capacity
_FRAME = binstruct.Struct("<qII")  # frontier_ns, n_records, lost
_REC = binstruct.Struct("<iqiii")  # host, time_ns, src, seq, kind


@struct.dataclass
class FlightRing:
    """Per-host ring of the last R committed events. `count` is the total
    committed records per host (never wraps); slot = count % R, so the
    ring needs no separate cursor and the spool can dedupe flushes by
    count alone."""

    time: jnp.ndarray  # [H, R] i64
    src: jnp.ndarray  # [H, R] i32
    seq: jnp.ndarray  # [H, R] i32
    kind: jnp.ndarray  # [H, R] i32
    count: jnp.ndarray  # [H] i64

    @classmethod
    def zeros(cls, num_hosts: int, capacity: int) -> "FlightRing":
        if capacity < 1:
            raise ValueError("flight recorder capacity must be >= 1")
        return cls(
            time=jnp.full((num_hosts, capacity), -1, jnp.int64),
            src=jnp.zeros((num_hosts, capacity), jnp.int32),
            seq=jnp.zeros((num_hosts, capacity), jnp.int32),
            kind=jnp.zeros((num_hosts, capacity), jnp.int32),
            count=jnp.zeros((num_hosts,), jnp.int64),
        )

    @property
    def capacity(self) -> int:
        return self.time.shape[-1]


def _put(arr, hit, val):
    val = jnp.asarray(val, arr.dtype)
    if val.ndim == arr.ndim - 1:
        val = val[..., None]
    return jnp.where(hit, val, arr)


def record(ring: FlightRing, mask, time, src, seq, kind) -> FlightRing:
    """Append one committed event per masked host at its ring cursor —
    a pure one-hot masked select over [H, R] (no scatter, no sync), fused
    into the window step. Sequential calls within one micro-step (bulk
    batches) compose: each call advances the masked hosts' counts."""
    R = ring.time.shape[-1]
    slot = (ring.count % R).astype(jnp.int32)
    cols = jnp.arange(R, dtype=jnp.int32)
    hit = mask[:, None] & (cols[None, :] == slot[:, None])
    return ring.replace(
        time=_put(ring.time, hit, time),
        src=_put(ring.src, hit, src),
        seq=_put(ring.seq, hit, seq),
        kind=_put(ring.kind, hit, kind),
        count=ring.count + mask.astype(jnp.int64),
    )


class FlightSpool:
    """Host-side spool writer: at each handoff boundary, drain the ring
    records committed since the previous flush into a binary frame.
    Records older than the ring window (more than R commits on one host
    between flushes) are overwritten on device and counted as `lost` —
    the flight-recorder contract is "the last R", not "all".
    """

    def __init__(self, path: str, num_hosts: int, capacity: int):
        self.path = path
        self.num_hosts = int(num_hosts)
        self.capacity = int(capacity)
        self._last = np.zeros(num_hosts, np.int64)  # flushed count per gid
        self.frames = 0
        self.records_written = 0
        self.records_lost = 0
        self._f = open(path, "wb")
        self._f.write(_HDR.pack(
            SPOOL_MAGIC, SPOOL_VERSION, self.num_hosts, self.capacity
        ))

    def flush(self, sim, frontier_ns: int, plane=None) -> int:
        """One device_get of the ring; emits only records not yet
        spooled (per-host count delta), in (time, host, seq) order.
        Returns the number of records written.

        With a multi-worker host plane attached (core/hostplane.py) the
        per-host record extraction is sharded across its pinned workers —
        each ring row is one host's partition — and the results merge in
        canonical (frontier, gid) order; the serial path walks rows in
        the same gid order, so the spool bytes are identical either
        way."""
        fl = getattr(sim.state, "flight", None)
        if fl is None or self._f is None:
            return 0
        blk = jax.device_get(fl)
        R = self.capacity
        t = np.asarray(blk.time).reshape(-1, R)
        s = np.asarray(blk.src).reshape(-1, R)
        q = np.asarray(blk.seq).reshape(-1, R)
        k = np.asarray(blk.kind).reshape(-1, R)
        cnt = np.asarray(blk.count).reshape(-1)
        gid = np.asarray(
            jax.device_get(sim.state.host.gid)
        ).reshape(-1)
        recs = []
        lost = 0

        def _extract(row):
            # partition-local: reads only host gid[row]'s ring row and
            # its own _last entry (mutated at the merge, not here)
            g = int(gid[row])
            n = int(cnt[row])
            prev = int(self._last[g])
            start = max(prev, n - R)
            out = []
            for i in range(start, n):
                sl = i % R
                out.append((
                    g, int(t[row, sl]), int(s[row, sl]),
                    int(q[row, sl]), int(k[row, sl]),
                ))
            return g, n, start - prev, out

        def _merge(res):
            nonlocal lost
            g, n, row_lost, out = res
            lost += row_lost
            recs.extend(out)
            self._last[g] = n

        order = sorted(range(t.shape[0]), key=lambda r: int(gid[r]))
        if plane is not None:
            from shadow_tpu.core import hostplane as hostplane_mod

            plane.drain([
                hostplane_mod.HostAction(
                    frontier_ns, int(gid[row]),
                    (lambda r=row: _extract(r)), _merge,
                )
                for row in order
            ])
        else:
            for row in order:
                _merge(_extract(row))
        if not recs and not lost:
            return 0
        recs.sort(key=lambda r: (r[1], r[0], r[3]))
        self._f.write(_FRAME.pack(int(frontier_ns), len(recs), lost))
        for r in recs:
            self._f.write(_REC.pack(*r))
        self._f.flush()
        self.frames += 1
        self.records_written += len(recs)
        self.records_lost += lost
        return len(recs)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def stats(self) -> dict:
        return {
            "frames": self.frames,
            "records_written": self.records_written,
            "records_lost": self.records_lost,
        }


def read_spool(path: str) -> dict:
    """Parse a spool file back into
    {"num_hosts", "capacity", "frames": [{"frontier_ns", "lost",
    "records": [(host, time_ns, src, seq, kind), ...]}, ...]}."""
    with open(path, "rb") as f:
        raw = f.read()
    if len(raw) < _HDR.size:
        raise ValueError(f"{path}: truncated spool header")
    magic, version, num_hosts, capacity = _HDR.unpack_from(raw, 0)
    if magic != SPOOL_MAGIC:
        raise ValueError(f"{path}: not a flight spool (bad magic)")
    if version != SPOOL_VERSION:
        raise ValueError(
            f"{path}: spool version {version} != {SPOOL_VERSION}"
        )
    off = _HDR.size
    frames = []
    while off < len(raw):
        if off + _FRAME.size > len(raw):
            raise ValueError(f"{path}: truncated frame header at {off}")
        frontier, n, lost = _FRAME.unpack_from(raw, off)
        off += _FRAME.size
        need = n * _REC.size
        if off + need > len(raw):
            raise ValueError(f"{path}: truncated frame body at {off}")
        recs = [
            _REC.unpack_from(raw, off + i * _REC.size) for i in range(n)
        ]
        off += need
        frames.append({
            "frontier_ns": int(frontier),
            "lost": int(lost),
            "records": recs,
        })
    return {
        "num_hosts": int(num_hosts),
        "capacity": int(capacity),
        "frames": frames,
    }
