"""shadowscope: the host-side profiling plane (time series + attribution).

The third observability plane (counters → audit → profiling): a
fixed-capacity ring of per-handoff interval records plus the mergeable
log-bucketed histograms of obs/hist.py, cheap enough to leave on in
production (a few dict writes per dispatch boundary — the driver already
synced there, so nothing here forces a device round-trip, and nothing
here touches simulation state: profiler-on runs keep bit-identical audit
chains).

Each ``tick_from(sim)`` at a handoff boundary records the DELTAS since
the previous tick — committed events, windows, async supersteps / yields
/ blocked-on-neighbor — stamped with both wall time (``wall_s`` since the
recorder's ``t0_unix``) and committed virtual time (``vt_ns``). Async
islands runs additionally contribute the per-shard frontier surface and
per-shard blocked deltas: frontier spread is the virtual-time roughness
of cond-mat/0302050 and ``blocked`` the desynchronization stall of
cs/0409032 — per interval, those name the shard the whole mesh is
waiting on (``critical_path`` below).

The ring dumps as a schema-versioned ``shadow_tpu.profile`` document
(``--profile-out``, the daemon's ``/timez``); histograms are pure int64
counts so the router can merge N peers' documents exactly
(``merge_profile_docs``), and ``align_series`` puts their rings on one
wall clock via each document's ``t0_unix``.
"""

from __future__ import annotations

import time

from shadow_tpu.obs.hist import LogHistogram

PROFILE_SCHEMA_VERSION = 1
PROFILE_DOC_KIND = "shadow_tpu.profile"

# ring capacity bounds (experimental.profiler_ring)
MIN_RING = 8
DEFAULT_RING = 512

# the driver-plane histograms every recorder carries (ns values); the
# serve plane adds request-latency histograms via hist() on demand
_DRIVER_HISTS = ("dispatch_wall_ns", "host_drain_wall_ns",
                 "window_width_ns")


class ProfRecorder:
    """Fixed-capacity interval ring + mergeable histograms.

    ``base_vt_ns`` seeds the virtual-time baseline: a resumed run passes
    the checkpoint's committed frontier so its first interval's width is
    the width the uninterrupted run would have recorded — the
    resume-then-merge equality the profile smoke gates on.
    """

    def __init__(self, capacity: int = DEFAULT_RING, *,
                 base_vt_ns: int = 0):
        if capacity < MIN_RING:
            raise ValueError(
                f"profiler ring capacity must be >= {MIN_RING}, "
                f"got {capacity}"
            )
        self.capacity = int(capacity)
        self.t0_unix = time.time()
        self._t0 = time.perf_counter()
        self._ring: list[dict] = []
        self._head = 0          # next write slot once the ring is full
        self.recorded = 0       # total intervals ever recorded
        self._hists: dict[str, LogHistogram] = {
            name: LogHistogram() for name in _DRIVER_HISTS
        }
        self._last_wall = self._t0
        self._last = {"events": 0, "windows": 0, "supersteps": 0,
                      "yields": 0, "blocked": 0, "vt_ns": int(base_vt_ns)}
        self._last_shard_blocked: list[int] | None = None
        self._lookahead_in: list[list[int]] | None = None
        self._shards = 0

    # -- histograms ----------------------------------------------------

    def hist(self, name: str) -> LogHistogram:
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = LogHistogram()
        return h

    def observe_wall(self, name: str, dt_s: float) -> None:
        """Wall-span observation in seconds, binned as integer ns."""
        self.hist(name).observe(int(dt_s * 1e9))

    # -- the interval ring ---------------------------------------------

    def _push(self, rec: dict) -> None:
        if len(self._ring) < self.capacity:
            self._ring.append(rec)
        else:
            self._ring[self._head] = rec
            self._head = (self._head + 1) % self.capacity
        self.recorded += 1

    def intervals(self) -> list[dict]:
        """Ring contents, oldest first."""
        return self._ring[self._head:] + self._ring[:self._head]

    @property
    def dropped(self) -> int:
        """Intervals overwritten by ring wraparound."""
        return max(0, self.recorded - self.capacity)

    def tick_from(self, sim, frontier_ns: int | None = None) -> None:
        """Record one interval at a handoff boundary: read-only against
        the sim (host-cached counters + the already-fetched frontier),
        so the profiled schedule is the unprofiled schedule."""
        c = sim.counters()
        events = int(c.get("events_committed", 0))
        windows = int(getattr(sim, "windows_run", 0) or 0)
        shard = None
        ap = getattr(sim, "async_shard_profile", None)
        if ap is not None:
            shard = ap()
        astats = {}
        ast = getattr(sim, "async_stats", None)
        if ast is not None:
            astats = ast() or {}
        vt = int(frontier_ns or 0)
        if shard is not None and shard.get("frontier_ns"):
            vt = max(vt, min(shard["frontier_ns"]))
        self.tick(
            vt_ns=vt, events=events, windows=windows,
            supersteps=int(astats.get("supersteps", 0)),
            yields=int(astats.get("yields", 0)),
            blocked=int(astats.get("blocked_on_neighbor", 0)),
            frontier_ns=(shard or {}).get("frontier_ns"),
            shard_blocked=(shard or {}).get("blocked"),
            lookahead_in=(shard or {}).get("lookahead_in"),
        )

    def tick(self, *, vt_ns: int, events: int, windows: int,
             supersteps: int = 0, yields: int = 0, blocked: int = 0,
             frontier_ns=None, shard_blocked=None,
             lookahead_in=None) -> None:
        """Record one interval from CUMULATIVE inputs; deltas against the
        previous tick are what lands in the ring."""
        now = time.perf_counter()
        last = self._last
        vt_ns = int(vt_ns)
        if vt_ns >= (1 << 62):
            # a drained pool reports NEVER as its frontier (the run's
            # final boundary): record the interval, not a 2^62 "width"
            vt_ns = last["vt_ns"]
        vt_ns = max(vt_ns, last["vt_ns"])  # committed vt is monotonic
        rec = {
            "wall_s": round(now - self._t0, 6),
            "d_wall_s": round(now - self._last_wall, 6),
            "vt_ns": vt_ns,
            "d_vt_ns": vt_ns - last["vt_ns"],
            "d_events": max(0, int(events) - last["events"]),
            "d_windows": max(0, int(windows) - last["windows"]),
            "d_supersteps": max(0, int(supersteps) - last["supersteps"]),
            "d_yields": max(0, int(yields) - last["yields"]),
            "d_blocked": max(0, int(blocked) - last["blocked"]),
        }
        if frontier_ns is not None:
            rec["frontier_ns"] = [int(x) for x in frontier_ns]
        if shard_blocked is not None:
            cur = [int(x) for x in shard_blocked]
            prev = self._last_shard_blocked
            if prev is not None and len(prev) == len(cur):
                rec["d_shard_blocked"] = [
                    max(0, a - b) for a, b in zip(cur, prev)
                ]
            else:
                rec["d_shard_blocked"] = cur
            self._last_shard_blocked = cur
            self._shards = len(cur)
        if lookahead_in is not None and self._lookahead_in is None:
            self._lookahead_in = [[int(x) for x in row]
                                  for row in lookahead_in]
        self._hists["window_width_ns"].observe(rec["d_vt_ns"])
        self._push(rec)
        self._last = {"events": int(events), "windows": int(windows),
                      "supersteps": int(supersteps), "yields": int(yields),
                      "blocked": int(blocked), "vt_ns": vt_ns}
        self._last_wall = now

    @property
    def last_vt_ns(self) -> int:
        """Committed virtual time at the last tick — the ``base_vt_ns``
        a resumed continuation recorder seeds from."""
        return self._last["vt_ns"]

    # -- documents -----------------------------------------------------

    def to_doc(self, meta: dict | None = None) -> dict:
        return {
            "kind": PROFILE_DOC_KIND,
            "schema_version": PROFILE_SCHEMA_VERSION,
            "created_unix": time.time(),
            "t0_unix": self.t0_unix,
            "meta": dict(meta or {}),
            "capacity": self.capacity,
            "recorded": self.recorded,
            "dropped": self.dropped,
            "intervals": self.intervals(),
            "hists": {k: h.to_doc()
                      for k, h in sorted(self._hists.items())
                      if h.count},
            **({"lookahead_in": self._lookahead_in}
               if self._lookahead_in is not None else {}),
        }


def validate_profile_doc(doc: dict) -> None:
    """Reference validator for shadow_tpu.profile documents."""
    if not isinstance(doc, dict):
        raise ValueError("profile doc must be a JSON object")
    if doc.get("kind") != PROFILE_DOC_KIND:
        raise ValueError(
            f"profile doc kind {doc.get('kind')!r} != {PROFILE_DOC_KIND!r}"
        )
    if doc.get("schema_version") != PROFILE_SCHEMA_VERSION:
        raise ValueError(
            f"profile schema_version {doc.get('schema_version')!r} != "
            f"{PROFILE_SCHEMA_VERSION}"
        )
    if not isinstance(doc.get("intervals"), list):
        raise ValueError("profile doc needs an intervals list")
    for i, rec in enumerate(doc["intervals"]):
        if not isinstance(rec, dict) or "wall_s" not in rec \
                or "vt_ns" not in rec:
            raise ValueError(
                f"intervals[{i}] must be an object stamped with wall_s "
                f"and vt_ns"
            )
    hists = doc.get("hists", {})
    if not isinstance(hists, dict):
        raise ValueError("profile doc hists must be an object")
    for k, h in hists.items():
        LogHistogram.from_doc(h)  # layout + shape check
    for k in ("capacity", "recorded", "dropped"):
        v = doc.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            raise ValueError(f"profile doc {k!r} must be a count, got {v!r}")


def merge_profile_docs(docs: dict[str, dict]) -> dict:
    """Federation roll-up (router /timez): merge named peers' profile
    documents — histograms fold exactly (int64 adds), rings align onto
    one wall clock (``align_series``). Raises ValueError on a layout or
    schema mismatch so a stale peer can't silently poison the fold."""
    hists: dict[str, LogHistogram] = {}
    peers = {}
    for name, doc in sorted(docs.items()):
        validate_profile_doc(doc)
        for k, h in doc.get("hists", {}).items():
            cur = hists.setdefault(k, LogHistogram())
            cur.merge(LogHistogram.from_doc(h))
        peers[name] = {
            "t0_unix": float(doc.get("t0_unix", 0.0)),
            "recorded": int(doc.get("recorded", 0)),
            "dropped": int(doc.get("dropped", 0)),
        }
    return {
        "kind": PROFILE_DOC_KIND,
        "schema_version": PROFILE_SCHEMA_VERSION,
        "created_unix": time.time(),
        "merged": True,
        "peers": peers,
        "hists": {k: h.to_doc() for k, h in sorted(hists.items())
                  if h.count},
        "series": align_series(docs),
    }


def align_series(docs: dict[str, dict]) -> list[dict]:
    """One interleaved time series from N peers' rings: every interval
    re-stamped onto the unix clock (``t0_unix + wall_s``) and tagged with
    its peer, sorted by absolute time — one timeline, N producers."""
    out = []
    for name, doc in sorted(docs.items()):
        t0 = float(doc.get("t0_unix", 0.0))
        for rec in doc.get("intervals", []):
            r = dict(rec)
            r["peer"] = name
            r["t_unix"] = round(t0 + float(rec.get("wall_s", 0.0)), 6)
            out.append(r)
    out.sort(key=lambda r: (r["t_unix"], r["peer"]))
    return out


def critical_path(doc: dict) -> dict | None:
    """Critical-path attribution from a profile document's per-shard
    interval data.

    Per interval the laggard is the shard holding the minimum frontier —
    under conservative sync every other shard's horizon is bounded by
    that frontier plus its in-edge lookahead, so when anyone is blocked,
    the minimum-frontier shard is what they are waiting on. Wall time of
    intervals that saw blocking is attributed to that interval's laggard;
    the report names the shard with the largest attribution, the in-edge
    link it throttles hardest (its most-blocked victim, with the baked
    lookahead bound when the document carries the matrix), and the
    blocked fraction of all shard-supersteps. Returns None when the
    document carries no per-shard intervals (barrier or global-engine
    runs)."""
    rows = [r for r in doc.get("intervals", [])
            if r.get("frontier_ns")]
    if not rows:
        return None
    S = len(rows[0]["frontier_ns"])
    attr_wall = [0.0] * S       # wall attributed to shard as laggard
    victim_blk = [[0] * S for _ in range(S)]  # [laggard][victim]
    tot_blocked = tot_steps = tot_yields = 0
    total_wall = 0.0
    for r in rows:
        fr = r["frontier_ns"]
        if len(fr) != S:
            continue  # elastic relayout changed the mesh mid-ring
        dw = float(r.get("d_wall_s", 0.0))
        total_wall += dw
        lag = min(range(S), key=lambda i: (fr[i], i))
        blk = r.get("d_shard_blocked")
        d_blocked = int(r.get("d_blocked", 0)) if blk is None \
            else int(sum(blk))
        tot_blocked += d_blocked
        tot_steps += int(r.get("d_supersteps", 0))
        tot_yields += int(r.get("d_yields", 0))
        if d_blocked > 0:
            attr_wall[lag] += dw
            if blk is not None and len(blk) == S:
                for v in range(S):
                    if v != lag:
                        victim_blk[lag][v] += blk[v]
    critical = max(range(S), key=lambda i: (attr_wall[i], -i))
    denom = tot_blocked + tot_steps + tot_yields
    result = {
        "shards": S,
        "intervals": len(rows),
        "critical_shard": int(critical),
        "wall_s": round(total_wall, 6),
        "attributed_wall_s": round(attr_wall[critical], 6),
        "wall_frac": (attr_wall[critical] / total_wall)
        if total_wall > 0 else 0.0,
        "blocked_frac": (tot_blocked / denom) if denom else 0.0,
        "per_shard_wall_s": [round(w, 6) for w in attr_wall],
    }
    vrow = victim_blk[critical]
    if any(vrow):
        victim = max(range(S), key=lambda v: (vrow[v], -v))
        link = {"src": int(critical), "dst": int(victim),
                "blocked": int(vrow[victim])}
        la = doc.get("lookahead_in")
        if la is not None and len(la) == S:
            bound = int(la[victim][critical])
            if bound < (1 << 62):  # NEVER-masked rows mean "no edge"
                link["lookahead_ns"] = bound
        result["link"] = link
    return result
