"""Determinism audit plane: in-kernel committed-event digest chains.

Every plane in this engine stakes correctness on bit-identical replay —
conservative vs optimistic, islands vs global, fleet lane vs solo, resume
vs uninterrupted — but until this module that property was only checked
inside tests by hauling full event arrays to the host. The digest chain
makes it a production signal (PARSIR's per-LP run-audit instrumentation,
arxiv 2410.00644, carried the way `host_last_t` carries the roughness
metric): a per-host i64 rolling-mix hash of every committed event's key
(time, src, dst, kind), folded INSIDE the jitted window step as an
`ObsBlock` field (block v4), so two runs that committed exactly the same
history carry exactly the same digests — and two that didn't, don't.

Design invariants:

* **Per-host order-dependent, cross-host order-independent.** Each host
  folds its own events in per-host key order (the order every engine
  commits them in), so per-host digests are layout-independent; the
  GLOBAL chain combines host digests with a commutative reduction, so
  islands shards / fleet lanes / rebalance permutations all report the
  value the global engine would.
* **Committed-only.** The digest rides the state pytree: an optimistic
  rollback drops the speculated digests with the rest of the speculated
  state, so chains never include rolled-back work.
* **Checkpointed.** `host_digest` is a SimState leaf, so every checkpoint
  carries the chain (plus a header copy in the .npz meta) and resume
  parity is auditable end-to-end with `tools/diff_digest.py`.

The host-side pieces here — `AuditTrail` (per-handoff chain records),
the digest-document schema + validator, and the diff engine behind
`tools/diff_digest.py` — turn "two runs disagree" into one tool
invocation instead of a full-rerun bisect.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np

_MASK = (1 << 64) - 1


def _i64(x: int) -> int:
    """A 64-bit constant as the (possibly negative) python int whose i64
    bit pattern matches — jnp promotes it into i64 expressions exactly."""
    x &= _MASK
    return x - (1 << 64) if x >= (1 << 63) else x


# splitmix64 / PCG-style odd multipliers: full-period under wrapping i64
# multiply, so single-field changes (one event's time, src, dst or kind)
# avalanche through the key. XLA integer arithmetic wraps two's-complement,
# which is exactly the modular arithmetic the chain is defined over.
_K_TIME = _i64(0xBF58476D1CE4E5B9)
_K_SRC = _i64(0x94D049BB133111EB)
_K_DST = _i64(0x2545F4914F6CDD1D)
_K_KIND = _i64(0xFF51AFD7ED558CCD)
_CHAIN_MULT = _i64(0x5851F42D4C957F2D)
_COMBINE_MULT = 0x9E3779B97F4A7C15


def event_key(time, src, dst, kind) -> jnp.ndarray:
    """Mix one committed event's total-order key into a single i64. All
    four fields participate (+1 offsets keep host/kind 0 from zeroing a
    term); a final xorshift spreads low-entropy inputs across the word."""
    k = jnp.asarray(time, jnp.int64) * _K_TIME
    k = k ^ ((jnp.asarray(src, jnp.int64) + 1) * _K_SRC)
    k = k ^ ((jnp.asarray(dst, jnp.int64) + 1) * _K_DST)
    k = k ^ ((jnp.asarray(kind, jnp.int64) + 1) * _K_KIND)
    return k ^ jax.lax.shift_right_logical(k, jnp.asarray(31, jnp.int64))


def fold(digest, mask, time, src, dst, kind) -> jnp.ndarray:
    """One rolling-mix chain step per masked host:
    digest' = digest * MULT + key(event). Order-DEPENDENT by construction
    — the per-host commit order IS part of what the chain audits — and a
    pure fused select/multiply/add, so it rides the window step at
    vector bandwidth (no sync, no gather)."""
    nd = digest * _CHAIN_MULT + event_key(time, src, dst, kind)
    return jnp.where(mask, nd, digest)


def combine(host_digests) -> int:
    """Collapse per-host digests into ONE unsigned 64-bit chain value with
    a commutative reduction (wrapping sum + xor), so the result is
    independent of host enumeration order — islands shard layouts, fleet
    lane slices and rebalance permutations all combine to the value the
    global engine reports. Host-side only (runs on snapshot output)."""
    d = np.asarray(host_digests).astype(np.uint64).reshape(-1)
    if d.size == 0:
        return 0
    s = int(np.sum(d, dtype=np.uint64))
    x = int(np.bitwise_xor.reduce(d))
    return ((s * _COMBINE_MULT) ^ x) & _MASK


# ---------------------------------------------------------------------------
# The digest document (--digest-out) + validator + diff engine
# ---------------------------------------------------------------------------

DOC_KIND = "shadow_tpu.digest"
DIGEST_SCHEMA_VERSION = 1


class AuditTrail:
    """Per-handoff chain records for one run. The drivers call
    `record()` at every handoff boundary they already sync at (one extra
    device_get of the obs block); `dump()` writes the schema'd digest
    document `tools/diff_digest.py` consumes."""

    def __init__(self, meta: dict | None = None):
        self.meta = dict(meta or {})
        self.records: list[dict] = []

    def record(self, snap: dict, frontier_ns: int) -> dict | None:
        """Append one chain record from an obs snapshot
        (obs.counters.snapshot output). Consecutive duplicates (stalled
        handoffs that committed nothing) collapse into one record."""
        if not snap or "host_digest" not in snap:
            return None
        chain = combine(snap["host_digest"])
        events = int(np.asarray(snap["host_events"]).sum())
        if self.records:
            last = self.records[-1]
            if (last["frontier_ns"] == int(frontier_ns)
                    and last["chain"] == chain):
                return last
        rec = {
            "seq": len(self.records),
            "frontier_ns": int(frontier_ns),
            "chain": chain,
            "events_committed": events,
        }
        self.records.append(rec)
        return rec

    def to_doc(self, snap: dict) -> dict:
        """The digest document: meta, the per-handoff chain records, the
        final per-host sub-chains (unsigned ints, GLOBAL host order) and
        the final combined chain."""
        hosts = [
            int(np.uint64(v)) for v in np.asarray(snap["host_digest"])
        ] if snap and "host_digest" in snap else []
        events = (
            int(np.asarray(snap["host_events"]).sum()) if snap else 0
        )
        final = {
            "chain": combine(snap["host_digest"]) if hosts else 0,
            "events_committed": events,
            "frontier_ns": (
                self.records[-1]["frontier_ns"] if self.records else -1
            ),
        }
        return {
            "kind": DOC_KIND,
            "schema_version": DIGEST_SCHEMA_VERSION,
            "meta": dict(self.meta),
            "final": final,
            "hosts": hosts,
            "records": list(self.records),
        }

    def dump(self, path: str, snap: dict) -> dict:
        doc = self.to_doc(snap)
        validate_digest_doc(doc)
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        return doc


_REC_KEYS = ("seq", "frontier_ns", "chain", "events_committed")


def validate_digest_doc(doc: dict) -> None:
    """Raise ValueError unless `doc` conforms to the digest-document
    schema (docs/observability.md)."""
    if not isinstance(doc, dict):
        raise ValueError("digest doc must be a JSON object")
    if doc.get("kind") != DOC_KIND:
        raise ValueError(
            f"digest doc kind {doc.get('kind')!r} != {DOC_KIND!r}"
        )
    if doc.get("schema_version") != DIGEST_SCHEMA_VERSION:
        raise ValueError(
            f"digest schema_version {doc.get('schema_version')!r} != "
            f"{DIGEST_SCHEMA_VERSION}"
        )
    if not isinstance(doc.get("meta"), dict):
        raise ValueError("digest doc meta missing or not an object")
    final = doc.get("final")
    if not isinstance(final, dict) or not {
        "chain", "events_committed", "frontier_ns"
    } <= set(final):
        raise ValueError(
            "digest doc final must carry chain/events_committed/frontier_ns"
        )
    hosts = doc.get("hosts")
    if not isinstance(hosts, list) or not all(
        isinstance(h, int) and not isinstance(h, bool) for h in hosts
    ):
        raise ValueError("digest doc hosts must be a list of integers")
    recs = doc.get("records")
    if not isinstance(recs, list):
        raise ValueError("digest doc records must be a list")
    prev = None
    for i, r in enumerate(recs):
        if not isinstance(r, dict) or not set(_REC_KEYS) <= set(r):
            raise ValueError(
                f"digest record [{i}] must carry keys {list(_REC_KEYS)}"
            )
        for k in _REC_KEYS:
            if not isinstance(r[k], int) or isinstance(r[k], bool):
                raise ValueError(f"digest record [{i}].{k} must be an integer")
        if prev is not None and r["frontier_ns"] < prev:
            raise ValueError(
                f"digest record [{i}] frontier_ns regresses "
                f"({r['frontier_ns']} < {prev})"
            )
        prev = r["frontier_ns"]


def diff_digest_docs(a: dict, b: dict) -> dict:
    """Compare two digest documents: the FIRST window (handoff record)
    whose chains disagree, and the hosts whose final sub-chains differ.

    Records are aligned by virtual-time frontier, not by index — two runs
    of the same scenario may chunk their dispatches differently (different
    windows_per_dispatch, a resume mid-run), so only frontiers both runs
    recorded are comparable; at each, the chain must match or the runs
    committed different histories up to that point."""
    fa = {r["frontier_ns"]: r for r in a.get("records", [])}
    fb = {r["frontier_ns"]: r for r in b.get("records", [])}
    common = sorted(set(fa) & set(fb))
    first = None
    last_match_ns = None
    for t in common:
        if fa[t]["chain"] != fb[t]["chain"]:
            first = {
                "frontier_ns": t,
                "seq_a": fa[t]["seq"],
                "seq_b": fb[t]["seq"],
                "chain_a": fa[t]["chain"],
                "chain_b": fb[t]["chain"],
                "events_a": fa[t]["events_committed"],
                "events_b": fb[t]["events_committed"],
            }
            break
        last_match_ns = t
    ha, hb = a.get("hosts") or [], b.get("hosts") or []
    divergent_hosts = [
        i for i, (x, y) in enumerate(zip(ha, hb)) if x != y
    ]
    final_equal = (
        a.get("final", {}).get("chain") == b.get("final", {}).get("chain")
    )
    identical = (
        final_equal and first is None and not divergent_hosts
        and len(ha) == len(hb)
    )
    out = {
        "identical": identical,
        "final_chain_equal": final_equal,
        "first_divergent_record": first,
        "divergent_hosts": divergent_hosts,
        "host_count": (len(ha), len(hb)),
        "common_windows": len(common),
        "records": (len(a.get("records", [])), len(b.get("records", []))),
    }
    if first is None and not final_equal:
        # no common frontier disagrees but the ends do: the divergence
        # happened after the last frontier both runs recorded
        out["diverged_after_ns"] = last_match_ns
    return out


def diff_digest_vs_checkpoint(doc: dict, ckpt_dir: str) -> dict:
    """Audit a checkpoint ring against a digest document: the newest
    readable checkpoint's header chain (written by core/checkpoint.save)
    must equal the document's chain record at the same frontier —
    checkpoints and chain records are written at the same handoff
    boundaries, so a matching frontier exists whenever both came from the
    same run."""
    from shadow_tpu.core import checkpoint as ckpt_mod

    entries = ckpt_mod.ring_entries(ckpt_dir)
    if not entries:
        raise ValueError(f"{ckpt_dir}: no ring checkpoints to audit")
    meta = chain = sim_ns = path = None
    for seq, ns, p in reversed(entries):
        try:
            m = ckpt_mod.load_meta(p)
        except ckpt_mod.CheckpointError:
            continue
        audit = m.get("audit")
        if isinstance(audit, dict) and "chain" in audit:
            meta, chain, sim_ns, path = m, int(audit["chain"]), ns, p
            break
    if meta is None:
        raise ValueError(
            f"{ckpt_dir}: no checkpoint carries an audit chain header "
            f"(written by builds with the digest chain enabled)"
        )
    recs = {r["frontier_ns"]: r for r in doc.get("records", [])}
    at = recs.get(sim_ns)
    if at is None:
        # fall back to the newest record at or before the checkpoint time
        older = [t for t in recs if t <= sim_ns]
        at = recs[max(older)] if older else None
    return {
        "checkpoint": path,
        "checkpoint_frontier_ns": sim_ns,
        "checkpoint_chain": chain,
        "record": at,
        "match": at is not None and at["chain"] == chain,
    }
