"""Support utilities: logging, tracking, pcap capture."""
