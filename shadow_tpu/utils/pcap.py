"""Wireshark-readable pcap capture of simulated traffic.

Reference: src/main/utility/pcap_writer.c — each host's NIC writes every
rx/tx packet when `pcap_directory` is configured
(network_interface.c:438-440). Simulated packets have no real wire bytes, so
like the reference we synthesize minimal IPv4 + UDP/TCP headers around the
payload (the reference stores header fields and emits them the same way).
"""

from __future__ import annotations

import struct

LINKTYPE_RAW = 101  # packets start with the IPv4 header

# classic pcap magics: the second-field granularity of every record
MAGIC_USEC = 0xA1B2C3D4  # microsecond timestamps (the historical default)
MAGIC_NSEC = 0xA1B23C4D  # nanosecond timestamps (libpcap >= 1.5 readers)

_PROTO_UDP = 17
_PROTO_TCP = 6


class PcapWriter:
    """One capture file (classic pcap format).

    The engine stamps packets in nanoseconds; the default microsecond
    records truncate that. ``nanosecond=True`` opts into the
    nanosecond-resolution magic (0xA1B23C4D) so captures round-trip the
    engine's timestamps exactly — Wireshark/tshark read both.
    """

    def __init__(self, path: str, *, nanosecond: bool = False):
        self._f = open(path, "wb")
        self._ns = bool(nanosecond)
        magic = MAGIC_NSEC if self._ns else MAGIC_USEC
        # magic, v2.4, thiszone=0, sigfigs=0, snaplen, linktype
        self._f.write(
            struct.pack("<IHHiIII", magic, 2, 4, 0, 0, 65535, LINKTYPE_RAW)
        )

    def _record(self, time_ns: int, data: bytes) -> None:
        sec, ns = divmod(int(time_ns), 1_000_000_000)
        frac = ns if self._ns else ns // 1000
        self._f.write(
            struct.pack("<IIII", sec, frac, len(data), len(data))
        )
        self._f.write(data)

    def write_packet(
        self,
        time_ns: int,
        *,
        proto: str,  # "udp" | "tcp"
        src_ip: int,
        src_port: int,
        dst_ip: int,
        dst_port: int,
        payload: bytes = b"",
        payload_len: int | None = None,
        seq: int = 0,
        ack: int = 0,
        flags: int = 0x10,  # TCP flags byte; default ACK
        window: int = 65535,
    ) -> None:
        """Write one packet with synthesized IPv4+L4 headers.

        ``payload_len`` supports device-plane packets where only the length
        is known: that many zero bytes stand in for the app data.
        """
        if payload_len is not None and not payload:
            payload = bytes(min(payload_len, 65000))
        if proto == "udp":
            l4 = struct.pack(
                ">HHHH", src_port, dst_port, 8 + len(payload), 0
            ) + payload
            pnum = _PROTO_UDP
        else:
            l4 = struct.pack(
                ">HHIIBBHHH",
                src_port, dst_port, seq & 0xFFFFFFFF, ack & 0xFFFFFFFF,
                5 << 4, flags & 0xFF, window & 0xFFFF, 0, 0,
            ) + payload
            pnum = _PROTO_TCP
        total = 20 + len(l4)
        ip = struct.pack(
            ">BBHHHBBHII",
            0x45, 0, total, 0, 0, 64, pnum, 0,
            src_ip & 0xFFFFFFFF, dst_ip & 0xFFFFFFFF,
        )
        self._record(time_ns, ip + l4)

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
