"""Sim-time-stamped structured logging.

Reference: the C macro API panic/error/warning/info/debug/trace
(src/lib/logger/logger.h:24-33) backed by the Rust ShadowLogger whose records
carry wall time, sim time, and host/process context from the thread-local
Worker (src/main/core/logger/shadow_logger.rs:109,184; worker.rs:40-50).
Log line shape follows docs/log_format.md:

    00:00:10.000001 [worker] 00:00:05.000000 [info] [hostname] message

Here there is one process and one logger; "context" is set around handler
execution (host name, process name) rather than read from a thread-local.
"""

from __future__ import annotations

import sys
import time as wall_time
from typing import IO

TRACE = 10
DEBUG = 20
INFO = 30
WARNING = 40
ERROR = 50
PANIC = 60

_LEVELS = {
    "trace": TRACE,
    "debug": DEBUG,
    "info": INFO,
    "warning": WARNING,
    "error": ERROR,
    "panic": PANIC,
}
_NAMES = {v: k for k, v in _LEVELS.items()}


def parse_level(name: str) -> int:
    try:
        return _LEVELS[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown log level {name!r} (expected one of {sorted(_LEVELS)})"
        ) from None


def _fmt_msg(msg: str, args: tuple) -> str:
    """%-format only when args are present; a message whose literal '%'
    doesn't match the args (URLs, \"50% full\" with trailing args) must
    never crash the logger — fall back to appending the args."""
    if not args:
        return msg
    try:
        return msg % args
    except (TypeError, ValueError):
        return f"{msg} {' '.join(str(a) for a in args)}"


def _fmt_time(ns: int) -> str:
    """ns → HH:MM:SS.micros (log_format.md sim-time shape)."""
    us, _ = divmod(int(ns), 1_000)
    s, us = divmod(us, 1_000_000)
    m, s = divmod(s, 60)
    h, m = divmod(m, 60)
    return f"{h:02d}:{m:02d}:{s:02d}.{us:06d}"


class SimLogger:
    """Level-filtered logger stamping wall time, sim time, and host context."""

    def __init__(self, stream: IO[str] | None = None, level: int = INFO):
        self.stream = stream if stream is not None else sys.stderr
        self.level = level
        self._t0 = wall_time.monotonic()
        # current context, set by the driver around handler execution
        self.sim_now_fn = lambda: 0  # returns current sim ns
        self.host: str | None = None

    def set_level(self, level: int | str) -> None:
        self.level = parse_level(level) if isinstance(level, str) else level

    def log(self, level: int, msg: str, *args, host: str | None = None) -> None:
        if level < self.level:
            return
        msg = _fmt_msg(msg, args)
        wall = wall_time.monotonic() - self._t0
        sim = self.sim_now_fn()
        ctx = host or self.host
        parts = [
            _fmt_time(int(wall * 1e9)),
            _fmt_time(sim),
            f"[{_NAMES.get(level, level)}]",
        ]
        if ctx:
            parts.append(f"[{ctx}]")
        parts.append(msg)
        print(" ".join(parts), file=self.stream, flush=level >= WARNING)

    def trace(self, msg, *a, **kw):
        self.log(TRACE, msg, *a, **kw)

    def debug(self, msg, *a, **kw):
        self.log(DEBUG, msg, *a, **kw)

    def info(self, msg, *a, **kw):
        self.log(INFO, msg, *a, **kw)

    def warning(self, msg, *a, **kw):
        self.log(WARNING, msg, *a, **kw)

    def error(self, msg, *a, **kw):
        self.log(ERROR, msg, *a, **kw)

    def panic(self, msg, *a, **kw):
        self.log(PANIC, msg, *a, **kw)
        raise RuntimeError(_fmt_msg(msg, a))


# module-level default logger (the reference's single global logger)
logger = SimLogger()
