"""Minimal GML (Graph Modelling Language) parser — igraph-free.

Parses the subset the reference's network graphs use
(docs/network_graph_spec.md): a ``graph [ ... ]`` block with ``directed``,
``node [ id ... ]`` and ``edge [ source target ... ]`` sub-blocks, and
string/int/float attribute values. Nested blocks are handled generically.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any


class GmlParseError(ValueError):
    pass


_TOKEN_RE = re.compile(
    r"""
    \s*(
        \#[^\n]*                   # comment to end of line (outside strings)
      | \[ | \]
      | "(?:[^"\\]|\\.)*"          # quoted string (may contain '#')
      | [^\s\[\]"]+                # bare word / number
    )
    """,
    re.VERBOSE,
)


def _tokenize(text: str):
    # Comments are recognized at token boundaries only, so a '#' inside a
    # quoted string attribute value is preserved.
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            if text[pos:].strip():
                raise GmlParseError(f"bad token at offset {pos}: {text[pos:pos+20]!r}")
            return
        if not m.group(1).startswith("#"):
            yield m.group(1)
        pos = m.end()


def _coerce(tok: str) -> Any:
    if tok.startswith('"'):
        return tok[1:-1].replace('\\"', '"').replace("\\\\", "\\")
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    return tok


def _parse_block(tokens, top_level: bool = False) -> dict:
    """Parse key/value pairs until a closing ']' (or EOF at top level).
    Repeated keys (node, edge) accumulate into lists."""
    out: dict[str, Any] = {}
    for tok in tokens:
        if tok == "]":
            if top_level:
                raise GmlParseError("unbalanced ']'")
            return out
        if tok == "[":
            raise GmlParseError("unexpected '['")
        key = tok
        try:
            val_tok = next(tokens)
        except StopIteration:
            raise GmlParseError(f"missing value for key {key!r}") from None
        value = _parse_block(tokens) if val_tok == "[" else _coerce(val_tok)
        if key in out:
            if not isinstance(out[key], list):
                out[key] = [out[key]]
            out[key].append(value)
        else:
            out[key] = value
    if not top_level:
        raise GmlParseError("unexpected end of input: unclosed '[' block")
    return out


@dataclasses.dataclass
class GmlGraph:
    directed: bool
    nodes: list[dict]  # each has at least "id"
    edges: list[dict]  # each has at least "source", "target"
    attrs: dict


def parse_gml(text: str) -> GmlGraph:
    tokens = _tokenize(text)
    top = _parse_block(tokens, top_level=True)
    if "graph" not in top:
        raise GmlParseError("no `graph [ ... ]` block found")
    g = top["graph"]
    if isinstance(g, list):
        raise GmlParseError("multiple graph blocks")
    nodes = g.get("node", [])
    edges = g.get("edge", [])
    if isinstance(nodes, dict):
        nodes = [nodes]
    if isinstance(edges, dict):
        edges = [edges]
    for n in nodes:
        if "id" not in n:
            raise GmlParseError("node missing id")
    for e in edges:
        if "source" not in e or "target" not in e:
            raise GmlParseError("edge missing source/target")
    attrs = {k: v for k, v in g.items() if k not in ("node", "edge")}
    return GmlGraph(
        directed=bool(g.get("directed", 0)),
        nodes=nodes,
        edges=edges,
        attrs=attrs,
    )
