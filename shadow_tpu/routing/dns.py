"""Global name ↔ IP registry (reference: src/main/routing/dns.c:125-193).

Assigns each host a unique IPv4 address at setup, honoring an
``ip_address_hint`` when it is valid and unused, otherwise allocating
sequentially from 11.0.0.1 (public-range addresses, like the reference,
so managed processes never confuse simulated addresses with loopback).
Resolution backs the getaddrinfo interposition
(src/lib/shim/preload_libraries.c:292) and packet delivery addressing.
"""

from __future__ import annotations

import ipaddress


class DnsError(ValueError):
    pass


# Reserved IPv4 ranges the reference refuses to assign (dns.c:84-110,
# _dns_isRestricted): hints inside these are regenerated, and the sequential
# allocator skips them (which is why its counter lands at 11.0.0.0+).
_RESERVED_NETS = [
    ipaddress.ip_network(n)
    for n in (
        "0.0.0.0/8", "10.0.0.0/8", "100.64.0.0/10", "127.0.0.0/8",
        "169.254.0.0/16", "172.16.0.0/12", "192.0.0.0/29", "192.0.2.0/24",
        "192.88.99.0/24", "192.168.0.0/16", "198.18.0.0/15", "198.51.100.0/24",
        "203.0.113.0/24", "224.0.0.0/4", "240.0.0.0/4", "255.255.255.255/32",
    )
]


def _is_restricted(ip: int) -> bool:
    addr = ipaddress.ip_address(ip)
    return any(addr in net for net in _RESERVED_NETS)


class Dns:
    def __init__(self, base_ip: str = "11.0.0.1"):
        self._next = int(ipaddress.ip_address(base_ip))
        self._name_to_ip: dict[str, int] = {}
        self._ip_to_name: dict[int, str] = {}
        self._ip_to_host: dict[int, int] = {}

    def register(self, host_index: int, name: str, ip_hint: str | None = None) -> int:
        """Register a host; returns its assigned IPv4 as a u32."""
        if name in self._name_to_ip:
            raise DnsError(f"duplicate hostname {name!r}")
        ip = None
        if ip_hint is not None:
            want = int(ipaddress.ip_address(ip_hint))
            if want not in self._ip_to_name and not _is_restricted(want):
                ip = want
        if ip is None:
            while self._next in self._ip_to_name or _is_restricted(self._next):
                self._next += 1
            ip = self._next
            self._next += 1
        self._name_to_ip[name] = ip
        self._ip_to_name[ip] = name
        self._ip_to_host[ip] = host_index
        return ip

    def resolve_name(self, name: str) -> int | None:
        return self._name_to_ip.get(name)

    def resolve_ip(self, ip: int) -> str | None:
        return self._ip_to_name.get(ip)

    def host_for_ip(self, ip: int) -> int | None:
        return self._ip_to_host.get(ip)

    @staticmethod
    def ip_str(ip: int) -> str:
        return str(ipaddress.ip_address(ip))

    def __len__(self) -> int:
        return len(self._name_to_ip)
