"""Network topology: GML graph → device latency/reliability arrays.

The reference loads an igraph GML graph, attaches each host to a vertex
(honoring ip/city/country hints), and lazily runs Dijkstra (edge weight =
latency) per (src, dst) vertex pair, caching results
(src/main/routing/topology.c:1682-1723, 1144-1259, 2218). The minimum path
latency feeds the scheduler's conservative runahead window
(src/main/core/worker.c:624-626 → controller.c:141-153).

TPU-first inversion: instead of a lazily-filled locked hashtable, we bake the
path model into dense device arrays *over the used vertices only* (vertices
with attached hosts) before the simulation starts:

    latency_vv[U, U]     int64 ns       path latency
    reliability_vv[U, U] float32        ∏(1 - packet_loss) along path
    host_vertex[H]       int32          host → used-vertex index

Per-packet lookups on device are then two gathers — no locks, no cache, and
the arrays shard cleanly over a mesh. U is the used-vertex count (≤ hosts),
so a 100k-host simulation over a few thousand-vertex graph stays small.
"""

from __future__ import annotations

import dataclasses
import ipaddress

import numpy as np
from scipy.sparse import csr_matrix
from scipy.sparse.csgraph import dijkstra

from shadow_tpu.core import units
from shadow_tpu.routing.gml import GmlGraph, parse_gml


class TopologyError(ValueError):
    pass


@dataclasses.dataclass
class Vertex:
    id: int
    index: int  # dense index in the parsed graph
    ip_address: str | None
    city_code: str | None
    country_code: str | None
    bandwidth_down: int | None  # bits/sec
    bandwidth_up: int | None


@dataclasses.dataclass
class Edge:
    source: int  # dense vertex index
    target: int
    latency_ns: int
    jitter_ns: int
    packet_loss: float


class Topology:
    """Parsed graph + host attachment + baked path arrays."""

    def __init__(self, graph: GmlGraph, use_shortest_path: bool = True):
        self.directed = graph.directed
        self.use_shortest_path = use_shortest_path
        self.vertices: list[Vertex] = []
        self._id_to_index: dict[int, int] = {}
        for idx, n in enumerate(graph.nodes):
            v = Vertex(
                id=int(n["id"]),
                index=idx,
                ip_address=n.get("ip_address"),
                city_code=str(n["city_code"]) if "city_code" in n else None,
                country_code=str(n["country_code"]) if "country_code" in n else None,
                bandwidth_down=(
                    units.parse_bits(n["bandwidth_down"])
                    if "bandwidth_down" in n
                    else None
                ),
                bandwidth_up=(
                    units.parse_bits(n["bandwidth_up"]) if "bandwidth_up" in n else None
                ),
            )
            if v.id in self._id_to_index:
                raise TopologyError(f"duplicate vertex id {v.id}")
            self._id_to_index[v.id] = idx
            self.vertices.append(v)
        self.edges: list[Edge] = []
        for e in graph.edges:
            if "latency" not in e:
                raise TopologyError("edge missing required latency attribute")
            # Bare numeric latency/jitter are seconds per the graph spec
            # (docs/network_graph_spec.md: base unit of "seconds").
            lat = units.parse_time_ns(e["latency"])
            if lat <= 0:
                raise TopologyError("edge latency must be > 0 (runahead requires it)")
            src_id, dst_id = int(e["source"]), int(e["target"])
            for vid in (src_id, dst_id):
                if vid not in self._id_to_index:
                    raise TopologyError(f"edge references unknown node id {vid}")
            self.edges.append(
                Edge(
                    source=self._id_to_index[src_id],
                    target=self._id_to_index[dst_id],
                    latency_ns=lat,
                    jitter_ns=units.parse_time_ns(e.get("jitter", 0)),
                    packet_loss=float(e.get("packet_loss", 0.0)),
                )
            )
        # host attachments
        self._attached_vertex: list[int] = []  # per host, dense vertex index

    @classmethod
    def from_gml(cls, text: str, use_shortest_path: bool = True) -> "Topology":
        return cls(parse_gml(text), use_shortest_path)

    @property
    def num_vertices(self) -> int:
        return len(self.vertices)

    # ---- attachment (reference topology.c:2132-2216 candidate filtering) ----

    def attach_host(
        self,
        host_index: int,
        ip_address_hint: str | None = None,
        city_code_hint: str | None = None,
        country_code_hint: str | None = None,
        network_node_id: int | None = None,
    ) -> Vertex:
        """Pick the attachment vertex for a host, most-specific hint first:
        city candidates, else country candidates, else all; exact/longest-
        prefix IP match within candidates; else deterministic round-robin by
        host index (the reference draws from its seeded RNG here — ours is
        deterministic in host order, which the determinism tests pin).
        An explicit network_node_id (graph vertex id) bypasses hint search."""
        if network_node_id is not None:
            if network_node_id not in self._id_to_index:
                raise TopologyError(f"no graph vertex with id {network_node_id}")
            chosen = self.vertices[self._id_to_index[network_node_id]]
            if host_index != len(self._attached_vertex):
                raise TopologyError("hosts must attach in index order")
            self._attached_vertex.append(chosen.index)
            return chosen
        cands = [v for v in self.vertices if city_code_hint and v.city_code == city_code_hint]
        if not cands:
            cands = [
                v
                for v in self.vertices
                if country_code_hint and v.country_code == country_code_hint
            ]
        if not cands:
            cands = list(self.vertices)
        if ip_address_hint is not None:
            want = int(ipaddress.ip_address(ip_address_hint))
            best, best_len = None, -1
            for v in cands:
                if v.ip_address is None:
                    continue
                have = int(ipaddress.ip_address(v.ip_address))
                if have == want:
                    best, best_len = v, 33
                    break
                # longest common prefix length
                x = have ^ want
                plen = 32 - x.bit_length()
                if plen > best_len:
                    best, best_len = v, plen
            if best is not None:
                chosen = best
            else:
                chosen = cands[host_index % len(cands)]
        else:
            chosen = cands[host_index % len(cands)]
        if host_index != len(self._attached_vertex):
            raise TopologyError("hosts must attach in index order")
        self._attached_vertex.append(chosen.index)
        return chosen

    # ---- path baking ----

    def bake(self) -> "BakedPaths":
        """Compute path arrays over used vertices. Call after all attaches."""
        V = self.num_vertices
        used = sorted(set(self._attached_vertex))
        if not used:
            raise TopologyError("no hosts attached")
        uidx = {v: i for i, v in enumerate(used)}
        U = len(used)
        H = len(self._attached_vertex)

        # Build sparse latency graph. For undirected graphs add both arcs.
        # Parallel edges keep the minimum latency, like Dijkstra would.
        rows, cols, lats = [], [], []
        # per-arc loss/jitter for path accumulation
        arc_attr: dict[tuple[int, int], tuple[int, float, int]] = {}

        def add_arc(s, t, e: Edge):
            key = (s, t)
            prev = arc_attr.get(key)
            if prev is None or e.latency_ns < prev[0]:
                arc_attr[key] = (e.latency_ns, e.packet_loss, e.jitter_ns)

        for e in self.edges:
            add_arc(e.source, e.target, e)
            if not self.directed:
                add_arc(e.target, e.source, e)
        for (s, t), (lat, _loss, _jit) in arc_attr.items():
            rows.append(s)
            cols.append(t)
            lats.append(float(lat))
        graph = csr_matrix((lats, (rows, cols)), shape=(V, V))

        lat_vv = np.full((U, U), np.iinfo(np.int64).max, dtype=np.int64)
        rel_vv = np.zeros((U, U), dtype=np.float32)
        jit_vv = np.zeros((U, U), dtype=np.int64)

        if self.use_shortest_path:
            dist, predecessors = dijkstra(
                graph, directed=True, indices=used, return_predecessors=True
            )
            for i, src in enumerate(used):
                for j, dst in enumerate(used):
                    if src == dst:
                        # Dijkstra reports a 0-cost self path, but the
                        # reference requires an explicit self-loop edge for
                        # co-located hosts to communicate — use its attributes.
                        a = arc_attr.get((src, dst))
                        if a is None:
                            continue
                        lat_vv[i, j] = a[0]
                        rel_vv[i, j] = 1.0 - a[1]
                        jit_vv[i, j] = a[2]
                        continue
                    d = dist[i, dst]
                    if not np.isfinite(d):
                        continue
                    # Walk predecessors to accumulate reliability and jitter.
                    rel = 1.0
                    jit = 0
                    cur = dst
                    while cur != src:
                        prev = predecessors[i, cur]
                        if prev < 0:
                            break
                        a = arc_attr[(prev, cur)]
                        rel *= 1.0 - a[1]
                        jit += a[2]
                        cur = prev
                    lat_vv[i, j] = np.int64(d)
                    rel_vv[i, j] = np.float32(rel)
                    jit_vv[i, j] = np.int64(jit)
        else:
            # Complete-graph direct-edge mode (configuration.rs:203-208):
            # only direct edges route; pairs without one stay unreachable
            # (the reference errors at lookup time — we drop at send time
            # and count it, since unreachable pairs may never be used).
            for i, src in enumerate(used):
                for j, dst in enumerate(used):
                    a = arc_attr.get((src, dst))
                    if a is None:
                        continue
                    lat_vv[i, j] = a[0]
                    rel_vv[i, j] = 1.0 - a[1]
                    jit_vv[i, j] = a[2]

        host_vertex = np.array([uidx[v] for v in self._attached_vertex], dtype=np.int32)
        reachable = lat_vv != np.iinfo(np.int64).max
        if not reachable.any():
            raise TopologyError("no reachable paths between attached hosts")
        min_latency = int(lat_vv[reachable].min())
        vert_bw_down = np.array(
            [
                self.vertices[v].bandwidth_down or 0
                for v in used
            ],
            dtype=np.int64,
        )
        vert_bw_up = np.array(
            [self.vertices[v].bandwidth_up or 0 for v in used], dtype=np.int64
        )
        return BakedPaths(
            latency_vv=lat_vv,
            reliability_vv=rel_vv,
            jitter_vv=jit_vv,
            host_vertex=host_vertex,
            min_latency_ns=min_latency,
            used_vertices=np.array(used, dtype=np.int32),
            vertex_bw_down_bits=vert_bw_down,
            vertex_bw_up_bits=vert_bw_up,
        )


@dataclasses.dataclass
class BakedPaths:
    latency_vv: np.ndarray  # [U, U] int64 ns (NEVER = unreachable)
    reliability_vv: np.ndarray  # [U, U] float32 in [0,1]
    jitter_vv: np.ndarray  # [U, U] int64 ns (stored; not applied by default,
    # matching the reference which logs but does not sample jitter in 2.0)
    host_vertex: np.ndarray  # [H] int32 → used-vertex index
    min_latency_ns: int  # conservative runahead bound (controller.c:125-139)
    used_vertices: np.ndarray  # [U] int32 dense vertex indices
    vertex_bw_down_bits: np.ndarray  # [U] int64 bits/sec (0 = unspecified)
    vertex_bw_up_bits: np.ndarray  # [U] int64
