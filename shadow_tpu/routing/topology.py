"""Network topology: GML graph → device latency/reliability arrays.

The reference loads an igraph GML graph, attaches each host to a vertex
(honoring ip/city/country hints), and lazily runs Dijkstra (edge weight =
latency) per (src, dst) vertex pair, caching results
(src/main/routing/topology.c:1682-1723, 1144-1259, 2218). The minimum path
latency feeds the scheduler's conservative runahead window
(src/main/core/worker.c:624-626 → controller.c:141-153).

TPU-first inversion: instead of a lazily-filled locked hashtable, we bake the
path model into dense device arrays *over the used vertices only* (vertices
with attached hosts) before the simulation starts:

    latency_vv[U, U]     int64 ns       path latency
    reliability_vv[U, U] float32        ∏(1 - packet_loss) along path
    host_vertex[H]       int32          host → used-vertex index

Per-packet lookups on device are then two gathers — no locks, no cache, and
the arrays shard cleanly over a mesh. U is the used-vertex count (≤ hosts),
so a 100k-host simulation over a few thousand-vertex graph stays small.
"""

from __future__ import annotations

import dataclasses
import ipaddress

import numpy as np
from scipy.sparse import csr_matrix
from scipy.sparse.csgraph import dijkstra

from shadow_tpu.core import units
from shadow_tpu.routing.gml import GmlGraph, parse_gml


class TopologyError(ValueError):
    pass


@dataclasses.dataclass
class Vertex:
    id: int
    index: int  # dense index in the parsed graph
    ip_address: str | None
    city_code: str | None
    country_code: str | None
    bandwidth_down: int | None  # bits/sec
    bandwidth_up: int | None


@dataclasses.dataclass
class Edge:
    source: int  # dense vertex index
    target: int
    latency_ns: int
    jitter_ns: int
    packet_loss: float


class Topology:
    """Parsed graph + host attachment + baked path arrays."""

    def __init__(self, graph: GmlGraph, use_shortest_path: bool = True):
        self.directed = graph.directed
        self.use_shortest_path = use_shortest_path
        self.vertices: list[Vertex] = []
        self._id_to_index: dict[int, int] = {}
        for idx, n in enumerate(graph.nodes):
            v = Vertex(
                id=int(n["id"]),
                index=idx,
                ip_address=n.get("ip_address"),
                city_code=str(n["city_code"]) if "city_code" in n else None,
                country_code=str(n["country_code"]) if "country_code" in n else None,
                bandwidth_down=(
                    units.parse_bits(n["bandwidth_down"])
                    if "bandwidth_down" in n
                    else None
                ),
                bandwidth_up=(
                    units.parse_bits(n["bandwidth_up"]) if "bandwidth_up" in n else None
                ),
            )
            if v.id in self._id_to_index:
                raise TopologyError(f"duplicate vertex id {v.id}")
            self._id_to_index[v.id] = idx
            self.vertices.append(v)
        self.edges: list[Edge] = []
        for e in graph.edges:
            if "latency" not in e:
                raise TopologyError("edge missing required latency attribute")
            # Bare numeric latency/jitter are seconds per the graph spec
            # (docs/network_graph_spec.md: base unit of "seconds").
            lat = units.parse_time_ns(e["latency"])
            if lat <= 0:
                raise TopologyError("edge latency must be > 0 (runahead requires it)")
            src_id, dst_id = int(e["source"]), int(e["target"])
            for vid in (src_id, dst_id):
                if vid not in self._id_to_index:
                    raise TopologyError(f"edge references unknown node id {vid}")
            self.edges.append(
                Edge(
                    source=self._id_to_index[src_id],
                    target=self._id_to_index[dst_id],
                    latency_ns=lat,
                    jitter_ns=units.parse_time_ns(e.get("jitter", 0)),
                    packet_loss=float(e.get("packet_loss", 0.0)),
                )
            )
        # host attachments
        self._attached_vertex: list[int] = []  # per host, dense vertex index

    @classmethod
    def from_gml(cls, text: str, use_shortest_path: bool = True) -> "Topology":
        return cls(parse_gml(text), use_shortest_path)

    @property
    def num_vertices(self) -> int:
        return len(self.vertices)

    # ---- attachment (reference topology.c:2132-2216 candidate filtering) ----

    def attach_host(
        self,
        host_index: int,
        ip_address_hint: str | None = None,
        city_code_hint: str | None = None,
        country_code_hint: str | None = None,
        network_node_id: int | None = None,
    ) -> Vertex:
        """Pick the attachment vertex for a host, most-specific hint first:
        city candidates, else country candidates, else all; exact/longest-
        prefix IP match within candidates; else deterministic round-robin by
        host index (the reference draws from its seeded RNG here — ours is
        deterministic in host order, which the determinism tests pin).
        An explicit network_node_id (graph vertex id) bypasses hint search."""
        if network_node_id is not None:
            if network_node_id not in self._id_to_index:
                raise TopologyError(f"no graph vertex with id {network_node_id}")
            chosen = self.vertices[self._id_to_index[network_node_id]]
            if host_index != len(self._attached_vertex):
                raise TopologyError("hosts must attach in index order")
            self._attached_vertex.append(chosen.index)
            return chosen
        cands = [v for v in self.vertices if city_code_hint and v.city_code == city_code_hint]
        if not cands:
            cands = [
                v
                for v in self.vertices
                if country_code_hint and v.country_code == country_code_hint
            ]
        if not cands:
            cands = list(self.vertices)
        if ip_address_hint is not None:
            want = int(ipaddress.ip_address(ip_address_hint))
            best, best_len = None, -1
            for v in cands:
                if v.ip_address is None:
                    continue
                have = int(ipaddress.ip_address(v.ip_address))
                if have == want:
                    best, best_len = v, 33
                    break
                # longest common prefix length
                x = have ^ want
                plen = 32 - x.bit_length()
                if plen > best_len:
                    best, best_len = v, plen
            if best is not None:
                chosen = best
            else:
                chosen = cands[host_index % len(cands)]
        else:
            chosen = cands[host_index % len(cands)]
        if host_index != len(self._attached_vertex):
            raise TopologyError("hosts must attach in index order")
        self._attached_vertex.append(chosen.index)
        return chosen

    # ---- path baking ----

    def _arcs(self):
        """Min-latency arc set: (csr latency graph, per-arc attr csr pair).
        For undirected graphs both directions are added; parallel edges
        keep the minimum-latency arc (the one Dijkstra would use)."""
        V = self.num_vertices
        arc_attr: dict[tuple[int, int], tuple[int, float, int]] = {}

        def add_arc(s, t, e: Edge):
            key = (s, t)
            prev = arc_attr.get(key)
            if prev is None or e.latency_ns < prev[0]:
                arc_attr[key] = (e.latency_ns, e.packet_loss, e.jitter_ns)

        for e in self.edges:
            add_arc(e.source, e.target, e)
            if not self.directed:
                add_arc(e.target, e.source, e)
        rows = np.fromiter((k[0] for k in arc_attr), dtype=np.int64,
                           count=len(arc_attr))
        cols = np.fromiter((k[1] for k in arc_attr), dtype=np.int64,
                           count=len(arc_attr))
        lats = np.fromiter((v[0] for v in arc_attr.values()), dtype=np.float64,
                           count=len(arc_attr))
        loss = np.fromiter((v[1] for v in arc_attr.values()), dtype=np.float64,
                           count=len(arc_attr))
        jit = np.fromiter((v[2] for v in arc_attr.values()), dtype=np.int64,
                          count=len(arc_attr))
        graph = csr_matrix((lats, (rows, cols)), shape=(V, V))
        loss_m = csr_matrix((loss, (rows, cols)), shape=(V, V))
        jit_m = csr_matrix((jit.astype(np.float64), (rows, cols)),
                           shape=(V, V))
        return graph, loss_m, jit_m, arc_attr

    @staticmethod
    def _tree_accumulate(pred_rows: np.ndarray, srcs: np.ndarray,
                         loss_m, jit_m):
        """Accumulate reliability (∏(1-loss)) and jitter (Σ) along the
        shortest-path trees, vectorized with pointer doubling — the
        predecessor-walk loop the scalar form needs is O(U·V·depth) Python
        at 10k vertices (hours); this is O(U·V·log V) numpy (seconds).
        pred_rows: [N, V] predecessor matrix (scipy convention, -9999 for
        none); srcs: [N] source vertex per row."""
        N, V = pred_rows.shape
        cols = np.arange(V, dtype=np.int64)
        valid = pred_rows >= 0
        prows = np.where(valid, pred_rows, 0).astype(np.int64)
        rel = np.ones((N, V), dtype=np.float64)
        jit = np.zeros((N, V), dtype=np.int64)
        for i in range(N):
            rel[i] = np.where(
                valid[i],
                1.0 - np.asarray(loss_m[prows[i], cols]).ravel(), 1.0
            )
            jit[i] = np.where(
                valid[i],
                np.asarray(jit_m[prows[i], cols]).ravel().astype(np.int64), 0
            )
        # each hop: fold in the parent's accumulated value, then jump the
        # pointer twice as far; log2(V)+1 rounds cover any path length
        ptr = np.where(valid, prows, srcs[:, None]).astype(np.int64)
        rows_idx = np.arange(N)[:, None]
        for _ in range(max(1, int(np.ceil(np.log2(max(V, 2)))) + 1)):
            rel = rel * rel[rows_idx, ptr]
            jit = jit + jit[rows_idx, ptr]
            ptr = ptr[rows_idx, ptr]
        return rel, jit

    def bake_lazy(self) -> "LazyPaths":
        """On-demand path model (no dense [U, U] allocation) for the
        managed-process plane on big graphs. Call after all attaches."""
        return LazyPaths(self)

    def bake(self) -> "BakedPaths":
        """Compute path arrays over used vertices. Call after all attaches."""
        used = sorted(set(self._attached_vertex))
        if not used:
            raise TopologyError("no hosts attached")
        uidx = {v: i for i, v in enumerate(used)}
        U = len(used)

        graph, loss_m, jit_m, arc_attr = self._arcs()
        used_a = np.asarray(used, dtype=np.int64)

        lat_vv = np.full((U, U), np.iinfo(np.int64).max, dtype=np.int64)
        rel_vv = np.zeros((U, U), dtype=np.float32)
        jit_vv = np.zeros((U, U), dtype=np.int64)

        if self.use_shortest_path:
            dist, predecessors = dijkstra(
                graph, directed=True, indices=used, return_predecessors=True
            )
            rel_all, jit_all = self._tree_accumulate(
                predecessors, used_a, loss_m, jit_m
            )
            reach = np.isfinite(dist[:, used_a])  # [U, U]
            lat_vv = np.where(
                reach,
                np.where(reach, dist[:, used_a], 0.0).astype(np.int64),
                lat_vv,
            )
            rel_vv = np.where(
                reach, rel_all[:, used_a].astype(np.float32), rel_vv
            )
            jit_vv = np.where(reach, jit_all[:, used_a], jit_vv)
            # Dijkstra reports a 0-cost self path, but the reference
            # requires an explicit self-loop edge for co-located hosts to
            # communicate — overwrite the diagonal with its attributes.
            for i, src in enumerate(used):
                a = arc_attr.get((src, src))
                if a is None:
                    lat_vv[i, i] = np.iinfo(np.int64).max
                    rel_vv[i, i] = 0.0
                    jit_vv[i, i] = 0
                else:
                    lat_vv[i, i] = a[0]
                    rel_vv[i, i] = np.float32(1.0 - a[1])
                    jit_vv[i, i] = a[2]
        else:
            # Complete-graph direct-edge mode (configuration.rs:203-208):
            # only direct edges route; pairs without one stay unreachable
            # (the reference errors at lookup time — we drop at send time
            # and count it, since unreachable pairs may never be used).
            for (s, t), a in arc_attr.items():
                i, j = uidx.get(s), uidx.get(t)
                if i is None or j is None:
                    continue
                lat_vv[i, j] = a[0]
                rel_vv[i, j] = np.float32(1.0 - a[1])
                jit_vv[i, j] = a[2]

        host_vertex = np.array([uidx[v] for v in self._attached_vertex], dtype=np.int32)
        reachable = lat_vv != np.iinfo(np.int64).max
        if not reachable.any():
            raise TopologyError("no reachable paths between attached hosts")
        min_latency = int(lat_vv[reachable].min())
        vert_bw_down = np.array(
            [
                self.vertices[v].bandwidth_down or 0
                for v in used
            ],
            dtype=np.int64,
        )
        vert_bw_up = np.array(
            [self.vertices[v].bandwidth_up or 0 for v in used], dtype=np.int64
        )
        return BakedPaths(
            latency_vv=lat_vv,
            reliability_vv=rel_vv,
            jitter_vv=jit_vv,
            host_vertex=host_vertex,
            min_latency_ns=min_latency,
            used_vertices=np.array(used, dtype=np.int32),
            vertex_bw_down_bits=vert_bw_down,
            vertex_bw_up_bits=vert_bw_up,
        )


class LazyPaths:
    """On-demand per-source shortest paths with a row cache — the
    reference's strategy at Tor scale (topology.c:1144-1259 lazily fills a
    locked 2-level hashtable per (src, dst) pair; we cache whole source
    ROWS, which one Dijkstra run yields anyway). NO dense [U, U] is ever
    allocated: memory is O(cached sources × V). Used by the managed-process
    plane's latency_fn/reliability_fn on big graphs; the device plane keeps
    dense baked arrays (per-packet lookups on device can't fault rows in).

    ``min_latency_ns`` is the minimum EDGE latency — a lower bound on every
    path latency, hence a sound (conservative) runahead window
    (controller.c:125-139 seeds its min-time-jump the same way before any
    path is computed).
    """

    def __init__(self, topo: "Topology"):
        used = sorted(set(topo._attached_vertex))
        if not used:
            raise TopologyError("no hosts attached")
        self._graph, self._loss_m, self._jit_m, self._arc_attr = topo._arcs()
        self.use_shortest_path = topo.use_shortest_path
        uidx = {v: i for i, v in enumerate(used)}
        self.host_vertex = np.array(
            [uidx[v] for v in topo._attached_vertex], dtype=np.int32
        )
        self.used_vertices = np.array(used, dtype=np.int32)
        self.vertex_bw_down_bits = np.array(
            [topo.vertices[v].bandwidth_down or 0 for v in used],
            dtype=np.int64,
        )
        self.vertex_bw_up_bits = np.array(
            [topo.vertices[v].bandwidth_up or 0 for v in used],
            dtype=np.int64,
        )
        if self._graph.nnz == 0:
            raise TopologyError("no edges between attached hosts")
        self.min_latency_ns = int(self._graph.data.min())
        # src used-index -> (lat_row [V] i64 | NEVER, rel_row [V] f32)
        self._rows: dict[int, tuple[np.ndarray, np.ndarray]] = {}

    def _row(self, u: int) -> tuple[np.ndarray, np.ndarray]:
        got = self._rows.get(u)
        if got is not None:
            return got
        src = int(self.used_vertices[u])
        V = self._graph.shape[0]
        never = np.iinfo(np.int64).max
        if self.use_shortest_path:
            dist, pred = dijkstra(
                self._graph, directed=True, indices=[src],
                return_predecessors=True,
            )
            rel_a, _ = Topology._tree_accumulate(
                pred, np.array([src], dtype=np.int64),
                self._loss_m, self._jit_m,
            )
            reach = np.isfinite(dist[0])
            lat_row = np.where(
                reach, np.where(reach, dist[0], 0.0).astype(np.int64), never
            )
            rel_row = np.where(reach, rel_a[0].astype(np.float32), 0.0)
        else:
            lat_row = np.full((V,), never, dtype=np.int64)
            rel_row = np.zeros((V,), dtype=np.float32)
            for (s, t), a in self._arc_attr.items():
                if s == src:
                    lat_row[t] = a[0]
                    rel_row[t] = np.float32(1.0 - a[1])
        # diagonal: explicit self-loop edge required (reference semantics)
        a = self._arc_attr.get((src, src))
        if a is None:
            lat_row[src] = never
            rel_row[src] = 0.0
        else:
            lat_row[src] = a[0]
            rel_row[src] = np.float32(1.0 - a[1])
        self._rows[u] = (lat_row, rel_row)
        return self._rows[u]

    def latency_ns(self, src_u: int, dst_u: int) -> int:
        """Path latency between used-vertex indices (NEVER if unreachable)."""
        lat_row, _ = self._row(int(src_u))
        return int(lat_row[int(self.used_vertices[int(dst_u)])])

    def reliability(self, src_u: int, dst_u: int) -> float:
        _, rel_row = self._row(int(src_u))
        return float(rel_row[int(self.used_vertices[int(dst_u)])])


@dataclasses.dataclass
class BakedPaths:
    latency_vv: np.ndarray  # [U, U] int64 ns (NEVER = unreachable)
    reliability_vv: np.ndarray  # [U, U] float32 in [0,1]
    jitter_vv: np.ndarray  # [U, U] int64 ns (stored; not applied by default,
    # matching the reference which logs but does not sample jitter in 2.0)
    host_vertex: np.ndarray  # [H] int32 → used-vertex index
    min_latency_ns: int  # conservative runahead bound (controller.c:125-139)
    used_vertices: np.ndarray  # [U] int32 dense vertex indices
    vertex_bw_down_bits: np.ndarray  # [U] int64 bits/sec (0 = unspecified)
    vertex_bw_up_bits: np.ndarray  # [U] int64
