"""Sweep expansion: a `sweep:` config matrix → a validated job list.

Shadow's primary workload is parameter sweeps: many near-identical
experiment configs (seeds, latencies, loss rates, stop times) that the
reference runs one-at-a-time as separate OS processes. Here a sweep file
is ONE base experiment config plus a `sweep:` section:

    sweep:
      name: loss-sweep          # optional job-name prefix
      lanes: 4                  # optional: device lanes (default = jobs)
      matrix:                   # cross product, declaration order
        general.seed: [1, 2, 3]
        general.stop_time: ["300 ms", "1 s"]
      jobs:                     # optional explicit extra jobs
        - name: long-tail
          set: {general.seed: 99, general.stop_time: "2 s"}
    general: {...}              # base config — everything else
    network: {...}
    hosts:   {...}

Every expanded job must (a) load as a valid experiment config and (b) be
KERNEL-COMPATIBLE with the others: the fleet runs all jobs as one vmapped
device program, so fields that are baked into the compiled window kernel
(host counts, pool shapes, app handler options) must be identical across
jobs — only data-plane fields (seeds, stop times, graph latencies/losses,
fault plans) may vary. Incompatible sweeps fail at expansion time with the
offending field paths, never mid-run.
"""

from __future__ import annotations

import copy
import dataclasses
import io
import re
from typing import Any, Optional

import yaml


class SweepError(ValueError):
    pass


# Dotted config paths (prefix match) that are DATA to the compiled window
# kernel: they land in NetParams / rng keys / host-side window bounds, so
# jobs may vary them while sharing one compiled program. Everything else
# is (conservatively) assumed to change the kernel — shapes, handler
# closures, payload layouts — and must be identical across a fleet.
DATA_PATHS = (
    "general.seed",
    "general.stop_time",
    "general.bootstrap_end_time",
    "general.data_directory",
    "general.log_level",
    "general.progress",
    "general.heartbeat_interval",
    "network.graph",  # latency/loss VALUES; baked shapes re-checked at build
    "faults",  # job-scoped injections are scheduler-plane, not compiled
    "sweep",
    "fleet",
)


@dataclasses.dataclass
class JobSpec:
    """One experiment of a fleet: a name, the fully-expanded config dict,
    and scheduler-plane options."""

    name: str
    config: dict
    deadline_s: Optional[float] = None  # wall-clock budget once admitted

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "config": self.config,
            "deadline_s": self.deadline_s,
        }

    @classmethod
    def from_json(cls, d: dict) -> "JobSpec":
        return cls(
            name=str(d["name"]),
            config=dict(d["config"]),
            deadline_s=d.get("deadline_s"),
        )


def _set_path(d: dict, path: str, value) -> None:
    parts = path.split(".")
    cur = d
    for p in parts[:-1]:
        nxt = cur.get(p)
        if not isinstance(nxt, dict):
            raise SweepError(
                f"sweep path {path!r}: {p!r} is not a config section in the "
                f"base document (matrix paths must point into existing "
                f"sections)"
            )
        cur = nxt
    if parts[-1] not in cur:
        raise SweepError(
            f"sweep path {path!r}: field {parts[-1]!r} is not present in "
            f"the base document; set a base value so the override target "
            f"is explicit"
        )
    cur[parts[-1]] = value


def _flatten(d, prefix="") -> dict[str, Any]:
    out = {}
    if isinstance(d, dict):
        for k, v in d.items():
            out.update(_flatten(v, f"{prefix}{k}."))
    else:
        out[prefix[:-1]] = d
    return out


def _is_data_path(path: str) -> bool:
    return any(
        path == p or path.startswith(p + ".") for p in DATA_PATHS
    )


def check_kernel_compat(jobs: list[JobSpec]) -> None:
    """Raise unless every job can share ONE compiled window kernel: all
    config differences vs the first job must lie under DATA_PATHS."""
    if not jobs:
        raise SweepError("sweep expanded to zero jobs")
    base = _flatten(jobs[0].config)
    for job in jobs[1:]:
        flat = _flatten(job.config)
        bad = sorted(
            p
            for p in set(base) | set(flat)
            if base.get(p) != flat.get(p) and not _is_data_path(p)
        )
        if bad:
            raise SweepError(
                f"job {job.name!r} differs from {jobs[0].name!r} in kernel-"
                f"shaping field(s) {bad[:6]}: these compile into the window "
                f"kernel (shapes or handler constants), so the jobs cannot "
                f"share one fleet program — run them as separate fleets, or "
                f"sweep only data-plane fields ({', '.join(DATA_PATHS[:6])}, "
                f"...)"
            )


_NAME_SANITIZE = re.compile(r"[^A-Za-z0-9._=-]+")


def _job_name(prefix: str, idx: int, overrides: dict) -> str:
    parts = [f"{prefix}{idx:03d}"]
    for path, v in overrides.items():
        leaf = path.rsplit(".", 1)[-1]
        parts.append(_NAME_SANITIZE.sub("_", f"{leaf}={v}"))
    return "-".join(parts)


def expand_sweep(doc: dict, validate: bool = True) -> list[JobSpec]:
    """Expand a sweep document (base config + `sweep:` section) into the
    ordered job list: matrix cross product (declaration order, first key
    slowest) followed by explicit `jobs:` entries. With `validate`, each
    expanded config is loaded through the experiment-config parser and the
    cross-job kernel-compatibility check runs — a bad spec fails HERE with
    its job name, never mid-fleet."""
    if not isinstance(doc, dict):
        raise SweepError("sweep document must be a YAML mapping")
    sweep = doc.get("sweep")
    if not isinstance(sweep, dict):
        raise SweepError("document has no `sweep:` section")
    unknown = set(sweep) - {"name", "matrix", "jobs", "lanes", "deadline_s"}
    if unknown:
        raise SweepError(f"unknown field(s) in sweep: {sorted(unknown)}")
    base = {k: copy.deepcopy(v) for k, v in doc.items() if k != "sweep"}
    prefix = str(sweep.get("name", "job"))
    deadline = sweep.get("deadline_s")
    deadline = float(deadline) if deadline is not None else None

    matrix = sweep.get("matrix") or {}
    if not isinstance(matrix, dict):
        raise SweepError("sweep.matrix must be a mapping of path -> values")
    for path, vals in matrix.items():
        if not isinstance(vals, list) or not vals:
            raise SweepError(
                f"sweep.matrix.{path} must be a non-empty list of values"
            )

    combos: list[dict] = [{}]
    for path, vals in matrix.items():
        combos = [
            {**c, path: v} for c in combos for v in vals
        ]
    if not matrix:
        combos = []

    jobs: list[JobSpec] = []
    for i, overrides in enumerate(combos):
        cfg = copy.deepcopy(base)
        for path, v in overrides.items():
            _set_path(cfg, path, v)
        jobs.append(JobSpec(
            name=_job_name(prefix, i, overrides), config=cfg,
            deadline_s=deadline,
        ))
    for j, entry in enumerate(sweep.get("jobs") or []):
        if not isinstance(entry, dict) or "set" not in entry:
            raise SweepError(
                f"sweep.jobs[{j}] must be a mapping with a `set:` override "
                f"table"
            )
        cfg = copy.deepcopy(base)
        for path, v in (entry["set"] or {}).items():
            _set_path(cfg, path, v)
        name = str(entry.get("name", _job_name(prefix, len(jobs), entry["set"])))
        jobs.append(JobSpec(
            name=name, config=cfg,
            deadline_s=entry.get("deadline_s", deadline),
        ))
    if not jobs:
        raise SweepError(
            "sweep expanded to zero jobs (empty matrix and no jobs list)"
        )
    names = [j.name for j in jobs]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise SweepError(f"duplicate job name(s): {sorted(dupes)[:4]}")
    if validate:
        validate_jobs(jobs)
    return jobs


def validate_jobs(jobs: list[JobSpec]) -> None:
    """Each job's config must parse as an experiment config (ConfigError
    surfaces with the job name) and the set must be kernel-compatible."""
    from shadow_tpu.core.config import ConfigError, load_config

    for job in jobs:
        try:
            cfg = load_config(job.config)
        except (ConfigError, ValueError) as e:
            raise SweepError(f"job {job.name!r}: {e}") from e
        if any(h.processes for h in cfg.hosts):
            raise SweepError(
                f"job {job.name!r}: fleet jobs run on the device plane "
                f"only (hosts with `processes` need their own managed-"
                f"process run)"
            )
        for f in cfg.faults.load_faults():
            if f.op not in ("kill_host", "skew_hosts"):
                raise SweepError(
                    f"job {job.name!r}: fleet fault plans support the "
                    f"device-plane `kill_host` / `skew_hosts` ops only "
                    f"(got {f.op!r}); proc/file ops need a solo run"
                )
    check_kernel_compat(jobs)


def load_sweep(source) -> tuple[list[JobSpec], dict]:
    """Load a sweep document from a YAML path/string/dict; returns
    (jobs, sweep_section)."""
    if isinstance(source, dict):
        doc = source
    elif isinstance(source, io.IOBase):
        doc = yaml.safe_load(source)
    else:
        text = str(source)
        if "\n" in text:
            doc = yaml.safe_load(text)
        else:
            with open(text) as f:
                doc = yaml.safe_load(f)
    jobs = expand_sweep(doc)
    return jobs, dict(doc.get("sweep") or {})


def load_job_list(path: str) -> list[JobSpec]:
    """Load an explicit job list (`--fleet jobs.yaml` / expand_sweep.py
    output): either {"jobs": [{name, config, deadline_s?}, ...]} or a bare
    list of those entries. Validates like expand_sweep."""
    with open(path) as f:
        doc = yaml.safe_load(f)
    entries = doc.get("jobs") if isinstance(doc, dict) else doc
    if not isinstance(entries, list) or not entries:
        raise SweepError(
            f"{path}: expected a `jobs:` list of {{name, config}} entries"
        )
    jobs = []
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or "config" not in e:
            raise SweepError(f"{path}: jobs[{i}] needs a `config` mapping")
        jobs.append(JobSpec(
            name=str(e.get("name", f"job{i:03d}")),
            config=dict(e["config"]),
            deadline_s=e.get("deadline_s"),
        ))
    validate_jobs(jobs)
    return jobs
