"""Scenario fleet: batched multi-experiment execution with a job scheduler.

Public surface:

  expand_sweep / load_sweep / load_job_list   sweep matrix → job list
  JobSpec                                     one experiment of a fleet
  build_fleet / FleetSimulation               the batched runner
  save_fleet / resume_fleet                   fleet checkpointing
  FleetError / SweepError                     configuration-shaped errors
"""

from shadow_tpu.fleet.checkpoint import resume_fleet, save_fleet
from shadow_tpu.fleet.engine import FleetError, FleetSimulation, build_fleet
from shadow_tpu.fleet.sweep import (
    JobSpec,
    SweepError,
    expand_sweep,
    load_job_list,
    load_sweep,
)

__all__ = [
    "FleetError",
    "FleetSimulation",
    "JobSpec",
    "SweepError",
    "build_fleet",
    "expand_sweep",
    "load_job_list",
    "load_sweep",
    "resume_fleet",
    "save_fleet",
]
