"""Fleet checkpoint/resume: per-job state slices + a scheduler manifest.

A fleet checkpoint is a directory holding

  manifest.json       scheduler-plane truth: every job's spec + status +
                      harvested results, lane assignments, the fleet gear,
                      and per-lane fault state — written LAST, atomically,
                      so a crash mid-checkpoint leaves the previous
                      manifest pointing at the previous slices;
  job-<name>.npz      one core/checkpoint.py archive per RUNNING lane:
                      the lane's state slice in the SOLO layout (the same
                      digest-verified crash-consistent format solo runs
                      use), so a fleet slice is also directly loadable
                      into a solo Simulation for debugging.

Resume rebuilds the fleet from the manifest: completed jobs keep their
recorded results, running jobs restore their slices into fresh lanes, and
still-queued jobs re-queue — so an interrupted sweep finishes from where
it stopped instead of re-running finished experiments.
"""

from __future__ import annotations

import json
import os

from shadow_tpu.core import checkpoint as ckpt_mod
from shadow_tpu.core import state as state_mod
from shadow_tpu.fleet import scheduler as sched_mod
from shadow_tpu.fleet.sweep import JobSpec

MANIFEST = "manifest.json"
MANIFEST_KIND = "shadow_tpu.fleet_ckpt"
MANIFEST_VERSION = 1


class _LaneView:
    """The solo-shaped handle core/checkpoint.save expects, wrapping one
    lane's slice of the stacked fleet state."""

    def __init__(self, fleet, lane: int, stop_time: int, runahead: int):
        self.state = state_mod.slice_lane(fleet.state, lane)
        self.num_hosts = fleet.template.num_hosts
        self.stop_time = int(stop_time)
        self.runahead = int(runahead)
        self._gear_ladder = fleet._ladder
        self._gear = fleet._gear


def _job_file(name: str) -> str:
    return f"job-{name}.npz"


def save_fleet(fleet, ckpt_dir: str, extra_meta: dict | None = None) -> str:
    """Write every running lane's slice + the manifest. Returns the
    manifest path. `extra_meta` keys merge into the manifest — the
    backend supervisor records its drain reason there
    (core/supervisor.py) so `sweep --resume` after an outage is
    distinguishable from a scheduled checkpoint."""
    os.makedirs(ckpt_dir, exist_ok=True)
    jobs = []
    for rec in sorted(fleet.sched.records, key=lambda r: r.submit_idx):
        entry = {
            "spec": rec.spec.to_json(),
            "status": rec.status,
            "order": rec.submit_idx,
            "summary": rec.summary(),
        }
        if rec.status == sched_mod.RUNNING and rec.lane is not None:
            j = rec.lane
            fname = _job_file(rec.name)
            view = _LaneView(
                fleet, j, fleet._stop[j], fleet._runahead[j]
            )
            ckpt_mod.save(view, os.path.join(ckpt_dir, fname))
            lf = fleet._lane_faults[j]
            entry["file"] = fname
            entry["faults_state"] = {
                "pending": [[int(a), int(h)] for a, h in lf.pending],
                "dead": sorted(int(h) for h in lf.dead),
                "stats": {k: int(v) for k, v in lf.stats.items()},
            }
        jobs.append(entry)
    manifest = {
        "kind": MANIFEST_KIND,
        "version": MANIFEST_VERSION,
        "lanes": fleet.lanes,
        "gear": fleet._gear,
        "ckpt_next_t": int(fleet._ckpt_next_t),
        "stats": fleet.fleet_stats(),
        "jobs": jobs,
    }
    if extra_meta:
        manifest.update(extra_meta)
    path = os.path.join(ckpt_dir, MANIFEST)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def load_manifest(ckpt_dir: str) -> dict:
    path = os.path.join(ckpt_dir, MANIFEST)
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        raise ckpt_mod.CheckpointError(
            f"{ckpt_dir}: no fleet manifest ({MANIFEST}) to resume from"
        ) from None
    except json.JSONDecodeError as e:
        raise ckpt_mod.CheckpointError(
            f"{path}: corrupt fleet manifest: {e}"
        ) from e
    if doc.get("kind") != MANIFEST_KIND:
        raise ckpt_mod.CheckpointError(
            f"{path}: kind {doc.get('kind')!r} != {MANIFEST_KIND!r}"
        )
    if doc.get("version") != MANIFEST_VERSION:
        raise ckpt_mod.CheckpointError(
            f"{path}: fleet manifest version {doc.get('version')!r} != "
            f"{MANIFEST_VERSION}"
        )
    return doc


def _patch_shards(cfg: dict, num_shards: int,
                  exclude_chips: tuple = ()) -> dict:
    """Rewrite a job config's partition for a mesh-size-changing resume
    (elastic shrink/re-expand, parallel/elastic.py): num_shards
    overridden, dead chips excluded; at 1 the islands keys drop away so
    the global engine builds (the S→1 endpoint). The slice restore goes
    through the relayout seam, so the layout change is invisible to the
    job's results."""
    import json as _json

    c = _json.loads(_json.dumps(cfg))
    exp = c.setdefault("experimental", {})
    if num_shards <= 1:
        for k in ("num_shards", "exchange_slots", "island_mode",
                  "mesh_exchange", "placement", "exclude_chips",
                  "async_spread", "balancer"):
            exp.pop(k, None)
        exp["num_shards"] = 1
    else:
        exp["num_shards"] = int(num_shards)
        exp["exclude_chips"] = [int(x) for x in exclude_chips]
    return c


def resume_fleet(ckpt_dir: str, lanes: int | None = None,
                 num_shards: int | None = None,
                 exclude_chips: tuple = (), **fleet_kw):
    """Rebuild a FleetSimulation from a fleet checkpoint directory.

    Job order in the rebuilt fleet: formerly-running jobs first (their
    lanes restore from the saved slices), then the still-queued jobs;
    completed jobs are carried as terminal records with their recorded
    results. Slice restores go through core/checkpoint.restore_relayout,
    so a corrupt slice fails with a clean CheckpointError naming the
    job — and a slice saved at a DIFFERENT partition (a fleet drained by
    chip loss, resumed on the shrunk mesh via `num_shards=` /
    `exclude_chips=`) re-layouts instead of failing: the lane-requeue-
    on-shrink path of the elastic resilience plane
    (parallel/elastic.py).

    `lanes` overrides the manifest's lane count (the sweep CLI's
    --lanes; None keeps the recorded width); either way the rebuilt
    fleet never opens more lanes than it has unfinished jobs."""
    from shadow_tpu.fleet.engine import FleetSimulation, _align_gear, \
        _build_solo

    doc = load_manifest(ckpt_dir)
    running = [e for e in doc["jobs"] if e["status"] == sched_mod.RUNNING]
    queued = [e for e in doc["jobs"] if e["status"] == sched_mod.QUEUED]
    terminal = [
        e for e in doc["jobs"]
        if e["status"] in sched_mod.TERMINAL
    ]
    unfinished = running + queued
    if not unfinished:
        raise ckpt_mod.CheckpointError(
            f"{ckpt_dir}: every job in the manifest is already terminal; "
            f"nothing to resume"
        )
    specs = [JobSpec.from_json(e["spec"]) for e in unfinished + terminal]
    if num_shards is not None:
        for s in specs:
            s.config = _patch_shards(s.config, num_shards, exclude_chips)
    want = int(doc["lanes"]) if lanes is None else int(lanes)
    lanes = min(want, len(unfinished))
    fleet_kw.setdefault("checkpoint_dir", ckpt_dir)
    fleet = FleetSimulation(specs, lanes=lanes, **fleet_kw)
    fleet._ckpt_next_t = int(doc.get("ckpt_next_t", fleet._ckpt_next_t))

    # every record reports at its ORIGINAL submission position, even
    # though the rebuilt internal order is running-jobs-first (results()
    # sorts by submit_idx, so resumed results match an uninterrupted run
    # row for row)
    by_name = {r.name: r for r in fleet.sched.records}
    for i, e in enumerate(doc["jobs"]):
        rec = by_name[e["spec"]["name"]]
        rec.submit_idx = int(e.get("order", i))

    # restore formerly-running lanes (the constructor admitted the first
    # `lanes` unfinished jobs in order, so each running entry's record is
    # already in a lane — find it and overwrite the fresh state)
    for e in running:
        rec = by_name[e["spec"]["name"]]
        if rec.lane is None:
            continue  # more running jobs than lanes (shrunk fleet): requeue
        sim = _build_solo(rec.spec)
        # relayout-tolerant: a slice saved at another partition (mesh
        # shrink/re-expand) re-layouts through the same seam checkpoint
        # resume across mesh sizes uses; same-layout slices fall through
        # to the strict restore path unchanged
        ckpt_mod.restore_relayout(sim, os.path.join(ckpt_dir, e["file"]))
        _align_gear(sim, fleet._gear)
        fleet.state = state_mod.set_lane(fleet.state, rec.lane, sim.state)
        fleet.params = state_mod.set_lane(fleet.params, rec.lane, sim.params)
        fs = e.get("faults_state") or {}
        lf = fleet._lane_faults[rec.lane]
        lf.pending = [(int(a), int(h)) for a, h in fs.get("pending", [])]
        lf.dead = set(fs.get("dead", []))
        lf.stats = dict(fs.get("stats", {}))

    # carry terminal jobs' recorded results (they never touch a lane)
    for e in terminal:
        rec = by_name[e["spec"]["name"]]
        s = e["summary"]
        rec.status = e["status"]
        rec.reason = s.get("reason", "")
        rec.events_committed = s.get("events_committed", 0)
        rec.windows = s.get("windows", 0)
        rec.frontier_ns = s.get("frontier_ns", -1)
        rec.wall_s = s.get("wall_s", 0.0)
        rec.counters = dict(s.get("counters", {}))
        rec.faults = dict(s.get("faults", {}))
        # the job's determinism-audit chain must survive the restart:
        # the serve daemon's crash-recovery bar is chain equality with an
        # uninterrupted run ACROSS every job, including ones that
        # finished before the crash
        rec.audit = dict(s.get("audit", {}))
    return fleet
