"""FleetSimulation: F independent experiments as ONE device program.

Shadow runs parameter sweeps one process per config; every solo run on the
TPU engine pays the same XLA compile and leaves the device under-occupied
at small host counts. The fleet stacks per-job state/`NetParams`/seeds
along a NEW leading vmap axis over the existing window kernel
(core/state.py stack_pytrees) and vmaps the driver kernels over it:

  * per-job HALT comes from per-lane (runahead, stop) window bounds — a
    finished job's fused-loop condition goes false and JAX's batched
    while_loop masks its lane, so jobs of different lengths finish
    raggedly without mutating each other;
  * a freed lane is REUSED: the host-side scheduler (fleet/scheduler.py)
    swaps the next queued job's freshly-built state into the lane slice —
    the compiled kernel's shapes never change, so the whole sweep costs
    ONE window-kernel compile (`kernel_traces` is the auditable metric);
  * the fleet axis composes with the islands engine: vmap-of-jobs
    OUTSIDE, shards INSIDE (parallel/islands.make_shard_run_to), so each
    lane is itself an S-shard island program;
  * per-job results ship through sliced counter/obs blocks at harvest
    (metrics schema v4 `fleet.jobs[*]`), and per-job checkpoint slices
    (fleet/checkpoint.py) make a partially-finished fleet resumable;
  * job-scoped fault quarantine: a `kill_host` injection in one job's
    fault plan drains THAT lane's rows only (the PR-3 crashed-host
    semantic, scoped to a lane), and a lane that cannot progress fails
    its job — never the fleet.

Determinism: a lane's trajectory is a pure function of its own (state,
params, window bounds); vmapped integer kernels compute the same values
as solo runs, so each job is bit-identical to the same scenario run solo
(tests/test_fleet.py asserts this for conservative AND optimistic,
global AND islands engines).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.core import gearbox, simtime
from shadow_tpu.core import engine as engine_mod
from shadow_tpu.core import hostplane as hostplane_mod
from shadow_tpu.core import pipeline as pipeline_mod
from shadow_tpu.core import pressure as pressure_mod
from shadow_tpu.core import state as state_mod
from shadow_tpu.core import supervisor as supervisor_mod
from shadow_tpu.core.config import load_config
from shadow_tpu.fleet.scheduler import (
    DONE, FAILED, TIMEOUT, FleetScheduler, JobRecord,
)
from shadow_tpu.fleet.sweep import JobSpec, validate_jobs
from shadow_tpu.obs import audit as audit_mod
from shadow_tpu.obs import counters as obs_mod
from shadow_tpu.obs import metrics as metrics_mod
from shadow_tpu.parallel import islands as islands_mod

NEVER = simtime.NEVER

# Per-attempt sub-step ceiling for optimistic fleet rounds (mirrors
# parallel/islands._MAX_SUBSTEPS: generous, but a pool-headroom stall
# surfaces as a driver error in seconds rather than hanging).
_MAX_SUBSTEPS = 4096


class FleetError(ValueError):
    pass


@dataclasses.dataclass
class _LaneFaults:
    """Job-scoped fault plane: resolved kill_host / skew_hosts injections
    + the lane's dead-host set (drained recurringly, the crashed-host
    semantic)."""

    # [(at_ns, op, payload)] sorted, unfired: payload is the host id for
    # kill_host, ([host_ids], factor) for skew_hosts
    pending: list
    dead: set
    stats: dict

    @classmethod
    def empty(cls) -> "_LaneFaults":
        return cls(pending=[], dead=set(), stats={})


def _build_solo(spec: JobSpec):
    """Build one job's solo Simulation (host-side: topology bake + initial
    events; no kernel is ever dispatched on it)."""
    from shadow_tpu.sim import build_simulation

    return build_simulation(load_config(spec.config))


def _align_gear(sim, level: int) -> None:
    """Force a freshly-built solo sim onto the fleet's gear (pool shapes
    must match the compiled lanes). Pure resize — no kernel rebind, no
    telemetry bump (the solo kernels are never used)."""
    if sim._gear == level:
        return
    spec = sim._gear_ladder[level]
    pool, dropped = gearbox.resize_pool(sim.state.pool, spec.capacity)
    if int(np.sum(np.asarray(jax.device_get(dropped)))):
        raise FleetError(
            f"job pool resize to gear {level} dropped events (initial "
            f"occupancy exceeds the fleet gear's capacity)"
        )
    sim.state = sim.state.replace(pool=pool)
    sim._gear = level


class FleetSimulation:
    """Batched multi-experiment runner over one compiled window kernel.

    Build via `build_fleet(jobs, lanes=...)`. Drive with `run()`
    (conservative windows) or `run_optimistic()` (per-lane speculative
    windows); read `results()` / `fleet_stats()` afterwards.
    """

    def __init__(
        self,
        jobs: list[JobSpec],
        lanes: int | None = None,
        windows_per_dispatch: int = 32,
        keep_final_subs: bool = False,
        checkpoint_dir: str | None = None,
        checkpoint_every_ns: int = 0,
    ):
        if not jobs:
            raise FleetError("fleet needs at least one job")
        validate_jobs(jobs)
        L = min(len(jobs), lanes) if lanes else len(jobs)
        self.sched = FleetScheduler(jobs, L)
        self.lanes = L
        self.windows_per_dispatch = int(windows_per_dispatch)
        self.keep_final_subs = bool(keep_final_subs)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every_ns = int(checkpoint_every_ns)
        self._ckpt_next_t = self.checkpoint_every_ns or int(NEVER)
        self.kernel_traces = 0
        self.gear_shifts = 0
        # Backend supervision (core/supervisor.py): dispatches route
        # through _sv(); a drain pauses admission until recovery. Backend
        # fault injections (kill_backend/stall_backend) are FLEET-scoped —
        # the accelerator serves every lane — and fire against the fleet
        # frontier, unlike the per-job kill_host plans.
        self.supervisor = None
        self._cpu_failover = False
        self._admission_paused = False
        self._backend_faults: list = []
        # Resource-pressure plane (core/pressure.py): lazily attached on
        # the first pressure signal. Lane eviction holds admission for a
        # few handoffs so the freed lane actually lowers the resident
        # set; reshaping rungs are forbidden mid-optimistic-attempt.
        self.pressure = None
        self._pressure_reshape_ok = True
        self._evict_hold = 0
        # AOT kernel cache (serve/kcache.py): when attached, fleet window
        # kernels bind from serialized exports on disk — a warm restart
        # re-binds every known shape with ZERO Python traces
        # (kernel_traces stays 0, the serve-smoke gated property).
        self.kernel_cache = None
        self._kc_digest = None
        # Telemetry session (obs/metrics.ObsSession): attached by the
        # sweep CLI (--metrics-out/--trace-out) via attach_obs. Fleet
        # traces give each lane its own tid (lane index + 1; tid 0 is the
        # driver row), named with "M" metadata events.
        self.obs_session = None

        # --- build the first wave of solo sims; the first is the template
        # whose kernel config (handlers, shapes, ladder) the fleet adopts
        sims = [_build_solo(r.spec) for r in self.sched.records[:L]]
        t = sims[0]
        self.template = t
        self._islands = isinstance(t, islands_mod.IslandSimulation)
        # Pipelined CPU↔TPU handoff (core/pipeline.py): the fleet adopts
        # the template job's experimental.pipelined_dispatch knob — one
        # sweep, one dispatch discipline. Stats lazily created so serial
        # sweeps emit no pipeline.* keys; handoff hooks run in the
        # host-drain phase of every fleet dispatch boundary.
        self.pipelined_dispatch = bool(
            getattr(t, "pipelined_dispatch", True)
        )
        self._pipeline_stats: dict | None = None
        self._handoff_hooks: list = []
        # Multi-worker host plane (core/hostplane.py): the fleet adopts
        # the template job's experimental.host_workers knob; sharded
        # handoff hooks partition PER LANE (the lane is the fleet's
        # owning-host unit) across pinned drain workers and merge in
        # canonical (frontier, lane) order. 1 = serial inline hooks and
        # no hostplane.* keys.
        self.host_workers = max(1, int(getattr(t, "host_workers", 1)))
        self._hostplane_obj = None
        self._hostplane_stats: dict | None = None
        if self._islands and t.mode != "vmap":
            raise FleetError(
                "fleet islands jobs run in island_mode: vmap (virtual "
                "shards batch under the job axis); shard_map composition "
                "is not supported yet"
            )
        self._ladder = t._gear_ladder
        self._shifter = (
            gearbox.GearShifter(self._ladder) if len(self._ladder) > 1
            else None
        )
        for s in sims[1:]:
            self._check_compat(s)

        # --- fleet gear: smallest level admitting every first-wave job
        g = t._gear
        for s in sims:
            g = max(g, FleetScheduler.admission_gear(
                self._ladder, self._occupancy_of(s), g
            ))
        self._gear = g
        for s in sims:
            _align_gear(s, g)

        # --- stack along the new leading job axis ---
        try:
            self.state = state_mod.stack_pytrees([s.state for s in sims])
            self.params = state_mod.stack_pytrees([s.params for s in sims])
        except ValueError as e:
            raise FleetError(str(e)) from e
        self._runahead = np.array([s.runahead for s in sims], np.int64)
        self._stop = np.array([s.stop_time for s in sims], np.int64)
        # Asynchronous conservative sync (parallel/islands.py): islands
        # jobs built with async_islands carry per-shard window widths and
        # an in-edge lookahead matrix; the fleet stacks them per lane —
        # traced inputs, so a lane swap never recompiles — and the sweep
        # gets BOTH axes of asynchrony: per-lane (runahead, stop) bounds
        # outside, per-shard frontiers inside.
        self._async = bool(self._islands and getattr(t, "_async", False))
        if self._async:
            # neighbor-only frontier exchange (parallel/lookahead.py):
            # the compiled ppermute schedule must cover every lane's
            # in-edges, so the fleet compiles the UNION of the initial
            # jobs' shift sets (per-edge lookahead VALUES stay per-lane
            # traced rows); _check_compat refuses a later swap-in whose
            # topology needs an uncovered shift — structural drift would
            # otherwise force the recompile the factory seam exists to
            # avoid. None = the template runs the all_gather arm.
            self._async_shifts = None
            if getattr(t, "_exchange", "all_gather") == "ppermute":
                self._async_shifts = tuple(sorted({
                    int(d) for s in sims
                    for d in getattr(s, "_async_shifts", ())
                }))
            self._async_runahead = np.stack([
                np.asarray(jax.device_get(s._async_runahead)) for s in sims
            ])
            self._async_look = np.stack([
                np.asarray(jax.device_get(s._async_look_in)) for s in sims
            ])
            self._async_spread = np.array(
                [int(s._async_spread) for s in sims], np.int64
            )
            self._async_counters = {
                "dispatches": 0, "supersteps": 0, "shard_windows": 0,
                "yields": 0, "blocked_on_neighbor": 0,
            }
            self._async_spread_max = 0
            self._async_frontier = None
            # cumulative per-(lane, shard) [3, L, S] steps/yields/blocked
            # — the fleet's critical-path signal (obs/prof.py)
            self._async_shard_stats = None
            self._look_in_cache = None
        self._lane_faults = [
            self._resolve_faults(s) for s in sims
        ]
        for j, rec in enumerate(self.sched.records[:L]):
            self.sched.admit(j, rec)

        self._gear_fns: dict[int, dict] = {}
        self._bind_gear()

    # ------------------------------------------------------------------
    # compatibility + admission plumbing
    # ------------------------------------------------------------------

    def _check_compat(self, sim) -> None:
        t = self.template
        if type(sim) is not type(t):
            raise FleetError(
                "fleet jobs mix engine layouts (islands vs global); the "
                "sweep must hold experimental.num_shards fixed"
            )
        if sim.num_hosts != t.num_hosts:
            raise FleetError(
                f"fleet jobs disagree on host count ({sim.num_hosts} vs "
                f"{t.num_hosts}); host topology compiles into the kernel"
            )
        if self._islands and (
            sim.num_shards != t.num_shards
            or getattr(sim, "exclude_chips", ())
            != getattr(t, "exclude_chips", ())
        ):
            raise FleetError(
                f"fleet jobs disagree on the mesh partition "
                f"(num_shards {getattr(sim, 'num_shards', 1)} vs "
                f"{getattr(t, 'num_shards', 1)}, exclude_chips "
                f"{getattr(sim, 'exclude_chips', ())} vs "
                f"{getattr(t, 'exclude_chips', ())}); after an elastic "
                f"relayout every swap-in must be rebuilt for the "
                f"surviving mesh (fleet/checkpoint.resume_fleet "
                f"num_shards=)"
            )
        if self._islands and bool(getattr(sim, "_async", False)) != bool(
            getattr(t, "_async", False)
        ):
            raise FleetError(
                "fleet jobs mix sync modes (async_islands vs barrier); "
                "the sweep must hold experimental.async_islands fixed"
            )
        if self._islands and getattr(self, "_async", False):
            if getattr(sim, "_exchange", None) != getattr(
                t, "_exchange", None
            ):
                raise FleetError(
                    "fleet jobs mix frontier-exchange modes (ppermute vs "
                    "all_gather); the sweep must hold "
                    "experimental.mesh_exchange fixed"
                )
            need = set(getattr(sim, "_async_shifts", ()) or ())
            have = self._async_shifts
            if have is not None and not need <= set(have):
                raise FleetError(
                    f"job topology needs ppermute shifts "
                    f"{sorted(need - set(have))} the fleet kernel did not "
                    f"compile (compiled {list(have)}); the sweep must "
                    f"hold shard-level connectivity fixed, or run with "
                    f"experimental.mesh_exchange: all_gather"
                )
        lt = [(s.capacity, s.K) for s in t._gear_ladder]
        ls = [(s.capacity, s.K) for s in sim._gear_ladder]
        if lt != ls:
            raise FleetError(
                f"fleet jobs disagree on the pool gear ladder ({ls} vs "
                f"{lt}); event_capacity / K / pool_gears compile into the "
                f"kernel"
            )

    def _occupancy_of(self, sim) -> int:
        """Live resident rows of a solo sim (max shard under islands) —
        the admission-control signal."""
        occ = jnp.sum(sim.state.pool.time != NEVER, axis=-1)
        return int(np.max(np.asarray(jax.device_get(occ))))

    def _resolve_faults(self, sim) -> _LaneFaults:
        """Resolve the job's fault plan (kill_host / skew_hosts; validated
        by fleet/sweep.py) into (at_ns, op, payload) records against ITS
        config's host names — job-scoped: the injections only ever touch
        this lane."""
        lf = _LaneFaults.empty()
        cfg = getattr(sim, "config", None)
        faults = cfg.faults.load_faults() if cfg is not None else []
        for f in faults:
            if f.op == "kill_host":
                lf.pending.append(
                    (int(f.at_ns), "kill_host", sim._resolve_host_id(f.host))
                )
            elif f.op == "skew_hosts":
                ids = [
                    sim._resolve_host_id(h)
                    for h in sim._skew_fault_ids(f)
                ]
                lf.pending.append(
                    (int(f.at_ns), "skew_hosts", (ids, int(f.factor)))
                )
            else:  # validated earlier; belt-and-braces
                raise FleetError(
                    f"fleet fault plans support kill_host/skew_hosts "
                    f"only, got {f.op!r}"
                )
        lf.pending.sort(key=lambda r: r[0])
        return lf

    # ------------------------------------------------------------------
    # kernel binding (one compiled program per active gear)
    # ------------------------------------------------------------------

    def _lane_step(self, spec: gearbox.GearSpec, optimistic: bool = False):
        """The raw per-job window step in the template's layout."""
        t = self.template
        if self._islands:
            isl = t._island_spec
            if optimistic:
                isl = isl._replace(optimistic=True)
            return t._step_builder(isl, spec.K)
        return engine_mod.make_window_step(
            t.handlers, t.num_hosts, K=spec.K, B=t.B, O=t.O,
            bulk_kinds=t._bulk_kinds,
            matrix_handlers=t._matrix_handlers,
            with_cpu_model=t._with_cpu,
            bulk_gate=t._bulk_gate,
            bulk_self_excluded=t._bulk_self_excluded,
            payload_words=t._payload_words,
            audit=t._audit_digest,
            # under vmap a lax.cond with a batched predicate executes BOTH
            # branches, so matrix-capable sims pin the matrix path — the
            # same rule sim.py applies to vmap islands
            _force_path="matrix" if t._matrix_handlers else None,
        )

    def _counted(self, fn):
        """jit with a trace counter: tracing happens exactly once per
        compiled program, so the count IS the window-kernel compile
        metric the fleet-smoke gate asserts on."""
        def counted(*args):
            self.kernel_traces += 1
            return fn(*args)

        return self._jit(counted)

    def attach_kernel_cache(self, kcache) -> None:
        """Bind an AOT kernel cache (serve/kcache.py) BEFORE the first
        dispatch: subsequent kernel binds consult the cache and only
        trace on a miss (exporting + persisting the artifact so the next
        process hits). The cache key folds in the template job's kernel-
        shaping config digest, so kernel-compatible sweeps share entries
        while any shape/handler change misses safely."""
        from shadow_tpu.serve.kcache import kernel_config_digest

        self.kernel_cache = kcache
        self._kc_digest = kernel_config_digest(
            self.sched.records[0].spec.config
        )
        # re-bind the active gear through the cache (build bound the jit
        # path before the cache existed; nothing has been traced yet when
        # this is called pre-dispatch, so the swap is free)
        self._gear_fns = {}
        self._bind_gear()

    def _kernel(self, tag: str, fn):
        """Cache-aware kernel bind: with no cache (or during CPU
        failover, whose re-lowered kernels are transient) this is plain
        counted jit; with one, the first call looks the export up by
        (config digest, tag, arg avals) and only traces on a miss.

        Export serialization cannot carry the repo's custom pytree nodes
        (SimState/EventPool/...), so the exported artifact is the LEAF-
        FLATTENED kernel: flat arrays in, flat arrays out. No treedef
        needs to survive on disk because every fleet kernel returns
        (state', *scalar_extras) where state' has exactly the INPUT
        state's structure — the call-time wrapper re-folds the leading
        leaves with the live treedef and passes the extras through."""
        kc = self.kernel_cache
        if kc is None or self._cpu_failover:
            return self._counted(fn)
        holder: dict = {}

        def call(*args):
            flat, in_tree = jax.tree_util.tree_flatten(args)
            bound = holder.get("fn")
            if bound is None:
                state_def = jax.tree_util.tree_structure(args[0])
                key = kc.key(self._kc_digest, tag, flat)
                ex = kc.get(key)
                if ex is None:

                    def flat_fn(*leaves):
                        out = fn(*jax.tree_util.tree_unflatten(
                            in_tree, leaves
                        ))
                        return tuple(jax.tree_util.tree_leaves(out))

                    self.kernel_traces += 1
                    ex = kc.export_and_put(key, flat_fn, flat)
                jf = jax.jit(ex.call)
                n = state_def.num_leaves

                def bound(leaves, _jf=jf, _n=n, _sd=state_def):
                    out = _jf(*leaves)
                    st = jax.tree_util.tree_unflatten(_sd, out[:_n])
                    return (st, *out[_n:])

                holder["fn"] = bound
            return holder["fn"](flat)

        return call

    def _jit(self, fn):
        """jit honoring supervisor CPU failover: while the accelerator is
        gone, fleet kernels re-lower on the CPU backend and the sweep
        keeps advancing (core/supervisor.py)."""
        jf = jax.jit(fn)
        if not self._cpu_failover:
            return jf
        try:
            dev = jax.devices("cpu")[0]
        except RuntimeError:
            return jf

        def on_cpu(*args):
            with jax.default_device(dev):
                return jf(*args)

        return on_cpu

    def _build_gear_fns(self, spec: gearbox.GearSpec) -> dict:
        step = self._lane_step(spec)
        if self._islands and self._async:
            # async conservative loop: vmap-of-jobs outside, shards
            # inside; per-lane [S] runahead / [S, S] lookahead / spread
            # stack one more leading axis
            lane = islands_mod.make_shard_run_to_async(
                step, spec.hi, shifts=self._async_shifts,
                num_shards=self.template.num_shards,
            )
            inner = jax.vmap(
                lane, in_axes=(0, None, 0, 0, None, None, None),
                axis_name=islands_mod.AXIS,
            )
            run_to = jax.vmap(inner, in_axes=(0, 0, 0, 0, 0, 0, None))
        elif self._islands:
            lane = islands_mod.make_shard_run_to(step, spec.hi)
            inner = jax.vmap(
                lane, in_axes=(0, None, None, None, None),
                axis_name=islands_mod.AXIS,
            )
            run_to = jax.vmap(inner, in_axes=(0, 0, 0, 0, None))
        else:
            inner = engine_mod.make_run_to(step, spec.hi)
            run_to = jax.vmap(inner, in_axes=(0, 0, 0, 0, None))
        return {
            "run_to": self._kernel(f"run_to:g{spec.level}", run_to),
            "attempt": None,  # compiled lazily by run_optimistic
        }

    def _bind_gear(self) -> None:
        spec = self._ladder[self._gear]
        fns = self._gear_fns.get(spec.level)
        if fns is None:
            fns = self._gear_fns[spec.level] = self._build_gear_fns(spec)
        self._run_to = fns["run_to"]
        self._attempt = fns["attempt"]

    def _ensure_attempt(self) -> None:
        """Lazily build the optimistic kernel for the bound gear:
        conservative fleets never pay for the done_t machinery."""
        if self._attempt is not None:
            return
        spec = self._ladder[self._gear]
        if self._islands:
            sub = islands_mod.make_shard_substep(
                self._lane_step(spec, optimistic=True)
            )
            inner = jax.vmap(
                sub, in_axes=(0, None, None, None),
                axis_name=islands_mod.AXIS,
            )
        else:
            inner = engine_mod.make_attempt(
                self._lane_step(spec)
            )
        att = jax.vmap(inner, in_axes=(0, 0, 0, 0))
        self._attempt = self._gear_fns[spec.level]["attempt"] = \
            self._kernel(f"attempt:g{spec.level}", att)

    def _shift_gear(self, level: int) -> None:
        """Move EVERY lane's pool to `level`'s capacity (one batched
        truncating/padding re-sort) and rebind the fleet kernels. Handoff
        boundary only, exactly like the solo drivers."""
        spec = self._ladder[level]
        pool, dropped = gearbox.resize_pool(self.state.pool, spec.capacity)
        n = int(np.sum(np.asarray(jax.device_get(dropped))))
        if n:
            raise FleetError(
                f"fleet gear shift to level {level} dropped {n} events "
                f"(decision-rule bug: occupancy exceeded the target gear)"
            )
        self.state = self.state.replace(pool=pool)
        self._gear = level
        self.gear_shifts += 1
        if self._shifter is not None:
            self._shifter.reset()
        self._bind_gear()

    # ------------------------------------------------------------------
    # backend supervision (core/supervisor.py): drain pauses admission,
    # in-flight lanes requeue for the resumed sweep, recovery resumes it
    # ------------------------------------------------------------------

    def attach_supervisor(self, supervisor) -> None:
        supervisor.bind(self)
        self.supervisor = supervisor

    def _sv(self, label: str, thunk):
        if self.supervisor is None:
            return thunk()
        return self.supervisor.call(label, thunk)

    def _sv_issue(self, label: str, issue_fn, fetch_fn):
        """ISSUE half of a split fleet dispatch (core/supervisor.py
        PendingDispatch): enqueue async, never block."""
        if self.supervisor is None:
            return supervisor_mod.PendingDispatch.direct(
                label, issue_fn, fetch_fn
            )
        return self.supervisor.issue(label, issue_fn, fetch_fn)

    def _sv_await(self, pending):
        """AWAIT half: blocking fetches under the classified retry
        ladder / watchdog / loss policies when supervised."""
        if self.supervisor is None:
            return pending.await_direct()
        return self.supervisor.await_result(pending)

    def _sv_disrupted(self) -> bool:
        sup = self.supervisor
        return sup is not None and sup.pending_disruption

    # -- pipelined CPU↔TPU handoff (core/pipeline.py) --

    def _pipeline(self):
        if not self.pipelined_dispatch:
            return None
        if self._pipeline_stats is None:
            self._pipeline_stats = pipeline_mod.new_stats()
        return pipeline_mod.TwoSlotPipeline(self._pipeline_stats)

    def pipeline_stats(self) -> dict:
        """`pipeline.*` telemetry (schema v14); {} until a pipelined
        fleet loop ran (serial sweeps emit no pipeline keys)."""
        st = self._pipeline_stats
        return dict(st) if st is not None else {}

    def add_handoff_hook(self, fn, sharded: bool = False) -> None:
        """Register per-boundary host work, called in the host-drain
        phase of every fleet dispatch boundary (after scheduler work).
        sharded=False: fn(fleet, frontier_ns), one whole-fleet call on
        the coordinator. sharded=True: fn(fleet, frontier_ns, lane), one
        call per lane, partitioned by lane across the multi-worker host
        plane (core/hostplane.py) — partition-local state only. With
        host_workers == 1 sharded hooks run inline in the same canonical
        (frontier, lane) order the parallel merge uses."""
        self._handoff_hooks.append((fn, bool(sharded)))

    # -- multi-worker host plane (core/hostplane.py) --

    def _hostplane(self):
        if self.host_workers <= 1:
            return None
        if self._hostplane_obj is None:
            if self._hostplane_stats is None:
                self._hostplane_stats = hostplane_mod.new_stats(
                    self.host_workers
                )
            self._hostplane_obj = hostplane_mod.HostPlane(
                self.host_workers, self._hostplane_stats
            )
        return self._hostplane_obj

    def hostplane_stats(self) -> dict:
        """`hostplane.*` telemetry (schema v15); {} until a multi-worker
        fleet drain ran (host_workers == 1 emits no hostplane keys)."""
        st = self._hostplane_stats
        return dict(st) if st is not None else {}

    def _run_handoff_hooks(self, mn) -> None:
        if not self._handoff_hooks:
            return
        frontier = int(np.min(mn)) if np.ndim(mn) else int(mn)
        sharded = [fn for fn, sh in self._handoff_hooks if sh]
        if sharded:
            hp = self._hostplane()
            if hp is None:
                for lane in range(self.lanes):
                    for fn in sharded:
                        fn(self, frontier, lane)
            else:
                obs = self.obs_session
                hp.drain(
                    [
                        hostplane_mod.HostAction(
                            frontier, lane,
                            (lambda f=fn, j=lane: f(self, frontier, j)),
                        )
                        for lane in range(self.lanes)
                        for fn in sharded
                    ],
                    tracer=obs.tracer if obs is not None else None,
                )
        for fn, sh in self._handoff_hooks:
            if not sh:
                fn(self, frontier)

    def _handoff_quiet(self, mn: np.ndarray) -> bool:
        """True when the upcoming fleet handoff cannot take a scheduler
        or state action at frontier vector `mn`: no lane finished, no
        due lane/backend injection, no wall deadline armed on a running
        job, no checkpoint mark due, no pressure hold. Speculation only
        crosses QUIET boundaries; everything else is a barrier point."""
        if self._evict_hold > 0 or self._admission_paused:
            return False
        frontier = int(NEVER)
        for j in range(self.lanes):
            rec = self.sched.lane_job[j]
            if rec is None:
                # an empty lane means queued work could admit
                if self.sched.pending():
                    return False
                continue
            if mn[j] >= self._stop[j]:
                return False  # harvest due
            if rec.spec.deadline_s:
                return False  # wall-clock deadline: unpredictable
            lf = self._lane_faults[j]
            if lf.pending and lf.pending[0][0] <= mn[j]:
                return False
            if lf.dead:
                return False  # recurring quarantine drain
            frontier = min(frontier, int(mn[j]))
        if self._backend_fault_mark() <= frontier:
            return False
        if (self.checkpoint_dir and self.checkpoint_every_ns
                and frontier >= self._ckpt_next_t):
            return False
        pc = self.pressure
        if (pc is not None and pc.saturate_frac is not None
                and pc.saturate_frac < 1.0):
            return False
        return True

    def attach_faults(self, faults) -> None:
        """Arm FLEET-scoped injections: backend ops (kill_backend /
        stall_backend / exhaust_backend) plus saturate_pool — the
        accelerator and the pressure plane serve every lane, so these
        fire at the handoff whose fleet frontier (min over active lanes)
        reaches `at`. Per-job plans carry kill_host only (validated by
        fleet/sweep.py)."""
        from shadow_tpu.faults import plan as plan_mod

        allowed = plan_mod.BACKEND_OPS | {"saturate_pool"}
        for f in faults:
            if f.op not in allowed:
                raise FleetError(
                    f"fleet-level fault plans support backend + pressure "
                    f"ops only ({sorted(allowed)}); {f.op!r} belongs "
                    f"in a per-job plan"
                )
        self._backend_faults = sorted(faults, key=lambda f: (f.at_ns, f.seq))
        if self.supervisor is None and any(
            f.op in plan_mod.BACKEND_OPS for f in self._backend_faults
        ):
            from shadow_tpu.core.supervisor import BackendSupervisor

            self.attach_supervisor(BackendSupervisor())

    def _backend_fault_mark(self) -> int:
        """Earliest unfired backend injection: dispatches clamp their
        stop here so the loss lands at a deterministic frontier."""
        for f in self._backend_faults:
            if not f.fired:
                return f.at_ns
        return int(NEVER)

    def _backend_fault_tick(self, mn: np.ndarray) -> None:
        active = [
            mn[j] for j in range(self.lanes)
            if self.sched.lane_job[j] is not None
        ]
        if not active:
            return
        frontier = int(min(active))
        for f in self._backend_faults:
            if f.fired or f.at_ns > frontier:
                continue
            f.fired = True
            sup = self.supervisor
            if f.op == "kill_backend":
                sup.inject_kill(f.recover_after)
            elif f.op == "kill_chip":
                sup.inject_kill_chip(f.chip, f.recover_after)
            elif f.op == "exhaust_backend":
                sup.inject_exhaust(f.recover_after)
            elif f.op == "saturate_pool":
                # fleet-scoped pool saturation: the controller records
                # the pressure; the fleet has no spill tier, so relief
                # is gear headroom / lane eviction via the ladder
                self._pressure().saturate(f.frac)
            else:  # stall_backend
                sup.inject_stall(f.count)
            obs = self.obs_session
            if obs is not None and obs.tracer is not None:
                obs.tracer.fault("fault_injection", op=f.op, at_ns=f.at_ns)

    def _rebind_kernels(self) -> None:
        """Fresh compiled kernels for the active gear (hot resume /
        failover re-lowering); re-ensures the optimistic attempt kernel
        when one was bound, and reopens admission — the drained sweep
        resumes."""
        had_attempt = self._attempt is not None
        self._gear_fns = {}
        self._bind_gear()
        if had_attempt and self._attempt is None:
            self._ensure_attempt()
        self._admission_paused = False

    def _enter_cpu_failover(self) -> None:
        if self._islands and self.template.mode == "shard_map":
            raise RuntimeError(
                "CPU failover is not available under shard_map islands; "
                "use --on-backend-loss wait or abort"
            )
        try:
            dev = jax.devices("cpu")[0]
        except RuntimeError as e:
            raise RuntimeError(f"no CPU backend to fail over to: {e}") from e
        self.state = jax.device_put(jax.device_get(self.state), dev)
        self.params = jax.device_put(jax.device_get(self.params), dev)
        self._cpu_failover = True
        self._rebind_kernels()

    def _exit_cpu_failover(self) -> None:
        self._cpu_failover = False
        self.state = jax.device_put(jax.device_get(self.state))
        self.params = jax.device_put(jax.device_get(self.params))
        self._rebind_kernels()

    def _drain_to_checkpoint(self, reason: str,
                             ckpt_dir: str | None = None) -> str | None:
        """Backend-loss drain: pause admission, flush every running
        lane's slice + the manifest (fleet/checkpoint.py) with the drain
        reason, and — under policies `abort` and `relayout` — requeue
        the in-flight jobs so the scheduler truth matches reality
        (nothing is running on a dead backend; the saved slices let
        `sweep --resume` restore their progress instead of re-running
        them). `relayout` requeues because a fleet cannot reshape its
        compiled lane × shard program in place: the ChipLost that
        follows hands the rebuild to the caller, and `resume_fleet
        (num_shards=...)` restores every lane through the relayout seam
        on the shrunk mesh — the lane-requeue-on-shrink contract."""
        self._admission_paused = True
        sup = self.supervisor
        policy = sup.policy if sup is not None else "abort"
        d = ckpt_dir or self.checkpoint_dir
        path = None
        if d:
            from shadow_tpu.fleet import checkpoint as fleet_ckpt

            path = fleet_ckpt.save_fleet(self, d, extra_meta={"drain": {
                "reason": reason, "policy": policy,
            }})
        obs = self.obs_session
        if obs is not None and obs.tracer is not None:
            obs.tracer.fault("drain_checkpoint", reason=reason)
        if policy in ("abort", "relayout"):
            for j in range(self.lanes):
                if self.sched.lane_job[j] is not None:
                    self.sched.requeue(j, reason="backend drain")
        return path

    def resilience_stats(self) -> dict:
        """The `resilience.*` metrics namespace (schema v6): supervisor
        counters plus the scheduler's reclaim/requeue totals."""
        sup = self.supervisor
        d = sup.stats() if sup is not None else {}
        d["lane_reclaims"] = self.sched.lane_reclaims
        d["jobs_requeued"] = self.sched.jobs_requeued
        return d

    # ------------------------------------------------------------------
    # resource-pressure plane (core/pressure.py): fleet-shaped rungs
    # ------------------------------------------------------------------

    def attach_pressure(self, controller) -> None:
        self.pressure = controller

    def _pressure(self):
        if self.pressure is None:
            self.pressure = pressure_mod.PressureController()
        return self.pressure

    def _pressure_ladder_step(self, label: str) -> bool:
        return self._pressure().on_backend_exhausted(self, label)

    def _pressure_stall(self, *, window=None, occupancy=None,
                        capacity=None) -> bool:
        return self._pressure().on_pool_exhausted(
            self, window=window, occupancy=occupancy, capacity=capacity
        )

    def _pool_exhausted(self, message: str, window=None, occupancy=None,
                        capacity=None):
        """Terminal pool exhaustion: drain the fleet (slices + manifest,
        jobs requeued so `sweep --resume` restores them at a reshaped
        config) and build the typed error — never a bare RuntimeError."""
        path = self._drain_to_checkpoint("pool_exhausted")
        if path:
            message += f" (drained to {path}; resume with sweep --resume)"
        return pressure_mod.PoolExhausted(
            message, window=window, occupancy=occupancy, capacity=capacity
        )

    def _lane_occupancies(self) -> np.ndarray:
        occ = jnp.sum(self.state.pool.time != NEVER, axis=-1)
        return np.asarray(jax.device_get(occ)).reshape(
            self.lanes, -1
        ).max(axis=1)

    def _pressure_relieve_pool(self, step: int):
        """Per-lane pools share ONE compiled shape, so more headroom is a
        fleet-wide upshift; at the top gear, shed the heaviest job rather
        than the fleet (the existing fail-THIS-job posture)."""
        pc = self._pressure()
        if (not pc.hold_gear and self._pressure_reshape_ok
                and self._gear < self._ladder[-1].level):
            self._shift_gear(self._gear + 1)
            return "upshift"
        if pc.policy.allow_lane_eviction:
            j = self._heaviest_lane()
            if j is not None:
                self._kill_lane(j)
                self._harvest(
                    j, FAILED,
                    "pool pressure: job shed by the degradation ladder "
                    "(raise experimental.event_capacity for this sweep)",
                )
                return "job_shed"
        return None

    def _pressure_relieve_memory(self, step: int):
        """Memory rungs, fleet-shaped: forced downshift when every lane's
        occupancy fits the smaller gear (the fleet has no spill tier to
        park overflow), else evict the heaviest lane — the freed lane
        shrinks the resident working set and admission holds."""
        pc = self._pressure()
        pol = pc.policy
        if not self._pressure_reshape_ok:
            # mid-optimistic-attempt: the rollback snapshot pins both the
            # compiled shapes AND the lane rows (an eviction's row clear
            # would be overwritten by the attempt's state) — no safe rung;
            # the supervisor's drain + recovery path takes over
            return None
        if pol.allow_downshift and self._gear > self._ladder[0].level:
            target = self._ladder[self._gear - 1]
            if int(self._lane_occupancies().max(initial=0)) <= target.fill:
                self._shift_gear(target.level)
                pc.hold_gear = True
                return "downshift"
        if pol.allow_lane_eviction and self._pressure_evict_lane():
            return "lane_eviction"
        return None

    def _heaviest_lane(self) -> int | None:
        occ = self._lane_occupancies()
        best, best_occ = None, -1
        for j in range(self.lanes):
            if self.sched.lane_job[j] is None:
                continue
            if int(occ[j]) > best_occ:
                best, best_occ = j, int(occ[j])
        return best

    def _pressure_evict_lane(self) -> bool:
        """Requeue the heaviest running job (FleetScheduler.requeue — it
        re-admits FIFO at its original position) and clear its lane; the
        eviction hold keeps the freed lane empty for a few handoffs so
        the resident set actually shrinks. The re-run is bit-identical
        (jobs are pure functions of their spec)."""
        j = self._heaviest_lane()
        if j is None:
            return False
        self.sched.requeue(j, reason="pressure eviction")
        self._kill_lane(j)
        self._lane_faults[j] = _LaneFaults.empty()
        self._evict_hold = max(
            self._evict_hold,
            self._pressure().policy.eviction_hold_dispatches,
        )
        return True

    def pressure_stats(self) -> dict:
        """The `pressure.*` metrics namespace (schema v8); {} until a
        pressure signal engaged."""
        pc = self.pressure
        return pc.stats() if pc is not None else {}

    def _reclaim_expired(self) -> bool:
        """Free lanes whose job blew its wall-clock deadline NOW — before
        the next dispatch would ride the dead job along — and hand each
        freed lane straight to the admission queue (`lane_reclaims`)."""
        changed = False
        for j in range(self.lanes):
            rec = self.sched.lane_job[j]
            if rec is None or not rec.deadline_exceeded():
                continue
            self._kill_lane(j)
            self._harvest(
                j, TIMEOUT,
                f"wall deadline {rec.spec.deadline_s}s exceeded",
            )
            self.sched.lane_reclaims += 1
            self._admit_next(j)
            changed = True
        return changed

    # ------------------------------------------------------------------
    # telemetry session + per-lane trace rows
    # ------------------------------------------------------------------

    def attach_obs(self, session) -> None:
        """Attach an ObsSession (metrics + optional Chrome tracer). Lanes
        already occupied at attach time get their thread rows named and
        an `admit` marker immediately, so a session attached right after
        build still renders every job's full residency."""
        self.obs_session = session
        tr = session.tracer if session is not None else None
        if tr is not None:
            tr.thread_name(0, "driver")
            for j, rec in enumerate(self.sched.lane_job):
                if rec is not None:
                    self._trace_admit(j, rec)

    def _trace_admit(self, lane: int, rec: JobRecord) -> None:
        obs = self.obs_session
        if obs is None or obs.tracer is None:
            return
        tid = lane + 1
        obs.tracer.thread_name(tid, f"lane {lane}")
        rec._trace_ts0 = obs.tracer._now_us()
        obs.tracer.instant("admit", tid=tid, job=rec.name, lane=lane)

    def _trace_harvest(self, lane: int, rec: JobRecord) -> None:
        obs = self.obs_session
        if obs is None or obs.tracer is None:
            return
        tid = lane + 1
        now = obs.tracer._now_us()
        t0 = getattr(rec, "_trace_ts0", None)
        if t0 is not None:
            # one complete event per job residency on the lane's row
            obs.tracer.complete(
                rec.name, t0, now - t0, cat="job", tid=tid,
                status=rec.status,
                events_committed=int(rec.events_committed),
            )
        obs.tracer.instant(
            "harvest", tid=tid, job=rec.name, status=rec.status
        )

    def counters(self) -> dict[str, int]:
        """Engine counters summed across every lane (fleet-wide progress;
        per-job counters are harvested per lane)."""
        c = jax.device_get(self.state.counters)
        return {
            f.name: int(np.sum(np.asarray(getattr(c, f.name))))
            for f in dataclasses.fields(c)
        }

    # ------------------------------------------------------------------
    # lane lifecycle
    # ------------------------------------------------------------------

    def _lane_min_times(self) -> np.ndarray:
        mn = jnp.min(self.state.pool.time, axis=-1)
        return np.asarray(jax.device_get(mn)).reshape(
            self.lanes, -1
        ).min(axis=1)

    def _bump_lane_win(self, lane: int, idx: int, n: int = 1) -> None:
        if self.state.obs is None or n == 0:
            return
        w = self.state.obs.win
        if w.ndim == 3:  # islands lanes: [L, S, NUM_WIN]; shard 0 carries
            w = w.at[lane, 0, idx].add(n)
        else:
            w = w.at[lane, idx].add(n)
        self.state = self.state.replace(
            obs=self.state.obs.replace(win=w)
        )

    def _harvest(self, lane: int, status: str = DONE,
                 reason: str = "") -> JobRecord:
        """Read one finished lane's results (counters, obs slice,
        frontier) at the handoff boundary and free the lane."""
        lane_state = state_mod.slice_lane(self.state, lane)
        rec = self.sched.release(lane, status, reason)
        c = jax.device_get(lane_state.counters)
        rec.counters = {
            f.name: int(np.sum(np.asarray(getattr(c, f.name))))
            for f in dataclasses.fields(c)
        }
        rec.events_committed = rec.counters["events_committed"]
        snap = obs_mod.snapshot(lane_state)
        if snap:
            rec.windows = snap["win"]["windows_run"]
            hl = snap["host_last_t"]
            rec.frontier_ns = int(hl.max()) if hl.size else -1
            rec.obs = {
                "win": snap["win"],
                "vtime": obs_mod.vtime_stats(hl),
            }
            if "host_digest" in snap:
                # the job's determinism-audit chain (obs/audit.py):
                # lane slices are solo-layout, so this equals the same
                # scenario's solo-run chain bit-for-bit (schema v5
                # fleet.jobs[*].audit)
                rec.audit = {
                    "chain": audit_mod.combine(snap["host_digest"]),
                }
        rec.faults = dict(self._lane_faults[lane].stats)
        if self.keep_final_subs:
            rec.subs = jax.device_get(lane_state.subs)
        self._lane_faults[lane] = _LaneFaults.empty()
        if status == DONE:
            # fold the observed event count into the packing estimator's
            # rate EWMA (fleet/scheduler.calibrate)
            self.sched.calibrate(rec)
        self._trace_harvest(lane, rec)
        return rec

    def _admit_next(self, lane: int) -> bool:
        """Swap the next queued job into a freed lane: build its solo
        state, clear the admission gate (upshifting the fleet gear if the
        job's initial rows demand it), and write the lane slice. The
        compiled kernel is untouched — compile once, reuse the lane."""
        if self._admission_paused:
            # backend drain in progress: no new work enters until the
            # supervisor's recovery reopens admission (_rebind_kernels)
            return False
        if self._evict_hold > 0:
            # pressure eviction in effect: the freed lane stays empty so
            # the resident working set actually shrinks (core/pressure.py)
            return False
        # predicted-load packing / lane stealing (self-balancing plane):
        # under "load" packing the freed lane takes the heaviest pending
        # job instead of the FIFO head (fleet/scheduler.pick)
        rec = self.sched.pick(lane)
        if rec is None:
            return False
        sim = _build_solo(rec.spec)
        self._check_compat(sim)
        want = FleetScheduler.admission_gear(
            self._ladder, self._occupancy_of(sim), self._gear
        )
        if want > self._gear:
            self.sched.admission_upshifts += 1
            self._shift_gear(want)
        _align_gear(sim, self._gear)
        try:
            def _swap():
                st = state_mod.set_lane(self.state, lane, sim.state)
                pr = state_mod.set_lane(self.params, lane, sim.params)
                return st, pr

            self.state, self.params = self._sv("lane_swap", _swap)
        except ValueError as e:
            raise FleetError(f"job {rec.name!r}: {e}") from e
        self._runahead[lane] = sim.runahead
        self._stop[lane] = sim.stop_time
        if self._async:
            self._async_runahead[lane] = np.asarray(
                jax.device_get(sim._async_runahead))
            self._async_look[lane] = np.asarray(
                jax.device_get(sim._async_look_in))
            self._async_spread[lane] = int(sim._async_spread)
            self._look_in_cache = None
        self._lane_faults[lane] = self._resolve_faults(sim)
        self.sched.admit(lane, rec)
        self.sched.lane_swaps += 1
        self._trace_admit(lane, rec)
        return True

    def _kill_lane(self, lane: int) -> None:
        """Drop every pending event of a lane (timeout / pressure kill):
        the lane's frontier jumps to NEVER and its fused-loop cond goes
        false — a dead lane is indistinguishable from a finished one."""
        t = self.state.pool.time
        self.state = self.state.replace(
            pool=self.state.pool.replace(
                time=t.at[lane].set(jnp.full_like(t[lane], NEVER))
            )
        )

    def _drain_lane_dead(self, lane: int) -> int:
        """Cancel pool rows destined to the lane's quarantined hosts —
        THIS lane only (the job-scoped crashed-host semantic). Recurring:
        late emissions and islands exchange-deferred rows are caught at
        every subsequent handoff."""
        lf = self._lane_faults[lane]
        if not lf.dead:
            return 0
        pool = self.state.pool
        tl, dl = pool.time[lane], pool.dst[lane]
        mask = jnp.isin(dl, jnp.asarray(sorted(lf.dead), dl.dtype)) \
            & (tl != NEVER)
        n = int(jnp.sum(mask))
        if n:
            self.state = self.state.replace(pool=pool.replace(
                time=pool.time.at[lane].set(jnp.where(mask, NEVER, tl))
            ))
            lf.stats["events_drained"] = lf.stats.get("events_drained", 0) + n
            self._bump_lane_win(lane, obs_mod.WIN_FAULTS)
        return n

    def _fault_marks(self) -> np.ndarray:
        """Per-lane earliest unfired injection time (NEVER if none): the
        conservative driver clamps each lane's dispatch stop here, so an
        injection executes at a handoff whose committed frontier sits
        exactly at its mark — the solo drivers' _fault_mark clamp,
        lane-scoped. Without the clamp a fused multi-window dispatch
        would sail past the mark and the injection timing would degrade
        to dispatch granularity."""
        marks = np.full(self.lanes, int(NEVER), np.int64)
        for j in range(self.lanes):
            lf = self._lane_faults[j]
            if lf.pending and self.sched.lane_job[j] is not None:
                marks[j] = lf.pending[0][0]
        return marks

    def _fault_tick(self, mn: np.ndarray) -> bool:
        """Fire due job-scoped injections + recurring drains at the
        handoff boundary. Returns True if any lane's pool changed."""
        changed = False
        for j in range(self.lanes):
            if self.sched.lane_job[j] is None:
                continue
            lf = self._lane_faults[j]
            while lf.pending and lf.pending[0][0] <= mn[j]:
                _, op, payload = lf.pending.pop(0)
                lf.stats["injections_fired"] = \
                    lf.stats.get("injections_fired", 0) + 1
                obs = self.obs_session
                if op == "skew_hosts":
                    ids, factor = payload
                    n = self._skew_lane(j, ids, factor)
                    lf.stats["events_skewed"] = \
                        lf.stats.get("events_skewed", 0) + n
                    changed = True
                    if obs is not None and obs.tracer is not None:
                        obs.tracer.fault(
                            "skew_hosts", tid=j + 1, lane=j,
                            hosts=len(ids), factor=factor, injected=n,
                        )
                elif payload not in lf.dead:
                    lf.dead.add(payload)
                    lf.stats["hosts_quarantined"] = \
                        lf.stats.get("hosts_quarantined", 0) + 1
                    if obs is not None and obs.tracer is not None:
                        obs.tracer.fault(
                            "kill_host", tid=j + 1, host=payload, lane=j
                        )
            if lf.dead and self._drain_lane_dead(j):
                changed = True
        return changed

    def _skew_lane(self, lane: int, ids: list[int], factor: int) -> int:
        """Apply one skew_hosts injection to a single lane's pool slice
        (faults/injector.skew_pool_np — the solo engines' replication,
        lane-scoped). The per-lane dispatch clamp (_fault_marks) pinned
        this lane's frontiers at or below the injection time, so copies
        (which inherit pending-event times) are frontier-safe. The fleet
        has no spill tier: copies that do not fit the lane's pool are
        counted dropped (`skew_overflow_dropped`) — deterministic, so
        chain-parity arms see identical drops."""
        from shadow_tpu.faults import injector as inj_mod

        lf = self._lane_faults[lane]
        pool = self.state.pool
        cols = [
            np.array(jax.device_get(c[lane])) for c in (
                pool.time, pool.dst, pool.src, pool.seq, pool.kind,
                pool.payload,
            )
        ]
        flat = cols[0].ndim == 1  # global lanes [C] vs islands [S, C]
        if flat:
            cols = [c[None] for c in cols]
        out, made, overflow = inj_mod.skew_pool_np(
            cols, ids, factor, dead=lf.dead
        )
        t, d, s, q, k, p = (
            (c[0] for c in out) if flat else out
        )
        self.state = self.state.replace(pool=pool.replace(
            time=pool.time.at[lane].set(jnp.asarray(t)),
            dst=pool.dst.at[lane].set(jnp.asarray(d)),
            src=pool.src.at[lane].set(jnp.asarray(s)),
            seq=pool.seq.at[lane].set(jnp.asarray(q)),
            kind=pool.kind.at[lane].set(jnp.asarray(k)),
            payload=pool.payload.at[lane].set(jnp.asarray(p)),
        ))
        dropped = sum(
            rows[0].shape[0] for _, rows in sorted(overflow.items())
        )
        if dropped:
            lf.stats["skew_overflow_dropped"] = \
                lf.stats.get("skew_overflow_dropped", 0) + dropped
        self._bump_lane_win(lane, obs_mod.WIN_FAULTS)
        return made

    def _handoff(self, mn: np.ndarray, press: np.ndarray) -> bool:
        """Everything the host does between dispatches: job-scoped fault
        injections, harvest of finished lanes, lane swaps, wall-clock
        deadlines, pressure kills, checkpoint marks. Returns True when
        any scheduler-visible action happened (the stall guard's
        signal)."""
        if self._evict_hold > 0:
            self._evict_hold -= 1
        changed = self._fault_tick(mn)
        if changed:
            mn[:] = self._lane_min_times()  # a drain may move frontiers
        for j in range(self.lanes):
            rec = self.sched.lane_job[j]
            if rec is None:
                continue
            if mn[j] >= self._stop[j]:
                self._harvest(j, DONE)
                changed = True
            elif rec.deadline_exceeded():
                self._kill_lane(j)
                self._harvest(
                    j, TIMEOUT,
                    f"wall deadline {rec.spec.deadline_s}s exceeded",
                )
                # the lane goes straight to the admission queue below —
                # never parked until another harvest pass
                self.sched.lane_reclaims += 1
                changed = True
            elif press[j] and self._gear >= self._ladder[-1].level:
                # red zone at the top gear with no spill tier: the lane
                # cannot place one window's inflow — fail THIS job, not
                # the fleet
                self._kill_lane(j)
                self._harvest(
                    j, FAILED,
                    "pool pressure at top gear (raise "
                    "experimental.event_capacity for this sweep)",
                )
                changed = True
            if self.sched.lane_job[j] is None and self._admit_next(j):
                changed = True
        if changed:
            mn[:] = self._lane_min_times()
        self._checkpoint_tick(mn)
        return changed

    def _checkpoint_tick(self, mn: np.ndarray) -> None:
        if not (self.checkpoint_dir and self.checkpoint_every_ns):
            return
        active = [
            mn[j] for j in range(self.lanes)
            if self.sched.lane_job[j] is not None
        ]
        if not active:
            return
        frontier = int(min(min(active), max(self._stop)))
        if frontier >= self._ckpt_next_t:
            from shadow_tpu.fleet import checkpoint as fleet_ckpt

            fleet_ckpt.save_fleet(self, self.checkpoint_dir)
            self._ckpt_next_t = (
                frontier // self.checkpoint_every_ns + 1
            ) * self.checkpoint_every_ns

    # ------------------------------------------------------------------
    # drivers
    # ------------------------------------------------------------------

    def _run_to_halves(self, eff_stop: np.ndarray, wpd: int):
        """(issue_fn, fetch_fn) halves of one fused fleet dispatch.
        issue enqueues the vmapped per-lane window loops (async —
        futures); fetch performs every blocking host read. Supervised
        retries re-run both halves against the bound kernels."""

        def issue(eff_stop=eff_stop, wpd=wpd):
            if self._async:
                return self._run_to(
                    self.state, self.params,
                    jnp.asarray(self._async_runahead),
                    jnp.asarray(self._async_look),
                    jnp.asarray(self._async_spread),
                    jnp.asarray(eff_stop), wpd,
                )
            return self._run_to(
                self.state, self.params,
                jnp.asarray(self._runahead),
                jnp.asarray(eff_stop), wpd,
            )

        def fetch(out):
            extra = None
            if self._async:
                # frontier [L, S] + fleet-summed async counters + the
                # per-(lane, shard) deltas the profiling plane keeps
                stp_v = np.asarray(jax.device_get(out[7])).reshape(
                    self.lanes, -1)
                yld_v = np.asarray(jax.device_get(out[8])).reshape(
                    self.lanes, -1)
                blk_v = np.asarray(jax.device_get(out[9])).reshape(
                    self.lanes, -1)
                extra = (
                    np.asarray(jax.device_get(out[5])).reshape(
                        self.lanes, -1),
                    int(np.max(np.asarray(jax.device_get(out[6])))),
                    int(stp_v.sum()),
                    int(yld_v.sum()),
                    int(blk_v.sum()),
                    int(np.max(np.asarray(jax.device_get(out[4])))),
                    np.stack([stp_v, yld_v, blk_v]).astype(np.int64),
                )
            return (
                out[0],
                np.asarray(jax.device_get(out[1])).reshape(
                    self.lanes, -1).min(axis=1),
                np.asarray(jax.device_get(out[2])).reshape(
                    self.lanes, -1).any(axis=1),
                int(np.max(np.asarray(jax.device_get(out[3])))),
                extra,
            )

        return issue, fetch

    def run(self, windows_per_dispatch: int | None = None,
            max_dispatches: int | None = None) -> int:
        """Conservative fleet run: fused per-lane window loops in one
        vmapped dispatch, scheduler work at every handoff boundary.
        Returns the dispatch count.

        Pipelined (core/pipeline.py): dispatch N+1 is issued before
        window N's scheduler work runs — only across quiet boundaries
        (no harvest/admission/injection/deadline/checkpoint due), and
        the issue is recomputed whenever the handoff took any scheduler
        action (`changed`), shifted the gear, or mutated fleet state."""
        wpd = windows_per_dispatch or self.windows_per_dispatch
        dispatches = 0
        last_sig = None
        obs = self.obs_session
        pipe = self._pipeline()
        pending = None
        try:
            while not self.sched.all_terminal():
                if max_dispatches is not None \
                        and dispatches >= max_dispatches:
                    break
                # expired-deadline lanes free up BEFORE the dispatch — a
                # dead job never rides another dispatch holding its lane
                if self._reclaim_expired() and pipe is not None:
                    pipe.discard()
                if self.sched.all_terminal():
                    break
                eff_stop = np.minimum(
                    np.minimum(self._stop, self._fault_marks()),
                    self._backend_fault_mark(),
                )
                pending = (
                    pipe.take(self.state,
                              (eff_stop.tobytes(), wpd))
                    if pipe is not None else None
                )
                if pending is None:
                    with metrics_mod.span(obs, "dispatch", windows=wpd):
                        p = self._sv_issue(
                            "run_to", *self._run_to_halves(eff_stop, wpd)
                        )
                        self.state, mn, press, occ, ainfo = \
                            self._sv_await(p)
                else:
                    with metrics_mod.span(obs, "await", windows=wpd):
                        self.state, mn, press, occ, ainfo = \
                            self._sv_await(pending)
                    pending = None
                # two-slot pipeline: issue dispatch N+1 while the host
                # runs this boundary's scheduler work
                if pipe is not None and not self.sched.all_terminal():
                    if (not press.any() and self._handoff_quiet(mn)
                            and not self._sv_disrupted()):
                        nxt = np.minimum(
                            np.minimum(self._stop, self._fault_marks()),
                            self._backend_fault_mark(),
                        )
                        with metrics_mod.span(obs, "issue", windows=wpd):
                            pipe.put(
                                self._sv_issue(
                                    "run_to",
                                    *self._run_to_halves(nxt, wpd),
                                ),
                                self.state, (nxt.tobytes(), wpd),
                            )
                    else:
                        pipe.forced_drain()
                with metrics_mod.span(obs, "host_drain"):
                    if ainfo is not None:
                        c = self._async_counters
                        c["dispatches"] += 1
                        c["supersteps"] += ainfo[5]
                        c["shard_windows"] += ainfo[2]
                        c["yields"] += ainfo[3]
                        c["blocked_on_neighbor"] += ainfo[4]
                        self._async_spread_max = max(
                            self._async_spread_max, ainfo[1]
                        )
                        self._async_frontier = ainfo[0]
                        if len(ainfo) > 6 and ainfo[6] is not None:
                            st6 = self._async_shard_stats
                            if st6 is None or st6.shape != ainfo[6].shape:
                                st6 = np.zeros_like(ainfo[6])
                            self._async_shard_stats = st6 + ainfo[6]
                    dispatches += 1
                    if obs is not None:
                        obs.round_done(self, int(mn.min()))
                    self._backend_fault_tick(mn)
                    changed = self._handoff(mn, press)
                    if self._shifter is not None and not (
                        self.pressure is not None
                        and self.pressure.hold_gear
                    ):
                        new = self._shifter.observe(
                            self._gear, occ, press=bool(press.any())
                        )
                        if new is not None:
                            self._shift_gear(new)
                            changed = True
                    # handoff hooks: sharded ones fan out per lane
                    # across the host plane's pinned drain workers,
                    # inside this host_drain span — i.e. inside the
                    # pipeline's issue->await overlap window
                    self._run_handoff_hooks(mn)
                if pipe is not None:
                    if changed or self._sv_disrupted():
                        pipe.discard()
                    else:
                        pipe.invalidate(self.state)
                sig = (tuple(mn),
                       tuple(r.status for r in self.sched.records),
                       tuple(len(lf.pending) for lf in self._lane_faults),
                       self._gear)
                if not changed and sig == last_sig:
                    cap = self._ladder[self._gear].capacity
                    if self._pressure_stall(
                        window=int(mn.min()), occupancy=occ,
                        capacity=cap,
                    ):
                        last_sig = None  # a ladder rung reshaped the fleet
                        continue
                    raise self._pool_exhausted(
                        "fleet cannot make progress: no lane advanced and "
                        "no scheduler action fired (pool occupancy leaves "
                        "too little headroom for even one window's "
                        "emissions); raise experimental.event_capacity",
                        window=int(mn.min()), occupancy=occ,
                        capacity=cap,
                    )
                elif self.pressure is not None:
                    self.pressure.note_progress()
                last_sig = sig
        finally:
            if pipe is not None:
                pipe.close()
        return dispatches

    def _reset_done_t(self) -> None:
        d = self.state.host.done_t
        self.state = self.state.replace(
            host=self.state.host.replace(done_t=jnp.full_like(d, -1))
        )

    def _attempt_round(self, base, ws: np.ndarray, we: np.ndarray):
        """One optimistic attempt over all lanes from the snapshot
        `base`: per-lane windows [ws, we) processed to completion.
        Returns (state, mn[L], viol[L]). Global engine: one fused
        dispatch (vmapped attempt kernel). Islands: host-driven sub-steps
        (vmap-of-jobs over vmap-of-shards), mirroring the solo islands
        attempt loop — every lane gets at least one sub-step, so a lane
        parked on an exchange-deferred frontier retries its exchange (the
        solo driver's null-window stall)."""
        ws_d, we_d = jnp.asarray(ws), jnp.asarray(we)
        obs = self.obs_session
        if not self._islands:
            with metrics_mod.span(obs, "dispatch"):

                def _dispatch():
                    st, mn, viol = self._attempt(
                        base, self.params, ws_d, we_d
                    )
                    return (
                        st,
                        np.array(jax.device_get(mn), np.int64),
                        np.array(jax.device_get(viol), np.int64),
                    )

                return self._sv("attempt", _dispatch)
        st = base
        mn = ws.copy()
        viol = np.full(self.lanes, int(NEVER), np.int64)
        k = 0
        while True:
            with metrics_mod.span(obs, "dispatch"):

                def _substep(st=st, lo=jnp.asarray(np.maximum(mn, ws))):
                    s2, mn_d, viol_d = self._attempt(
                        st, self.params, lo, we_d
                    )
                    return (
                        s2,
                        np.asarray(jax.device_get(mn_d)),
                        np.asarray(jax.device_get(viol_d)),
                    )

                st, mn_d, viol_d = self._sv("attempt", _substep)
            mn = mn_d.reshape(self.lanes, -1).min(axis=1)
            viol = np.minimum(
                viol, viol_d.reshape(self.lanes, -1).min(axis=1)
            )
            k += 1
            need = (mn < we) & (viol >= int(NEVER))
            if not need.any():
                return st, mn, viol
            if k >= _MAX_SUBSTEPS:
                if (need & (mn <= ws)).any():
                    # mid-attempt: the snapshot pins the compiled shapes,
                    # so no reshaping rung is safe — typed exhaustion
                    j = int(np.argmax(need & (mn <= ws)))
                    raise self._pool_exhausted(
                        "optimistic fleet attempt cannot make progress "
                        "(pool-headroom stall); raise "
                        "experimental.event_capacity",
                        window=int(ws[j]),
                        occupancy=int(self._lane_occupancies()[j]),
                        capacity=self._ladder[self._gear].capacity,
                    )
                # genuinely enormous window: report the reached frontier;
                # the caller shrinks those lanes and retries from base
                return st, mn, viol

    def run_optimistic(
        self,
        window_factor: int = 8,
        adaptive: bool = True,
        max_rounds: int | None = None,
    ) -> tuple[int, int]:
        """Per-lane speculative windows (the Time-Warp shape of the solo
        run_optimistic, vectorized over jobs): every lane speculates its
        own [ws, ws + factor·runahead) window each round; a lane whose
        attempt reports a violation shrinks ITS window and the round
        retries from the snapshot (clean lanes recompute identical
        results — pure functions). The per-lane adaptive factor follows
        Simulation.adapt_window_factor. Returns (rounds, rollbacks)."""
        self._ensure_attempt()
        L = self.lanes
        factor = np.full(L, int(window_factor), np.int64)
        streak = np.zeros(L, np.int64)
        rounds = rollbacks = 0
        never = int(NEVER)
        self._reset_done_t()
        mn = self._lane_min_times()
        last_sig = None
        while not self.sched.all_terminal():
            if max_rounds is not None and rounds >= max_rounds:
                break
            if self._reclaim_expired():
                mn = self._lane_min_times()
                if self.sched.all_terminal():
                    break
            cons = self._runahead
            stop = self._stop
            ws = mn.copy()
            if self._islands:
                clamp = np.asarray(jax.device_get(jnp.min(
                    self.state.exch_deferred_min.reshape(L, -1), axis=-1
                )), np.int64)
                floor = np.minimum(ws + cons, clamp)
            else:
                floor = ws + cons
            we = np.minimum(
                np.maximum(np.minimum(ws + factor * cons, stop), floor),
                stop,
            )
            # finished/idle lanes attempt nothing: ws == we == frontier
            idle = mn >= stop
            we = np.where(idle, np.maximum(ws, stop), we)
            # in-transit deferred row parked AT a lane's frontier: that
            # lane gets a null-window round (we == ws) so its first
            # sub-step retries the exchange — the solo islands driver's
            # null-window stall, lane-scoped
            stalled = (~idle) & (floor <= ws)
            we = np.where(stalled, ws, we)
            base = self.state
            rb_round = np.zeros(L, np.int64)
            # reshaping pressure rungs are unsafe while `base` pins the
            # compiled shapes (core/pressure.py)
            self._pressure_reshape_ok = False
            while True:
                st, mn_a, viol = self._attempt_round(base, ws, we)
                bad = (viol < never) & ~idle
                guard = bad & (we <= floor)
                if guard.any():
                    j = int(np.argmax(guard))
                    # A floor-width window is violation-free BY
                    # CONSTRUCTION; a violation here means the
                    # conservative-width invariant itself is broken —
                    # refuse to commit (ADVICE r5 #1, fleet-scoped).
                    raise RuntimeError(
                        f"speculation violation at t={int(viol[j])} inside "
                        f"a floor-width window [{int(ws[j])}, {int(we[j])}) "
                        f"on lane {j}: the conservative-width invariant is "
                        f"broken (runahead exceeds a real path latency, or "
                        f"a handler emitted into the past); refusing to "
                        f"commit"
                    )
                incomplete = (viol >= never) & (mn_a < we) & ~idle
                if incomplete.any():
                    # sub-step ceiling hit: shrink to the reached frontier
                    we = np.where(incomplete, np.maximum(mn_a, floor), we)
                    rb_round += incomplete  # counted as shrinks
                    continue
                if not bad.any():
                    break
                rb_round += bad
                we = np.where(
                    bad, np.minimum(np.maximum(viol, floor), stop), we
                )
            self._pressure_reshape_ok = True
            rollbacks += int(rb_round.sum())
            self.state = st
            for j in np.flatnonzero(rb_round):
                self._bump_lane_win(int(j), obs_mod.WIN_ROLLBACKS,
                                    int(rb_round[j]))
                self._bump_lane_win(int(j), obs_mod.WIN_SHRINKS,
                                    int(rb_round[j]))
            self._reset_done_t()
            mn = mn_a
            rounds += 1
            if self.obs_session is not None:
                self.obs_session.round_done(self, int(mn.min()))
            self._backend_fault_tick(mn)
            if adaptive:
                for j in range(L):
                    if not idle[j]:
                        f, s = engine_mod.Simulation.adapt_window_factor(
                            int(factor[j]), int(streak[j]),
                            bool(rb_round[j]), int(window_factor),
                        )
                        factor[j], streak[j] = f, s
            before = [self.sched.lane_job[j] for j in range(L)]
            changed = self._handoff(mn, np.zeros(L, bool))
            for j in range(L):
                if self.sched.lane_job[j] is not before[j]:
                    # a fresh job entered lane j: it speculates from the
                    # full factor with a clean streak, like a solo run
                    factor[j] = int(window_factor)
                    streak[j] = 0
            if changed:
                mn = self._lane_min_times()
            sig = (tuple(mn), tuple(r.status for r in self.sched.records))
            if not changed and not (mn > ws).any() and sig == last_sig:
                cap = self._ladder[self._gear].capacity
                if self._pressure_stall(
                    window=int(mn.min()),
                    occupancy=int(self._lane_occupancies().max(initial=0)),
                    capacity=cap,
                ):
                    last_sig = None  # a ladder rung reshaped the fleet
                    continue
                raise self._pool_exhausted(
                    "optimistic fleet cannot make progress; raise "
                    "experimental.event_capacity",
                    window=int(mn.min()),
                    occupancy=int(self._lane_occupancies().max(initial=0)),
                    capacity=cap,
                )
            elif self.pressure is not None:
                self.pressure.note_progress()
            last_sig = sig
        return rounds, rollbacks

    # ------------------------------------------------------------------
    # results / telemetry
    # ------------------------------------------------------------------

    def results(self) -> list[dict]:
        """Per-job result rows (metrics schema v4 `fleet.jobs[*]`), in
        job DECLARATION order — stable across checkpoint/resume even
        though a resumed fleet's internal records list is rebuilt
        running-jobs-first (each record carries its original
        submit_idx)."""
        return [r.summary() for r in self.records()]

    def records(self) -> list[JobRecord]:
        return sorted(self.sched.records, key=lambda r: r.submit_idx)

    def fleet_stats(self) -> dict:
        spec = self._ladder[self._gear]
        st = self.sched.stats()
        st.update({
            "kernel_traces": self.kernel_traces,
            "gear_level": self._gear,
            "gear_capacity": spec.capacity,
            "gear_shifts": self.gear_shifts,
            "islands": self._islands,
        })
        return st

    def async_stats(self) -> dict[str, int] | None:
        """Fleet-summed async-sync counters (schema v9 `async.*`); None
        for barrier or non-islands fleets."""
        if not self._async:
            return None
        return dict(self._async_counters)

    def async_shard_profile(self) -> dict | None:
        """Per-shard async profile for the profiling plane (obs/prof.py
        critical-path attribution, schema v18). Lanes are folded: the
        cumulative steps/yields/blocked sum over lanes, the frontier is
        the lane-min per shard (the bound conservative sync enforces),
        and the in-edge lookahead matrix comes from lane 0 (identical
        across lanes — one topology per fleet). None for barrier fleets
        or before the first async dispatch."""
        if not self._async or self._async_shard_stats is None:
            return None
        st = self._async_shard_stats  # [3, L, S]
        prof = {
            "shards": int(st.shape[-1]),
            "lanes": self.lanes,
            "steps": [int(x) for x in st[0].sum(axis=0)],
            "yields": [int(x) for x in st[1].sum(axis=0)],
            "blocked": [int(x) for x in st[2].sum(axis=0)],
        }
        if self._async_frontier is not None:
            f = np.asarray(self._async_frontier)
            prof["frontier_ns"] = [int(x) for x in f.min(axis=0)]
        if self._look_in_cache is None:
            self._look_in_cache = [
                [int(x) for x in row] for row in self._async_look[0]
            ]
        prof["lookahead_in"] = self._look_in_cache
        return prof

    def async_gauges(self) -> dict[str, int] | None:
        if not self._async:
            return None
        g = {
            "spread_bound_ns": int(np.max(self._async_spread)),
            "frontier_spread_max_ns": int(self._async_spread_max),
        }
        if self._async_frontier is not None:
            g["frontier_min_ns"] = int(self._async_frontier.min())
            g["frontier_max_ns"] = int(self._async_frontier.max())
        return g

    def async_posture(self) -> dict:
        """Operator-facing async posture for the serve daemon's /healthz
        (docs/serving.md): the live frontier spread and WHICH (lane,
        shard) is the laggard — the hot-shard signal `shadowctl status`
        surfaces without grepping metrics JSON. {} for barrier fleets or
        before the first async dispatch."""
        if not self._async or self._async_frontier is None:
            return {}
        f = np.asarray(self._async_frontier)
        lane, shard = np.unravel_index(int(np.argmin(f)), f.shape)
        return {
            "frontier_spread_ns": int(f.max() - f.min()),
            "frontier_spread_max_ns": int(self._async_spread_max),
            "laggard_lane": int(lane),
            "laggard_shard": int(shard),
        }

    def mesh_posture(self) -> dict:
        """Operator-facing mesh posture for the serve daemon's /healthz
        and `shadowctl status` (schema v12): chips up/total, the
        partition shape, the exchange-schedule rebuild count, and —
        when an elastic runner is attached — the last relayout record.
        {} for non-islands fleets (no mesh keys on non-mesh runs)."""
        if not self._islands:
            return {}
        t = self.template
        total = int(t.num_shards) + len(getattr(t, "exclude_chips", ()))
        p = {
            "chips_up": int(t.num_shards),
            "chips_total": total,
            "shard_map": int(getattr(t, "mode", "") == "shard_map"),
            "chips_down": sorted(getattr(t, "exclude_chips", ())),
            "exchange_rebuilds": int(
                getattr(t, "_exchange_rebuilds", 0)
            ),
        }
        el = getattr(self, "elastic", None)
        if el is not None:
            p.update(el.posture())
        return p

    def balance_stats(self) -> dict[str, int] | None:
        """Fleet-side balance plane (schema v10 `balance.*`): the
        scheduler's predicted-load packing + lane-steal tallies; None
        under plain FIFO with no decisions taken (solo sweeps carry no
        balance keys)."""
        s = self.sched
        if s.packing == "fifo" and not s.pack_decisions:
            return None
        return {
            "pack_decisions": int(s.pack_decisions),
            "lane_steals": int(s.lane_steals),
        }

    def balance_gauges(self) -> dict | None:
        s = self.sched
        if s.packing == "fifo" and not s.pack_decisions:
            return None
        return {
            "packing_load": int(s.packing == "load"),
            "calibrated_rate": float(s.rate_ewma or 0.0),
        }

    def ok(self) -> bool:
        return all(r.status == DONE for r in self.sched.records)


def build_fleet(
    jobs: list[JobSpec],
    lanes: int | None = None,
    **kw,
) -> FleetSimulation:
    """Build a FleetSimulation from a validated job list (fleet/sweep.py
    expand_sweep / load_job_list output)."""
    return FleetSimulation(jobs, lanes=lanes, **kw)
