"""Host-side fleet scheduler state: job lifecycle, queue, admission.

The device side of the fleet (fleet/engine.py) is a fixed set of LANES —
slots of the vmapped window kernel. This module owns everything about the
JOBS that flow through those lanes: the FIFO queue, per-job lifecycle
records (status, wall clocks, harvested results), and the admission rule
that decides whether a queued job may enter a freed lane at the fleet's
current pool gear.

Lifecycle:  queued → running → done | failed | timeout
A job leaves `running` exactly once (harvest), and its lane is then free
for the next queued job — the compiled kernel never changes shape on a
swap, so the fleet pays XLA compilation once for the whole sweep.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Optional

from shadow_tpu.fleet.sweep import JobSpec

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
TIMEOUT = "timeout"

TERMINAL = (DONE, FAILED, TIMEOUT)


@dataclasses.dataclass
class JobRecord:
    """One job's scheduler-plane state, from spec to harvested result."""

    spec: JobSpec
    status: str = QUEUED
    lane: Optional[int] = None
    reason: str = ""  # failure/timeout detail
    # original submission position: results/manifests report in THIS
    # order even when a resumed fleet's internal records list is
    # rebuilt running-jobs-first (fleet/checkpoint.resume_fleet)
    submit_idx: int = -1
    admitted_wall: Optional[float] = None
    wall_s: float = 0.0
    # harvested at completion (device reads at the handoff boundary):
    events_committed: int = 0
    windows: int = 0
    frontier_ns: int = -1
    counters: dict = dataclasses.field(default_factory=dict)
    faults: dict = dataclasses.field(default_factory=dict)
    # determinism-audit sub-object (schema v5): at least {"chain": int},
    # the job's digest-chain value — equal to the same scenario run solo
    audit: dict = dataclasses.field(default_factory=dict)
    # optional deep captures for tests / downstream analysis
    subs: Any = None
    obs: Optional[dict] = None
    checkpoint: Optional[str] = None

    @property
    def name(self) -> str:
        return self.spec.name

    def deadline_exceeded(self) -> bool:
        d = self.spec.deadline_s
        return (
            d is not None
            and self.admitted_wall is not None
            and time.monotonic() - self.admitted_wall > d
        )

    def summary(self) -> dict:
        """The metrics-schema-v4 `fleet.jobs[*]` row (and the manifest
        entry a fleet checkpoint records)."""
        return {
            "name": self.name,
            "status": self.status,
            "lane": self.lane,
            "reason": self.reason,
            "events_committed": int(self.events_committed),
            "windows": int(self.windows),
            "frontier_ns": int(self.frontier_ns),
            "wall_s": round(float(self.wall_s), 4),
            "counters": {k: int(v) for k, v in self.counters.items()},
            "faults": {k: int(v) for k, v in self.faults.items()},
            "audit": {k: int(v) for k, v in self.audit.items()},
        }


class FleetScheduler:
    """FIFO job queue + admission control over a fixed lane count.

    Admission is keyed to pool occupancy and the gear ladder
    (core/gearbox.py): a job is admitted into a freed lane only when its
    initial resident rows fit under the CURRENT gear's rebalance fill
    mark; otherwise the scheduler demands an upshift first (`admit`
    returns the gear level the fleet must shift to). The fleet shares one
    compiled pool shape across lanes, so gear decisions are fleet-global:
    the decision signal is the max lane occupancy, exactly the fullest-
    shard rule the islands runner uses.
    """

    def __init__(self, jobs: list[JobSpec], lanes: int):
        if lanes < 1:
            raise ValueError("fleet needs at least one lane")
        self.records = [
            JobRecord(spec=j, submit_idx=i) for i, j in enumerate(jobs)
        ]
        self._by_name = {r.name: r for r in self.records}
        if len(self._by_name) != len(self.records):
            raise ValueError("duplicate job names in fleet")
        self.lanes = lanes
        self.lane_job: list[Optional[JobRecord]] = [None] * lanes
        self._next = 0  # queue cursor (records are admitted in order)
        self.lane_swaps = 0
        self.admission_upshifts = 0
        # resilience plane (core/supervisor.py / ISSUE 6): lanes freed at
        # the wall-clock deadline and handed straight to admission, and
        # in-flight jobs returned to the queue by a backend drain
        self.lane_reclaims = 0
        self.jobs_requeued = 0
        # self-balancing plane (ISSUE 11, metrics schema v10 balance.*):
        # "load" packing hands a freed lane the HEAVIEST pending job by
        # predicted load (LPT — lanes level out instead of draining
        # FIFO), so a lane that finishes early effectively steals the
        # biggest remaining job ahead of its queue position. The serve
        # daemon enables it; solo sweeps keep strict FIFO.
        self.packing = "fifo"  # "fifo" | "load"
        self.pack_decisions = 0
        self.lane_steals = 0
        self._cost_cache: dict[str, float] = {}
        # PHOLD-calibrated rate: EWMA of (events committed / predicted
        # load units) over finished jobs — turns the static config proxy
        # into an events estimate for telemetry and Retry-After hints
        self.rate_ewma: Optional[float] = None

    # -- queue --

    def submit(self, spec: JobSpec) -> JobRecord:
        """Append a new job to the queue tail (daemon-plane dynamic
        submission, shadow_tpu/serve). Submission order IS the records
        order, so FIFO admission needs no extra bookkeeping."""
        if spec.name in self._by_name:
            raise ValueError(f"duplicate job name {spec.name!r}")
        rec = JobRecord(
            spec=spec,
            submit_idx=1 + max(r.submit_idx for r in self.records),
        )
        self.records.append(rec)
        self._by_name[rec.name] = rec
        return rec

    def pending(self) -> list[JobRecord]:
        """QUEUED records in admission (= submission) order. Requeued
        jobs appear at their ORIGINAL position, never at the tail: the
        records list is submission-ordered and the cursor rewinds on
        requeue, so this scan is the FIFO truth."""
        return [r for r in self.records[self._next:] if r.status == QUEUED]

    def peek(self) -> Optional[JobRecord]:
        while self._next < len(self.records):
            r = self.records[self._next]
            if r.status == QUEUED:
                return r
            self._next += 1
        return None

    # -- predicted-load packing (self-balancing plane, ISSUE 11) --

    def predicted_load(self, record: JobRecord) -> float:
        """Static per-job load proxy from the job's config — host count x
        message load x simulated seconds (the PHOLD event-population
        model; `estimate_hbm_bytes`-style preflight, but for event WORK
        rather than memory). Cached per job name; multiplied by the
        calibrated rate EWMA when one exists. Coarse on purpose: packing
        only needs a total order, and a bad estimate costs placement
        quality, never correctness."""
        c = self._cost_cache.get(record.name)
        if c is None:
            try:
                from shadow_tpu.core.config import load_config

                cfg = load_config(record.spec.config)
                H = sum(
                    int(getattr(h, "quantity", 1)) for h in cfg.hosts
                )
                msgload = 1
                for h in cfg.hosts:
                    if h.app_model == "phold":
                        msgload = int(h.app_options.get("msgload", 1))
                        break
                c = float(H * max(1, msgload)) * (
                    cfg.general.stop_time / 1e9
                )
            except (ValueError, OSError):
                c = 1.0  # unparseable config fails at admission anyway
            self._cost_cache[record.name] = c
        return c * (self.rate_ewma if self.rate_ewma else 1.0)

    def calibrate(self, record: JobRecord) -> None:
        """Fold one finished job's observed events into the rate EWMA
        (called by the fleet after harvest, when the counters are in)."""
        base = self._cost_cache.get(record.name)
        if not base or record.events_committed <= 0:
            return
        rate = record.events_committed / base
        self.rate_ewma = (
            rate if self.rate_ewma is None
            else 0.7 * self.rate_ewma + 0.3 * rate
        )

    def pick(self, lane: int) -> Optional[JobRecord]:
        """The job a freed lane should admit: the FIFO head by default;
        under "load" packing, the heaviest pending job by predicted load
        (LPT onto the lane that freed first — lanes level out, and the
        sweep's makespan stops being hostage to a heavy tail job parked
        behind light ones). Taking a job from deeper in the queue is the
        lane-level steal (`lane_steals`); deterministic tiebreak by
        submission order."""
        head = self.peek()
        if head is None or self.packing != "load":
            return head
        pend = self.pending()
        if len(pend) <= 1:
            return head
        best = max(
            pend, key=lambda r: (self.predicted_load(r), -r.submit_idx)
        )
        self.pack_decisions += 1
        if best is not head:
            self.lane_steals += 1
        return best

    # -- admission --

    @staticmethod
    def admission_gear(ladder, initial_rows: int, gear: int) -> int:
        """The gear the fleet must be in before a job with
        `initial_rows` resident events may enter a lane: the smallest
        ladder level whose fill mark covers the rows, never below the
        current gear (other lanes' live occupancy holds the floor)."""
        for spec in ladder:
            if spec.level >= gear and initial_rows <= spec.fill:
                return spec.level
        return ladder[-1].level

    def admit(self, lane: int, record: JobRecord) -> None:
        if self.lane_job[lane] is not None:
            raise RuntimeError(f"lane {lane} is occupied")
        if record.status != QUEUED:
            raise RuntimeError(f"job {record.name} is {record.status}")
        record.status = RUNNING
        record.lane = lane
        record.admitted_wall = time.monotonic()
        self.lane_job[lane] = record
        if self._next < len(self.records) and \
                self.records[self._next] is record:
            self._next += 1

    def release(self, lane: int, status: str, reason: str = "") -> JobRecord:
        record = self.lane_job[lane]
        if record is None:
            raise RuntimeError(f"lane {lane} is already free")
        record.status = status
        record.reason = reason
        record.wall_s = time.monotonic() - (
            record.admitted_wall or time.monotonic()
        )
        self.lane_job[lane] = None
        return record

    def requeue(self, lane: int, reason: str = "") -> JobRecord:
        """Return a RUNNING job to the queue (backend drain: the lane's
        progress survives in the drain checkpoint's per-job slice, so the
        resumed sweep restores it rather than re-running from scratch).
        The queue cursor rewinds to the job's ORIGINAL submission index
        so it re-admits in FIFO order, ahead of every later submission —
        never at the queue tail. The rewind is identity-based: JobRecord
        is a value-comparing dataclass, and `list.index` under value
        equality could match a different record (or trip over harvested
        array payloads), silently mis-positioning the cursor."""
        record = self.lane_job[lane]
        if record is None:
            raise RuntimeError(f"lane {lane} is already free")
        record.status = QUEUED
        record.reason = reason
        record.lane = None
        record.admitted_wall = None
        self.lane_job[lane] = None
        self.jobs_requeued += 1
        idx = next(
            i for i, r in enumerate(self.records) if r is record
        )
        self._next = min(self._next, idx)
        return record

    # -- introspection --

    def steal_export(self) -> dict:
        """The lane-steal posture lifted one level up (the serve
        federation's cross-daemon work stealing, serve/federation.py):
        in-fleet steal/pack tallies plus the predicted load still queued
        — the router compares queued load ACROSS daemons exactly the way
        `pick` compares jobs across lanes, so a peer whose queue holds
        heavy tail jobs is stolen from before a peer with many light
        ones."""
        return {
            "lane_steals": int(self.lane_steals),
            "pack_decisions": int(self.pack_decisions),
            "queued_predicted_load": float(
                sum(self.predicted_load(r) for r in self.pending())
            ),
        }

    def running(self) -> list[JobRecord]:
        return [r for r in self.lane_job if r is not None]

    def all_terminal(self) -> bool:
        return all(r.status in TERMINAL for r in self.records)

    def stats(self) -> dict:
        by = {s: 0 for s in (QUEUED, RUNNING, DONE, FAILED, TIMEOUT)}
        for r in self.records:
            by[r.status] += 1
        return {
            "jobs_total": len(self.records),
            "jobs_done": by[DONE],
            "jobs_failed": by[FAILED],
            "jobs_timeout": by[TIMEOUT],
            "jobs_queued": by[QUEUED],
            "jobs_running": by[RUNNING],
            "lanes": self.lanes,
            "lane_swaps": self.lane_swaps,
            "admission_upshifts": self.admission_upshifts,
            "lane_reclaims": self.lane_reclaims,
            "jobs_requeued": self.jobs_requeued,
            "pack_decisions": self.pack_decisions,
            "lane_steals": self.lane_steals,
        }
