"""The `sweep` CLI subcommand: ``python -m shadow_tpu sweep sweep.yaml``.

Expands a `sweep:` config matrix (or an explicit ``--fleet jobs.yaml`` job
list) into a validated job queue and runs it as ONE batched device fleet
(shadow_tpu/fleet). Prints one JSON result line per job as it completes
plus a final summary line; exit status is nonzero when any job failed or
timed out, mirroring the solo CLI's plugin-error accounting.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="shadow_tpu sweep",
        description="batched multi-experiment execution (scenario fleet)",
    )
    p.add_argument(
        "config", nargs="?",
        help="sweep YAML: a base experiment config plus a `sweep:` matrix "
             "section (docs/fleet.md)",
    )
    p.add_argument(
        "--fleet", metavar="JOBS_YAML",
        help="explicit job list (tools/expand_sweep.py output) instead of "
             "a sweep config",
    )
    p.add_argument(
        "--lanes", type=int, metavar="N",
        help="device lanes (parallel jobs resident on the kernel's batch "
             "axis); default fleet.lanes, else one lane per job",
    )
    p.add_argument(
        "--sync", choices=("conservative", "optimistic"),
        help="window synchronization mode (default fleet.sync)",
    )
    p.add_argument(
        "--deadline-s", type=float, metavar="SECS",
        help="wall-clock budget per job once admitted (default "
             "fleet.deadline_s)",
    )
    p.add_argument(
        "--list", action="store_true",
        help="expand and validate the job list, print it, and exit",
    )
    p.add_argument(
        "--metrics-out", metavar="PATH",
        help="write the fleet metrics document (schema v7: fleet.jobs[*] "
             "per-job rows incl. audit.chain digests) as JSON",
    )
    p.add_argument(
        "--trace-out", metavar="PATH",
        help="write driver-phase spans + per-lane job lifecycles as "
             "Chrome trace-event JSON (each lane gets its own named tid; "
             "load in Perfetto or summarize with tools/trace_summary.py)",
    )
    p.add_argument(
        "--checkpoint-every", metavar="TIME",
        help="write a fleet checkpoint (per-job slices + manifest) every "
             "TIME of fleet frontier progress into --checkpoint-dir",
    )
    p.add_argument(
        "--checkpoint-dir", metavar="DIR",
        help="fleet checkpoint directory (default fleet.checkpoint_dir)",
    )
    p.add_argument(
        "--resume", metavar="DIR",
        help="resume a partially-finished fleet from its checkpoint "
             "directory (completed jobs keep their results; running "
             "lanes restore their slices)",
    )
    p.add_argument(
        "--on-backend-loss", choices=("wait", "cpu", "abort"),
        help="survive accelerator loss mid-sweep (core/supervisor.py): "
             "drain every running lane to the fleet checkpoint, pause "
             "admission, then re-probe until the backend returns (wait), "
             "fail over to the CPU backend (cpu), or abort after the "
             "drain (abort; requeued lanes finish via --resume)",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    from shadow_tpu.core import units
    from shadow_tpu.core.checkpoint import CheckpointError
    from shadow_tpu.core.config import ConfigError, FleetOptions, load_config
    from shadow_tpu.core.supervisor import BackendLost as BackendLostError
    from shadow_tpu.fleet import (
        FleetError,
        SweepError,
        build_fleet,
        load_job_list,
        load_sweep,
        resume_fleet,
    )

    fopts = FleetOptions()
    jobs = []
    try:
        if args.resume is None:
            if bool(args.config) == bool(args.fleet):
                print(
                    "error: pass exactly one of a sweep config or "
                    "--fleet jobs.yaml (or --resume DIR)",
                    file=sys.stderr,
                )
                return 2
            if args.fleet:
                jobs = load_job_list(args.fleet)
            else:
                jobs, sweep_opts = load_sweep(args.config)
                # fleet options ride the base config's `fleet:` section;
                # sweep.lanes is a convenience alias that wins over it
                fopts = load_config(jobs[0].config).fleet
                if sweep_opts.get("lanes") is not None:
                    fopts.lanes = int(sweep_opts["lanes"])
        if args.deadline_s is not None:
            for j in jobs:
                j.deadline_s = args.deadline_s
        elif fopts.deadline_s is not None:
            for j in jobs:
                if j.deadline_s is None:
                    j.deadline_s = fopts.deadline_s
    except (SweepError, ConfigError, FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.list:
        for j in jobs:
            print(json.dumps(j.to_json()))
        print(f"# {len(jobs)} job(s), validated", file=sys.stderr)
        return 0

    lanes = args.lanes if args.lanes is not None else (fopts.lanes or None)
    sync = args.sync or fopts.sync
    ckpt_dir = args.checkpoint_dir or fopts.checkpoint_dir
    ckpt_every = (
        units.parse_time_ns(args.checkpoint_every)
        if args.checkpoint_every else fopts.checkpoint_every
    )
    if ckpt_every and not ckpt_dir:
        print(
            "error: --checkpoint-every needs --checkpoint-dir "
            "(or fleet.checkpoint_dir)", file=sys.stderr,
        )
        return 2

    t0 = time.monotonic()
    session = None
    try:
        if args.resume:
            fleet = resume_fleet(
                args.resume, lanes=lanes,
                checkpoint_every_ns=ckpt_every or 0,
            )
        else:
            fleet = build_fleet(
                jobs, lanes=lanes,
                windows_per_dispatch=fopts.windows_per_dispatch,
                checkpoint_dir=ckpt_dir,
                checkpoint_every_ns=ckpt_every or 0,
            )
        if args.metrics_out or args.trace_out:
            from shadow_tpu.obs import metrics as obs_metrics
            from shadow_tpu.obs import trace as obs_trace

            session = obs_metrics.ObsSession(
                tracer=obs_trace.ChromeTracer("shadow_tpu sweep")
                if args.trace_out else None
            )
            fleet.attach_obs(session)
        if args.on_backend_loss:
            from shadow_tpu.core.supervisor import BackendSupervisor

            sup = BackendSupervisor(
                args.on_backend_loss, drain_dir=ckpt_dir
            )
            fleet.attach_supervisor(sup)
        if sync == "optimistic":
            fleet.run_optimistic()
        else:
            fleet.run()
    except (FleetError, SweepError, ConfigError, CheckpointError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except BackendLostError as e:
        print(f"error: {e}", file=sys.stderr)
        return 3
    wall = time.monotonic() - t0

    failed = 0
    for row in fleet.results():
        print(json.dumps(row), flush=True)
        if row["status"] != "done":
            failed += 1
    stats = fleet.fleet_stats()
    stats["wall_s"] = round(wall, 3)
    print(json.dumps({"fleet": stats}), flush=True)
    if ckpt_dir:
        from shadow_tpu.fleet import save_fleet

        save_fleet(fleet, ckpt_dir)
    if args.metrics_out:
        from shadow_tpu.obs import metrics as obs_metrics

        # the session's registry (when attached) already carries the
        # dispatch wall histograms; the fleet section rides on top
        reg = (
            session.metrics if session is not None
            else obs_metrics.MetricsRegistry()
        )
        obs_metrics.snapshot_fleet(fleet, reg)
        reg.dump(args.metrics_out, meta={
            "jobs": stats["jobs_total"], "wall_s": stats["wall_s"],
        })
        print(f"metrics written to {args.metrics_out}", file=sys.stderr)
    if args.trace_out and session is not None and session.tracer is not None:
        session.tracer.write(args.trace_out)
        print(f"trace written to {args.trace_out}", file=sys.stderr)
    if failed:
        print(f"{failed} job(s) did not complete", file=sys.stderr)
        return 1
    return 0
