"""The simulator CLI: ``python -m shadow_tpu config.yaml``.

The reference's entry path (src/main/main.c:10 → core/main.c:121
main_runShadow) parses CLI + YAML, merges CLI overrides over the file config,
sets up the data directory, and runs the controller. This module is that
surface for both execution planes:

- hosts with ``processes``  → the managed-process plane (real binaries under
  the LD_PRELOAD shim, serviced by ProcessDriver against the topology);
- hosts with ``app_model``  → the device plane (workload models compiled into
  the batched TPU window kernel).

Exit status is nonzero when any managed process fails, like the reference's
plugin-error accounting (manager.c:255-257,579-584).
"""

from __future__ import annotations

import argparse
import pathlib
import shutil
import sys
import time


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="shadow_tpu",
        description="TPU-native discrete-event network simulator",
    )
    p.add_argument("config", help="YAML experiment configuration file")
    p.add_argument(
        "--show-config", action="store_true",
        help="print the merged configuration and exit (core/main.c:207-213)",
    )
    p.add_argument("--seed", type=int, help="override general.seed")
    p.add_argument(
        "--stop-time", help="override general.stop_time (e.g. '10 s')"
    )
    p.add_argument(
        "--data-directory", "-d",
        help="override general.data_directory (default shadow.data)",
    )
    p.add_argument(
        "--template-directory", "-e",
        help="override general.template_directory: copied to the data "
             "directory before the simulation runs",
    )
    p.add_argument("--log-level", "-l", help="override general.log_level")
    p.add_argument(
        "--parallelism", "-p", type=int, help="override general.parallelism"
    )
    p.add_argument(
        "--progress", action="store_true", help="log round progress"
    )
    p.add_argument(
        "--metrics-out", metavar="PATH",
        help="write the run's metrics registry (device counters, wall-time "
             "histograms, virtual-time roughness) as versioned JSON "
             "(docs/observability.md); device plane only",
    )
    p.add_argument(
        "--trace-out", metavar="PATH",
        help="write driver-phase spans as Chrome trace-event JSON "
             "(load in Perfetto, or summarize with tools/trace_summary.py); "
             "device plane only",
    )
    p.add_argument(
        "--profile-out", metavar="PATH",
        help="write the profiling plane's time-series + histogram doc "
             "(fixed ring of per-handoff interval deltas, log-bucketed "
             "latency histograms, per-shard critical-path counters; "
             "obs/prof.py): implies experimental.profiler; analyze with "
             "tools/critical_path.py; device plane only",
    )
    p.add_argument(
        "--digest-out", metavar="PATH",
        help="write the determinism-audit digest document (per-handoff "
             "chain records + final per-host sub-chains, obs/audit.py); "
             "compare two runs with tools/diff_digest.py; device plane "
             "only",
    )
    p.add_argument(
        "--flight-out", metavar="PATH",
        help="spool the flight-recorder ring (last R committed events per "
             "host; requires experimental.flight_recorder) to a binary "
             "file at every handoff boundary; convert with "
             "tools/flight_to_trace.py; device plane only",
    )
    p.add_argument(
        "--pool-gears", type=int, metavar="N",
        help="override experimental.pool_gears: compile the window kernel "
             "at N pool-capacity tiers (C/4, C/2, C for 3) and shift to "
             "the smallest gear covering live occupancy at each dispatch "
             "boundary (core/gearbox.py); 1 = single fixed-capacity kernel",
    )
    p.add_argument(
        "--fault-plan", metavar="PATH",
        help="fault-plan JSON (docs/fault_tolerance.md): virtual-time-"
             "keyed injections (kill/wedge a managed process, refuse an "
             "IPC reply, kill a device host, corrupt a checkpoint, force "
             "a spill) executed deterministically — merged with the "
             "config's faults.inject list",
    )
    p.add_argument(
        "--on-backend-loss", choices=("wait", "cpu", "abort", "relayout"),
        help="override faults.on_backend_loss: survive accelerator loss "
             "mid-run by draining the committed frontier to a crash-"
             "consistent checkpoint and then either re-probing until the "
             "backend returns (wait, hot resume), failing over to the "
             "CPU backend (cpu, upshifting back on recovery), "
             "aborting after the drain (abort; finish with --resume), or "
             "— on a multi-chip mesh with chip-scoped loss — raising "
             "ChipLost for an elastic relayout onto the surviving chips "
             "(relayout; parallel/elastic.py drives the full "
             "shrink/re-expand loop; a bare CLI run exits resumable "
             "like abort); device plane only "
             "(docs/fault_tolerance.md §Backend loss, §7)",
    )
    p.add_argument(
        "--on-proc-failure", choices=("abort", "quarantine"),
        help="override faults.on_proc_failure: what the supervisor does "
             "when a managed process wedges — abort the run, or "
             "quarantine the simulated host (mark it dead, drain its "
             "events) and keep going",
    )
    p.add_argument(
        "--checkpoint-every", metavar="TIME",
        help="write a crash-consistent device-state checkpoint (atomic "
             "tmp+fsync+rename, digest-verified) every TIME of sim time "
             "at handoff boundaries, into --checkpoint-dir with a small "
             "retention ring; device plane only",
    )
    p.add_argument(
        "--checkpoint-dir", metavar="DIR",
        help="checkpoint ring directory (default: <data-dir>/checkpoints)",
    )
    p.add_argument(
        "--checkpoint-retain", type=int, default=3, metavar="N",
        help="ring size: keep the newest N checkpoints (default 3)",
    )
    p.add_argument(
        "--resume", metavar="DIR",
        help="restore the newest checkpoint in DIR that passes integrity "
             "validation (falling back past corrupt entries) before "
             "running; the config must match the checkpointed build",
    )
    return p


def _apply_overrides(cfg, args) -> None:
    """CLI flags override file values field-wise (configuration.rs:92-117)."""
    from shadow_tpu.core import units

    if args.seed is not None:
        cfg.general.seed = args.seed
    if args.stop_time is not None:
        cfg.general.stop_time = units.parse_time_ns(args.stop_time)
    if args.data_directory is not None:
        cfg.general.data_directory = args.data_directory
    if args.template_directory is not None:
        cfg.general.template_directory = args.template_directory
    if args.log_level is not None:
        cfg.general.log_level = args.log_level
    if args.parallelism is not None:
        cfg.general.parallelism = args.parallelism
    if args.progress:
        cfg.general.progress = True
    if args.pool_gears is not None:
        if args.pool_gears < 1:
            raise ValueError("--pool-gears must be >= 1")
        cfg.experimental.pool_gears = args.pool_gears
    if args.fault_plan is not None:
        cfg.faults.plan = args.fault_plan
    if args.on_proc_failure is not None:
        cfg.faults.on_proc_failure = args.on_proc_failure
    if args.on_backend_loss is not None:
        cfg.faults.on_backend_loss = args.on_backend_loss


def _dump_config(cfg) -> str:
    import dataclasses

    import yaml

    def clean(x):
        # asdict() already recurses through nested dataclasses/dicts/lists
        return dataclasses.asdict(x) if dataclasses.is_dataclass(x) else x

    return yaml.safe_dump(
        {
            "general": clean(cfg.general),
            "network": clean(cfg.network),
            "experimental": clean(cfg.experimental),
            "faults": clean(cfg.faults),
            "hosts": {h.name: clean(h) for h in cfg.hosts},
        },
        sort_keys=False,
    )


def _prepare_data_dir(cfg, resuming: bool = False) -> pathlib.Path:
    """Create the data directory; refuse to clobber an existing one, exactly
    like the reference (manager.c:177-190 errors out if the path exists).
    A --resume re-launch is the exception: the crashed run's directory (and
    its checkpoint ring) is precisely what we are coming back for."""
    data_dir = pathlib.Path(cfg.general.data_directory)
    if data_dir.exists():
        if resuming:
            return data_dir
        raise SystemExit(
            f"error: data directory '{data_dir}' already exists; remove it "
            f"or pass --data-directory"
        )
    if cfg.general.template_directory:
        template = pathlib.Path(cfg.general.template_directory)
        if not template.is_dir():
            raise SystemExit(
                f"error: template directory '{template}' does not exist"
            )
        shutil.copytree(template, data_dir)
    else:
        data_dir.mkdir(parents=True)
    return data_dir


def _run_process_plane(cfg, driver, progress: bool) -> int:
    from shadow_tpu.utils import log

    t0 = time.monotonic()
    if progress:
        driver.heartbeat_interval = cfg.general.heartbeat_interval

        def beat(d):
            c = d.counters
            print(
                f"heartbeat: sim {d.now / 1e9:.3f}s, "
                f"{c['syscalls']} syscalls, {c['packets_sent']} packets, "
                f"wall {time.monotonic() - t0:.1f}s",
                flush=True,
            )
            # per-host tracker heartbeat (tracker.c:128-143 analog)
            for name, t in d.host_trackers().items():
                log.logger.debug(
                    "tracker: tx %d pkts / %d B, rx %d pkts / %d B, "
                    "%d dropped",
                    t["tx_packets"], t["tx_bytes"],
                    t["rx_packets"], t["rx_bytes"], t["dropped_packets"],
                    host=name,
                )

        driver.heartbeat_fn = beat
    driver.run()
    wall = time.monotonic() - t0
    errors = 0
    for p in driver.procs:
        if p.stopped_by_sim:
            continue  # stopped at its stop_time, not an app failure
        if p.faulted:
            continue  # killed/quarantined by the fault plane, not the app
        if p.exit_code not in (0, None):
            errors += 1
            print(
                f"process {p.name} exited with {p.exit_code}",
                file=sys.stderr,
            )
    fstats = driver.fault_stats()
    if any(fstats.values()):
        print(
            "fault plane: " + ", ".join(
                f"{k}={v}" for k, v in sorted(fstats.items()) if v
            ),
            file=sys.stderr,
        )
    c = driver.counters
    print(
        f"done: {len(driver.hosts)} hosts, {len(driver.procs)} processes, "
        f"{c['syscalls']} syscalls, {c['packets_sent']} packets "
        f"({c['packets_dropped']} dropped), sim {driver.now / 1e9:.3f}s "
        f"in wall {wall:.3f}s"
    )
    if errors:
        print(f"{errors} managed process(es) failed", file=sys.stderr)
        return 1
    return 0


def _run_device_plane(
    cfg, sim, progress: bool,
    metrics_out: str | None = None, trace_out: str | None = None,
    checkpoint_every: str | None = None, checkpoint_dir: str | None = None,
    checkpoint_retain: int = 3, resume: str | None = None,
    data_dir=None, digest_out: str | None = None,
    flight_out: str | None = None, profile_out: str | None = None,
) -> int:
    session = None
    profiling = bool(profile_out) or cfg.experimental.profiler
    if metrics_out or trace_out or profiling:
        from shadow_tpu.obs import metrics as obs_metrics
        from shadow_tpu.obs import trace as obs_trace

        prof = None
        if profiling:
            from shadow_tpu.obs import prof as obs_prof

            prof = obs_prof.ProfRecorder(cfg.experimental.profiler_ring)
        session = obs_metrics.ObsSession(
            tracer=obs_trace.ChromeTracer() if trace_out else None,
            prof=prof,
        )
        sim.obs_session = session
    if digest_out:
        try:
            sim.attach_audit(meta={
                "hosts": sim.num_hosts,
                "stop_time_ns": sim.stop_time,
                "seed": cfg.general.seed,
            })
        except ValueError as e:
            print(f"error: --digest-out: {e}", file=sys.stderr)
            return 2
    if flight_out:
        try:
            sim.attach_flight_spool(flight_out)
        except ValueError as e:
            print(f"error: --flight-out: {e}", file=sys.stderr)
            return 2
    faults = cfg.faults.load_faults()
    if faults:
        sim.attach_faults(faults)
    if cfg.faults.on_backend_loss is not None:
        # backend supervision (core/supervisor.py): drain to a checkpoint
        # on accelerator loss, then recover per policy. The drain target
        # defaults into the data directory so a loss is survivable even
        # without --checkpoint-every.
        from shadow_tpu.core.supervisor import BackendSupervisor

        drain_dir = checkpoint_dir or str(
            pathlib.Path(data_dir or cfg.general.data_directory)
            / "checkpoints"
        )
        sup = sim.supervisor
        if sup is None:
            sup = BackendSupervisor(cfg.faults.on_backend_loss)
            sim.attach_supervisor(sup)
        else:  # auto-attached by attach_faults (backend ops in the plan)
            sup.policy = cfg.faults.on_backend_loss
        if sup.drain_dir is None:
            sup.drain_dir = drain_dir
    if resume:
        from shadow_tpu.core.checkpoint import CheckpointError

        try:
            info = sim.resume_from(resume)
        except CheckpointError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        note = (
            f" ({info['fallbacks']} corrupt checkpoint(s) skipped)"
            if info["fallbacks"] else ""
        )
        print(
            f"resumed from {info['path']} at sim "
            f"{info['sim_ns'] / 1e9:.3f}s{note}",
            file=sys.stderr,
        )
    if checkpoint_every:
        from shadow_tpu.core import units

        ckpt_dir = checkpoint_dir or str(
            pathlib.Path(data_dir or cfg.general.data_directory)
            / "checkpoints"
        )
        sim.configure_auto_checkpoint(
            ckpt_dir, units.parse_time_ns(checkpoint_every),
            checkpoint_retain,
        )
    t0 = time.monotonic()
    from shadow_tpu.core.supervisor import BackendLost

    try:
        if progress:
            stop = sim.stop_time
            hb = max(cfg.general.heartbeat_interval, sim.runahead)
            next_hb = hb
            while True:
                # run() already synchronized at its final handoff (the
                # committed-frontier fetch); a block_until_ready here
                # would re-serialize the pipelined dispatch loop for
                # nothing (core/pipeline.py)
                sim.run(until=next_hb)
                now = min(next_hb, stop)
                c = sim.counters()
                print(
                    f"heartbeat: sim {now / 1e9:.3f}s / {stop / 1e9:.3f}s, "
                    f"{c['events_committed']} events committed, "
                    f"wall {time.monotonic() - t0:.1f}s",
                    flush=True,
                )
                if now >= stop:
                    break
                next_hb += hb
        else:
            sim.run()
    except BackendLost as e:
        # the supervisor already drained to a checkpoint (when a drain
        # directory was available) — this run is resumable, not lost
        print(f"error: {e}", file=sys.stderr)
        return 3
    wall = time.monotonic() - t0
    c = sim.counters()
    print(
        f"done: {sim.num_hosts} hosts, {c['events_committed']} events, "
        f"sim {sim.stop_time / 1e9:.3f}s in wall {wall:.3f}s"
    )
    dropped = c.get("pool_overflow_dropped", 0)
    overflow_advice = None
    if dropped:
        # actionable, not just a counter (docs/fault_tolerance.md §5):
        # name the capacity/gearing that would have absorbed the overflow
        from shadow_tpu.core import pressure as pressure_mod

        hint, overflow_advice = pressure_mod.overflow_advice(sim, dropped)
        print(
            f"warning: {dropped} events dropped on pool overflow — "
            f"{hint}",
            file=sys.stderr,
        )
    fstats = sim.fault_stats()
    if any(fstats.values()):
        print(
            "fault plane: " + ", ".join(
                f"{k}={v}" for k, v in sorted(fstats.items()) if v
            ),
            file=sys.stderr,
        )
    if session is not None:
        session.finalize(sim)
        if overflow_advice is not None:
            # reflect the sizing advice in the metrics doc (schema v8
            # pressure.* gauges, docs/observability.md)
            for k, v in overflow_advice.items():
                session.metrics.gauge_set(f"pressure.{k}", int(v))
        meta = {
            "hosts": sim.num_hosts,
            "stop_time_ns": sim.stop_time,
            "seed": cfg.general.seed,
            "wall_s": round(wall, 3),
        }
        if metrics_out:
            session.metrics.dump(metrics_out, meta=meta)
            print(f"metrics written to {metrics_out}", file=sys.stderr)
        if trace_out:
            session.tracer.write(trace_out)
            print(f"trace written to {trace_out}", file=sys.stderr)
        if session.prof is not None:
            from shadow_tpu.obs import metrics as obs_metrics

            ppath = profile_out or str(
                pathlib.Path(data_dir or cfg.general.data_directory)
                / "shadow.profile.json"
            )
            obs_metrics.dump_json_atomic(
                ppath, session.prof.to_doc(meta=meta)
            )
            print(
                f"profile written to {ppath} "
                f"({session.prof.recorded} intervals, "
                f"{session.prof.dropped} dropped)",
                file=sys.stderr,
            )
    if sim.flight_spool is not None:
        # final flush at the run's end frontier, then close the spool
        sim.flight_spool.flush(sim, sim.stop_time)
        sim.flight_spool.close()
        st = sim.flight_spool.stats()
        print(
            f"flight spool written to {flight_out} "
            f"({st['records_written']} records, {st['frames']} frames)",
            file=sys.stderr,
        )
    if digest_out:
        doc = sim.write_digest(digest_out)
        print(
            f"digest written to {digest_out} "
            f"(chain {doc['final']['chain']:#018x}, "
            f"{len(doc['records'])} records)",
            file=sys.stderr,
        )
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "sweep":
        # batched multi-experiment execution (shadow_tpu/fleet): expand a
        # `sweep:` config matrix into a job queue and run it as ONE
        # vmapped device fleet — `python -m shadow_tpu sweep --help`
        from shadow_tpu.fleet.cli import main as sweep_main

        return sweep_main(argv[1:])
    if argv and argv[0] == "serve":
        # crash-safe sim-as-a-service daemon (shadow_tpu/serve): journaled
        # sweep queue + AOT kernel cache + graceful drain; operators talk
        # to it with tools/shadowctl.py — `python -m shadow_tpu serve -h`
        from shadow_tpu.serve.cli import main as serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "route":
        # federation router (shadow_tpu/serve/router): place sweeps
        # across N serve daemons, probe their health, replay a lost
        # peer's journal onto survivors — `python -m shadow_tpu route -h`
        from shadow_tpu.serve.router import main as route_main

        return route_main(argv[1:])
    args = _build_parser().parse_args(argv)
    from shadow_tpu.core.config import ConfigError, load_config

    try:
        cfg = load_config(args.config)
        _apply_overrides(cfg, args)
        from shadow_tpu.utils import log

        log.logger.set_level(cfg.general.log_level)
    except (ConfigError, FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.show_config:
        print(_dump_config(cfg), end="")
        return 0

    if cfg.sweep_raw is not None:
        print(
            "error: this file carries a `sweep:` matrix (a multi-"
            "experiment fleet); run it with `python -m shadow_tpu sweep "
            f"{args.config}` instead of the single-run CLI",
            file=sys.stderr,
        )
        return 2

    has_procs = any(h.processes for h in cfg.hosts)
    has_apps = any(h.app_model for h in cfg.hosts)
    if has_procs and has_apps:
        print(
            "error: mixing hosts with `processes` and hosts with `app_model` "
            "in one simulation is not supported yet",
            file=sys.stderr,
        )
        return 2
    if not has_procs and not has_apps:
        print(
            "error: no hosts define `processes` or `app_model`; nothing to "
            "simulate",
            file=sys.stderr,
        )
        return 2

    try:
        # fail on a malformed fault plan BEFORE creating the data dir
        cfg.faults.load_faults()
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    data_dir = _prepare_data_dir(cfg, resuming=args.resume is not None)
    try:
        if has_procs:
            from shadow_tpu.procs.builder import build_process_driver

            built = build_process_driver(cfg, data_root=data_dir)
        else:
            from shadow_tpu.sim import build_simulation

            built = build_simulation(cfg)
    except ValueError as e:
        # BuildError / ProcessBuildError / TopologyError / DnsError all
        # derive from ValueError: configuration-shaped failures, not bugs.
        # Remove the data dir we just created so the corrected re-run
        # isn't refused with "already exists".
        shutil.rmtree(data_dir, ignore_errors=True)
        print(f"error: {e}", file=sys.stderr)
        return 2

    if has_procs:
        if args.metrics_out or args.trace_out or args.digest_out \
                or args.flight_out or args.profile_out:
            print(
                "note: --metrics-out/--trace-out/--digest-out/--flight-out/"
                "--profile-out cover the device plane only; ignored for "
                "managed-process simulations",
                file=sys.stderr,
            )
        if args.checkpoint_every or args.resume:
            print(
                "note: --checkpoint-every/--resume cover the device plane "
                "only (managed-process state lives in native images and "
                "cannot be snapshotted); ignored",
                file=sys.stderr,
            )
        return _run_process_plane(cfg, built, cfg.general.progress)
    return _run_device_plane(
        cfg, built, cfg.general.progress,
        metrics_out=args.metrics_out, trace_out=args.trace_out,
        checkpoint_every=args.checkpoint_every,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_retain=args.checkpoint_retain,
        resume=args.resume, data_dir=data_dir,
        digest_out=args.digest_out, flight_out=args.flight_out,
        profile_out=args.profile_out,
    )


if __name__ == "__main__":
    sys.exit(main())
