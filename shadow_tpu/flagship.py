"""The flagship on-device workload: PHOLD over a 50ms self-loop link.

This is the reference's PDES canary (src/test/phold/phold.yaml: peers on a
single-vertex self-loop graph exchanging random-destination messages) scaled
to arbitrary host counts. Shared by bench.py and __graft_entry__.py so the
benchmark and the driver's compile checks always exercise the same model.
"""

from __future__ import annotations

SELF_LOOP_50MS_GML = """\
graph [
  node [ id 0 bandwidth_down "81920 Kibit" bandwidth_up "81920 Kibit" ]
  edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
]
"""


def build_phold_flagship(
    num_hosts: int,
    msgload: int = 2,
    stop_s: int = 10,
    runtime_s: int | None = None,
    event_capacity: int | None = None,
    K: int | None = None,
    seed: int = 42,
    num_shards: int = 1,
    island_mode: str = "vmap",
    exchange_slots: int = 0,
    mesh_exchange: str = "ppermute",
    placement: str = "block",
    obs_counters: bool = True,
    pool_gears: int = 1,
    audit_digest: bool = True,
    flight_recorder: int = 0,
    pipelined_dispatch: bool = True,
    host_workers: int = 1,
):
    from shadow_tpu.sim import build_simulation

    if runtime_s is None:
        runtime_s = max(stop_s - 2, 1)
    if event_capacity is None:
        # PHOLD's live population is num_hosts × msgload messages; the
        # merge only ever holds leftovers + one window's emissions, so
        # 1.5× covers it with headroom (pool_overflow_dropped is asserted
        # zero by the bench). The window sort scales with the pool and the
        # merge sort with pool + H*K, so tight sizing is a direct speedup.
        event_capacity = max(3 * num_hosts * msgload // 2, 4096)
    if K is None:
        # Random destinations make per-host wave occupancy Poisson(msgload);
        # K must cover the max over ALL hosts or straggler hosts defer into
        # an EXTRA whole window pass per wave (correct but ~2x slower —
        # each pass costs the full sort pipeline). msgload + 16 puts the
        # per-wave straggler probability near zero beyond 100k hosts while
        # the [H, K] filler block stays modest.
        K = msgload + 16
    island_exp = {}
    if num_shards > 1:
        if exchange_slots <= 0:
            # PHOLD cross-shard volume per window per destination shard:
            # one wave ≈ Hl·msgload emissions per shard spread uniformly
            # over S destinations. No headroom multiplier: misses defer
            # safely under the window-end clamp, while every extra slot
            # costs S pool rows AND S grouping-sort fillers per shard —
            # oversizing re-grows the sort volume islands exist to shrink
            # (VERDICT r4 weak #1; islands.suggest_exchange_slots() gives
            # the measured-traffic figure for retuning).
            hl = num_hosts // num_shards
            exchange_slots = max(64, hl * msgload // num_shards)
        island_exp = {
            "num_shards": num_shards,
            "island_mode": island_mode,
            "exchange_slots": exchange_slots,
            "mesh_exchange": mesh_exchange,
            "placement": placement,
        }
    return build_simulation(
        {
            "general": {"stop_time": stop_s, "seed": seed},
            "network": {"graph": {"type": "gml", "inline": SELF_LOOP_50MS_GML}},
            "experimental": {
                "event_capacity": event_capacity,
                "events_per_host_per_window": K,
                **island_exp,
                # PHOLD emits exactly one event per handled event, so K
                # outbox slots per host can never overflow; small boxes keep
                # the per-window merge sort lean (the hot cost at scale).
                "outbox_slots": K,
                "inbox_slots": 4,
                "obs_counters": obs_counters,
                "pool_gears": pool_gears,
                "audit_digest": audit_digest,
                "flight_recorder": flight_recorder,
                "pipelined_dispatch": pipelined_dispatch,
                "host_workers": host_workers,
            },
            "hosts": {
                "peer": {
                    "quantity": num_hosts,
                    "app_model": "phold",
                    "app_options": {"msgload": msgload, "runtime": runtime_s},
                }
            },
        }
    )
