"""Upstream-router queue with CoDel AQM, vectorized per host.

Reference (src/main/routing/router_queue_codel.c, RFC 8289): packets from
the simulated network enter the host's upstream-ISP router queue; the NIC
receive path dequeues them. CoDel tracks per-packet sojourn time; if it
stays ≥ TARGET (10 ms, Shadow's doubled value) for a full INTERVAL (100 ms),
the router enters drop mode and drops with increasing frequency per the
control law, until delays recover.

Differences from the reference, both deliberate:
- The reference's control law divides the absolute timestamp by sqrt(count)
  (`(ts + interval)/sqrt(count)`), which for count > 1 produces a
  next-drop time far in the past and collapses into consecutive drops. We
  implement the law its own comments cite (RFC 8289):
  next = ts + interval/sqrt(count).
- At most DROP_UNROLL packets are dropped per dequeue; a longer drop burst
  continues on the next pump round (the receive pump re-arms itself while
  the queue is non-empty), so bursts are spread over same-timestamp
  micro-steps instead of one call.
- The queue is a bounded ring (the reference is unbounded); overflow drops
  are counted separately.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

from shadow_tpu.core import simtime, soa
from shadow_tpu.core.state import PAYLOAD_WORDS
from shadow_tpu.net import packet as pkt

TARGET_NS = 10 * simtime.NS_PER_MS
INTERVAL_NS = 100 * simtime.NS_PER_MS
DROP_UNROLL = 1

SUB = "router"


@struct.dataclass
class RouterState:
    # ring [H, Q]
    q_payload: jnp.ndarray  # [H, Q, P] i32
    q_src: jnp.ndarray  # [H, Q] i32
    q_enq_ts: jnp.ndarray  # [H, Q] i64
    q_head: jnp.ndarray  # [H] i32
    q_tail: jnp.ndarray  # [H] i32
    # codel per-host state
    drop_mode: jnp.ndarray  # [H] bool (False = store)
    interval_expire: jnp.ndarray  # [H] i64 (0 = unset)
    next_drop: jnp.ndarray  # [H] i64
    drop_count: jnp.ndarray  # [H] i32
    drop_count_last: jnp.ndarray  # [H] i32
    total_size: jnp.ndarray  # [H] i64 queued wire bytes
    # counters
    codel_dropped: jnp.ndarray  # [] i64
    overflow_dropped: jnp.ndarray  # [] i64
    # last AQM-dropped packet per host (PDS breadcrumb registers; trail
    # word is 0 for simulations built without packet_trails)
    drop_trail: jnp.ndarray  # [H] i32
    drop_time: jnp.ndarray  # [H] i64


def init(num_hosts: int, queue_slots: int = 64,
         payload_words: int = PAYLOAD_WORDS) -> RouterState:
    H, Q = num_hosts, queue_slots
    z64 = lambda: jnp.zeros((H,), jnp.int64)  # noqa: E731
    z32 = lambda: jnp.zeros((H,), jnp.int32)  # noqa: E731
    return RouterState(
        q_payload=jnp.zeros((H, Q, payload_words), jnp.int32),
        q_src=jnp.zeros((H, Q), jnp.int32),
        q_enq_ts=jnp.zeros((H, Q), jnp.int64),
        q_head=z32(),
        q_tail=z32(),
        drop_mode=jnp.zeros((H,), bool),
        interval_expire=z64(),
        next_drop=z64(),
        drop_count=z32(),
        drop_count_last=z32(),
        total_size=z64(),
        codel_dropped=jnp.zeros((), jnp.int64),
        overflow_dropped=jnp.zeros((), jnp.int64),
        drop_trail=z32(),
        drop_time=z64(),
    )


def enqueue(router: RouterState, mask, payload, src, now) -> RouterState:
    """router_enqueue (router.c:103-121): append with enqueue timestamp."""
    H, Q = router.q_src.shape
    room = (router.q_tail - router.q_head) < Q
    ok = mask & room
    slot = router.q_tail % Q
    size = pkt.total_bytes(payload).astype(jnp.int64)
    return router.replace(
        q_payload=soa.set_at(router.q_payload, ok, slot, payload),
        q_src=soa.set_at(router.q_src, ok, slot, src.astype(jnp.int32)),
        q_enq_ts=soa.set_at(
            router.q_enq_ts, ok, slot,
            jnp.broadcast_to(now, (H,)).astype(jnp.int64),
        ),
        q_tail=router.q_tail + ok.astype(jnp.int32),
        total_size=router.total_size + jnp.where(ok, size, 0),
        overflow_dropped=router.overflow_dropped
        + jnp.sum(mask & ~room, dtype=jnp.int64),
    )


def _record_drop(router: RouterState, mask, payload, now):
    """Keep the dropped (in-hand) packet's breadcrumb trail + drop time in
    per-host registers (packet.c PDS_* trail analog for the AQM's drops —
    they happen inside the dequeue walk where no caller sees the packet).
    Trail word 0 when the sim runs without packet_trails."""
    if payload.shape[-1] <= pkt.W_TRAIL:
        return router
    tr = (payload[..., pkt.W_TRAIL] << 4) | jnp.int32(pkt.PDS_DROPPED_CODEL)
    return router.replace(
        drop_trail=jnp.where(mask, tr, router.drop_trail),
        drop_time=jnp.where(
            mask, jnp.broadcast_to(now, mask.shape).astype(jnp.int64),
            router.drop_time,
        ),
    )


def _control_law(count, ts):
    # next = ts + interval/sqrt(count); float64 is fine here — this runs on
    # [H] scalars a few times per dequeue, not in the packet fast path.
    inc = jnp.round(
        INTERVAL_NS / jnp.sqrt(jnp.maximum(count, 1).astype(jnp.float64))
    ).astype(jnp.int64)
    return ts + inc


def _pop_helper(router: RouterState, now, want):
    """One masked ring pop with sojourn bookkeeping
    (_routerqueuecodel_dequeueHelper). Returns
    (router, have [H], payload [H,P], src [H], ok_to_drop [H])."""
    H, Q = router.q_src.shape
    hosts = jnp.arange(H, dtype=jnp.int32)
    nonempty = router.q_head < router.q_tail
    have = want & nonempty
    empty_hit = want & ~nonempty

    slot = router.q_head % Q
    # one-hot ring reads — row gathers serialize on TPU (soa.get_at)
    payload = soa.get_at(router.q_payload, slot)
    src = soa.get_at(router.q_src, slot)
    enq_ts = soa.get_at(router.q_enq_ts, slot)

    size = pkt.total_bytes(payload).astype(jnp.int64)
    new_total = jnp.where(have, router.total_size - size, router.total_size)
    sojourn = now - enq_ts
    good = (sojourn < TARGET_NS) | (new_total < pkt.MTU)

    # good state: reset interval expiration
    interval_expire = jnp.where(have & good, 0, router.interval_expire)
    # bad state, first time: arm the interval
    entering_bad = have & ~good & (router.interval_expire == 0)
    interval_expire = jnp.where(entering_bad, now + INTERVAL_NS, interval_expire)
    # bad state, sustained a full interval: ok to drop
    ok_to_drop = have & ~good & (router.interval_expire != 0) & (
        now >= router.interval_expire
    )
    # empty queue resets the interval expiration
    interval_expire = jnp.where(empty_hit, 0, interval_expire)

    router = router.replace(
        q_head=router.q_head + have.astype(jnp.int32),
        total_size=new_total,
        interval_expire=interval_expire,
    )
    return router, have, payload, src, ok_to_drop


def dequeue(router: RouterState, now, mask, aqm: bool = True):
    """CoDel dequeue (_routerqueuecodel_dequeue), one deliverable packet per
    masked host. Returns (router, have, payload, src).

    aqm=False gives the reference's non-AQM router variants
    (router_queue_static.c / router_queue_single.c): a plain drop-tail
    FIFO pop with no control law — "single" is this with a 1-slot ring.
    """
    if not aqm:
        router, have, payload, src, _ok = _pop_helper(router, now, mask)
        return router, have, payload, src
    router, have, payload, src, ok = _pop_helper(router, now, mask)

    # empty → store mode
    router = router.replace(
        drop_mode=jnp.where(mask & ~have, False, router.drop_mode)
    )

    in_drop = mask & have & router.drop_mode
    # delays low again → leave drop mode
    router = router.replace(
        drop_mode=jnp.where(in_drop & ~ok, False, router.drop_mode)
    )

    # drop-mode loop: drop while now >= next_drop (bounded unroll).
    # `ok` tracks the okToDrop verdict of the packet CURRENTLY in hand —
    # it must follow each re-pop or a fresh low-sojourn packet would be
    # judged by its dropped predecessor's verdict.
    for _ in range(DROP_UNROLL):
        cond = mask & have & router.drop_mode & (now >= router.next_drop)
        router = router.replace(
            codel_dropped=router.codel_dropped + jnp.sum(cond, dtype=jnp.int64),
            drop_count=router.drop_count + cond.astype(jnp.int32),
        )
        router = _record_drop(router, cond, payload, now)
        router, have2, payload2, src2, ok2 = _pop_helper(router, now, cond)
        have = jnp.where(cond, have2, have)
        payload = jnp.where(cond[:, None], payload2, payload)
        src = jnp.where(cond, src2, src)
        ok = jnp.where(cond, ok2, ok)
        router = router.replace(
            next_drop=jnp.where(
                cond & ok2,
                _control_law(router.drop_count, router.next_drop),
                router.next_drop,
            ),
            drop_mode=jnp.where(cond & ~ok2, False, router.drop_mode),
        )

    # store mode but the packet in hand should now drop: drop it, enter
    # drop mode
    trans = mask & have & ~router.drop_mode & ok
    router = router.replace(
        codel_dropped=router.codel_dropped + jnp.sum(trans, dtype=jnp.int64)
    )
    router = _record_drop(router, trans, payload, now)
    router, have3, payload3, src3, _ok3 = _pop_helper(router, now, trans)
    have = jnp.where(trans, have3, have)
    payload = jnp.where(trans[:, None], payload3, payload)
    src = jnp.where(trans, src3, src)
    delta = router.drop_count - router.drop_count_last
    recently = now < (router.next_drop + 16 * INTERVAL_NS)
    new_count = jnp.where(recently & (delta > 1), delta, 1).astype(jnp.int32)
    router = router.replace(
        drop_mode=jnp.where(trans, True, router.drop_mode),
        drop_count=jnp.where(trans, new_count, router.drop_count),
        next_drop=jnp.where(
            trans, _control_law(new_count, jnp.broadcast_to(now, new_count.shape)),
            router.next_drop,
        ),
        drop_count_last=jnp.where(trans, new_count, router.drop_count_last),
    )
    return router, have, payload, src


def nonempty(router: RouterState):
    return router.q_head < router.q_tail
