"""UDP sockets: per-host socket table, port binding, demux, delivery.

Reference: src/main/host/descriptor/udp.c (straight packet in/out queues
over the Socket base) and the NIC's (proto, port, peer)-keyed binding
hashtable (network_interface.c:391-441) — a general (peer=0) binding catches
server traffic, a peer-specific binding catches connected sockets.

Device form: a fixed [H, S] socket table; demux compares the incoming
packet's (proto, dst_port, src_host, src_port) against all S slots at once;
peer-specific matches outrank general ones. Received datagrams are counted
and handed to the app-receive hook (device apps) or queued for the CPU
syscall plane (managed processes; the recv ring lands with that plane).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

from shadow_tpu.core import soa
from shadow_tpu.net import packet as pkt

SUB = "udp"

ANY_PEER = -1


@struct.dataclass
class UdpState:
    used: jnp.ndarray  # [H, S] bool
    bind_port: jnp.ndarray  # [H, S] i32
    peer_host: jnp.ndarray  # [H, S] i32 (ANY_PEER = unconnected)
    peer_port: jnp.ndarray  # [H, S] i32
    recv_pkts: jnp.ndarray  # [H, S] i64
    recv_bytes: jnp.ndarray  # [H, S] i64
    sent_pkts: jnp.ndarray  # [H, S] i64
    sent_bytes: jnp.ndarray  # [H, S] i64
    drop_no_socket: jnp.ndarray  # [] i64 (PDS_RCV_INTERFACE_DROPPED analog)


def init(num_hosts: int, sockets_per_host: int = 8) -> UdpState:
    H, S = num_hosts, sockets_per_host
    return UdpState(
        used=jnp.zeros((H, S), bool),
        bind_port=jnp.zeros((H, S), jnp.int32),
        peer_host=jnp.full((H, S), ANY_PEER, jnp.int32),
        peer_port=jnp.zeros((H, S), jnp.int32),
        recv_pkts=jnp.zeros((H, S), jnp.int64),
        recv_bytes=jnp.zeros((H, S), jnp.int64),
        sent_pkts=jnp.zeros((H, S), jnp.int64),
        sent_bytes=jnp.zeros((H, S), jnp.int64),
        drop_no_socket=jnp.zeros((), jnp.int64),
    )


def bind_static(udp: UdpState, host: int, slot: int, port: int,
                peer_host: int = ANY_PEER, peer_port: int = 0) -> UdpState:
    """Build-time binding (device apps declare their sockets up front)."""
    return udp.replace(
        used=udp.used.at[host, slot].set(True),
        bind_port=udp.bind_port.at[host, slot].set(port),
        peer_host=udp.peer_host.at[host, slot].set(peer_host),
        peer_port=udp.peer_port.at[host, slot].set(peer_port),
    )


def demux(udp: UdpState, mask, payload, src_host):
    """Find the receiving socket slot per host for an incoming packet.

    Returns (slot [H] i32, found [H] bool); peer-specific beats general,
    lowest slot wins ties (deterministic).
    """
    H, S = udp.used.shape
    dport = payload[:, pkt.W_DST_PORT][:, None]  # [H,1]
    sport = payload[:, pkt.W_SRC_PORT][:, None]
    srch = src_host.astype(jnp.int32)[:, None]
    port_ok = udp.used & (udp.bind_port == dport)
    specific = port_ok & (udp.peer_host == srch) & (udp.peer_port == sport)
    general = port_ok & (udp.peer_host == ANY_PEER)
    # prefer specific: score 2 for specific, 1 for general, 0 none; take the
    # highest-score, lowest-slot match.
    score = specific.astype(jnp.int32) * 2 + general.astype(jnp.int32)
    best = jnp.max(score, axis=1)
    slot = jnp.argmax(score, axis=1).astype(jnp.int32)
    found = mask & (best > 0)
    return slot, found


def deliver(udp: UdpState, mask, slot, payload) -> UdpState:
    """Count a datagram into its socket (the app hook runs separately)."""
    nbytes = payload[:, pkt.W_LEN].astype(jnp.int64)
    return udp.replace(
        recv_pkts=soa.add_at(udp.recv_pkts, mask, slot, 1),
        recv_bytes=soa.add_at(udp.recv_bytes, mask, slot, nbytes),
    )


def count_sent(udp: UdpState, mask, slot, payload) -> UdpState:
    nbytes = payload[:, pkt.W_LEN].astype(jnp.int64)
    return udp.replace(
        sent_pkts=soa.add_at(udp.sent_pkts, mask, slot, 1),
        sent_bytes=soa.add_at(udp.sent_bytes, mask, slot, nbytes),
    )
