"""Simulated packet representation: payload-word layout.

The reference's Packet (src/main/routing/packet.c:37-77) carries protocol
headers (local/UDP/TCP with seq/ack/window/SACK), payload bytes, an app
priority used by the FIFO qdisc, and a delivery-status trail. On device a
packet is PAYLOAD_WORDS int32 words riding inside an event row; actual
payload BYTES are never materialized on device — only lengths (for device
apps) or CPU-side buffer handles (for managed processes).
"""

from __future__ import annotations

import jax.numpy as jnp

from shadow_tpu.core.state import PAYLOAD_WORDS

# word indices
W_PROTO = 0  # 17 = UDP, 6 = TCP
W_SRC_PORT = 1
W_DST_PORT = 2
W_LEN = 3  # payload bytes
W_PRIORITY = 4  # app-order priority (qdisc FIFO key, packet.c priority)
W_FLAGS = 5  # TCP flags
W_SEQ = 6  # TCP sequence number
W_ACK = 7  # TCP acknowledgment
W_WND = 8  # TCP advertised window
W_SRC_HOST = 9  # global host index of the original sender
W_SOCKET = 10  # sender-side socket slot (for completions)
W_HANDLE = 11  # CPU-side payload buffer handle (managed processes)
# Pure TCP ACKs (len 0, no SYN/FIN) carry a 32-chunk SACK bitmap in the
# handle word (unused there): bit k = receiver holds chunk
# [rcv_nxt + k*MSS, +(k+1)*MSS). The bounded form of the reference's SACK
# ranges (tcp.h:145,171 + tcp_retransmit_tally.cc interval lists).
W_SACK = W_HANDLE

# ---------------------------------------------------------------------------
# Per-packet delivery-status breadcrumb trail (reference packet.c:37-77
# PDS_* trail — its debugging workhorse). Debug mode: simulations built
# with experimental.packet_trails carry ONE EXTRA payload word (index 12)
# into which each stage shifts a 4-bit status code, preserving order —
# up to 8 hops, enough for the longest stage chain. Zero cost when off:
# the word (and every stamp) only exists at payload width >= 13.
# ---------------------------------------------------------------------------
W_TRAIL = 12
TRAILED_PAYLOAD_WORDS = 13

PDS_CREATED = 1
PDS_NIC_QUEUED = 2  # send-ring enqueue (throttled path)
PDS_SENT = 3  # left the NIC onto the wire
PDS_DROPPED_LOSS = 4  # path reliability roll failed (worker.c:539)
PDS_ROUTER_ENQUEUED = 5  # entered the upstream router (router.c:103)
PDS_DROPPED_CODEL = 6  # CoDel control-law drop
PDS_DROPPED_OVERFLOW = 7  # router ring overflow (drop-tail)
PDS_DELIVERED = 8  # reached the destination socket
PDS_DROPPED_SENDQ = 9  # NIC send-ring overflow

PDS_NAMES = {
    PDS_CREATED: "CREATED",
    PDS_NIC_QUEUED: "NIC_QUEUED",
    PDS_SENT: "SENT",
    PDS_DROPPED_LOSS: "DROPPED_LOSS",
    PDS_ROUTER_ENQUEUED: "ROUTER_ENQUEUED",
    PDS_DROPPED_CODEL: "DROPPED_CODEL",
    PDS_DROPPED_OVERFLOW: "DROPPED_OVERFLOW",
    PDS_DELIVERED: "DELIVERED",
    PDS_DROPPED_SENDQ: "DROPPED_SENDQ",
}


def stamp(payload, mask, code):
    """Shift status `code` into masked packets' trail word; no-op when the
    simulation was built without trails (payload width < 13)."""
    if payload.shape[-1] <= W_TRAIL:
        return payload
    tr = payload[..., W_TRAIL]
    new = (tr << 4) | jnp.int32(code)
    if mask.ndim == tr.ndim:
        m = mask
    else:
        m = jnp.broadcast_to(mask, tr.shape)
    return payload.at[..., W_TRAIL].set(jnp.where(m, new, tr))


def decode_trail(word: int) -> list[str]:
    """Trail word → ordered status names (oldest first)."""
    out = []
    w = int(word) & 0xFFFFFFFF
    while w:
        out.append(PDS_NAMES.get(w & 0xF, f"?{w & 0xF}"))
        w >>= 4
    return list(reversed(out))

PROTO_UDP = 17
PROTO_TCP = 6

# header sizes (IPv4 20 + UDP 8 / TCP 20), matching the reference's
# packet_getHeaderSize accounting.
UDP_HEADER_BYTES = 28
TCP_HEADER_BYTES = 40
MTU = 1500  # CONFIG_MTU


def header_bytes(proto):
    return jnp.where(proto == PROTO_TCP, TCP_HEADER_BYTES, UDP_HEADER_BYTES)


def total_bytes(payload):
    """Wire size of a packet given its payload words [...,P]."""
    return payload[..., W_LEN] + header_bytes(payload[..., W_PROTO])


def pack_time(payload, t):
    """Stash an int64 timestamp in the (UDP-unused) seq/ack words."""
    lo = (t & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32)
    hi = (t >> 32).astype(jnp.int32)
    return payload.at[:, W_SEQ].set(lo).at[:, W_ACK].set(hi)


def unpack_time(payload):
    lo = payload[:, W_SEQ].astype(jnp.int64) & 0xFFFFFFFF
    hi = payload[:, W_ACK].astype(jnp.int64)
    return (hi << 32) | lo


def make_udp(src_port, dst_port, length, priority, src_host, socket_slot=None,
             payload_words: int = PAYLOAD_WORDS):
    """Assemble [H, P] payload words for a UDP datagram (vectorized)."""
    H = src_port.shape[0]
    pl = jnp.zeros((H, payload_words), dtype=jnp.int32)
    if payload_words > W_TRAIL:
        pl = pl.at[:, W_TRAIL].set(PDS_CREATED)
    pl = pl.at[:, W_PROTO].set(PROTO_UDP)
    pl = pl.at[:, W_SRC_PORT].set(src_port.astype(jnp.int32))
    pl = pl.at[:, W_DST_PORT].set(dst_port.astype(jnp.int32))
    pl = pl.at[:, W_LEN].set(length.astype(jnp.int32))
    pl = pl.at[:, W_PRIORITY].set(priority.astype(jnp.int32))
    pl = pl.at[:, W_SRC_HOST].set(src_host.astype(jnp.int32))
    if socket_slot is not None:
        pl = pl.at[:, W_SOCKET].set(socket_slot.astype(jnp.int32))
    return pl
